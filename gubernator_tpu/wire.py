"""Converters between the internal dataclasses and the protobuf wire
messages (gubernator_pb2 / peers_pb2).

The dataclasses in `types.py` stay the in-process currency (the JSON
gateway and the stores use them directly); protobuf enters only at the
gRPC edge, mirroring how the reference's generated pb types live at its
gRPC boundary (gubernator.pb.go / peers.pb.go).
"""

from __future__ import annotations

import json
import struct
import threading
from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np

from .proto import gubernator_pb2 as pb
from .proto import peers_columns_pb2 as pc_pb
from .proto import peers_pb2 as peers_pb
from .types import (
    GetRateLimitsRequest,
    GetRateLimitsResponse,
    HealthCheckResponse,
    RateLimitRequest,
    RateLimitResponse,
    UpdatePeerGlobal,
)

# A forwarded batch as parallel columns — the peer-hop currency shared
# by PeerClient (send) and wire codecs (both transports):
# (names, unique_keys, algorithm i32, behavior i32, hits i64, limit
# i64, duration i64), all length n.
PeerColumns = Tuple[Sequence[str], Sequence[str], np.ndarray, np.ndarray,
                    np.ndarray, np.ndarray, np.ndarray]


# ---- RateLimitReq ----------------------------------------------------
def req_to_pb(r: RateLimitRequest) -> pb.RateLimitReq:
    return pb.RateLimitReq(
        name=r.name,
        unique_key=r.unique_key,
        hits=int(r.hits),
        limit=int(r.limit),
        duration=int(r.duration),
        algorithm=int(r.algorithm),
        behavior=int(r.behavior),
    )


def req_from_pb(m: pb.RateLimitReq) -> RateLimitRequest:
    return RateLimitRequest(
        name=m.name,
        unique_key=m.unique_key,
        hits=m.hits,
        limit=m.limit,
        duration=m.duration,
        algorithm=int(m.algorithm),
        behavior=int(m.behavior),
    )


# ---- RateLimitResp ---------------------------------------------------
def resp_to_pb(r: RateLimitResponse) -> pb.RateLimitResp:
    m = pb.RateLimitResp(
        status=int(r.status),
        limit=int(r.limit),
        remaining=int(r.remaining),
        reset_time=int(r.reset_time),
        error=r.error,
    )
    for k, v in (r.metadata or {}).items():
        m.metadata[k] = v
    return m


def resp_from_pb(m: pb.RateLimitResp) -> RateLimitResponse:
    return RateLimitResponse(
        status=int(m.status),
        limit=m.limit,
        remaining=m.remaining,
        reset_time=m.reset_time,
        error=m.error,
        metadata=dict(m.metadata),
    )


# ---- batch envelopes -------------------------------------------------
def get_rate_limits_req_to_pb(req: GetRateLimitsRequest) -> pb.GetRateLimitsReq:
    return pb.GetRateLimitsReq(requests=[req_to_pb(r) for r in req.requests])


def get_rate_limits_req_from_pb(m: pb.GetRateLimitsReq) -> GetRateLimitsRequest:
    return GetRateLimitsRequest(requests=[req_from_pb(r) for r in m.requests])


def get_rate_limits_resp_to_pb(resp: GetRateLimitsResponse) -> pb.GetRateLimitsResp:
    return pb.GetRateLimitsResp(responses=[resp_to_pb(r) for r in resp.responses])


def get_rate_limits_resp_from_pb(m: pb.GetRateLimitsResp) -> GetRateLimitsResponse:
    return GetRateLimitsResponse(responses=[resp_from_pb(r) for r in m.responses])


def peer_rate_limits_req_to_pb(req: GetRateLimitsRequest) -> peers_pb.GetPeerRateLimitsReq:
    return peers_pb.GetPeerRateLimitsReq(requests=[req_to_pb(r) for r in req.requests])


def peer_rate_limits_req_from_pb(m: peers_pb.GetPeerRateLimitsReq) -> GetRateLimitsRequest:
    return GetRateLimitsRequest(requests=[req_from_pb(r) for r in m.requests])


def peer_rate_limits_resp_to_pb(resp: GetRateLimitsResponse) -> peers_pb.GetPeerRateLimitsResp:
    return peers_pb.GetPeerRateLimitsResp(rate_limits=[resp_to_pb(r) for r in resp.responses])


def peer_rate_limits_resp_from_pb(m: peers_pb.GetPeerRateLimitsResp) -> GetRateLimitsResponse:
    return GetRateLimitsResponse(responses=[resp_from_pb(r) for r in m.rate_limits])


# ---- columnar fast path ---------------------------------------------
def columns_from_pb(m: pb.GetRateLimitsReq):
    """Parse the pb batch straight into ingress columns (the gRPC half
    of the zero-dataclass hot path)."""
    import numpy as np

    from .service import IngressColumns

    items = m.requests
    n = len(items)
    return IngressColumns(
        names=[r.name for r in items],
        unique_keys=[r.unique_key for r in items],
        algorithm=np.fromiter((r.algorithm for r in items), np.int32, count=n),
        behavior=np.fromiter((r.behavior for r in items), np.int32, count=n),
        hits=np.fromiter((r.hits for r in items), np.int64, count=n),
        limit=np.fromiter((r.limit for r in items), np.int64, count=n),
        duration=np.fromiter((r.duration for r in items), np.int64, count=n),
    )


def _columns_to_resp_list(result):
    ov = result.overrides
    status = result.status
    limit = result.limit
    remaining = result.remaining
    reset = result.reset_time
    owner_of = getattr(result, "owner_of", None)
    owner_addrs = getattr(result, "owner_addrs", None)
    out = []
    for i in range(result.n):
        r = ov.get(i)
        if r is not None:
            out.append(resp_to_pb(r))
        else:
            m = pb.RateLimitResp(
                status=int(status[i]),
                limit=int(limit[i]),
                remaining=int(remaining[i]),
                reset_time=int(reset[i]),
            )
            if owner_of is not None and owner_of[i] >= 0:
                # Forwarded lane: the owner's address rides metadata
                # (gubernator.go:190,209 parity) without a per-lane
                # dataclass on the fast path.
                m.metadata["owner"] = owner_addrs[owner_of[i]]
            out.append(m)
    return out


def columns_to_pb(result) -> pb.GetRateLimitsResp:
    """Serialize a service.ColumnarResult directly from its arrays."""
    return pb.GetRateLimitsResp(responses=_columns_to_resp_list(result))


def columns_to_peer_pb(result) -> peers_pb.GetPeerRateLimitsResp:
    """PeersV1 twin of columns_to_pb (field name rate_limits,
    peers.proto:42-45)."""
    return peers_pb.GetPeerRateLimitsResp(rate_limits=_columns_to_resp_list(result))


# ---- columnar peer hop (zero-dataclass forwarded path) ---------------
#
# Two encodings of the same PeerColumns batch (architecture.md
# "Columnar pipeline: the peer hop"):
#   * proto columns (peers_columns.proto) for the gRPC transport —
#     served as PeersV1/GetPeerRateLimitsColumns; old peers answer
#     UNIMPLEMENTED and the sender falls back to the classic
#     per-request GetPeerRateLimits encoding.
#   * a compact binary frame for the HTTP transport — POSTed to the
#     SAME /v1/peer.GetPeerRateLimits path; the receiver sniffs the
#     magic (JSON bodies can never start with it), old receivers
#     answer 400 and the sender falls back to per-request JSON.
#
# Neither direction materializes a RateLimitRequest/RateLimitResponse
# per lane: requests decode straight into service.IngressColumns,
# responses into a service.ColumnarResult whose sparse overrides
# (error/metadata lanes) are the only per-lane objects.

FRAME_MAGIC = b"GUBC"
FRAME_VERSION = 1
_FRAME_KIND_REQ = 1
_FRAME_KIND_RESP = 2
# Public V1 ingress twins of kinds 1/2 (architecture.md "Columnar
# pipeline: the front door"): the SAME column layout magic-sniffed on
# POST /v1/GetRateLimits.  A distinct kind byte (not a path) carries
# the public/peer distinction because the public response must carry
# the owner annotation (forwarded lanes' metadata.owner) that the peer
# hop never needs — kind 6 appends it as two columns.
_FRAME_KIND_INGRESS_REQ = 5
_FRAME_KIND_INGRESS_RESP = 6
COLUMNS_CONTENT_TYPE = "application/x-gubernator-columns"


_FRAME_HEADER_LEN = 10  # magic(4) + version(1) + kind(1) + n(4)

# Optional trace-context trailer on a request frame (tracing.py): after
# the seven columns, `TRACE_MAGIC | u32 n_entries | n_entries * 32B`
# where each entry is `<II` lane_lo, lane_hi (exclusive) + 16B trace id
# + 8B span id (big-endian, the traceparent byte order).  Entries are
# lane RANGES because a coalesced RPC's lanes arrive as contiguous
# per-ingress-batch runs that share one context.  A frame without the
# trailer is byte-identical to the pre-trace layout (the
# GUBER_TRACE_SAMPLE=0 wire-parity contract); receivers that predate
# the trailer reject it as a length mismatch, which the sender treats
# as a version answer and renegotiates (peer_client._post_columns_inner).
TRACE_MAGIC = b"GTRC"
_TRACE_ENTRY_LEN = 32

# (lane_lo, lane_hi, trace_id 128-bit int, span_id 64-bit int)
TraceEntry = Tuple[int, int, int, int]


def _pack_trace_entry(entry: TraceEntry) -> bytes:
    """THE 32-byte entry layout, shared by the frame trailer and the
    proto column (one codec: a format change lands everywhere)."""
    lo, hi, tid, sid = entry
    return (
        struct.pack("<II", lo, hi)
        + int(tid).to_bytes(16, "big")
        + int(sid).to_bytes(8, "big")
    )


def _unpack_trace_entry(raw: bytes, pos: int = 0) -> TraceEntry:
    lo, hi = struct.unpack_from("<II", raw, pos)
    return (
        lo, hi,
        int.from_bytes(raw[pos + 8:pos + 24], "big"),
        int.from_bytes(raw[pos + 24:pos + 32], "big"),
    )


def pack_trace_entries(entries: Sequence[TraceEntry]) -> bytes:
    parts = [TRACE_MAGIC, struct.pack("<I", len(entries))]
    parts.extend(_pack_trace_entry(e) for e in entries)
    return b"".join(parts)


def unpack_trace_entries(raw: bytes, pos: int) -> Tuple[list, int]:
    """Parse a trace trailer at `pos`; raises ValueError when
    malformed/truncated (the decode edge maps it to a 400)."""
    if raw[pos:pos + 4] != TRACE_MAGIC:
        raise ValueError("columns frame length mismatch")
    pos += 4
    try:
        (count,) = struct.unpack_from("<I", raw, pos)
    except struct.error:
        raise ValueError("trace trailer truncated") from None
    pos += 4
    if pos + count * _TRACE_ENTRY_LEN > len(raw):
        raise ValueError("trace trailer truncated")
    entries = []
    for _ in range(count):
        entries.append(_unpack_trace_entry(raw, pos))
        pos += _TRACE_ENTRY_LEN
    return entries, pos


def is_columns_frame(raw: bytes) -> bool:
    return len(raw) >= _FRAME_HEADER_LEN and raw[:4] == FRAME_MAGIC


def _pack_str_column(strs: Sequence[str]) -> bytes:
    """u32 blob_len | u32 offsets[n+1] | utf-8 blob (byte offsets)."""
    parts = [s.encode("utf-8") for s in strs]
    offsets = np.zeros(len(parts) + 1, dtype=np.uint32)
    if parts:
        np.cumsum([len(p) for p in parts], out=offsets[1:])
    blob = b"".join(parts)
    return struct.pack("<I", len(blob)) + offsets.tobytes() + blob


def _read_array(raw: bytes, pos: int, dtype, n: int):
    try:
        arr = np.frombuffer(raw, dtype=dtype, count=n, offset=pos)
    except ValueError:
        raise ValueError("columns frame truncated") from None
    return arr, pos + arr.nbytes


def encode_columns_frame(
    cols: PeerColumns, trace: "Optional[Sequence[TraceEntry]]" = None,
    kind: int = _FRAME_KIND_REQ,
) -> bytes:
    """PeerColumns -> binary request frame (see architecture.md for the
    byte-level spec).  `trace` (sampled lanes' contexts) appends the
    optional trace trailer; None/empty keeps the frame byte-identical
    to the pre-trace layout.  `kind` selects the peer hop (1, default)
    or the public ingress twin (5) — same byte layout either way."""
    names, uks, algo, beh, hits, limit, duration = cols
    n = len(names)
    parts = [
        FRAME_MAGIC,
        struct.pack("<BBI", FRAME_VERSION, kind, n),
        _pack_str_column(names),
        _pack_str_column(uks),
        np.ascontiguousarray(algo, dtype=np.int32).tobytes(),
        np.ascontiguousarray(beh, dtype=np.int32).tobytes(),
        np.ascontiguousarray(hits, dtype=np.int64).tobytes(),
        np.ascontiguousarray(limit, dtype=np.int64).tobytes(),
        np.ascontiguousarray(duration, dtype=np.int64).tobytes(),
    ]
    if trace:
        parts.append(pack_trace_entries(trace))
    return b"".join(parts)


def _read_str_blob(raw: bytes, pos: int, n: int):
    """(offsets u32[n+1], blob bytes, next_pos) — no string decode."""
    try:
        (blob_len,) = struct.unpack_from("<I", raw, pos)
        pos += 4
        offsets = np.frombuffer(raw, dtype=np.uint32, count=n + 1, offset=pos)
    except (struct.error, ValueError):
        raise ValueError("columns frame truncated") from None
    pos += 4 * (n + 1)
    blob = raw[pos:pos + blob_len]
    if len(blob) != blob_len or (n and int(offsets[-1]) != blob_len):
        raise ValueError("columns frame string column truncated")
    if n and (
        int(offsets[0]) != 0
        or bool(np.any(np.diff(offsets.astype(np.int64)) < 0))
    ):
        # Non-monotonic offsets would later surface as negative lengths
        # deep inside the service (a 500); reject at the decode edge
        # where the caller maps it to a 400.
        raise ValueError("columns frame string offsets invalid")
    return offsets, blob, pos + blob_len


def _packed_hash_keys(nb: bytes, no, ub: bytes, uo):
    """Build the per-lane hash keys (name + "_" + unique_key) as a
    native.PackedKeys with ONE vectorized byte scatter — the owner's
    planner consumes packed keys directly, so the receive path never
    materializes n Python strings."""
    from .native import PackedKeys

    no64 = no.astype(np.int64)
    uo64 = uo.astype(np.int64)
    nlen = np.diff(no64)
    ulen = np.diff(uo64)
    n = len(nlen)
    out_off = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(nlen + 1 + ulen, out=out_off[1:])
    buf = np.empty(int(out_off[-1]), dtype=np.uint8)
    nb_a = np.frombuffer(nb, dtype=np.uint8)
    ub_a = np.frombuffer(ub, dtype=np.uint8)
    if nb_a.size:
        buf[
            np.arange(nb_a.size, dtype=np.int64)
            + np.repeat(out_off[:-1] - no64[:-1], nlen)
        ] = nb_a
    buf[out_off[:-1] + nlen] = ord("_")
    if ub_a.size:
        buf[
            np.arange(ub_a.size, dtype=np.int64)
            + np.repeat(out_off[:-1] + nlen + 1 - uo64[:-1], ulen)
        ] = ub_a
    return PackedKeys(buf, out_off)


class FrameIngressColumns:
    """service.IngressColumns twin decoded LAZILY from a binary frame:
    numeric columns are zero-copy views of the frame buffer, hash keys
    come packed (prevalidated — forwarded lanes were validated at the
    sender's ingress, so the error column is all-zero), and
    name/unique_key strings only materialize for the lanes that need
    dataclasses (GLOBAL / MULTI_REGION / slow legs)."""

    __slots__ = ("algorithm", "behavior", "hits", "limit", "duration",
                 "_n", "_nb", "_no", "_ub", "_uo", "_names", "_uks",
                 "trace_ctx", "_err", "_packed")

    def __init__(self, n, nb, no, ub, uo, algo, beh, hits, limit, duration,
                 trace_ctx=None, err=None, packed=None):
        self._n = n
        self._nb, self._no = nb, no
        self._ub, self._uo = ub, uo
        self.algorithm = algo
        self.behavior = beh
        self.hits = hits
        self.limit = limit
        self.duration = duration
        self._names = None
        self._uks = None
        # Wire trace-context column (lane ranges -> trace/span ids);
        # consumed by tracing.request_links on the owner's dispatch.
        self.trace_ctx = trace_ctx
        # Public-ingress validation codes (1 = empty unique_key, 2 =
        # empty name; the LazyIngressColumns convention).  None on the
        # peer hop — forwarded lanes were validated at the sender's
        # ingress, so the error column is all-zero by contract.
        self._err = err
        # Pre-built packed hash keys (the native gt_frame_parse hands
        # them over ready); None = build with the numpy scatter.
        self._packed = packed

    def __len__(self) -> int:
        return self._n

    @property
    def prevalidated(self):
        packed = self._packed
        if packed is None:
            packed = _packed_hash_keys(self._nb, self._no, self._ub, self._uo)
        err = self._err
        if err is None:
            err = np.zeros(self._n, dtype=np.uint8)
        return packed, err

    def _name_at(self, i: int) -> str:
        return self._nb[self._no[i]:self._no[i + 1]].decode("utf-8")

    def _uk_at(self, i: int) -> str:
        return self._ub[self._uo[i]:self._uo[i + 1]].decode("utf-8")

    @property
    def names(self):
        if self._names is None:
            self._names = [self._name_at(i) for i in range(self._n)]
        return self._names

    @property
    def unique_keys(self):
        if self._uks is None:
            self._uks = [self._uk_at(i) for i in range(self._n)]
        return self._uks

    def request_at(self, i: int) -> RateLimitRequest:
        return RateLimitRequest(
            name=self._name_at(i),
            unique_key=self._uk_at(i),
            hits=int(self.hits[i]),
            limit=int(self.limit[i]),
            duration=int(self.duration[i]),
            algorithm=int(self.algorithm[i]),
            behavior=int(self.behavior[i]),
        )


def _decode_req_frame(raw: bytes, want_kind: int, validate: bool):
    """Shared body of the two request-frame decoders.  `validate` is
    the public-ingress mode: compute per-lane empty-name/unique_key
    codes (untrusted client) and range-check the algorithm column; the
    peer hop skips both (sender-side ingress already validated)."""
    from . import native
    from .service import IngressColumns

    if not is_columns_frame(raw):
        raise ValueError("not a columns frame")
    version, kind, n = struct.unpack_from("<BBI", raw, 4)
    if version != FRAME_VERSION or kind != want_kind:
        raise ValueError(
            f"unsupported columns frame (version={version}, kind={kind})"
        )
    pos = 10
    no, nb, pos = _read_str_blob(raw, pos, n)
    uo, ub, pos = _read_str_blob(raw, pos, n)
    algo, pos = _read_array(raw, pos, np.int32, n)
    beh, pos = _read_array(raw, pos, np.int32, n)
    hits, pos = _read_array(raw, pos, np.int64, n)
    limit, pos = _read_array(raw, pos, np.int64, n)
    duration, pos = _read_array(raw, pos, np.int64, n)
    trace_ctx = None
    if pos != len(raw):
        # The only legal continuation is the trace-context trailer
        # (tracing.py); anything else is still a length mismatch.
        trace_ctx, pos = unpack_trace_entries(raw, pos)
        if pos != len(raw):
            raise ValueError("columns frame length mismatch")
    if validate and n and bool(np.any((algo < 0) | (algo > 1))):
        # An out-of-range algorithm would reach the kernel as a
        # garbage branch selector; reject the frame at the decode
        # edge (the gateway maps it to a 400) — the client library
        # only ever emits 0/1.
        raise ValueError("ingress frame algorithm out of range")
    if validate:
        _check_utf8_blobs(nb, ub)
    if native.available():
        err = None
        if validate and n:
            # Per-lane validation codes, consumed via `prevalidated`.
            # Only worth computing on THIS branch: the eager
            # IngressColumns below has no err channel — the service
            # re-validates those lane-wise anyway.
            err = np.zeros(n, dtype=np.uint8)
            err[np.diff(no.astype(np.int64)) == 0] = 2  # empty name
            err[np.diff(uo.astype(np.int64)) == 0] = 1  # empty unique_key
        return FrameIngressColumns(
            n, nb, no, ub, uo, algo, beh, hits, limit, duration,
            trace_ctx=trace_ctx, err=err,
        )
    return IngressColumns(
        names=[nb[no[i]:no[i + 1]].decode("utf-8") for i in range(n)],
        unique_keys=[ub[uo[i]:uo[i + 1]].decode("utf-8") for i in range(n)],
        algorithm=algo, behavior=beh,
        hits=hits, limit=limit, duration=duration,
        trace_ctx=trace_ctx,
    )


def _check_utf8_blobs(nb: bytes, ub: bytes) -> None:
    """Public-edge string validation: the lazy decode paths defer
    per-lane .decode('utf-8') into the service's slow legs, where
    invalid bytes from an untrusted client would surface as a 500 deep
    in routing (failing every coalesced rider) instead of a 400 here —
    and would make the native and fallback builds answer the same
    frame differently.  One whole-blob decode per column; trusted peer
    frames skip this (their strings were validated at the sender's
    ingress)."""
    try:
        nb.decode("utf-8")
        ub.decode("utf-8")
    except UnicodeDecodeError:
        raise ValueError(
            "columns frame strings are not valid utf-8"
        ) from None


def decode_columns_frame(raw: bytes):
    """Binary request frame -> ingress columns (the receiver half of
    the zero-dataclass peer hop).  With the native runtime present the
    result is a lazy FrameIngressColumns (packed hash keys for the
    planner, no per-lane strings); otherwise an eager
    service.IngressColumns.  Raises ValueError on a malformed/foreign
    frame."""
    return _decode_req_frame(raw, _FRAME_KIND_REQ, validate=False)


# ---- public columnar ingress (the front door) ------------------------
#
# The PR 2 playbook applied to the CLIENT->daemon hop (architecture.md
# "Columnar pipeline: the front door"): a GUBC frame (kind 5, same
# column layout as the peer hop) magic-sniffed on the existing
# POST /v1/GetRateLimits path, or proto columns served as
# V1/GetRateLimitsColumns on the gRPC transport.  The response is a
# kind-6 frame / IngressColumnsResp: the kind-2 layout plus the owner
# annotation (owner_of i32[n] + owner address column) so forwarded
# lanes keep their metadata.owner without a per-lane JSON override.
# A daemon with GUBER_INGRESS_COLUMNS=0 never sniffs: the frame falls
# into json.loads and answers 400 exactly like a pre-columns build —
# that IS the client's version probe (sticky classic fallback).

def is_ingress_frame(raw: bytes) -> bool:
    return is_columns_frame(raw) and raw[5] == _FRAME_KIND_INGRESS_REQ


def encode_ingress_frame(
    cols: PeerColumns, trace: "Optional[Sequence[TraceEntry]]" = None
) -> bytes:
    """PeerColumns -> public ingress request frame (kind 5; byte layout
    of the kind-1 peer frame, trace trailer rules included)."""
    return encode_columns_frame(cols, trace=trace, kind=_FRAME_KIND_INGRESS_REQ)


def decode_ingress_frame(raw: bytes):
    """Public ingress frame -> ingress columns.  Unlike the peer hop
    the sender is UNTRUSTED: empty-name/unique_key lanes get per-lane
    validation codes (the service answers them per lane, JSON parity)
    and an out-of-range algorithm rejects the frame.  Tries the native
    single-pass parser first (gt_frame_parse: validation, column
    slicing and the packed hash-key scatter all before Python-level
    work); falls back to the numpy decode."""
    from . import native

    cols = native.parse_ingress_frame(raw)
    if cols is not None:
        return cols
    return _decode_req_frame(raw, _FRAME_KIND_INGRESS_REQ, validate=True)


def is_ingress_result_frame(raw: bytes) -> bool:
    return is_columns_frame(raw) and raw[5] == _FRAME_KIND_INGRESS_RESP


def encode_ingress_result_frame(result) -> bytes:
    """service.ColumnarResult -> public ingress response frame (kind
    6): the kind-2 arrays + `u32 n_owner_addrs [str column owner_addrs
    | i32 owner_of[n]]` + the sparse override pairs.  Owner columns are
    written only when the batch had forwarded lanes (n_owner_addrs=0
    otherwise), so a purely-local batch costs 4 extra bytes."""
    owner_addrs = result.owner_addrs if result.owner_of is not None else []
    parts = [
        FRAME_MAGIC,
        struct.pack("<BBI", FRAME_VERSION, _FRAME_KIND_INGRESS_RESP, result.n),
        *_result_array_parts(result),
        struct.pack("<I", len(owner_addrs)),
    ]
    if owner_addrs:
        parts.append(_pack_str_column(owner_addrs))
        parts.append(
            np.ascontiguousarray(result.owner_of, dtype=np.int32).tobytes()
        )
    _append_override_parts(parts, result.overrides)
    return b"".join(parts)


def decode_ingress_result_frame(raw: bytes):
    """Public ingress response frame -> service.ColumnarResult (client
    side: response_at / the waiter scatter reads owner metadata off the
    arrays, no per-lane dataclasses)."""
    from .service import ColumnarResult

    if not is_columns_frame(raw):
        raise ValueError("not a columns frame")
    version, kind, n = struct.unpack_from("<BBI", raw, 4)
    if version != FRAME_VERSION or kind != _FRAME_KIND_INGRESS_RESP:
        raise ValueError(
            f"unsupported columns frame (version={version}, kind={kind})"
        )
    status, limit, remaining, reset_time, pos = _read_result_arrays(raw, 10, n)
    owner_addrs: list = []
    owner_of = None
    try:
        (n_addr,) = struct.unpack_from("<I", raw, pos)
        pos += 4
        if n_addr:
            ao, ab, pos = _read_str_blob(raw, pos, n_addr)
            owner_addrs = [
                ab[ao[i]:ao[i + 1]].decode("utf-8") for i in range(n_addr)
            ]
            owner_of, pos = _read_array(raw, pos, np.int32, n)
    except struct.error:
        raise ValueError("columns frame truncated") from None
    overrides, pos = _read_overrides(raw, pos)
    if pos != len(raw):
        raise ValueError("columns frame length mismatch")
    return ColumnarResult(
        n=n, status=status, limit=limit, remaining=remaining,
        reset_time=reset_time, overrides=overrides,
        owner_addrs=owner_addrs,
        owner_of=None if owner_of is None else np.array(owner_of),
    )


def result_to_ingress_columns_pb(result) -> "pc_pb.IngressColumnsResp":
    """ColumnarResult -> proto columns response for the public
    V1/GetRateLimitsColumns RPC (kind-6 twin on the gRPC transport)."""
    m = _fill_result_columns_pb(pc_pb.IngressColumnsResp(), result)
    if result.owner_of is not None:
        m.owner_of.extend(np.asarray(result.owner_of, dtype=np.int32).tolist())
        m.owner_addrs.extend(result.owner_addrs)
    return m


def result_from_ingress_columns_pb(m) -> "object":
    from .service import ColumnarResult

    n = len(m.status)
    owner_of = None
    if len(m.owner_of):
        owner_of = np.fromiter(m.owner_of, np.int32, count=len(m.owner_of))
    return ColumnarResult(
        n=n,
        status=np.fromiter(m.status, np.int32, count=n),
        limit=np.fromiter(m.limit, np.int64, count=n),
        remaining=np.fromiter(m.remaining, np.int64, count=n),
        reset_time=np.fromiter(m.reset_time, np.int64, count=n),
        overrides={int(o.lane): resp_from_pb(o.resp) for o in m.overrides},
        owner_addrs=list(m.owner_addrs),
        owner_of=owner_of,
    )


def _result_array_parts(result) -> list:
    """The four result arrays' wire bytes — the section kinds 2 and 6
    share (one encoder: a layout change lands in both)."""
    return [
        np.ascontiguousarray(result.status, dtype=np.int32).tobytes(),
        np.ascontiguousarray(result.limit, dtype=np.int64).tobytes(),
        np.ascontiguousarray(result.remaining, dtype=np.int64).tobytes(),
        np.ascontiguousarray(result.reset_time, dtype=np.int64).tobytes(),
    ]


def _append_override_parts(parts: list, overrides) -> None:
    """Sparse (lane, json) override pairs — the trailer kinds 2 and 6
    share; the only per-lane encode work on a result."""
    parts.append(struct.pack("<I", len(overrides)))
    for lane, resp in overrides.items():
        body = json.dumps(resp.to_json(), separators=(",", ":")).encode("utf-8")
        parts.append(struct.pack("<II", int(lane), len(body)))
        parts.append(body)


def _read_result_arrays(raw: bytes, pos: int, n: int):
    status, pos = _read_array(raw, pos, np.int32, n)
    limit, pos = _read_array(raw, pos, np.int64, n)
    remaining, pos = _read_array(raw, pos, np.int64, n)
    reset_time, pos = _read_array(raw, pos, np.int64, n)
    return status, limit, remaining, reset_time, pos


def _read_overrides(raw: bytes, pos: int):
    try:
        (n_ov,) = struct.unpack_from("<I", raw, pos)
        pos += 4
        overrides = {}
        for _ in range(n_ov):
            lane, blen = struct.unpack_from("<II", raw, pos)
            pos += 8
            if pos + blen > len(raw):
                raise ValueError("columns frame truncated")
            overrides[int(lane)] = RateLimitResponse.from_json(
                json.loads(raw[pos:pos + blen])
            )
            pos += blen
    except struct.error:
        raise ValueError("columns frame truncated") from None
    return overrides, pos


def encode_result_frame(result) -> bytes:
    """service.ColumnarResult -> binary response frame.  Plain lanes
    ride the arrays; overrides (error/metadata lanes) ride as sparse
    (lane, json) pairs — the only per-lane encode work."""
    parts = [
        FRAME_MAGIC,
        struct.pack("<BBI", FRAME_VERSION, _FRAME_KIND_RESP, result.n),
        *_result_array_parts(result),
    ]
    _append_override_parts(parts, result.overrides)
    return b"".join(parts)


def decode_result_frame(raw: bytes):
    """Binary response frame -> service.ColumnarResult (client side:
    the sender scatters these arrays into its own result arrays)."""
    from .service import ColumnarResult

    if not is_columns_frame(raw):
        raise ValueError("not a columns frame")
    version, kind, n = struct.unpack_from("<BBI", raw, 4)
    if version != FRAME_VERSION or kind != _FRAME_KIND_RESP:
        raise ValueError(
            f"unsupported columns frame (version={version}, kind={kind})"
        )
    status, limit, remaining, reset_time, pos = _read_result_arrays(raw, 10, n)
    overrides, pos = _read_overrides(raw, pos)
    if pos != len(raw):
        raise ValueError("columns frame length mismatch")
    return ColumnarResult(
        n=n, status=status, limit=limit, remaining=remaining,
        reset_time=reset_time, overrides=overrides,
    )


# -- proto columns (gRPC transport) ------------------------------------
def peer_columns_req_to_pb(
    cols: PeerColumns, trace: "Optional[Sequence[TraceEntry]]" = None
) -> pc_pb.PeerColumnsReq:
    names, uks, algo, beh, hits, limit, duration = cols
    m = pc_pb.PeerColumnsReq()
    m.names.extend(names)
    m.unique_keys.extend(uks)
    m.algorithm.extend(np.asarray(algo, dtype=np.int32).tolist())
    m.behavior.extend(np.asarray(beh, dtype=np.int32).tolist())
    m.hits.extend(np.asarray(hits, dtype=np.int64).tolist())
    m.limit.extend(np.asarray(limit, dtype=np.int64).tolist())
    m.duration.extend(np.asarray(duration, dtype=np.int64).tolist())
    if trace:
        # One 32-byte packed entry per field element; proto3 receivers
        # that predate the field skip it as an unknown field (that IS
        # the negotiation: no probe needed on this transport).
        m.trace.extend(_pack_trace_entry(e) for e in trace)
    return m


def _trace_entries_from_pb(m) -> "Optional[list]":
    entries = [
        _unpack_trace_entry(raw)
        for raw in getattr(m, "trace", ())
        if len(raw) == _TRACE_ENTRY_LEN  # skip foreign/corrupt entries
    ]
    return entries or None


def ingress_from_peer_columns_pb(m: pc_pb.PeerColumnsReq):
    from .service import IngressColumns

    n = len(m.names)
    return IngressColumns(
        names=list(m.names),
        unique_keys=list(m.unique_keys),
        algorithm=np.fromiter(m.algorithm, np.int32, count=n),
        behavior=np.fromiter(m.behavior, np.int32, count=n),
        hits=np.fromiter(m.hits, np.int64, count=n),
        limit=np.fromiter(m.limit, np.int64, count=n),
        duration=np.fromiter(m.duration, np.int64, count=n),
        trace_ctx=_trace_entries_from_pb(m),
    )


def _fill_result_columns_pb(m, result):
    """Shared column fill for PeerColumnsResp / IngressColumnsResp
    (same field numbers 1-5; the ingress twin adds owners on top)."""
    m.status.extend(np.asarray(result.status, dtype=np.int32).tolist())
    m.limit.extend(np.asarray(result.limit, dtype=np.int64).tolist())
    m.remaining.extend(np.asarray(result.remaining, dtype=np.int64).tolist())
    m.reset_time.extend(np.asarray(result.reset_time, dtype=np.int64).tolist())
    for lane, resp in result.overrides.items():
        ov = m.overrides.add()
        ov.lane = int(lane)
        ov.resp.CopyFrom(resp_to_pb(resp))
    return m


def result_to_peer_columns_pb(result) -> pc_pb.PeerColumnsResp:
    return _fill_result_columns_pb(pc_pb.PeerColumnsResp(), result)


def result_from_peer_columns_pb(m: pc_pb.PeerColumnsResp):
    from .service import ColumnarResult

    n = len(m.status)
    return ColumnarResult(
        n=n,
        status=np.fromiter(m.status, np.int32, count=n),
        limit=np.fromiter(m.limit, np.int64, count=n),
        remaining=np.fromiter(m.remaining, np.int64, count=n),
        reset_time=np.fromiter(m.reset_time, np.int64, count=n),
        overrides={int(o.lane): resp_from_pb(o.resp) for o in m.overrides},
    )


def peer_columns_slice(cols: PeerColumns, lo: int, hi: int) -> PeerColumns:
    """Lane slice of a PeerColumns batch (the classic-downgrade resend
    must re-chunk an oversized columnar chunk to MAX_BATCH_SIZE)."""
    names, uks, algo, beh, hits, limit, duration = cols
    return (
        names[lo:hi], uks[lo:hi], algo[lo:hi], beh[lo:hi],
        hits[lo:hi], limit[lo:hi], duration[lo:hi],
    )


def concat_results(parts):
    """Concatenate ColumnarResults lane-wise (the inverse of
    peer_columns_slice for the classic-downgrade resend)."""
    from .service import ColumnarResult

    if len(parts) == 1:
        return parts[0]
    out = ColumnarResult.empty(sum(p.n for p in parts))
    lo = 0
    for p in parts:
        sl = slice(lo, lo + p.n)
        out.status[sl] = p.status
        out.limit[sl] = p.limit
        out.remaining[sl] = p.remaining
        out.reset_time[sl] = p.reset_time
        for lane, r in p.overrides.items():
            out.overrides[lo + int(lane)] = r
        lo += p.n
    return out


# -- classic fallback, built from columns ------------------------------
# The mixed-version slow lane: a peer that doesn't speak columns still
# receives a correct classic batch.  Per-lane pb/JSON objects are built
# here (the wire format demands them), but still no dataclasses.
def peer_columns_to_classic_pb(cols: PeerColumns) -> peers_pb.GetPeerRateLimitsReq:
    names, uks, algo, beh, hits, limit, duration = cols
    return peers_pb.GetPeerRateLimitsReq(
        requests=[
            pb.RateLimitReq(
                name=names[i], unique_key=uks[i], hits=int(hits[i]),
                limit=int(limit[i]), duration=int(duration[i]),
                algorithm=int(algo[i]), behavior=int(beh[i]),
            )
            for i in range(len(names))
        ]
    )


def result_from_classic_peer_pb(m: peers_pb.GetPeerRateLimitsResp):
    """Classic per-request response -> ColumnarResult: plain lanes fill
    the arrays, error/metadata lanes become overrides."""
    from .service import ColumnarResult

    items = m.rate_limits
    n = len(items)
    result = ColumnarResult.empty(n)
    for i, r in enumerate(items):
        if r.error or r.metadata:
            result.overrides[i] = resp_from_pb(r)
        else:
            result.status[i] = r.status
            result.limit[i] = r.limit
            result.remaining[i] = r.remaining
            result.reset_time[i] = r.reset_time
    return result


def peer_columns_to_classic_json(cols: PeerColumns) -> dict:
    names, uks, algo, beh, hits, limit, duration = cols
    from .types import Algorithm

    return {
        "requests": [
            {
                "name": names[i],
                "uniqueKey": uks[i],
                "hits": str(int(hits[i])),
                "limit": str(int(limit[i])),
                "duration": str(int(duration[i])),
                "algorithm": Algorithm(int(algo[i])).name,
                "behavior": int(beh[i]),
            }
            for i in range(len(names))
        ]
    }


def _result_from_classic_items(items: list):
    """Classic per-response JSON objects -> ColumnarResult: plain lanes
    fill the arrays, error/metadata lanes become overrides.  Shared by
    the peer ("rateLimits") and public-ingress ("responses") envelopes
    so the two decoders cannot drift."""
    from .service import ColumnarResult
    from .types import Status, _parse_enum

    n = len(items)
    result = ColumnarResult.empty(n)
    for i, d in enumerate(items):
        if d.get("error") or d.get("metadata"):
            result.overrides[i] = RateLimitResponse.from_json(d)
        else:
            result.status[i] = int(_parse_enum(d.get("status", 0), Status))
            result.limit[i] = int(d.get("limit", 0))
            result.remaining[i] = int(d.get("remaining", 0))
            result.reset_time[i] = int(
                d.get("resetTime", d.get("reset_time", 0))
            )
    return result


def result_from_classic_peer_json(body: dict):
    """Classic {"rateLimits": [...]} JSON response -> ColumnarResult."""
    return _result_from_classic_items(body.get("rateLimits", []))


def result_from_classic_ingress_json(body: dict):
    """Classic {"responses": [...]} JSON (the public /v1/GetRateLimits
    shape) -> ColumnarResult — the columns client's downgraded-receive
    twin of result_from_classic_peer_json."""
    return _result_from_classic_items(body.get("responses", []))


# ---- GLOBAL broadcast ------------------------------------------------
#
# Columnar replication plane (architecture.md "GLOBAL plane"): the
# owner's sync pass emits its broadcasts as one GlobalsColumns batch
# and fans the SAME encoded payload to every peer.  Two encodings of
# the batch, mirroring the peer-forward hop:
#   * proto columns (GlobalsColumnsReq) for the gRPC transport — served
#     as PeersV1/UpdatePeerGlobalsColumns; old peers answer
#     UNIMPLEMENTED and the sender falls back to the classic per-item
#     UpdatePeerGlobals encoding.
#   * a GUBC frame (kind 3) for the HTTP transport, POSTed to the SAME
#     /v1/peer.UpdatePeerGlobals path; the receiver sniffs the magic
#     (JSON bodies can never start with it), old receivers answer
#     4xx/"codec can't decode" and the sender falls back to per-item
#     JSON.
# BroadcastBatch caches every encoding, so an N-peer fan-out encodes
# each at most once per tick.

_FRAME_KIND_GLOBALS = 3


def is_globals_frame(raw: bytes) -> bool:
    return is_columns_frame(raw) and raw[5] == _FRAME_KIND_GLOBALS


def encode_globals_frame(cols) -> bytes:
    """GlobalsColumns -> binary broadcast frame: GUBC header (kind 3)
    + key string column + algo/status i32 + limit/remaining/reset i64."""
    n = len(cols.keys)
    return b"".join(
        (
            FRAME_MAGIC,
            struct.pack("<BBI", FRAME_VERSION, _FRAME_KIND_GLOBALS, n),
            _pack_str_column(cols.keys),
            np.ascontiguousarray(cols.algorithm, dtype=np.int32).tobytes(),
            np.ascontiguousarray(cols.status, dtype=np.int32).tobytes(),
            np.ascontiguousarray(cols.limit, dtype=np.int64).tobytes(),
            np.ascontiguousarray(cols.remaining, dtype=np.int64).tobytes(),
            np.ascontiguousarray(cols.reset_time, dtype=np.int64).tobytes(),
        )
    )


def decode_globals_frame(raw: bytes):
    """Binary broadcast frame -> GlobalsColumns.  Raises ValueError on
    a malformed/foreign frame (the gateway maps it to a 400)."""
    from .parallel.global_mgr import GlobalsColumns

    if not is_columns_frame(raw):
        raise ValueError("not a columns frame")
    version, kind, n = struct.unpack_from("<BBI", raw, 4)
    if version != FRAME_VERSION or kind != _FRAME_KIND_GLOBALS:
        raise ValueError(
            f"unsupported globals frame (version={version}, kind={kind})"
        )
    pos = _FRAME_HEADER_LEN
    ko, kb, pos = _read_str_blob(raw, pos, n)
    algo, pos = _read_array(raw, pos, np.int32, n)
    status, pos = _read_array(raw, pos, np.int32, n)
    limit, pos = _read_array(raw, pos, np.int64, n)
    remaining, pos = _read_array(raw, pos, np.int64, n)
    reset, pos = _read_array(raw, pos, np.int64, n)
    if pos != len(raw):
        raise ValueError("columns frame length mismatch")
    return GlobalsColumns(
        keys=[kb[ko[i]:ko[i + 1]].decode("utf-8") for i in range(n)],
        algorithm=algo, status=status, limit=limit,
        remaining=remaining, reset_time=reset,
    )


def globals_cols_to_pb(cols) -> pc_pb.GlobalsColumnsReq:
    m = pc_pb.GlobalsColumnsReq()
    m.keys.extend(cols.keys)
    m.algorithm.extend(np.asarray(cols.algorithm, dtype=np.int32).tolist())
    m.status.extend(np.asarray(cols.status, dtype=np.int32).tolist())
    m.limit.extend(np.asarray(cols.limit, dtype=np.int64).tolist())
    m.remaining.extend(np.asarray(cols.remaining, dtype=np.int64).tolist())
    m.reset_time.extend(np.asarray(cols.reset_time, dtype=np.int64).tolist())
    return m


def globals_cols_from_pb(m: pc_pb.GlobalsColumnsReq):
    from .parallel.global_mgr import GlobalsColumns

    n = len(m.keys)
    return GlobalsColumns(
        keys=list(m.keys),
        algorithm=np.fromiter(m.algorithm, np.int32, count=n),
        status=np.fromiter(m.status, np.int32, count=n),
        limit=np.fromiter(m.limit, np.int64, count=n),
        remaining=np.fromiter(m.remaining, np.int64, count=n),
        reset_time=np.fromiter(m.reset_time, np.int64, count=n),
    )


class BroadcastBatch:
    """One sync pass's broadcasts with every wire encoding cached: the
    N-peer fan-out encodes ONCE per encoding actually used (the
    pre-columns sender re-encoded the whole batch per peer per tick).
    The classic encodings are built through the exact dataclass path
    the pre-columns sender used, so a GUBER_GLOBAL_COLUMNS=0 daemon —
    or a classic-negotiated peer — sees byte-identical wire.

    Lazy init is LOCKED: the fan-out pool hands one batch to many
    concurrent sends, and an unguarded check-then-encode would let
    every worker encode its own copy — per-peer encoding through the
    back door."""

    __slots__ = ("cols", "_lock", "_frame", "_pb", "_classic_pb",
                 "_classic_json", "_updates")

    def __init__(self, cols):
        self.cols = cols
        self._lock = threading.Lock()
        self._frame = None
        self._pb = None
        self._classic_pb = None
        self._classic_json = None
        self._updates = None

    def __len__(self) -> int:
        return len(self.cols.keys)

    def updates(self):
        # Callers hold self._lock (or are single-threaded test code).
        if self._updates is None:
            self._updates = self.cols.to_updates()
        return self._updates

    def frame(self) -> bytes:
        with self._lock:
            if self._frame is None:
                self._frame = encode_globals_frame(self.cols)
            return self._frame

    def columns_pb(self) -> pc_pb.GlobalsColumnsReq:
        with self._lock:
            if self._pb is None:
                self._pb = globals_cols_to_pb(self.cols)
            return self._pb

    def classic_pb(self) -> peers_pb.UpdatePeerGlobalsReq:
        with self._lock:
            if self._classic_pb is None:
                self._classic_pb = update_globals_req_to_pb(self.updates())
            return self._classic_pb

    def classic_json_bytes(self) -> bytes:
        with self._lock:
            if self._classic_json is None:
                self._classic_json = json.dumps(
                    {"globals": [u.to_json() for u in self.updates()]}
                ).encode("utf-8")
            return self._classic_json


# ---- Ownership transfer (elastic membership, reshard.py) -------------
# A ring delta ships the moved keys' FULL device bucket rows from the
# old owner to the new one:
#   * proto columns (TransferColumnsReq) served as the gRPC
#     PeersV1/TransferOwnership method;
#   * a GUBC frame (kind 4) POSTed to /v1/peer.TransferOwnership on the
#     HTTP transport.
# Both carry the destination ring's fingerprint so a receiver whose
# ring changed again FENCES the batch (dead-epoch transfer).  A peer
# without the transfer surface answers UNIMPLEMENTED / 404 — provably
# unapplied — and the sender falls back sticky to the classic
# (pre-reshard) behavior for that peer: the moved keys reset there,
# counted as aborts.

_FRAME_KIND_TRANSFER = 4


def is_transfer_frame(raw: bytes) -> bool:
    return is_columns_frame(raw) and raw[5] == _FRAME_KIND_TRANSFER


def encode_transfer_frame(cols) -> bytes:
    """TransferColumns -> binary transfer frame: GUBC header (kind 4)
    + `<Q` ring_hash + key string column + algo/status i32 +
    limit/remaining/duration/stamp/expire_at i64."""
    n = len(cols.keys)
    return b"".join(
        (
            FRAME_MAGIC,
            struct.pack("<BBI", FRAME_VERSION, _FRAME_KIND_TRANSFER, n),
            struct.pack("<Q", cols.ring_hash & 0xFFFFFFFFFFFFFFFF),
            _pack_str_column(cols.keys),
            np.ascontiguousarray(cols.algorithm, dtype=np.int32).tobytes(),
            np.ascontiguousarray(cols.status, dtype=np.int32).tobytes(),
            np.ascontiguousarray(cols.limit, dtype=np.int64).tobytes(),
            np.ascontiguousarray(cols.remaining, dtype=np.int64).tobytes(),
            np.ascontiguousarray(cols.duration, dtype=np.int64).tobytes(),
            np.ascontiguousarray(cols.stamp, dtype=np.int64).tobytes(),
            np.ascontiguousarray(cols.expire_at, dtype=np.int64).tobytes(),
        )
    )


def decode_transfer_frame(raw: bytes):
    """Binary transfer frame -> reshard.TransferColumns.  Raises
    ValueError on a malformed/foreign frame (the gateway maps it to a
    400)."""
    from .reshard import TransferColumns

    if not is_columns_frame(raw):
        raise ValueError("not a columns frame")
    version, kind, n = struct.unpack_from("<BBI", raw, 4)
    if version != FRAME_VERSION or kind != _FRAME_KIND_TRANSFER:
        raise ValueError(
            f"unsupported transfer frame (version={version}, kind={kind})"
        )
    pos = _FRAME_HEADER_LEN
    (ring_hash,) = struct.unpack_from("<Q", raw, pos)
    pos += 8
    ko, kb, pos = _read_str_blob(raw, pos, n)
    algo, pos = _read_array(raw, pos, np.int32, n)
    status, pos = _read_array(raw, pos, np.int32, n)
    limit, pos = _read_array(raw, pos, np.int64, n)
    remaining, pos = _read_array(raw, pos, np.int64, n)
    duration, pos = _read_array(raw, pos, np.int64, n)
    stamp, pos = _read_array(raw, pos, np.int64, n)
    expire, pos = _read_array(raw, pos, np.int64, n)
    if pos != len(raw):
        raise ValueError("columns frame length mismatch")
    return TransferColumns(
        keys=[kb[ko[i]:ko[i + 1]].decode("utf-8") for i in range(n)],
        algorithm=algo, status=status, limit=limit, remaining=remaining,
        duration=duration, stamp=stamp, expire_at=expire,
        ring_hash=int(ring_hash),
    )


def transfer_cols_to_pb(cols) -> "pc_pb.TransferColumnsReq":
    m = pc_pb.TransferColumnsReq()
    m.ring_hash = cols.ring_hash & 0xFFFFFFFFFFFFFFFF
    m.keys.extend(cols.keys)
    m.algorithm.extend(np.asarray(cols.algorithm, dtype=np.int32).tolist())
    m.status.extend(np.asarray(cols.status, dtype=np.int32).tolist())
    m.limit.extend(np.asarray(cols.limit, dtype=np.int64).tolist())
    m.remaining.extend(np.asarray(cols.remaining, dtype=np.int64).tolist())
    m.duration.extend(np.asarray(cols.duration, dtype=np.int64).tolist())
    m.stamp.extend(np.asarray(cols.stamp, dtype=np.int64).tolist())
    m.expire_at.extend(np.asarray(cols.expire_at, dtype=np.int64).tolist())
    return m


def transfer_cols_from_pb(m) -> "object":
    from .reshard import TransferColumns

    n = len(m.keys)
    return TransferColumns(
        keys=list(m.keys),
        algorithm=np.fromiter(m.algorithm, np.int32, count=n),
        status=np.fromiter(m.status, np.int32, count=n),
        limit=np.fromiter(m.limit, np.int64, count=n),
        remaining=np.fromiter(m.remaining, np.int64, count=n),
        duration=np.fromiter(m.duration, np.int64, count=n),
        stamp=np.fromiter(m.stamp, np.int64, count=n),
        expire_at=np.fromiter(m.expire_at, np.int64, count=n),
        ring_hash=int(m.ring_hash),
    )


# ---- Multi-region federation (federation.py) -------------------------
# Cross-region hit replication batch (architecture.md "Multi-region
# federation"): per-key summed MULTI_REGION hits + the origin region's
# id, shipped to each remote region's owner:
#   * proto columns (RegionColumnsReq) served as the gRPC
#     PeersV1/UpdateRegionColumns method;
#   * a GUBC frame (kind 7) POSTed to /v1/peer.UpdateRegionColumns on
#     the HTTP transport.
# A peer without the region surface answers UNIMPLEMENTED / 404 —
# provably unapplied — and the sender falls back sticky to the classic
# per-item GetPeerRateLimits encoding (exactly the pre-federation
# wire; GUBER_REGION_COLUMNS=0 forces it, golden-tested
# byte-identical).

_FRAME_KIND_REGION = 7


def is_region_frame(raw: bytes) -> bool:
    return is_columns_frame(raw) and raw[5] == _FRAME_KIND_REGION


def encode_region_frame(cols) -> bytes:
    """federation.RegionColumns -> binary region frame: GUBC header
    (kind 7) + `u32 origin_len | origin utf-8` + the seven kind-1
    request columns (names/unique_keys string columns, algo/behavior
    i32, hits/limit/duration i64)."""
    n = len(cols.names)
    origin = cols.origin.encode("utf-8")
    return b"".join(
        (
            FRAME_MAGIC,
            struct.pack("<BBI", FRAME_VERSION, _FRAME_KIND_REGION, n),
            struct.pack("<I", len(origin)),
            origin,
            _pack_str_column(cols.names),
            _pack_str_column(cols.unique_keys),
            np.ascontiguousarray(cols.algorithm, dtype=np.int32).tobytes(),
            np.ascontiguousarray(cols.behavior, dtype=np.int32).tobytes(),
            np.ascontiguousarray(cols.hits, dtype=np.int64).tobytes(),
            np.ascontiguousarray(cols.limit, dtype=np.int64).tobytes(),
            np.ascontiguousarray(cols.duration, dtype=np.int64).tobytes(),
        )
    )


def decode_region_frame(raw: bytes):
    """Binary region frame -> federation.RegionColumns.  Raises
    ValueError on a malformed/foreign frame (the gateway maps it to a
    400)."""
    from .federation import RegionColumns

    if not is_columns_frame(raw):
        raise ValueError("not a columns frame")
    version, kind, n = struct.unpack_from("<BBI", raw, 4)
    if version != FRAME_VERSION or kind != _FRAME_KIND_REGION:
        raise ValueError(
            f"unsupported region frame (version={version}, kind={kind})"
        )
    pos = _FRAME_HEADER_LEN
    try:
        (origin_len,) = struct.unpack_from("<I", raw, pos)
    except struct.error:
        raise ValueError("columns frame truncated") from None
    pos += 4
    origin_b = raw[pos:pos + origin_len]
    if len(origin_b) != origin_len:
        raise ValueError("columns frame truncated")
    try:
        origin = origin_b.decode("utf-8")
    except UnicodeDecodeError:
        raise ValueError("region frame origin is not valid utf-8") from None
    pos += origin_len
    no, nb, pos = _read_str_blob(raw, pos, n)
    uo, ub, pos = _read_str_blob(raw, pos, n)
    algo, pos = _read_array(raw, pos, np.int32, n)
    beh, pos = _read_array(raw, pos, np.int32, n)
    hits, pos = _read_array(raw, pos, np.int64, n)
    limit, pos = _read_array(raw, pos, np.int64, n)
    duration, pos = _read_array(raw, pos, np.int64, n)
    if pos != len(raw):
        raise ValueError("columns frame length mismatch")
    return RegionColumns(
        origin=origin,
        names=[nb[no[i]:no[i + 1]].decode("utf-8") for i in range(n)],
        unique_keys=[ub[uo[i]:uo[i + 1]].decode("utf-8") for i in range(n)],
        algorithm=algo, behavior=beh,
        hits=hits, limit=limit, duration=duration,
    )


def region_cols_to_pb(cols) -> "pc_pb.RegionColumnsReq":
    m = pc_pb.RegionColumnsReq()
    m.origin = cols.origin
    m.names.extend(cols.names)
    m.unique_keys.extend(cols.unique_keys)
    m.algorithm.extend(np.asarray(cols.algorithm, dtype=np.int32).tolist())
    m.behavior.extend(np.asarray(cols.behavior, dtype=np.int32).tolist())
    m.hits.extend(np.asarray(cols.hits, dtype=np.int64).tolist())
    m.limit.extend(np.asarray(cols.limit, dtype=np.int64).tolist())
    m.duration.extend(np.asarray(cols.duration, dtype=np.int64).tolist())
    return m


def region_cols_from_pb(m) -> "object":
    from .federation import RegionColumns

    n = len(m.names)
    return RegionColumns(
        origin=m.origin,
        names=list(m.names),
        unique_keys=list(m.unique_keys),
        algorithm=np.fromiter(m.algorithm, np.int32, count=n),
        behavior=np.fromiter(m.behavior, np.int32, count=n),
        hits=np.fromiter(m.hits, np.int64, count=n),
        limit=np.fromiter(m.limit, np.int64, count=n),
        duration=np.fromiter(m.duration, np.int64, count=n),
    )


def update_global_to_pb(u: UpdatePeerGlobal) -> peers_pb.UpdatePeerGlobal:
    return peers_pb.UpdatePeerGlobal(
        key=u.key, status=resp_to_pb(u.status), algorithm=int(u.algorithm)
    )


def update_global_from_pb(m: peers_pb.UpdatePeerGlobal) -> UpdatePeerGlobal:
    return UpdatePeerGlobal(
        key=m.key, status=resp_from_pb(m.status), algorithm=int(m.algorithm)
    )


def update_globals_req_to_pb(updates: Iterable[UpdatePeerGlobal]) -> peers_pb.UpdatePeerGlobalsReq:
    return peers_pb.UpdatePeerGlobalsReq(globals=[update_global_to_pb(u) for u in updates])


def update_globals_req_from_pb(m: peers_pb.UpdatePeerGlobalsReq) -> List[UpdatePeerGlobal]:
    return [update_global_from_pb(u) for u in m.globals]


# ---- HealthCheck -----------------------------------------------------
def health_to_pb(h: HealthCheckResponse) -> pb.HealthCheckResp:
    return pb.HealthCheckResp(
        status=h.status, message=h.message, peer_count=int(h.peer_count)
    )


def health_from_pb(m: pb.HealthCheckResp) -> HealthCheckResponse:
    return HealthCheckResponse(
        status=m.status, message=m.message, peer_count=m.peer_count
    )
