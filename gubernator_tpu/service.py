"""V1Service — the service core (reference V1Instance, gubernator.go).

Routes each request in a GetRateLimits batch: keys this daemon owns are
evaluated in ONE vectorized store call (the reference's 1000-goroutine
fan-out collapses into the kernel batch); keys owned by another daemon
are forwarded through the batching PeerClient; GLOBAL keys owned
elsewhere answer from the local replica cache with async hit
forwarding.  Host-tier GLOBAL and MULTI_REGION pipelines mirror
global.go / multiregion.go on top of the device-tier collective sync.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass, field, replace
from functools import partial
from typing import Callable, Dict, List, Optional, Sequence

from .utils.logging import category_logger

import numpy as np

from . import audit as audit_mod
from . import blackbox as blackbox_mod
from . import profiling
from . import saturation
from . import snapshot as snapshot_mod
from . import telemetry
from . import tracing
from . import wire
from .reshard import ReshardManager, TransferColumns
from .config import MAX_BATCH_SIZE, PEER_COLUMNS_MAX_LANES, BehaviorConfig
from .faults import Backoff
from .federation import FederationManager
from .metrics import Metrics
from .parallel.global_mgr import GlobalsColumns, HitColumns
from .parallel.hash_ring import ReplicatedConsistentHash
from .parallel.mesh import MeshBucketStore
from .parallel.region import RegionPicker
from .peer_client import PeerClient, PeerError, is_circuit_open, is_not_ready
from .types import (
    Behavior,
    GetRateLimitsRequest,
    GetRateLimitsResponse,
    HealthCheckResponse,
    PeerInfo,
    RateLimitRequest,
    RateLimitResponse,
    UpdatePeerGlobal,
    has_behavior,
    set_behavior,
)
from .utils.batch_window import BatchWindow
from .utils.clock import DEFAULT_CLOCK, Clock
from .utils.interval import Interval

HEALTHY = "healthy"
UNHEALTHY = "unhealthy"
ERR_BATCHER_CLOSED = "local batcher is closed"

logger = category_logger("gubernator")


class ApiError(Exception):
    """Request-level error (maps to a gRPC status / HTTP error)."""

    def __init__(self, code: str, message: str, http_status: int = 400):
        super().__init__(message)
        self.code = code
        self.message = message
        self.http_status = http_status


class IngressShedError(ApiError):
    """The bounded ingress queue is full and this submission was SHED
    (429 semantics).  Deliberately an ERROR, not an OVER_LIMIT status:
    OVER_LIMIT is an answer about the client's rate limit; this is the
    daemon declining to queue more work than it can serve inside any
    useful deadline (BENCH_r05 measured an unbounded queue stretching
    ingress p99 to 4.5s).  Callers retry with backoff, exactly like a
    429."""

    def __init__(self, queued_lanes: int, cap: int):
        super().__init__(
            "ResourceExhausted",
            f"ingress queue saturated ({queued_lanes} lanes queued, "
            f"cap {cap}); retry with backoff",
            http_status=429,
        )


class _IngressGate:
    """Shared lane accounting for the bounded ingress queue
    (GUBER_INGRESS_QUEUE_LANES): admit at submit, release at flush.
    cap <= 0 disables the bound.  `track` keeps lane COUNTING on even
    with the bound off — the express bypass reads `queued` as its
    shallow-queue signal, which must work whether or not the shed
    bound is armed; with both the cap and the express lane off
    (`track=False`), admit/release are the pre-express no-ops."""

    def __init__(self, cap: int, metrics: Optional[Metrics],
                 track: bool = False):
        self.cap = cap
        self.track = track
        self.metrics = metrics
        self._queued = 0
        self._mu = threading.Lock()

    @property
    def queued(self) -> int:
        return self._queued

    def admit(self, lanes: int) -> None:
        """Reserve `lanes` or raise IngressShedError (counted)."""
        if self.cap <= 0 and not self.track:
            return
        with self._mu:
            if self.cap > 0 and self._queued + lanes > self.cap:
                queued = self._queued
                shed = True
            else:
                self._queued += lanes
                shed = False
                queued = self._queued
        # Saturation plane: sample the post-admit depth (sheds sample
        # the at-capacity depth) — /debug/status serves the p50/p99.
        saturation.observe_queue_depth(queued)
        if shed:
            if self.metrics is not None:
                self.metrics.ingress_shed.inc(lanes)
            # Flight-recorder event + automatic dump (tracing.py):
            # shedding is the overload signal the recorder exists for.
            tracing.record_event(
                "shed", lanes=lanes, queued=queued, cap=self.cap
            )
            raise IngressShedError(queued, self.cap)

    def release(self, lanes: int) -> None:
        if self.cap <= 0 and not self.track:
            return
        with self._mu:
            self._queued = max(self._queued - lanes, 0)


@dataclass
class ServiceConfig:
    """Library-user config (reference Config, config.go:66-104)."""

    store: Optional[MeshBucketStore] = None  # built from sizes when None
    cache_size: int = 50_000
    # Two-tier table: > 0 adds a device-resident back tier of this many
    # extra slots (total capacity = cache_size + back_cache_size; the
    # small front absorbs every kernel scatter, see MeshBucketStore).
    back_cache_size: int = 0
    # GLOBAL replica-table capacity (gslots).  None = auto-size to the
    # bucket-table capacity (clamped [4096, 65536]): the reference has
    # NO separate GLOBAL key cap — GLOBAL keys share its 50k cache
    # (global.go:83-91) — so a working set that fits the cache must fit
    # the replica table.  The sync collective scans every gslot each
    # pass (cost is linear in this capacity, ~us/gslot; see
    # benchmarks/RESULTS.md "GLOBAL capacity" row), and the auto-tuned
    # GlobalSyncWait stretches to keep that overhead ≤10%, so
    # convergence lag grows with the capacity you provision.
    global_cache_size: Optional[int] = None
    behaviors: BehaviorConfig = field(default_factory=BehaviorConfig)
    advertise_address: str = ""
    data_center: str = ""
    persist_store: object = None  # Store SPI
    loader: object = None  # Loader SPI
    # Durability plane (snapshot.py): path of the crash-safe columnar
    # device-state snapshot file ("" = disabled — the pre-durability
    # daemon, every restart a full reset).  Written on close()/SIGTERM
    # and every behaviors.snapshot_interval_s; restored at boot with
    # ONE monotone merge-commit.  Env: GUBER_SNAPSHOT.
    snapshot_path: str = ""
    clock: Clock = field(default_factory=lambda: DEFAULT_CLOCK)
    metrics: Optional[Metrics] = None
    devices: Optional[list] = None
    local_picker: Optional[ReplicatedConsistentHash] = None
    region_picker: Optional[RegionPicker] = None
    # ssl.SSLContext used by PeerClients on the HTTP fallback transport
    # (mTLS peer data plane, daemon.go:102-106 -> peer_client.go:87-132).
    peer_tls_context: object = None
    # grpc.ChannelCredentials for the gRPC peer transport (None => an
    # insecure channel, or — when peer_tls_context is set — the HTTP
    # fallback, which is the only transport able to skip verification).
    peer_channel_credentials: object = None
    # Deterministic chaos harness: a faults.FaultPlan handed to every
    # PeerClient this service creates (None = PeerClients honor the
    # process-wide faults.install() plan instead).
    fault_plan: object = None
    # Incident black box (blackbox.py): directory incident bundles are
    # written into ("" = rings only, no bundles).  Env:
    # GUBER_BLACKBOX_DIR.
    blackbox_dir: str = ""


class _ExpressPolicy:
    """The express-lane bypass rule, shared by both batchers
    (architecture.md "Express lane"): a submission of n lanes skips the
    coalescing window entirely when

      * the lane is enabled (GUBER_EXPRESS),
      * n <= GUBER_EXPRESS_MAX_LANES (the small interactive shapes the
        warm fused size-1/2/4 programs serve),
      * the batcher queue is SHALLOW — fewer than
        GUBER_EXPRESS_QUEUE_DEPTH lanes admitted and unflushed (a deep
        queue means the window is coalescing real backlog; bypassing it
        would add dispatches without helping anyone's latency), and
      * the dispatch pipeline is shallow (<= MAX_DEPTH unresolved
        batches — commits are FIFO, so an express dispatch behind a
        deep pipeline would wait out every older readback anyway).

    The bypass changes WHEN a dispatch launches, never what it
    computes: results are byte-identical to the windowed path.

    SAMPLED requests keep the windowed path (the callers gate on their
    trace context): the documented span taxonomy promises a
    batch.window span covering the coalescing wait, and the Python
    window owns span creation — the same rule that turns the native
    fast lane off under sampling (NativeIngressPump.active)."""

    #: Unresolved-pipeline ceiling for the bypass: past two in-flight
    #: batches the FIFO commit wait dominates whatever the window
    #: would have cost.
    MAX_DEPTH = 2

    __slots__ = ("enabled", "queue_depth", "max_lanes")

    def __init__(self, behaviors: BehaviorConfig):
        self.enabled = bool(getattr(behaviors, "express", False))
        self.queue_depth = int(
            getattr(behaviors, "express_queue_depth", 64)
        )
        self.max_lanes = int(getattr(behaviors, "express_max_lanes", 4))

    def window_cap_s(self, behaviors: BehaviorConfig) -> "Optional[float]":
        """The latency-mode ceiling on the coalescing window: half the
        GUBER_LATENCY_TARGET_MS budget (the other half pays for
        dispatch + readback).  None when the lane or the target is off
        — occupancy mode keeps the window."""
        target_ms = float(getattr(behaviors, "latency_target_ms", 0.0) or 0.0)
        if not self.enabled or target_ms <= 0:
            return None
        return target_ms / 2000.0

    def bypass_ok(self, n: int, gate: "_IngressGate", store) -> bool:
        if not self.enabled or n > self.max_lanes:
            return False
        if gate.queued + n > self.queue_depth:
            return False
        depth = getattr(store, "pipeline_depth", None)
        return depth is None or depth() <= self.MAX_DEPTH


class LocalBatcher:
    """Ingress batching window for owner-local evaluation.

    The reference's BATCHING coalesces only peer-FORWARDED requests
    (peer_client.go:272-312); locally-owned keys take the mutex+map
    path, which is cheap there.  Here every local evaluation is a
    device dispatch, so concurrent client requests inside one BatchWait
    window coalesce into ONE `store.apply` call — same knobs
    (batch_wait/batch_limit, config.go:107-109), same defeat-the-
    thundering-herd purpose, applied at the ingress edge.  Requests
    flagged NO_BATCHING bypass the window (proto/gubernator.proto:74-78
    semantics), and under the express lane (GUBER_EXPRESS) shallow-queue
    submissions bypass it too."""

    def __init__(self, store, behaviors: BehaviorConfig, clock: Clock,
                 metrics: Optional[Metrics] = None):
        self.store = store
        self.clock = clock
        # Bounded ingress (GUBER_INGRESS_QUEUE_LANES): a queue deeper
        # than the cap sheds new submissions with a 429-style error
        # instead of stretching every queued caller's latency.
        self._express = _ExpressPolicy(behaviors)
        self._gate = _IngressGate(
            getattr(behaviors, "ingress_queue_lanes", 0), metrics,
            track=self._express.enabled,
        )
        self._window = BatchWindow(
            self._flush, behaviors.batch_wait_s, behaviors.batch_limit,
            cap_s=self._express.window_cap_s(behaviors),
        )

    def submit(self, req: RateLimitRequest) -> "Future":
        fut: Future = Future()
        if self._window.stopped:
            fut.set_exception(PeerError(ERR_BATCHER_CLOSED))
            return fut
        if tracing.current() is None and self._express.bypass_ok(
            1, self._gate, self.store
        ):
            return self._submit_express(req, fut)
        try:
            self._gate.admit(1)
        except IngressShedError as e:
            fut.set_exception(e)
            return fut
        # Attribution stamp: the flush measures each submission's
        # coalescing-window wait from this instant (saturation.py).
        fut._submit_t = time.monotonic()
        # A submit racing past the stopped check is still safe: stop()
        # drains and flushes the queue after joining the worker.
        self._window.submit((req, fut))
        return fut

    def _submit_express(self, req: RateLimitRequest, fut: "Future") -> "Future":
        """Express bypass: evaluate NOW on the caller's thread — the
        same store.apply a one-element window flush would run, minus
        the window.  The caller blocks on fut.result() immediately
        after submit, so the inline evaluation moves the wait, it does
        not add one."""
        try:
            self._gate.admit(1)
        except IngressShedError as e:
            fut.set_exception(e)
            return fut
        t0 = time.monotonic()
        try:
            resp = self.store.apply([req], self.clock.now_ms())[0]
            if not fut.done():
                fut.set_result(resp)
        except Exception as e:  # noqa: BLE001
            if not fut.done():
                fut.set_exception(e)
        finally:
            self._gate.release(1)
        saturation.note_express("bypass", 1)
        saturation.observe_phase("express.submit", time.monotonic() - t0)
        return fut

    def _flush(self, batch) -> None:
        self._gate.release(len(batch))
        saturation.note_express("windowed", len(batch))
        t_flush = time.monotonic()
        for _, fut in batch:
            st = getattr(fut, "_submit_t", None)
            if st is not None:
                saturation.observe_phase("batch.window", t_flush - st)
                # Queue-residency pool (profiling.py): one lane waited
                # this long; tenants take proportional shares.
                profiling.note_queue_wait(1, t_flush - st)
        try:
            resps = self.store.apply(
                [r for r, _ in batch], self.clock.now_ms()
            )
            for (_, fut), resp in zip(batch, resps):
                if not fut.done():
                    fut.set_result(resp)
        except Exception as e:  # noqa: BLE001
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)

    def stop(self) -> None:
        self._window.stop()


@dataclass
class IngressColumns:
    """A GetRateLimits batch parsed straight into parallel columns —
    the zero-dataclass ingress representation (VERDICT: the reference's
    hot path is the whole service, gubernator.go:116-227, so the edge
    must feed the kernel without per-request object churn)."""

    names: List[str]
    unique_keys: List[str]
    algorithm: np.ndarray  # i32[n]
    behavior: np.ndarray  # i32[n]
    hits: np.ndarray  # i64[n]
    limit: np.ndarray  # i64[n]
    duration: np.ndarray  # i64[n]
    # Wire trace-context column of a forwarded peer batch (tracing.py):
    # (lane_lo, lane_hi, trace_id, span_id) ranges, or None.  Local
    # ingress leaves it None — the thread's ambient context covers it.
    trace_ctx: Optional[list] = None

    def __len__(self) -> int:
        return len(self.names)

    def request_at(self, i: int) -> RateLimitRequest:
        """Materialize one lane as a dataclass (slow-lane fallback)."""
        return RateLimitRequest(
            name=self.names[i],
            unique_key=self.unique_keys[i],
            hits=int(self.hits[i]),
            limit=int(self.limit[i]),
            duration=int(self.duration[i]),
            algorithm=int(self.algorithm[i]),
            behavior=int(self.behavior[i]),
        )


@dataclass
class ColumnarResult:
    """Column-form GetRateLimits responses: arrays for the fast lanes
    plus sparse per-lane overrides (validation errors, degraded /
    GLOBAL lanes that carry metadata or error strings).

    Forwarded fast lanes stay in the arrays: the owning peer's address
    rides the `owner_of`/`owner_addrs` annotation (an i32 index per
    lane into a per-batch address list) instead of a per-lane override,
    so the render edges can emit the reference's metadata.owner
    (gubernator.go:190,209) without materializing a dataclass per
    forwarded lane."""

    n: int
    status: np.ndarray
    limit: np.ndarray
    remaining: np.ndarray
    reset_time: np.ndarray
    overrides: Dict[int, RateLimitResponse] = field(default_factory=dict)
    owner_addrs: List[str] = field(default_factory=list)
    owner_of: Optional[np.ndarray] = None  # i32[n], -1 = local lane

    @classmethod
    def empty(cls, n: int) -> "ColumnarResult":
        z = np.zeros(n, dtype=np.int64)
        return cls(
            n=n, status=np.zeros(n, dtype=np.int32), limit=z,
            remaining=z.copy(), reset_time=z.copy(),
        )

    def set_owner(self, lanes, addr: str) -> None:
        """Annotate `lanes` (index array) as forwarded to `addr`."""
        if self.owner_of is None:
            self.owner_of = np.full(self.n, -1, dtype=np.int32)
        try:
            k = self.owner_addrs.index(addr)
        except ValueError:
            self.owner_addrs.append(addr)
            k = len(self.owner_addrs) - 1
        self.owner_of[lanes] = k

    def owner_at(self, i: int) -> Optional[str]:
        if self.owner_of is None or self.owner_of[i] < 0:
            return None
        return self.owner_addrs[self.owner_of[i]]

    def response_at(self, i: int) -> RateLimitResponse:
        ov = self.overrides.get(i)
        if ov is not None:
            return ov
        owner = self.owner_at(i)
        return RateLimitResponse(
            status=int(self.status[i]),
            limit=int(self.limit[i]),
            remaining=int(self.remaining[i]),
            reset_time=int(self.reset_time[i]),
            metadata={"owner": owner} if owner is not None else {},
        )

    def to_response(self) -> GetRateLimitsResponse:
        return GetRateLimitsResponse(
            responses=[self.response_at(i) for i in range(self.n)]
        )


@dataclass
class _ColumnsPlan:
    """Everything phase 1 (V1Service._submit_columns) left in flight:
    consumed either by the blocking _finalize_columns or by the
    callback-driven _ColumnsJoin — one submit path, two completion
    modes."""

    pendings: list  # [(batcher Future | (handle, lo, hi), fast_idx)]
    group_futs: Dict[str, "Future"]  # owner addr -> forward future
    remote_groups: Dict[str, list]  # owner addr -> [lane idx]
    slow_idx: list  # lanes for the dataclass router
    slow_fn: "Optional[Callable[[], list]]"  # blocking slow-lane resolver
    hash_keys: object  # List[str] | PackedKeys
    # Handoff double-dispatch peeks (elastic membership): one grouped
    # zero-hit read per PREVIOUS owner for lanes whose ownership moved,
    # merged monotonically after the primary legs resolve.  Entries are
    # ("remote", forward future, lanes) | ("local", (handle, lo, hi),
    # lanes); all best-effort.
    peeks: list = field(default_factory=list)
    # Tenant-ledger fold context (profiling.py): computed once at the
    # admission fold, reused by the outcome/shed folds at finalize.
    tenant_ctx: object = None


def _lane_response(out: dict, lo: int) -> RateLimitResponse:
    """One lane of a resolved columnar dispatch as a dataclass response
    (shared by the blocking _SingleLaneWait and the async fast path so
    the two cannot diverge on the packed-output schema)."""
    return RateLimitResponse(
        status=int(out["status"][lo]),
        limit=int(out["limit"][lo]),
        remaining=int(out["remaining"][lo]),
        reset_time=int(out["reset_time"][lo]),
    )


class _SingleLaneWait:
    """One single-key BATCHING request riding the columnar coalescer
    (V1Service._submit_single_local): .result() resolves the SHARED
    dispatch handle — concurrent waiters overlap their readbacks — and
    builds this lane's response from the packed output."""

    __slots__ = ("_fut",)

    def __init__(self, fut: "Future"):
        self._fut = fut

    def result(self) -> RateLimitResponse:
        handle, lo, _hi = self._fut.result()
        return _lane_response(handle.result(), lo)


def _attach_done(fut: "Future", fn) -> None:
    """add_done_callback that cannot re-raise into the attacher: on an
    ALREADY-resolved future the stdlib invokes fn inline and lets its
    exception propagate — here that exception can only have come from
    inside the consumer's delivery callback (delivery was already
    attempted), so re-raising would trigger a second delivery through
    the caller's error path."""
    try:
        fut.add_done_callback(fn)
    except Exception:  # noqa: BLE001
        logger.exception("async delivery callback failed")


def _deliver_future(callback, fut) -> None:
    """Bridge a concurrent Future to the callback(result, exc) shape,
    calling it exactly once (a raising callback must not re-enter)."""
    try:
        value, exc = fut.result(), None
    except Exception as e:  # noqa: BLE001
        value, exc = None, e
    callback(value, exc)


def _cols_to_requests(sub) -> List[RateLimitRequest]:
    """Materialize a forwarded column sub-batch as dataclasses — the
    FAILURE legs only (degraded local eval, per-item re-pick): the fast
    path never calls this."""
    names, uks, algo, beh, hits, limit, duration = sub
    return [
        RateLimitRequest(
            name=names[i],
            unique_key=uks[i],
            hits=int(hits[i]),
            limit=int(limit[i]),
            duration=int(duration[i]),
            algorithm=int(algo[i]),
            behavior=int(beh[i]),
        )
        for i in range(len(names))
    ]


def _merge_group_result(result, idxs, addr, resps) -> None:
    """Merge one owner-group forward outcome into `result` — the
    shared body of the blocking _finalize_columns and the async
    _ColumnsJoin.  ("cols", rc, lo, hi) scatters the decoded response
    arrays (zero-dataclass); a list is the fallback legs' per-lane
    dataclasses; an Exception converts per lane."""
    if isinstance(resps, Exception):
        for i in idxs:
            result.overrides[int(i)] = RateLimitResponse(
                error=f"while fetching rate limit from peer - '{resps}'"
            )
        return
    if isinstance(resps, tuple):
        _tag, rc, lo, hi = resps
        idx = np.asarray(idxs, dtype=np.int64)
        sl = slice(lo, hi)
        result.status[idx] = rc.status[sl]
        result.limit[idx] = rc.limit[sl]
        result.remaining[idx] = rc.remaining[sl]
        result.reset_time[idx] = rc.reset_time[sl]
        result.set_owner(idx, addr)
        for lane, r in rc.overrides.items():
            if lo <= lane < hi:
                r.metadata.setdefault("owner", addr)
                result.overrides[int(idxs[lane - lo])] = r
        return
    for i, r in zip(idxs, resps):
        result.overrides[int(i)] = r


def _merge_peek_result(result, lanes, payload) -> None:
    """Monotone-merge one resolved zero-hit peek group (the handoff
    double-dispatch, architecture.md "Membership & resharding") into
    the result arrays: status = max (OVER_LIMIT wins), remaining = min,
    reset_time = max — never more permissive than either side, so bulk
    columnar reads cannot observe a reset bucket mid-transfer.  Lanes
    that resolved as overrides (errors, fallback legs) and peek lanes
    that themselves errored are left untouched; payload None (a failed
    peek — the old owner dying is exactly when this runs) leaves every
    primary answer standing."""
    if payload is None:
        return
    kind, data = payload
    m = len(lanes)
    keep = np.fromiter(
        (int(i) not in result.overrides for i in lanes), bool, count=m
    )
    if kind == "remote":
        rc, lo, hi = data
        if rc.overrides:
            keep &= np.fromiter(
                ((lo + j) not in rc.overrides for j in range(m)),
                bool, count=m,
            )
        st = np.asarray(rc.status[lo:hi])
        rem = np.asarray(rc.remaining[lo:hi])
        rst = np.asarray(rc.reset_time[lo:hi])
        lim = np.asarray(rc.limit[lo:hi])
    else:
        out, sl = data
        st = np.asarray(out["status"][sl])
        rem = np.asarray(out["remaining"][sl])
        rst = np.asarray(out["reset_time"][sl])
        lim = np.asarray(out["limit"][sl])
    # Consumption evidence only: a REMOTE peek cannot be residency-
    # filtered at the sender, so a key already forgotten at the old
    # owner answers as a fresh bucket (remaining == limit, UNDER) —
    # merging that would only inflate reset_time.  An untouched
    # genuine bucket is skipped identically (nothing to carry).
    keep &= (rem < lim) | (st > 0)
    if not keep.any():
        return
    idx = np.asarray(lanes, dtype=np.int64)[keep]
    result.status[idx] = np.maximum(result.status[idx], st[keep])
    result.remaining[idx] = np.minimum(result.remaining[idx], rem[keep])
    result.reset_time[idx] = np.maximum(result.reset_time[idx], rst[keep])


def _merge_fast_result(result, hash_keys, fast_idx, out, sl, exc) -> None:
    """Scatter one resolved fast dispatch into `result` (or convert a
    dispatch failure to per-lane errors) — the shared merge body of the
    blocking _resolve_fast and the async _ColumnsJoin."""
    if exc is not None:
        for i in fast_idx:
            result.overrides[int(i)] = RateLimitResponse(
                error=f"while applying rate limit '{hash_keys[int(i)]}' - '{exc}'"
            )
        return
    if fast_idx.size == result.n:
        result.status = np.asarray(out["status"][sl], dtype=np.int32)
        result.limit = np.asarray(out["limit"][sl], dtype=np.int64)
        result.remaining = np.asarray(out["remaining"][sl], dtype=np.int64)
        result.reset_time = np.asarray(out["reset_time"][sl], dtype=np.int64)
    else:
        result.status[fast_idx] = out["status"][sl]
        result.limit[fast_idx] = out["limit"][sl]
        result.remaining[fast_idx] = out["remaining"][sl]
        result.reset_time[fast_idx] = out["reset_time"][sl]


class _HandleDrainer:
    """Resolves columnar dispatch handles OFF the request thread: a
    pool blocks on handle.result() (the device readback) and fires
    callbacks.  The pool size bounds concurrently-overlapping
    readbacks — it tracks the ACTUAL dispatch depth, not the in-flight
    request count, which is the point: the sync path parks one caller
    thread per request for the whole device round; this parks one
    thread per DISPATCH, so a 100-way storm coalescing into a handful
    of windows costs a handful of blocked threads.

    Sizing is demand-driven (a register() that finds no idle worker
    spawns one, up to MAX_THREADS): steady single-window traffic runs
    on MIN_THREADS, while a deep pipeline — many unresolved dispatches,
    e.g. NO_BATCHING storms or a device stall backing up handles —
    grows the pool to match instead of queueing callbacks behind a
    fixed-width pool (the round-5 fixed 8 threads were simultaneously
    too many idle for the common case and too few for a stall)."""

    MIN_THREADS = 2
    MAX_THREADS = 32

    def __init__(self):
        self._q: "deque" = deque()
        self._cv = threading.Condition()
        self._stopped = False
        self._threads: list = []
        self._idle = 0

    def start(self) -> None:
        with self._cv:
            for _ in range(self.MIN_THREADS):
                self._spawn()

    def _spawn(self) -> None:
        # _cv held.
        t = threading.Thread(
            target=self._run, daemon=True,
            name=f"columns-drain-{len(self._threads)}",
        )
        t.start()
        self._threads.append(t)

    def register(self, handle, cb) -> None:
        """cb(value, exc) fires exactly once from a drainer thread (or
        inline with a shutdown error when the drainer has stopped)."""
        # Backlog hint: ask for the handle's device->host copy NOW so a
        # deep pipeline's transfers overlap even while every worker is
        # parked on an older readback (the launch stage already
        # requested one; this covers handles that were registered after
        # their launch's request went stale).
        pf = getattr(handle, "prefetch", None)
        if pf is not None:
            try:
                pf()
            except Exception:  # noqa: BLE001 — a hint must never fail the path
                pass
        with self._cv:
            if not self._stopped:
                self._q.append((handle, cb))
                # Backlog deeper than the idle workers that will drain
                # it => the dispatch depth outgrew the pool; add one
                # thread per register until they match (bounded).
                if (
                    len(self._q) > self._idle
                    and len(self._threads) < self.MAX_THREADS
                ):
                    self._spawn()
                self._cv.notify()
                return
        cb(None, PeerError(ERR_BATCHER_CLOSED))

    def _run(self) -> None:
        while True:
            with self._cv:
                self._idle += 1
                while not self._q and not self._stopped:
                    self._cv.wait()
                self._idle -= 1
                if not self._q:
                    return  # stopped and drained
                handle, cb = self._q.popleft()
            value, exc = None, None
            try:
                value = handle.result()
            except Exception as e:  # noqa: BLE001
                exc = e
            try:
                cb(value, exc)
            except Exception:  # noqa: BLE001 — a callback must not kill the pool
                logger.exception("columns drainer callback failed")

    def stop(self, timeout_s: float = 30.0) -> None:
        """Resolve everything already registered (workers drain the
        queue before exiting), then join.  Late register() calls fail
        fast with the batcher-closed error."""
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
            threads = list(self._threads)
        deadline = time.monotonic() + timeout_s
        for t in threads:
            t.join(timeout=max(deadline - time.monotonic(), 0.1))


class _ColumnsJoin:
    """Completion join for one async columnar request: counts down the
    plan's sub-completions (fast dispatch handles via the drainer,
    owner-group forwards, the slow-lane route) and fires the callback
    exactly once from whichever completion thread finishes last.  The
    merge logic is the same _merge_fast_result / override-merge the
    blocking _finalize_columns uses."""

    def __init__(self, svc, plan, result, callback):
        self.svc = svc
        self.plan = plan
        self.result = result
        self.callback = callback
        self._lock = threading.Lock()
        self._remaining = 0
        self._failure: "Optional[Exception]" = None
        self._fast_outs: list = []  # (fast_idx, out, slice, exc)
        self._group_res: dict = {}  # addr -> resps | Exception
        self._slow_resps: "Optional[list]" = None
        self._peek_res: list = []  # (lanes, payload | None)

    def start(self) -> None:
        svc, plan = self.svc, self.plan
        parts = (
            len(plan.pendings)
            + len(plan.group_futs)
            + (1 if plan.slow_idx else 0)
            + len(plan.peeks)
        )
        if parts == 0:
            self._finish()
            return
        self._remaining = parts
        drainer = svc._get_drainer()
        if plan.slow_idx:
            # slow_fn runs _route / store.apply, which block on (and for
            # _route, submit to) _forward_pool — the slow pool keeps the
            # outer task off the pool its inner tasks need.
            _attach_done(
                svc._slow_pool.submit(plan.slow_fn), self._on_slow
            )
        for addr, fut in plan.group_futs.items():
            _attach_done(fut, partial(self._on_group, addr))
        for pending, fast_idx in plan.pendings:
            if isinstance(pending, Future):
                _attach_done(
                    pending, partial(self._on_dispatched, fast_idx, drainer)
                )
            else:
                handle, lo, hi = pending
                drainer.register(
                    handle, partial(self._on_out, fast_idx, slice(lo, hi))
                )
        for kind, payload, lanes in plan.peeks:
            # Handoff peeks: window flushes resolve every forward
            # future (result or exception) and the drainer resolves
            # every handle, so the countdown can never hang on one.
            if kind == "remote":
                _attach_done(payload, partial(self._on_peek_remote, lanes))
            else:
                handle, lo, hi = payload
                drainer.register(
                    handle,
                    partial(self._on_peek_local, lanes, slice(lo, hi)),
                )

    # -- sub-completion handlers (any thread) --------------------------
    def _on_dispatched(self, fast_idx, drainer, fut) -> None:
        try:
            handle, lo, hi = fut.result()
        except Exception as e:  # noqa: BLE001
            self._on_out(fast_idx, None, None, e)
            return
        drainer.register(handle, partial(self._on_out, fast_idx, slice(lo, hi)))

    def _on_out(self, fast_idx, sl, out, exc) -> None:
        with self._lock:
            self._fast_outs.append((fast_idx, out, sl, exc))
        self._countdown()

    def _on_group(self, addr, fut) -> None:
        try:
            resps = fut.result()
        except Exception as e:  # noqa: BLE001 — _forward_group_columns
            resps = e  # converts internally; this is pool-failure defensive
        with self._lock:
            self._group_res[addr] = resps
        self._countdown()

    def _on_slow(self, fut) -> None:
        try:
            self._slow_resps = fut.result()
        except Exception as e:  # noqa: BLE001
            # The sync path propagates a slow-route failure to the
            # caller (a 500 at the edge); same contract here.
            with self._lock:
                self._failure = e
        self._countdown()

    def _on_peek_remote(self, lanes, fut) -> None:
        try:
            rc, lo, hi = fut.result()
            payload = ("remote", (rc, lo, hi))
        except Exception:  # noqa: BLE001 — peek is best-effort
            payload = None
        with self._lock:
            self._peek_res.append((lanes, payload))
        self._countdown()

    def _on_peek_local(self, lanes, sl, out, exc) -> None:
        payload = None if exc is not None else ("local", (out, sl))
        with self._lock:
            self._peek_res.append((lanes, payload))
        self._countdown()

    def _countdown(self) -> None:
        with self._lock:
            self._remaining -= 1
            if self._remaining > 0:
                return
        self._finish()

    def _finish(self) -> None:
        result, err = self.result, self._failure
        if err is None:
            try:
                plan = self.plan
                if self._slow_resps is not None:
                    for i, r in zip(plan.slow_idx, self._slow_resps):
                        result.overrides[int(i)] = r
                for addr, resps in self._group_res.items():
                    _merge_group_result(
                        result, plan.remote_groups[addr], addr, resps
                    )
                for fast_idx, out, sl, exc in self._fast_outs:
                    if isinstance(exc, IngressShedError):
                        # Tenant shed attribution, async twin of
                        # _resolve_fast's.
                        self.svc.tenants.fold_shed(plan.tenant_ctx, fast_idx)
                    _merge_fast_result(
                        result, plan.hash_keys, fast_idx, out, sl, exc
                    )
                for lanes, payload in self._peek_res:
                    _merge_peek_result(result, lanes, payload)
                self.svc.tenants.fold_outcome(plan.tenant_ctx, result)
            except Exception as e:  # noqa: BLE001
                result, err = None, e
        self.callback(result if err is None else None, err)


class ColumnarBatcher:
    """Ingress coalescer for COLUMN-form batches: concurrent multi-item
    requests inside one BatchWait window (config.go:107-109 semantics)
    merge into ONE device dispatch; each caller gets back a slice of
    the shared handle.  The flush thread only dispatches — waiters
    resolve the handle themselves, so readbacks overlap across callers
    (ColumnarPipeline).  NO_BATCHING batches bypass the window."""

    # Lane budget per flush: the device batch ceiling.  Lane-weighted
    # (a coalesced columnar peer RPC submits up to
    # PEER_COLUMNS_MAX_LANES in ONE submission), equal to the previous
    # 64-submissions x 1000-lane-cap bound.
    MAX_LANES = 64_000
    # Overload backstop, NOT a pacing gate: the flush worker only blocks
    # when this many of ITS OWN dispatches are unresolved.  Round-5
    # probes showed a tight gate (depth 2) is actively harmful on a
    # high-latency device — flushes queue behind multi-100ms rounds and
    # forwarded peers blow their 5s RPC deadline — while the 500us
    # window already coalesces a 100-way storm into ~14 dispatches.  At
    # depth 8 the gate never fires in steady state; it only stops a
    # pathological pileup (arrival rate >> device rate for seconds).
    MAX_INFLIGHT = 8

    def __init__(self, store, behaviors: BehaviorConfig, clock: Clock,
                 metrics: Optional[Metrics] = None):
        self.store = store
        self.clock = clock
        # Bounded ingress, lane-weighted (GUBER_INGRESS_QUEUE_LANES).
        self._express = _ExpressPolicy(behaviors)
        self._gate = _IngressGate(
            getattr(behaviors, "ingress_queue_lanes", 0), metrics,
            track=self._express.enabled,
        )
        self._own_inflight: "deque" = deque()
        # _flush can run concurrently in edge cases (worker stuck past
        # stop()'s join timeout while the stop/post-stop-submit drain
        # flushes from another thread) — the backstop deque needs a lock.
        self._inflight_lock = threading.Lock()
        self._window = BatchWindow(
            self._flush, behaviors.batch_wait_s, self.MAX_LANES,
            weigh=lambda item: len(item[0][0]),
            cap_s=self._express.window_cap_s(behaviors),
        )

    def submit(self, keys, algo, behavior, hits, limit, duration,
               greg_expire, greg_duration, trace_links=None) -> "Future":
        fut: Future = Future()
        if self._window.stopped:
            fut.set_exception(PeerError(ERR_BATCHER_CLOSED))
            return fut
        n = len(keys)
        if not trace_links and self._express.bypass_ok(
            n, self._gate, self.store
        ):
            return self._submit_express(
                keys, algo, behavior, hits, limit, duration,
                greg_expire, greg_duration, fut,
            )
        try:
            self._gate.admit(n)
        except IngressShedError as e:
            fut.set_exception(e)
            return fut
        # Attribution stamp (always-on): the flush measures this
        # submission's coalescing-window wait (saturation.py).
        fut._submit_t = time.monotonic()
        if trace_links:
            # Per-lane span handles (tracing.py): the flush joins every
            # submission's links into the batch.window span and the
            # dispatch pipeline's stage spans.
            fut._trace_links = trace_links
            fut._trace_t = time.monotonic_ns()
        ge = np.zeros(n, np.int64) if greg_expire is None else greg_expire
        gd = np.zeros(n, np.int64) if greg_duration is None else greg_duration
        self._window.submit(
            ((keys, algo, behavior, hits, limit, duration, ge, gd), fut)
        )
        return fut

    def _submit_express(self, keys, algo, behavior, hits, limit, duration,
                        greg_expire, greg_duration,
                        fut: "Future") -> "Future":
        """Express bypass: dispatch NOW (no coalescing window) on the
        caller's thread — the pipelined apply the flush would have run
        for a one-submission window, launched on the warm solo/fused
        small-batch programs (or the host scalar slot for a capable
        singleton).  The future resolves immediately with the handle
        slice; the caller's readback overlaps like any other waiter's.
        Only unsampled submissions arrive here (submit gates on
        trace_links), so no span bookkeeping is owed."""
        n = len(keys)
        try:
            self._gate.admit(n)
        except IngressShedError as e:
            fut.set_exception(e)
            return fut
        t0 = time.monotonic()
        try:
            ge = np.zeros(n, np.int64) if greg_expire is None else greg_expire
            gd = (
                np.zeros(n, np.int64) if greg_duration is None
                else greg_duration
            )
            handle = self.store.apply_columns_async(
                keys, algo, behavior, hits, limit, duration,
                self.clock.now_ms(), ge, gd,
            )
            if not fut.done():
                fut.set_result((handle, 0, n))
        except Exception as e:  # noqa: BLE001
            if not fut.done():
                fut.set_exception(e)
        finally:
            self._gate.release(n)
        saturation.note_express("bypass", n)
        saturation.observe_phase("express.submit", time.monotonic() - t0)
        return fut

    def _flush(self, batch) -> None:
        lanes = sum(len(item[0][0]) for item in batch)
        self._gate.release(lanes)
        saturation.note_express("windowed", lanes)
        # Saturation plane: per-submission window-wait attribution and
        # the dispatcher's busy fraction (flush wall time over elapsed).
        t_flush = time.monotonic()
        for item, fut in batch:
            st = getattr(fut, "_submit_t", None)
            if st is not None:
                saturation.observe_phase("batch.window", t_flush - st)
                # Queue-residency pool (profiling.py): this
                # submission's lanes waited out the window; tenants
                # take proportional shares of the pool.
                profiling.note_queue_wait(len(item[0]), t_flush - st)
        # The window admits the submission that CROSSES the lane limit
        # (it cannot un-take from the queue), so one flush can overshoot
        # MAX_LANES by up to a submission; re-chunk so no single device
        # dispatch exceeds the ceiling (an oversized dispatch would pad
        # to a brand-new XLA bucket and compile mid-traffic).
        chunk, lanes = [], 0
        for item in batch:
            n = len(item[0][0])
            if chunk and lanes + n > self.MAX_LANES:
                self._flush_chunk(chunk)
                chunk, lanes = [], 0
            chunk.append(item)
            lanes += n
        if chunk:
            self._flush_chunk(chunk)
        saturation.dispatcher_busy.add(time.monotonic() - t_flush)

    def _flush_chunk(self, batch) -> None:
        t_chunk = time.monotonic()
        try:
            # Overload backstop (see MAX_INFLIGHT): block on the oldest
            # unresolved dispatch only when the pipeline is pathologically
            # deep.  Submissions queue behind the wait, so the next flush
            # merges them.  (Waiters resolve handles concurrently; `done`
            # flips as they do, and result() is idempotent/thread-safe.)
            oldest = None
            with self._inflight_lock:
                while self._own_inflight and self._own_inflight[0].done:
                    self._own_inflight.popleft()
                if len(self._own_inflight) >= self.MAX_INFLIGHT:
                    oldest = self._own_inflight.popleft()
            if oldest is not None:
                oldest.result()
            if len(batch) == 1:
                (cols, fut) = batch[0]
                keys = cols[0]
                arrays = cols[1:]
            else:
                from .native import PackedKeys

                if all(isinstance(c[0], PackedKeys) for c, _ in batch):
                    # Packed-keys coalesce: concat buffers, never decode
                    # per-lane strings.
                    keys = PackedKeys.concat([c[0] for c, _ in batch])
                else:
                    keys = []
                    for (c, _) in batch:
                        keys.extend(c[0])
                arrays = tuple(
                    np.concatenate([c[i] for c, _ in batch])
                    for i in range(1, 8)
                )
            algo, beh, hits, limit, duration, ge, gd = arrays
            # queue.wait: flush start -> dispatch submit — the backstop
            # wait on a pathologically deep pipeline plus the concat
            # (near-zero in steady state; the phase that grows when the
            # device falls behind the arrival rate).
            saturation.observe_phase(
                "queue.wait", time.monotonic() - t_chunk
            )
            bt = self._batch_trace(batch)
            if bt is not None:
                tracing.stage_batch_trace(bt)
            try:
                handle = self.store.apply_columns_async(
                    keys, algo, beh, hits, limit, duration,
                    self.clock.now_ms(), ge, gd,
                )
            finally:
                # A store that raised before consuming the staged trace
                # must not leak it into this thread's next dispatch.
                tracing.take_batch_trace()
            with self._inflight_lock:
                self._own_inflight.append(handle)
                # Reap resolved heads now, not just at the next flush:
                # after a burst goes idle, lingering done handles would
                # pin their result arrays until traffic resumes.
                while self._own_inflight and self._own_inflight[0].done:
                    self._own_inflight.popleft()
            lo = 0
            for (c, fut) in batch:
                hi = lo + len(c[0])
                if not fut.done():
                    fut.set_result((handle, lo, hi))
                lo = hi
        except Exception as e:  # noqa: BLE001
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)

    def _batch_trace(self, batch):
        """Join the chunk's sampled submissions into one BatchTrace and
        record its batch.window span (start = the earliest member's
        submit time: the span COVERS the coalescing wait, which is one
        of the four places a slow request loses time).  None when no
        member was sampled — the common fast path."""
        if not tracing.enabled():
            return None
        links, seen, t0 = [], set(), None
        for _, fut in batch:
            for ctx in getattr(fut, "_trace_links", ()):
                if (ctx.trace_id, ctx.span_id) not in seen:
                    seen.add((ctx.trace_id, ctx.span_id))
                    links.append(ctx)
            ts = getattr(fut, "_trace_t", None)
            if ts is not None and (t0 is None or ts < t0):
                t0 = ts
        bt = tracing.new_batch(links)
        if bt is not None:
            now = time.monotonic_ns()
            tracing.record_span(
                "batch.window", bt.ctx,
                start_ns=t0 if t0 is not None else now, end_ns=now,
                links=bt.links,
                lanes=sum(len(item[0][0]) for item in batch),
                submissions=len(batch),
            )
        return bt

    def stop(self) -> None:
        self._window.stop()
        with self._inflight_lock:
            self._own_inflight.clear()  # drop pinned result arrays


class V1Service:
    def __init__(self, conf: ServiceConfig):
        self.conf = conf
        self.clock = conf.clock
        self.metrics = conf.metrics or Metrics()
        self.store = conf.store or MeshBucketStore(
            capacity_per_shard=max(conf.cache_size // _n_local_devices(conf.devices), 1),
            g_capacity=(
                conf.global_cache_size
                if conf.global_cache_size is not None
                else min(max(4096, conf.cache_size), 65536)
            ),
            devices=conf.devices,
            store=conf.persist_store,
            # Ceil division: any nonzero back_cache_size must enable the
            # back tier (flooring to 0 on small-config/many-device hosts
            # silently disabled two-tier with no signal).
            back_capacity_per_shard=-(
                -conf.back_cache_size // _n_local_devices(conf.devices)
            )
            if conf.back_cache_size > 0
            else 0,
        )
        # gubernator_build_info: version/backend/mesh labels, set once —
        # the store's topology is fixed for the service's lifetime.
        self.metrics.set_build_info(self.store)
        self.local_picker = conf.local_picker or ReplicatedConsistentHash()
        self.region_picker = conf.region_picker or RegionPicker()
        self._peer_mutex = threading.RLock()
        # Elastic membership (reshard.py): the ring's generation counter
        # and membership fingerprint (the transfer epoch fence), the
        # previous ring retained for the double-dispatch read window,
        # and the manager running drains/transfers + dropped-peer
        # shutdowns on one bounded pool.  All ring fields are guarded by
        # _peer_mutex.
        self.ring_generation = 0
        self.ring_hash = 0
        self._prev_picker: "Optional[ReplicatedConsistentHash]" = None
        self._handoff_deadline = 0.0  # monotonic; 0 = no window
        self.reshard = ReshardManager(self)
        self._health = HealthCheckResponse(status=HEALTHY)
        # Per-service flight recorder (the PR 9 shared-ring fix):
        # co-resident daemons each get their own span/event rings, so
        # soak-cluster incidents are attributable.  Threads this service
        # owns bind it (pool initializers below, auditor/pump threads);
        # bare-store users who never bind still land on tracing's
        # process default — module-level behavior is unchanged.
        self.recorder = tracing.Recorder(
            name=conf.advertise_address or f"service-{id(self):x}"
        )
        # Incident black box (blackbox.py): the per-wire traffic rings
        # + triggered bundle writer.  Hooked into BOTH this service's
        # recorder and the process-default recorder: events recorded by
        # unbound threads (library embedders, module-level fallbacks)
        # still trigger bundles.
        self.blackbox = blackbox_mod.BlackBox(
            self,
            path=getattr(conf, "blackbox_dir", "") or "",
            budget_mb=getattr(conf.behaviors, "blackbox_mb", 64),
            retain=getattr(conf.behaviors, "blackbox_retain", 8),
            enabled=getattr(conf.behaviors, "blackbox", True),
        )
        self.recorder.dump_hooks.append(self.blackbox.on_trigger)
        tracing.default_recorder().dump_hooks.append(
            self.blackbox.on_trigger
        )
        self._forward_pool = ThreadPoolExecutor(
            max_workers=64,
            initializer=tracing.bind_recorder, initargs=(self.recorder,),
        )
        # Async slow-lane / dataclass-fallback work runs on its OWN pool:
        # those tasks run _route, which submits leaf forwards to
        # _forward_pool and BLOCKS — putting them on _forward_pool too
        # would let 64 outer tasks fill the pool and deadlock waiting on
        # inner tasks queued behind them (round-5 review finding).  Leaf
        # tasks never submit further work, so outer-on-_slow_pool /
        # inner-on-_forward_pool cannot cycle.
        # 128, not 64: async single-lane requests (native edge n==1
        # fallback) park one slow-pool thread each for a window+RTT, so
        # the pool size caps single-key fan-in exactly like the gRPC
        # handler pool — keep the two caps equal (both cover the
        # reference's 100-way bench shape).
        self._slow_pool = ThreadPoolExecutor(
            max_workers=128, thread_name_prefix="columns-slow",
            initializer=tracing.bind_recorder, initargs=(self.recorder,),
        )
        self._drainer: "Optional[_HandleDrainer]" = None
        self._drainer_lock = threading.Lock()
        # Jittered-backoff envelope shared by the forward re-pick loop
        # and the host-tier send loops (one instance: full jitter means
        # no cross-thread correlation to worry about).
        self._retry_backoff = Backoff(
            base_s=conf.behaviors.retry_backoff_base_s,
            max_s=conf.behaviors.retry_backoff_max_s,
        )
        self._closed = False
        # Native service loop attachments (gateway.NativeIngressPump /
        # NativeGatewayServer register themselves; the /metrics scrape
        # and set_peers consult these).
        self.native_ingress = None
        self.native_edges: list = []

        if conf.loader is not None:
            # Loader SPI over the columnar path (store.go:49-58 call
            # pattern, one device commit instead of one row scatter per
            # item): the whole load() stream merges in a single
            # gather+scatter program via the reshard monotone merge.
            # Stores without the columnar commit keep the legacy
            # one-placement-per-item path.
            items = list(conf.loader.load())
            if items and hasattr(self.store, "commit_transfer"):
                self.store.commit_transfer(
                    snapshot_mod.items_to_columns(items),
                    self.clock.now_ms(),
                )
            else:
                for item in items:
                    self.store.load_item(item)
        # Durability plane (snapshot.py): restore the last crash-safe
        # device-state snapshot (one H2D merge-commit; corrupt files
        # reject loudly to a cold start), then run the background save
        # cadence.  Restore happens BEFORE the batchers/gateway serve
        # traffic; the monotone merge makes even a late restore safe
        # (it can never un-spend hits already admitted).
        self.snapshots = snapshot_mod.SnapshotManager(
            self,
            path=getattr(conf, "snapshot_path", "") or "",
            interval_s=getattr(conf.behaviors, "snapshot_interval_s", 0.0),
        )
        self.snapshots.restore()
        self.snapshots.start()

        self.local_batcher = LocalBatcher(
            self.store, conf.behaviors, self.clock, metrics=self.metrics
        )
        self.columnar_batcher = ColumnarBatcher(
            self.store, conf.behaviors, self.clock, metrics=self.metrics
        )
        # Express lane (architecture.md "Express lane"): the host-side
        # scalar singleton slot is a SERVICE policy — bare stores keep
        # it off so their dispatch counting is unchanged; the store
        # probes its own capability (CPU backend, writable buffers)
        # lazily on the first eligible singleton.
        if (
            getattr(conf.behaviors, "express", False)
            and getattr(conf.behaviors, "express_scalar", False)
            and hasattr(self.store, "scalar_fast_path")
        ):
            self.store.scalar_fast_path = True
            self.store.scalar_max_lanes = int(
                getattr(conf.behaviors, "express_max_lanes", 4)
            )
        # Saturation & SLO plane (saturation.py): the latency-SLO burn
        # engine (GUBER_LATENCY_TARGET_MS; disabled at 0) judges every
        # ingress RPC via metrics.observe_latency, and the hot-key
        # sketch rides the ring's owner-code hashes (zero extra
        # hashing) for GET /debug/hotkeys.
        self.slo = saturation.SloEngine(
            getattr(conf.behaviors, "latency_target_ms", 0.0),
            getattr(conf.behaviors, "slo_objective", 0.99),
        )
        self.metrics.slo = self.slo
        self.hotkeys = saturation.HotKeySketch()
        # Cost observatory (profiling.py): the per-tenant cost ledger
        # (cardinality-bounded by GUBER_TENANT_TOPK; every audit
        # ingress note has a fold beside it).  The ledger must exist
        # BEFORE any router runs; the host SAMPLER is process-wide and
        # applied by the daemon (library embedders call
        # profiling.set_enabled themselves, the tracing rule).
        self.tenants = profiling.TenantLedger(
            topk=getattr(conf.behaviors, "tenant_topk", 16)
        )
        # Always-on conservation audit (audit.py): the chaos-suite
        # exactly-once oracles as a live windowed self-check.  The
        # auditor arms its ledger baseline here — post-construction
        # traffic (including startup warmup) reconciles cleanly because
        # every invariant is a one-sided inequality.
        self.auditor = audit_mod.Auditor(
            metrics=self.metrics,
            interval_s=getattr(conf.behaviors, "audit_interval_s", 5.0),
            enabled=getattr(conf.behaviors, "audit", True),
            recorder=self.recorder,
        )
        self.auditor.start()
        self._started_monotonic = time.monotonic()
        self.global_mgr = GlobalManager(self)
        self.multi_region_mgr = FederationManager(self)

    # ------------------------------------------------------------------
    @property
    def advertise_address(self) -> str:
        return self.conf.advertise_address

    @property
    def serves_peer_columns(self) -> bool:
        """Whether this daemon ADVERTISES the columnar peer encodings —
        the single rule both transport edges consult (gRPC method
        registration, gateway frame sniff), so mixed-version
        negotiation can never diverge per transport.  False under the
        GUBER_PEER_COLUMNS opt-out (the pre-columns interop mode) and
        for stores without columnar support: those fall back to the
        dataclass path capped at MAX_BATCH_SIZE, which would
        hard-reject the PEER_COLUMNS_MAX_LANES-sized batches the
        columns advertisement invites."""
        return getattr(self.conf.behaviors, "peer_columns", True) and getattr(
            self.store, "supports_columns", False
        )

    @property
    def serves_ingress_columns(self) -> bool:
        """Whether this daemon ADVERTISES the public columnar ingress
        encodings (the front door) — the single rule both transport
        edges consult (gRPC V1/GetRateLimitsColumns registration, the
        gateway's frame sniff on /v1/GetRateLimits), so client
        negotiation can never diverge per transport.  False under the
        GUBER_INGRESS_COLUMNS opt-out (the pre-columns interop mode:
        frames fall into json.loads and answer 400, exactly what a
        pre-PR build does) and for stores without columnar support —
        those route every lane through the dataclass path capped at
        MAX_BATCH_SIZE, which would hard-reject the
        INGRESS_COLUMNS_MAX_LANES-sized batches the advertisement
        invites."""
        return getattr(self.conf.behaviors, "ingress_columns", True) and getattr(
            self.store, "supports_columns", False
        )

    @property
    def serves_global_columns(self) -> bool:
        """Whether this daemon SPEAKS the columnar GLOBAL replication
        plane — the single rule both transport edges consult (gRPC
        method registration, gateway frame sniff) AND the receive-side
        batching switch.  False under the GUBER_GLOBAL_COLUMNS opt-out
        (the pre-columns interop mode: classic wire bytes, one replica
        commit dispatch per item) and for stores without the batched
        replica commit."""
        return getattr(self.conf.behaviors, "global_columns", True) and hasattr(
            self.store, "set_replica_batch"
        )

    @property
    def serves_reshard(self) -> bool:
        """Whether this daemon SPEAKS the ownership-transfer plane —
        the single rule both transport edges consult (gRPC method
        registration, gateway path gate) AND the sender-side switch
        (set_peers only schedules a handoff when it holds).  False
        under the GUBER_RESHARD opt-out (the pre-reshard interop mode:
        a ring change is metadata-only and moved buckets reset, exactly
        the legacy behavior) and for stores without the columnar
        drain/commit pair."""
        return getattr(self.conf.behaviors, "reshard", True) and hasattr(
            self.store, "commit_transfer"
        )

    @property
    def serves_region_columns(self) -> bool:
        """Whether this daemon SPEAKS the columnar inter-region wire —
        the single rule both transport edges consult (gRPC
        UpdateRegionColumns registration, gateway path gate), so
        mixed-version negotiation can never diverge per transport.
        False under the GUBER_REGION_COLUMNS opt-out (the
        pre-federation interop mode: senders see UNIMPLEMENTED / 404 —
        exactly what a pre-federation daemon answers — and fall back
        sticky to the classic per-item GetPeerRateLimits encoding,
        which this daemon serves like any peer receive) and for stores
        without columnar support."""
        return getattr(self.conf.behaviors, "region_columns", True) and getattr(
            self.store, "supports_columns", False
        )

    def get_peer(self, key: str) -> PeerClient:
        """Owner peer for a key (gubernator.go:440-449)."""
        with self._peer_mutex:
            if self.local_picker.size() == 0:
                raise PeerError("unable to pick a peer; pool is empty")
            owner_id = self.local_picker.get(key)
            return self.local_picker.get_by_peer_id(owner_id)

    def get_peer_list(self) -> List[PeerClient]:
        with self._peer_mutex:
            return list(self.local_picker.peers())

    def get_region_picker(self) -> RegionPicker:
        return self.region_picker

    # ------------------------------------------------------------------
    def get_rate_limits(self, req: GetRateLimitsRequest) -> GetRateLimitsResponse:
        """gubernator.go:116-227.  Per-RPC stats live at the transport
        edges (grpc_server.MetricsInterceptor / the gateway handlers),
        like the reference's stats handler (grpc_stats.go:95-118)."""
        if len(req.requests) > MAX_BATCH_SIZE:
            raise ApiError(
                "OutOfRange",
                f"Requests.RateLimits list too large; max size is '{MAX_BATCH_SIZE}'",
            )
        return self._route(req.requests)

    # ------------------------------------------------------------------
    # Columnar ingress (zero-dataclass hot path)
    # ------------------------------------------------------------------
    def get_rate_limits_columns(
        self, cols: IngressColumns, max_lanes: int = MAX_BATCH_SIZE
    ) -> ColumnarResult:
        """Column-form GetRateLimits: same routing/validation semantics
        as get_rate_limits (gubernator.go:116-227), but locally-owned
        plain lanes flow straight into the store's columnar kernel path
        with no per-request dataclasses.  GLOBAL / MULTI_REGION /
        remotely-owned lanes fall back to the dataclass path lane-wise.

        `max_lanes` is the ingress-encoding cap: classic (per-request
        JSON/pb) requests keep the reference's MAX_BATCH_SIZE; the
        columnar frame/proto edges pass INGRESS_COLUMNS_MAX_LANES — a
        columnar client's frame coalesces many callers' checks, exactly
        like a forwarded peer batch."""
        if len(cols) > max_lanes:
            raise ApiError(
                "OutOfRange",
                f"Requests.RateLimits list too large; max size is '{max_lanes}'",
            )
        return self._route_columns(cols)

    def _route_columns(self, cols: IngressColumns) -> ColumnarResult:
        n = len(cols)
        result = ColumnarResult.empty(n)
        if n == 0:
            return result
        store_columnar = getattr(self.store, "supports_columns", False)
        if n == 1 or not store_columnar:
            # Single-item requests ride the dataclass path: its
            # LocalBatcher coalesces concurrent single-key clients into
            # one dispatch (the routing policy lives HERE so the HTTP
            # and gRPC edges cannot diverge).
            resp = self._route([cols.request_at(i) for i in range(n)])
            result.overrides = dict(enumerate(resp.responses))
            return result
        plan = self._submit_columns(cols, result)
        if plan is None:
            return result
        return self._finalize_columns(plan, result)

    def _submit_columns(self, cols, result) -> "Optional[_ColumnsPlan]":
        """Phase 1 of the columnar route: validation, ownership, MR
        queueing, and EVERY dispatch/forward submission — no blocking on
        device rounds or peer RPCs.  Returns None when the request fully
        resolved already (empty pool); otherwise a plan for
        _finalize_columns (sync) or _ColumnsJoin (async) to complete.
        Shared by both so the two entry points cannot diverge."""
        n = len(cols)
        # Conservation ledger (audit.py): hits entering the public
        # front door on the columnar path (sync + async edges both
        # funnel here; the dataclass router counts in _route).
        audit_mod.note("ingress_hits", int(cols.hits.sum()))
        # Tenant cost ledger (profiling.py): the SAME admission fold —
        # every audit ingress note has a tenant fold beside it, so the
        # two ledgers reconcile exactly at quiesce (the soak asserts).
        tenant_ctx = self.tenants.fold_admit(cols)
        beh = cols.behavior
        # GLOBAL lanes need the replica-cache/dataclass path; MULTI_REGION
        # lanes stay columnar when locally owned (their only extra duty is
        # async hit queueing, handled below).
        slow = (beh & int(Behavior.GLOBAL)) != 0
        fast = np.logical_not(slow)

        # Validation (gubernator.go:142-152) + hash keys in one pass.
        # The native JSON edge precomputes both (gateway
        # LazyIngressColumns.prevalidated): packed hash keys flow to
        # the planner with zero per-lane Python.
        pre = getattr(cols, "prevalidated", None)
        if pre is not None:
            hash_keys, errc = pre
            for i in np.nonzero(errc)[0]:
                i = int(i)
                result.overrides[i] = RateLimitResponse(
                    error="field 'unique_key' cannot be empty"
                    if errc[i] == 1
                    else "field 'namespace' cannot be empty"
                )
                fast[i] = slow[i] = False
        else:
            hash_keys: List[str] = [""] * n
            for i in range(n):
                uk = cols.unique_keys[i]
                nm = cols.names[i]
                if not uk:
                    result.overrides[i] = RateLimitResponse(
                        error="field 'unique_key' cannot be empty"
                    )
                    fast[i] = slow[i] = False
                    continue
                if not nm:
                    result.overrides[i] = RateLimitResponse(
                        error="field 'namespace' cannot be empty"
                    )
                    fast[i] = slow[i] = False
                    continue
                hash_keys[i] = f"{nm}_{uk}"

        # Ownership: the single-self-peer daemon (the common standalone
        # topology) owns everything; multi-peer rings resolve owners in
        # one vectorized pass.  Plain remote lanes group by owner for
        # ONE forwarded RPC per owner (the batch-sized analogue of the
        # reference's per-item forward window); GLOBAL remote lanes
        # keep the replica-cache dataclass path.
        remote_groups: Dict[str, list] = {}  # owner addr -> [lane idx]
        remote_peers: Dict[str, PeerClient] = {}
        peek_plan: list = []  # [(prev owner PeerClient, lane idx array)]
        with self._peer_mutex:
            pp = self._handoff_prev_picker()  # handoff window: old ring
            psize = self.local_picker.size()
            single_owner = False
            if psize == 1 and pp is None:
                # The single-self shortcut is disabled during a handoff
                # window: a just-scaled-in ring still owes moved lanes
                # the double-dispatch peek at their old owner.
                (only,) = self.local_picker.peers()
                single_owner = only.info.is_owner
            if psize == 0:
                for i in range(n):
                    if i not in result.overrides:
                        result.overrides[i] = RateLimitResponse(
                            error=(
                                f"while finding peer that owns rate limit "
                                f"'{hash_keys[i]}' - 'unable to pick a peer; pool is empty'"
                            )
                        )
                return None
            grouped_mask = np.zeros(n, dtype=bool)
            if not single_owner and psize >= 1:
                # Vectorized ownership: one batch hash + searchsorted,
                # then one mask pass PER DISTINCT OWNER (not per lane)
                # — the ring hands back integer owner codes, so no
                # per-lane Python objects are touched here.  Works on
                # plain string lists and PackedKeys alike.
                valid = fast | slow  # validation-error lanes: both False
                all_valid = bool(valid.all())
                if all_valid:
                    keys_for_ring = hash_keys
                elif isinstance(hash_keys, list):
                    keys_for_ring = [
                        hash_keys[int(i)] for i in np.nonzero(valid)[0]
                    ]
                else:  # PackedKeys (native edge / peer frame decode)
                    keys_for_ring = hash_keys.subset(np.nonzero(valid)[0])
                codes, code_ids = self.local_picker.get_batch_codes(
                    keys_for_ring, sketch=self.hotkeys
                )
                if all_valid:
                    lane_code = codes
                else:
                    lane_code = np.full(n, -1, dtype=np.int32)
                    lane_code[valid] = codes
                if pp is not None and pp.size():
                    # Handoff window: lanes whose owner moved between
                    # the two rings double-dispatch COLUMNAR-natively —
                    # routing stays on the fast path under the NEW
                    # ring, and one grouped zero-hit peek per PREVIOUS
                    # owner merges monotonically at finalize
                    # (_merge_peek_result), so bulk reads never observe
                    # a reset bucket mid-transfer and never pay per-
                    # lane dataclass legs.  One extra vectorized ring
                    # pass + one extra RPC/dispatch per prev-owner per
                    # batch, only while the window is open.  GLOBAL
                    # lanes keep replica semantics; Gregorian lanes
                    # skip the peek (their duration column is an enum a
                    # raw zero-hit batch cannot carry safely).
                    pcodes, pids = pp.get_batch_codes(keys_for_ring)
                    moved_sel = (
                        np.asarray(code_ids, dtype=object)[codes]
                        != np.asarray(pids, dtype=object)[pcodes]
                    )
                    if moved_sel.any():
                        valid_idx = (
                            np.arange(n) if all_valid
                            else np.nonzero(valid)[0]
                        )
                        beh_v = np.asarray(beh)[valid_idx]
                        mv = (
                            moved_sel
                            & ((beh_v & int(Behavior.GLOBAL)) == 0)
                            & (
                                (beh_v
                                 & int(Behavior.DURATION_IS_GREGORIAN))
                                == 0
                            )
                        )
                        for pc in np.unique(pcodes[mv]):
                            prev_peer = pp.get_by_peer_id(pids[int(pc)])
                            if prev_peer is None:
                                continue
                            breaker = getattr(prev_peer, "breaker", None)
                            if (
                                breaker is not None
                                and breaker.is_open
                                and not prev_peer.info.is_owner
                            ):
                                # A dead old owner: the peek would only
                                # fast-fail — skip it outright.
                                continue
                            lanes = valid_idx[mv & (pcodes == pc)]
                            if lanes.size:
                                peek_plan.append((prev_peer, lanes))
                for c, pid in enumerate(code_ids):
                    peer = self.local_picker.get_by_peer_id(pid)
                    if peer is not None and peer.info.is_owner:
                        continue
                    lanes = np.nonzero(lane_code == c)[0]
                    if not lanes.size:
                        continue
                    fast[lanes] = False
                    if peer is not None:
                        # Plain remote lanes: group-forward.  A None
                        # peer (churn mid-resolve) stays on the
                        # dataclass router, which re-picks; GLOBAL
                        # lanes keep the replica-cache path.
                        plain = lanes[np.logical_not(slow[lanes])]
                        if plain.size:
                            addr = peer.info.grpc_address
                            remote_groups[addr] = plain
                            remote_peers[addr] = peer
                            grouped_mask[plain] = True
                    slow[lanes] = True

        self._queue_mr_fast(cols, beh, fast, hash_keys)
        pendings = self._dispatch_fast(cols, beh, fast, hash_keys, result)

        # Plain remote lanes: ONE forwarded columnar sub-batch per
        # owner, submitted in parallel while the local fast dispatch is
        # in flight (the batch-sized analogue of the per-item forward,
        # gubernator.go:195-210).  The lanes travel as COLUMN subsets —
        # no per-lane dataclasses — and concurrent ingress batches to
        # the same owner coalesce in the PeerClient window.  A group
        # containing any NO_BATCHING lane sends direct (window
        # bypassed), preserving the per-request opt-out.
        group_futs = {}
        for addr, idxs in remote_groups.items():
            idx = np.asarray(idxs, dtype=np.int64)
            sub = (
                [cols.names[int(i)] for i in idxs],
                [cols.unique_keys[int(i)] for i in idxs],
                np.asarray(cols.algorithm[idx], dtype=np.int32),
                np.asarray(beh[idx], dtype=np.int32),
                np.asarray(cols.hits[idx], dtype=np.int64),
                np.asarray(cols.limit[idx], dtype=np.int64),
                np.asarray(cols.duration[idx], dtype=np.int64),
            )
            direct = bool((beh[idx] & int(Behavior.NO_BATCHING)).any())
            group_futs[addr] = self._forward_pool.submit(
                self._forward_group_columns, remote_peers[addr], sub, direct,
                # Captured HERE: the forward runs on a pool thread with
                # no ambient context; the peer hop carries this as the
                # wire trace-context column (tracing.py).
                tracing.current(),
            )

        # Handoff double-dispatch: submit the grouped zero-hit peeks
        # (one per previous owner) alongside the in-flight primary
        # legs.  Local groups (the previous owner is THIS daemon,
        # draining away) dispatch one batched device read; remote
        # groups ride the peer's coalescing window.  Strictly
        # best-effort: a submit failure simply drops the peek.
        peeks: list = []
        for prev_peer, lanes in peek_plan:
            idx = np.asarray(lanes, dtype=np.int64)
            zero_hits = np.zeros(idx.size, np.int64)
            try:
                if prev_peer.info.is_owner:
                    if isinstance(hash_keys, list):
                        keys_sel = [hash_keys[int(i)] for i in idx]
                    else:
                        keys_sel = hash_keys.subset(idx)
                    # Peeks OBSERVE, they must not create: drop lanes
                    # with no resident bucket here — nothing to peek,
                    # and a zero-hit shadow bucket would later ride the
                    # transfer plane as noise.  resident_mask iterates
                    # plain lists and PackedKeys alike.
                    res = self.store.resident_mask(keys_sel)
                    if not res.all():
                        idx = idx[res]
                        if not idx.size:
                            continue
                        keys_sel = (
                            [k for k, r in zip(keys_sel, res) if r]
                            if isinstance(keys_sel, list)
                            else keys_sel.subset(np.nonzero(res)[0])
                        )
                        zero_hits = np.zeros(idx.size, np.int64)
                    handle = self.store.apply_columns_async(
                        keys_sel,
                        np.asarray(cols.algorithm[idx], dtype=np.int32),
                        np.asarray(beh[idx], dtype=np.int32),
                        zero_hits,
                        np.asarray(cols.limit[idx], dtype=np.int64),
                        np.asarray(cols.duration[idx], dtype=np.int64),
                        self.clock.now_ms(),
                    )
                    peeks.append(("local", (handle, 0, idx.size), idx))
                else:
                    sub = (
                        [cols.names[int(i)] for i in idx],
                        [cols.unique_keys[int(i)] for i in idx],
                        np.asarray(cols.algorithm[idx], dtype=np.int32),
                        np.asarray(beh[idx], dtype=np.int32),
                        zero_hits,
                        np.asarray(cols.limit[idx], dtype=np.int64),
                        np.asarray(cols.duration[idx], dtype=np.int64),
                    )
                    peeks.append(
                        ("remote", prev_peer.forward_columns(sub), idx)
                    )
            except Exception:  # noqa: BLE001 — peek is best-effort
                continue

        # Remaining slow lanes (GLOBAL remote/local specials) ride the
        # dataclass router.
        slow_idx = [
            int(i)
            for i in np.nonzero(np.logical_and(slow, ~grouped_mask))[0]
        ]
        slow_reqs = [cols.request_at(i) for i in slow_idx]
        return _ColumnsPlan(
            pendings=pendings,
            group_futs=group_futs,
            remote_groups=remote_groups,
            slow_idx=slow_idx,
            slow_fn=(
                # _counted: these lanes' hits were already noted by the
                # funnel above — the dataclass router must not re-note
                # the GLOBAL subset into the ingress ledger.
                (lambda: self._route(slow_reqs, _counted=True).responses)
                if slow_idx else None
            ),
            hash_keys=hash_keys,
            peeks=peeks,
            tenant_ctx=tenant_ctx,
        )

    def _finalize_columns(self, plan: "_ColumnsPlan", result) -> ColumnarResult:
        """Phase 2, blocking form: resolve every submission from phase 1
        and merge into `result` (the async twin is _ColumnsJoin).  The
        handoff peeks merge LAST — they adjust the arrays the primary
        merges populate."""
        if plan.slow_idx:
            resps = plan.slow_fn()
            for i, r in zip(plan.slow_idx, resps):
                result.overrides[int(i)] = r
        for addr, fut in plan.group_futs.items():
            _merge_group_result(
                result, plan.remote_groups[addr], addr, fut.result()
            )
        self._resolve_fast(
            plan.pendings, plan.hash_keys, result,
            tenant_ctx=plan.tenant_ctx,
        )
        for kind, payload, lanes in plan.peeks:
            data = None
            try:
                if kind == "remote":
                    rc, lo, hi = payload.result(
                        timeout=self.conf.behaviors.batch_timeout_s + 1.0
                    )
                    data = ("remote", (rc, lo, hi))
                else:
                    handle, lo, hi = payload
                    data = ("local", (handle.result(), slice(lo, hi)))
            except Exception:  # noqa: BLE001 — peek is best-effort
                data = None
            _merge_peek_result(result, lanes, data)
        # Tenant cost ledger: per-tenant OVER_LIMIT attribution from
        # the resolved arrays (admission was folded at submit).
        self.tenants.fold_outcome(plan.tenant_ctx, result)
        return result

    # -- shared fast-lane halves of the two columnar entry points ------
    def _resolve_greg_fast(self, cols, beh, fast, result):
        """Gregorian precompute for fast lanes (slow lanes redo it in
        prepare_requests; cheap, memoized per duration).  Mutates `fast`
        for error lanes; returns (greg_expire, greg_duration) or Nones."""
        n = len(cols)
        greg_lanes = fast & ((beh & int(Behavior.DURATION_IS_GREGORIAN)) != 0)
        if not greg_lanes.any():
            return None, None
        from .models.shard import GregResolver
        from .utils import gregorian as _greg

        greg_expire = np.zeros(n, dtype=np.int64)
        greg_duration = np.zeros(n, dtype=np.int64)
        resolver = GregResolver(self.clock.now_ms())
        for i in np.nonzero(greg_lanes)[0]:
            cached = resolver.resolve(int(cols.duration[i]))
            if isinstance(cached, _greg.GregorianError):
                result.overrides[int(i)] = RateLimitResponse(error=str(cached))
                fast[i] = False
                continue
            greg_expire[i], greg_duration[i] = cached
        return greg_expire, greg_duration

    def _queue_mr_fast(self, cols, beh, fast, hash_keys) -> None:
        """MULTI_REGION fast lanes owe the async cross-region hit queue
        (gubernator.go:343-345): aggregate per key first so the queue
        sees one materialized request per unique key, not per lane."""
        mr = fast & ((beh & int(Behavior.MULTI_REGION)) != 0)
        if not mr.any():
            return
        agg: Dict[str, RateLimitRequest] = {}
        for i in np.nonzero(mr)[0]:
            k = hash_keys[int(i)]
            cur = agg.get(k)
            if cur is None:
                agg[k] = cols.request_at(int(i))
            else:
                cur.hits += int(cols.hits[i])
        for r in agg.values():
            self.multi_region_mgr.queue_hits(r)

    def _dispatch_fast(self, cols, beh, fast, hash_keys, result):
        """Dispatch the fast lanes (Gregorian precompute included).
        Batching behavior is per request, as in the reference
        (proto/gubernator.proto:74-78): lanes flagged NO_BATCHING
        dispatch immediately, the rest coalesce through the window —
        a mixed batch splits into one direct and one windowed dispatch.
        Returns a list of (pending, idx) pairs for _resolve_fast."""
        greg_expire, greg_duration = self._resolve_greg_fast(cols, beh, fast, result)
        fast_idx = np.nonzero(fast)[0]
        if not fast_idx.size:
            return []
        n = len(cols)
        # Span handles for the dispatch (tracing.py): the ambient
        # ingress context plus any wire trace-context column a peer
        # batch carried; [] on unsampled traffic (one branch).
        links = tracing.request_links(cols)

        def dispatch(idx, direct):
            full = idx.size == n
            sl = slice(None) if full else idx
            if full:
                keys_sel = hash_keys
            elif isinstance(hash_keys, list):
                keys_sel = [hash_keys[i] for i in idx]
            else:
                keys_sel = hash_keys.subset(idx)  # PackedKeys, no per-lane Python
            args = (
                keys_sel, cols.algorithm[sl], beh[sl], cols.hits[sl],
                cols.limit[sl], cols.duration[sl],
                None if greg_expire is None else greg_expire[sl],
                None if greg_duration is None else greg_duration[sl],
            )
            if direct:
                bt = tracing.new_batch(links)
                if bt is not None:
                    tracing.stage_batch_trace(bt)
                try:
                    handle = self.store.apply_columns_async(
                        *args[:6], self.clock.now_ms(), *args[6:]
                    )
                finally:
                    tracing.take_batch_trace()
                return (handle, 0, idx.size), idx
            return (
                self.columnar_batcher.submit(*args, trace_links=links),
                idx,
            )

        nb = (beh[fast_idx] & int(Behavior.NO_BATCHING)) != 0
        if not nb.any():
            return [dispatch(fast_idx, False)]
        if nb.all():
            return [dispatch(fast_idx, True)]
        return [dispatch(fast_idx[nb], True), dispatch(fast_idx[~nb], False)]

    def _resolve_fast(self, pendings, hash_keys, result,
                      tenant_ctx=None) -> None:
        """Block on each fast dispatch and scatter its arrays into the
        result; a dispatch failure (e.g. shutdown race) converts to
        per-lane errors instead of failing lanes already computed."""
        for pending, fast_idx in pendings:
            out, sl, exc = None, None, None
            try:
                handle, lo, hi = (
                    pending.result() if isinstance(pending, Future) else pending
                )
                out = handle.result()
                sl = slice(lo, hi)
            except Exception as e:  # noqa: BLE001
                exc = e
            if isinstance(exc, IngressShedError):
                # Tenant cost ledger: the bounded ingress gate refused
                # these lanes — attribute the shed to their tenants
                # (ROADMAP item 2's "one tenant's burst sheds itself").
                self.tenants.fold_shed(tenant_ctx, fast_idx)
            _merge_fast_result(result, hash_keys, fast_idx, out, sl, exc)

    def _route(self, requests: Sequence[RateLimitRequest],
               _counted: bool = False) -> GetRateLimitsResponse:
        n = len(requests)
        # Conservation ledger: the dataclass router is the other public
        # front-door funnel (get_rate_limits, single-lane and
        # non-columnar fallbacks of the columnar entries).  `_counted`
        # marks lanes the columnar funnel already noted (its GLOBAL/
        # slow subset routes through here) — noting them twice would
        # overstate front-door hits by the GLOBAL fraction.
        if not _counted:
            audit_mod.note(
                "ingress_hits", sum(int(r.hits) for r in requests)
            )
        # Tenant cost ledger: the dataclass router's admission fold
        # (lanes the columnar funnel already folded arrive _counted).
        tenant_names = (
            None if _counted else self.tenants.fold_requests(requests)
        )
        out: List[Optional[RateLimitResponse]] = [None] * n
        local: List[int] = []
        global_remote: List[int] = []
        owner_by_idx: Dict[int, str] = {}
        forwards: List[tuple] = []  # (idx, req, peer)
        peeks: Dict[int, Future] = {}  # handoff double-dispatch legs

        for i, r in enumerate(requests):
            # Validation (gubernator.go:142-152; note the reference's
            # 'namespace' wording for an empty name).
            if not r.unique_key:
                out[i] = RateLimitResponse(error="field 'unique_key' cannot be empty")
                continue
            if not r.name:
                out[i] = RateLimitResponse(error="field 'namespace' cannot be empty")
                continue
            key = r.hash_key()
            peer, err = self._pick_ready_peer(key)
            if peer is None:
                out[i] = RateLimitResponse(
                    error=f"while finding peer that owns rate limit '{key}' - '{err}'"
                )
                continue
            if not has_behavior(r.behavior, Behavior.GLOBAL):
                # Handoff window (elastic membership): a lane whose
                # ownership moved between the previous and current ring
                # DOUBLE-DISPATCHES — the hit is served by the new
                # owner (the normal legs below) plus a concurrent
                # zero-hit peek at the old owner, merged monotonically
                # at the end, so the read can never observe a reset
                # bucket while the state transfer is in flight.
                prev = self._handoff_peek_peer(key, peer)
                if prev is not None:
                    peeks[i] = self._forward_pool.submit(
                        self._peek_one, r, prev
                    )
            if peer.info.is_owner:
                local.append(i)
                if has_behavior(r.behavior, Behavior.MULTI_REGION):
                    self.multi_region_mgr.queue_hits(r)
            elif has_behavior(r.behavior, Behavior.GLOBAL):
                global_remote.append(i)
                owner_by_idx[i] = peer.info.grpc_address
            else:
                forwards.append((i, r, peer))

        now = self.clock.now_ms()

        if local:
            # Whole-batch requests evaluate directly (they ARE the
            # batch); single-item requests with BATCHING ride the
            # ingress window so concurrent clients share one dispatch.
            local_reqs = [requests[i] for i in local]
            if len(local_reqs) > 1 or any(
                has_behavior(r.behavior, Behavior.NO_BATCHING) for r in local_reqs
            ):
                if len(local_reqs) == 1 and self._single_columnar_eligible(
                    local_reqs[0]
                ):
                    # Single NO_BATCHING lane: direct columnar dispatch
                    # (no window).  Same eligibility as the batched
                    # rider; keeps the latency-optimized flag FASTER
                    # than the windowed path, not slower (the object
                    # path's per-request dataclass machinery costs more
                    # than the 500 µs window it skips — cfg8).
                    i = local[0]
                    try:
                        out[i] = self._submit_single_local(
                            local_reqs[0], direct=True
                        ).result()
                    except Exception as e:  # noqa: BLE001
                        key = local_reqs[0].hash_key()
                        out[i] = RateLimitResponse(
                            error=f"while applying rate limit '{key}' - '{e}'"
                        )
                else:
                    resps = self.store.apply(local_reqs, now)
                    for i, resp in zip(local, resps):
                        out[i] = resp
            else:
                futs = [
                    (i, self._submit_single_local(r))
                    for i, r in zip(local, local_reqs)
                ]
                for i, fut in futs:
                    # Per-item error conversion, like the forward path
                    # (_forward_one): a batcher failure must not 500 the
                    # whole GetRateLimits call.
                    try:
                        # No timeout: the flush ALWAYS resolves every
                        # future (result or exception), and a timeout
                        # here would report an error for hits that the
                        # late flush still applies device-side.
                        out[i] = fut.result()
                    except Exception as e:  # noqa: BLE001
                        key = requests[i].hash_key()
                        out[i] = RateLimitResponse(
                            error=f"while applying rate limit '{key}' - '{e}'"
                        )
        if global_remote:
            resps = self.store.apply(
                [requests[i] for i in global_remote], now, remote_global=True
            )
            for i, resp in zip(global_remote, resps):
                resp.metadata = {"owner": owner_by_idx.get(i, "")}
                out[i] = resp

        if forwards:
            futures = {
                i: self._forward_pool.submit(
                    self._forward_one, r, p, tracing.current()
                )
                for i, r, p in forwards
            }
            for i, fut in futures.items():
                out[i] = fut.result()

        for i, fut in peeks.items():
            try:
                peek = fut.result(
                    timeout=self.conf.behaviors.batch_timeout_s + 1.0
                )
            except Exception:  # noqa: BLE001 — peek is best-effort
                peek = None
            if out[i] is not None:
                out[i] = self._merge_handoff(out[i], peek)

        if tenant_names is not None:
            self.tenants.fold_outcome_responses(tenant_names, out)
        return GetRateLimitsResponse(
            responses=[r if r is not None else RateLimitResponse() for r in out]
        )

    def _single_columnar_eligible(self, r: RateLimitRequest) -> bool:
        return not has_behavior(r.behavior, Behavior.GLOBAL) and getattr(
            self.store, "supports_columns", False
        )

    def _submit_single_local(self, r: RateLimitRequest, direct: bool = False):
        """Locally-owned single-item request: ride the COLUMNAR path
        when eligible.  Windowed (default): the coalescer's flush only
        dispatches — waiters resolve the shared handle themselves,
        overlapping readbacks via ColumnarPipeline — so concurrent
        single-key clients pipeline device rounds.  The dataclass
        LocalBatcher's flush calls store.apply, which holds the store
        lock across the whole dispatch+readback: on a high-latency
        device that serializes single-key traffic at one window per RTT
        (the measured cfg9 ThunderingHeard ceiling,
        benchmark_test.go:109-138 topology).  direct=True (NO_BATCHING)
        dispatches immediately with no window.  GLOBAL lanes
        (replica-cache semantics) and Store-SPI deployments keep the
        dataclass path."""
        if not self._single_columnar_eligible(r):
            return self.local_batcher.submit(r)
        ge_arr = gd_arr = None
        if has_behavior(r.behavior, Behavior.DURATION_IS_GREGORIAN):
            from .models.shard import GregResolver
            from .utils import gregorian as _greg

            cached = GregResolver(self.clock.now_ms()).resolve(int(r.duration))
            if isinstance(cached, _greg.GregorianError):
                done: Future = Future()
                done.set_result(RateLimitResponse(error=str(cached)))
                return done
            ge_arr = np.array([cached[0]], np.int64)
            gd_arr = np.array([cached[1]], np.int64)
        cols = (
            [r.hash_key()],
            np.array([int(r.algorithm)], np.int32),
            np.array([int(r.behavior)], np.int32),
            np.array([int(r.hits)], np.int64),
            np.array([int(r.limit)], np.int64),
            np.array([int(r.duration)], np.int64),
        )
        cur = tracing.current()
        links = [cur] if cur is not None else None
        if direct:
            bt = tracing.new_batch(links or [])
            if bt is not None:
                tracing.stage_batch_trace(bt)
            try:
                handle = self.store.apply_columns_async(
                    *cols, self.clock.now_ms(), ge_arr, gd_arr
                )
            finally:
                tracing.take_batch_trace()
            fut: Future = Future()
            fut.set_result((handle, 0, 1))
        else:
            fut = self.columnar_batcher.submit(
                *cols, ge_arr, gd_arr, trace_links=links
            )
        return _SingleLaneWait(fut)

    def _pick_ready_peer(self, key: str):
        """GetPeer for routing; the not-ready re-pick loop
        (gubernator.go:154-162) lives in _forward_one, where readiness
        is actually observed."""
        try:
            return self.get_peer(key), None
        except PeerError as e:
            return None, e

    def _forward_group_columns(self, peer: PeerClient, sub, direct: bool,
                               trace_ctx=None):
        """Forward a whole owner-group as ONE columnar sub-batch
        (riding the peer's coalescing window; `direct` bypasses it for
        NO_BATCHING groups).  Fast outcome: ("cols", result, lo, hi) —
        this group's slice of the shared decoded response arrays,
        scattered zero-dataclass by _merge_group_result.  Failure legs
        keep the dataclass route: an owner with an open circuit breaker
        degrades the whole group to local evaluation; a not-ready peer
        degrades to the per-item forward path, which owns the re-pick
        retry loop (gubernator.go:154-162); other failures convert per
        lane."""
        try:
            if direct:
                rc = peer.send_columns_direct(
                    sub, timeout_s=self.conf.behaviors.batch_timeout_s,
                    trace_ctx=trace_ctx,
                )
                return ("cols", rc, 0, len(sub[0]))
            fut = peer.forward_columns(sub, trace_ctx=trace_ctx)
            rc, lo, hi = fut.result(
                timeout=self.conf.behaviors.batch_timeout_s + 1.0
            )
            return ("cols", rc, lo, hi)
        except Exception as e:  # noqa: BLE001
            if is_circuit_open(e):
                # The RPC never left this host (breaker fast-fail), so
                # local evaluation cannot double-count.
                return self._degrade_local(_cols_to_requests(sub), peer)
            if is_not_ready(e):
                return [
                    self._forward_one(r, peer) for r in _cols_to_requests(sub)
                ]
            return [
                RateLimitResponse(
                    error=(
                        f"while fetching rate limit '{nm}_{uk}' from peer - '{e}'"
                    )
                )
                for nm, uk in zip(sub[0], sub[1])
            ]

    def _degrade_local(
        self, reqs: Sequence[RateLimitRequest], peer: PeerClient
    ) -> List[RateLimitResponse]:
        """The owner's circuit breaker is open: serve the hit from the
        LOCAL shard instead of blocking the batch window behind a dead
        peer.  Documented degraded semantics (architecture.md "Fault
        tolerance"): during the open window each surviving daemon
        enforces the key's full limit against its own share of the
        traffic, so OVER_LIMIT is still enforced (per daemon) and state
        converges back to owner-authoritative once the breaker's
        half-open probe re-closes it.  Responses are stamped
        degraded=true so callers/tests can observe the mode.

        Singles ride _submit_single_local (the windowed columnar
        coalescer): under exactly the load this path absorbs — a whole
        batch window's waiters failing over at once — one raw
        store.apply per waiter would serialize N device rounds at one
        store-lock hold each (the ThunderingHeard ceiling the coalescer
        exists to avoid).  Groups are already one batched apply."""
        if len(reqs) == 1:
            try:
                resps = [self._submit_single_local(reqs[0]).result()]
            except Exception as e:  # noqa: BLE001 (per-item, like _forward_one)
                resps = [
                    RateLimitResponse(
                        error=(
                            f"while applying rate limit "
                            f"'{reqs[0].hash_key()}' - '{e}'"
                        )
                    )
                ]
        else:
            resps = self.store.apply(list(reqs), self.clock.now_ms())
        for resp in resps:
            resp.metadata = {
                "owner": peer.info.grpc_address,
                "degraded": "true",
            }
        self.metrics.degraded_evals.inc(len(resps))
        return resps

    def _forward_one(self, r: RateLimitRequest, peer: PeerClient,
                     trace_ctx=None) -> RateLimitResponse:
        """Forward to the owner (the BATCHING leg, gubernator.go:195-210),
        retrying with a re-pick + jittered backoff when the peer is not
        ready (budget: behaviors.forward_retry_limit).  An owner whose
        circuit breaker was already open serves degraded local
        evaluation instead; a breaker that opens MID-retry keeps the
        error path — this request already burned its budget observing
        real failures, and the caller sees the same not-connected error
        the reference returns (the NEXT request gets the fast degraded
        path).  `trace_ctx` is the SUBMITTING request's span context:
        this runs on a forward-pool thread with no ambient context, so
        the router captures it at submit time — without it a
        single-lane forwarded request's trace would end at the ingress
        span instead of crossing the wire."""
        key = r.hash_key()
        attempts = 0
        budget = self.conf.behaviors.forward_retry_limit
        while True:
            try:
                resp = peer.get_peer_rate_limit(r, trace_ctx=trace_ctx)
                resp.metadata = {"owner": peer.info.grpc_address}
                return resp
            except Exception as e:  # noqa: BLE001
                if is_circuit_open(e):
                    if attempts == 0:
                        return self._degrade_local([r], peer)[0]
                    return RateLimitResponse(
                        error=(
                            "GetPeer() keeps returning peers that are not connected "
                            f"for '{key}' - '{e}'"
                        )
                    )
                if is_not_ready(e):
                    attempts += 1
                    if attempts > budget:
                        return RateLimitResponse(
                            error=(
                                "GetPeer() keeps returning peers that are not connected "
                                f"for '{key}' - '{e}'"
                            )
                        )
                    self.metrics.peer_retries.labels(op="forward").inc()
                    self._retry_backoff.sleep(attempts - 1)
                    try:
                        peer = self.get_peer(key)
                    except PeerError as pe:
                        return RateLimitResponse(
                            error=f"while finding peer that owns rate limit '{key}' - '{pe}'"
                        )
                    continue
                return RateLimitResponse(
                    error=f"while fetching rate limit '{key}' from peer - '{e}'"
                )

    # -- double-dispatch reads during a handoff window -----------------
    def _handoff_prev_picker(self):
        """The previous ring's picker while the double-dispatch window
        is open, else None (and the reference is dropped once the
        window lapses, so steady state pays one None check).  Caller
        holds _peer_mutex."""
        if self._prev_picker is None:
            return None
        if time.monotonic() >= self._handoff_deadline:
            self._prev_picker = None
            return None
        return self._prev_picker

    def _handoff_peek_peer(self, key: str, cur_peer: PeerClient):
        """The OLD owner to peek for `key` during the handoff window —
        None when no window is open, ownership didn't move, or the old
        owner is the current one."""
        if self._prev_picker is None:  # unlocked fast path (benign race)
            return None
        with self._peer_mutex:
            pp = self._handoff_prev_picker()
            if pp is None or pp.size() == 0:
                return None
            try:
                prev = pp.get_by_peer_id(pp.get(key))
            except RuntimeError:
                return None
        if prev is None or prev is cur_peer:
            return None
        pinfo = getattr(prev, "info", None)
        if pinfo is not None and pinfo.grpc_address == cur_peer.info.grpc_address:
            return None
        breaker = getattr(prev, "breaker", None)
        if (
            breaker is not None and breaker.is_open
            and not (pinfo is not None and pinfo.is_owner)
        ):
            # A dead old owner (breaker open): the peek would only
            # fast-fail — skip it so churn against unreachable peers
            # never taxes the request path.
            return None
        return prev

    def _peek_one(self, r: RateLimitRequest, prev_peer):
        """Zero-hit read at the PREVIOUS owner: the second leg of the
        double-dispatch.  hits=0 never consumes budget, so the peek
        cannot double-count — it only observes the bucket the transfer
        hasn't landed yet.  Best-effort: any failure (old owner dying
        is exactly when this runs) returns None and the primary answer
        stands."""
        r0 = replace(r, hits=0)
        try:
            if prev_peer.info.is_owner:
                # The previous owner is THIS daemon (we are draining
                # away): read our own store — only if the bucket is
                # actually resident (peeks observe, never create).
                mask_fn = getattr(self.store, "resident_mask", None)
                if mask_fn is not None and not mask_fn([r0.hash_key()])[0]:
                    return None
                return self.store.apply([r0], self.clock.now_ms())[0]
            return prev_peer.get_peer_rate_limit(r0)
        except Exception:  # noqa: BLE001 — peek is strictly best-effort
            return None

    @staticmethod
    def _merge_handoff(primary: RateLimitResponse,
                       peek: Optional[RateLimitResponse]) -> RateLimitResponse:
        """Monotone merge of a double-dispatched read (the documented
        rule, architecture.md "Membership & resharding"): status = max
        (OVER_LIMIT wins), remaining = min, reset_time = max.  Both
        sides answered about the same limit config; the merged view is
        never more permissive than either — so no request observes a
        reset bucket mid-handoff.  Error answers on either side leave
        the primary untouched."""
        if peek is None or peek.error or primary.error:
            return primary
        if int(peek.remaining) >= int(peek.limit) and int(peek.status) == 0:
            # No consumption evidence: the old owner answered a
            # fresh/untouched bucket (it may have already forgotten the
            # key post-ACK) — nothing to carry, and merging would only
            # inflate reset_time.
            return primary
        primary.status = max(int(primary.status), int(peek.status))
        primary.remaining = min(int(primary.remaining), int(peek.remaining))
        primary.reset_time = max(int(primary.reset_time), int(peek.reset_time))
        if primary.metadata:
            primary.metadata.setdefault("handoff", "true")
        else:
            primary.metadata = {"handoff": "true"}
        return primary

    def _peer_send(self, op: str, fn: Callable[[], object]) -> bool:
        """Host-tier peer send (GLOBAL hits/broadcast fan-out,
        multi-region push) with jittered-backoff retries on not-ready
        failures, replacing the bare try/except-pass hot loops that
        were dominated by network timeouts under failure.  Circuit-open
        fast-fails are skipped immediately (the breaker's open interval
        IS the backoff across ticks); budgets come from
        behaviors.global_send_retries.  Returns success."""
        ok, _ = self._peer_send_ex(op, fn)
        return ok

    def _peer_send_ex(self, op: str, fn: Callable[[], object]):
        """_peer_send returning (success, last_error): the GLOBAL
        requeue accounting reads the failure SHAPE — a breaker
        fast-fail / connection-level not-ready provably never applied
        (safe to requeue the hits), a timeout-shaped failure may have
        applied server-side (requeueing would double-count)."""
        budget = self.conf.behaviors.global_send_retries
        attempt = 0
        while True:
            try:
                fn()
                return True, None
            except Exception as e:  # noqa: BLE001 (logged-and-continue in ref)
                if is_circuit_open(e) or not is_not_ready(e) or attempt >= budget:
                    return False, e
                self.metrics.peer_retries.labels(op=op).inc()
                self._retry_backoff.sleep(attempt)
                attempt += 1

    # ------------------------------------------------------------------
    # PeersV1 surface
    # ------------------------------------------------------------------
    def get_peer_rate_limits(self, req: GetRateLimitsRequest) -> GetRateLimitsResponse:
        """Owner-authoritative batch (gubernator.go:275-292); never
        re-forwards."""
        if len(req.requests) > MAX_BATCH_SIZE:
            raise ApiError(
                "OutOfRange",
                f"'PeerRequest.rate_limits' list too large; max size is '{MAX_BATCH_SIZE}'",
            )
        audit_mod.note(
            "peer_ingress_hits", sum(int(r.hits) for r in req.requests)
        )
        tenant_names = self.tenants.fold_requests(list(req.requests))
        now = self.clock.now_ms()
        resps = self.store.apply(list(req.requests), now)
        for r in req.requests:
            if has_behavior(r.behavior, Behavior.MULTI_REGION):
                self.multi_region_mgr.queue_hits(r)
        self.tenants.fold_outcome_responses(tenant_names, resps)
        return GetRateLimitsResponse(responses=resps)

    def get_peer_rate_limits_columns(
        self, cols: IngressColumns, max_lanes: int = MAX_BATCH_SIZE
    ) -> ColumnarResult:
        """Column-form PeersV1 receive path: every lane is owned HERE
        (the sender already routed), so non-GLOBAL lanes go straight to
        the columnar kernel via the shared coalescing window —
        concurrent peers' sub-batches merge into one device dispatch.
        GLOBAL lanes keep the dataclass path (owner-side dirty marking
        for the broadcast pipeline, gubernator.go:339-341).

        `max_lanes` is the ingress-encoding cap: classic (per-request)
        receives keep the reference's MAX_BATCH_SIZE; the columnar
        frame/proto edges pass PEER_COLUMNS_MAX_LANES (a coalesced RPC
        carries many ingress batches)."""
        n = len(cols)
        if n > max_lanes:
            raise ApiError(
                "OutOfRange",
                f"'PeerRequest.rate_limits' list too large; max size is '{max_lanes}'",
            )
        result = ColumnarResult.empty(n)
        if n == 0:
            return result
        if not getattr(self.store, "supports_columns", False):
            req = GetRateLimitsRequest(
                requests=[cols.request_at(i) for i in range(n)]
            )
            result.overrides = dict(enumerate(self.get_peer_rate_limits(req).responses))
            return result
        # Conservation ledger: hits entering through the peer door (the
        # dataclass fallback above counts inside get_peer_rate_limits).
        audit_mod.note("peer_ingress_hits", int(cols.hits.sum()))
        plan = self._submit_peer_columns(cols, result)
        return self._finalize_columns(plan, result)

    def _submit_peer_columns(self, cols, result) -> "_ColumnsPlan":
        """Phase 1 of the PeersV1 columnar receive (shared by the sync
        entry above and get_peer_rate_limits_columns_async)."""
        n = len(cols)
        # Tenant cost ledger: the peer-door admission fold (beside the
        # callers' peer_ingress_hits audit notes) — forwarded traffic
        # attributes on the OWNER, which is where the hot-tenant
        # question is asked.
        tenant_ctx = self.tenants.fold_admit(cols)
        beh = cols.behavior
        slow = (beh & int(Behavior.GLOBAL)) != 0
        fast = np.logical_not(slow)
        # A frame-decoded batch (wire.FrameIngressColumns) hands the
        # hash keys over PACKED — the sender's ingress already
        # validated them, so no per-lane strings are built here; other
        # ingress shapes (classic JSON/pb decode) build the list.
        pre = getattr(cols, "prevalidated", None)
        if pre is not None:
            hash_keys, _errc = pre
        else:
            hash_keys = [
                f"{nm}_{uk}" for nm, uk in zip(cols.names, cols.unique_keys)
            ]
        # MULTI_REGION queueing covers EVERY lane here (the reference
        # queues after applying each forwarded request,
        # gubernator.go:340-341 via GetPeerRateLimits); pass an all-True
        # mask so GLOBAL+MULTI_REGION lanes queue too.
        self._queue_mr_fast(cols, beh, np.ones(n, dtype=bool), hash_keys)
        pendings = self._dispatch_fast(cols, beh, fast, hash_keys, result)

        slow_idx = [int(i) for i in np.nonzero(slow)[0]]
        slow_reqs = [cols.request_at(i) for i in slow_idx]
        return _ColumnsPlan(
            pendings=pendings,
            group_futs={},
            remote_groups={},
            slow_idx=slow_idx,
            slow_fn=(
                (lambda: self.store.apply(slow_reqs, self.clock.now_ms()))
                if slow_idx
                else None
            ),
            hash_keys=hash_keys,
            tenant_ctx=tenant_ctx,
        )

    # -- async columnar entry points (native-edge completion path) -----
    def _get_drainer(self) -> "_HandleDrainer":
        """Lazily start the handle-drainer pool (most embedders never
        use the async entry points; don't cost them 8 idle threads)."""
        with self._drainer_lock:
            if self._drainer is None:
                d = _HandleDrainer()
                d.start()
                self._drainer = d
            return self._drainer

    def get_rate_limits_columns_async(
        self, cols: IngressColumns, callback: "Callable",
        max_lanes: int = MAX_BATCH_SIZE,
    ) -> None:
        """Async twin of get_rate_limits_columns: submits everything on
        the calling thread (validation, routing, dispatch/forward — no
        blocking), then delivers via callback(result, exc) exactly once
        from a completion thread.  Built for the native epoll edge: a
        worker hands off and returns to the ingress queue immediately,
        so the number of in-flight requests — and therefore how many
        callers one coalescing window can merge — is bounded by the
        ingress queue, not by a blocked-thread pool (the measured
        convoy that cost the native edge its bulk throughput,
        benchmarks/RESULTS.md round-5 A/B)."""
        try:
            if len(cols) > max_lanes:
                raise ApiError(
                    "OutOfRange",
                    f"Requests.RateLimits list too large; max size is '{max_lanes}'",
                )
            n = len(cols)
            result = ColumnarResult.empty(n)
            if n == 0:
                callback(result, None)
                return
            if n == 1 or not getattr(self.store, "supports_columns", False):
                if n == 1 and self._try_single_async(cols, callback):
                    return
                # Dataclass fallback blocks (LocalBatcher / peer RPCs):
                # run it on the slow pool (NOT _forward_pool — _route
                # submits leaf forwards there and blocks; sharing the
                # pool deadlocks at saturation).  Per-REQUEST thread
                # use, but only for remotely-owned / multi-peer /
                # exotic-store single-key shapes the fast path declines.
                fut = self._slow_pool.submit(
                    self.get_rate_limits_columns, cols
                )
                _attach_done(fut, partial(_deliver_future, callback))
                return
            plan = self._submit_columns(cols, result)
        except Exception as e:  # noqa: BLE001
            callback(None, e)
            return
        if plan is None:
            callback(result, None)
            return
        _ColumnsJoin(self, plan, result, callback).start()

    def _try_single_async(self, cols, callback) -> bool:
        """Zero-extra-thread completion for the dominant async
        single-key shape: a standalone (single self-owner) daemon with
        the columnar store.  Submits through the same
        _submit_single_local rider the sync path uses and completes via
        the drainer (columnar) or the batcher flush thread (dataclass),
        so no slow-pool thread parks per request.  Returns False to
        decline — multi-peer rings, empty pools, and validation
        subtleties stay on the sync router via the slow pool."""
        if not getattr(self.store, "supports_columns", False):
            return False
        with self._peer_mutex:
            if self.local_picker.size() != 1:
                return False
            (only,) = self.local_picker.peers()
            if not only.info.is_owner:
                return False
        r = cols.request_at(0)
        if not r.unique_key or not r.name:
            return False  # sync router owns the validation wording
        if has_behavior(r.behavior, Behavior.GLOBAL) and has_behavior(
            r.behavior, Behavior.NO_BATCHING
        ):
            # Sync parity: this shape takes store.apply directly (no
            # window); riding the LocalBatcher here would add the very
            # window NO_BATCHING opts out of.
            return False
        result = ColumnarResult.empty(1)

        def deliver_resp(resp: RateLimitResponse) -> None:
            if resp.status == 1 and not resp.error:
                self.tenants.fold_outcome_responses([r.name], [resp])
            result.overrides[0] = resp
            callback(result, None)

        def to_error(e: BaseException) -> RateLimitResponse:
            return RateLimitResponse(
                error=f"while applying rate limit '{r.hash_key()}' - '{e}'"
            )

        if has_behavior(r.behavior, Behavior.MULTI_REGION):
            self.multi_region_mgr.queue_hits(r)
        # Conservation ledger: this lane bypasses both router funnels.
        audit_mod.note("ingress_hits", int(r.hits))
        # Tenant ledger: same bypass, same pairing rule.
        self.tenants.fold_one(
            r.name, int(r.hits),
            len(r.name) + len(r.unique_key) + profiling.NUMERIC_LANE_BYTES,
        )
        try:
            w = self._submit_single_local(
                r, direct=has_behavior(r.behavior, Behavior.NO_BATCHING)
            )
        except Exception as e:  # noqa: BLE001
            # Per-lane error, not a transport exc — sync-router parity
            # (_route converts the same failure per item).
            deliver_resp(to_error(e))
            return True

        if isinstance(w, _SingleLaneWait):
            drainer = self._get_drainer()

            def on_out(lo, out, exc):
                deliver_resp(
                    to_error(exc) if exc is not None
                    else _lane_response(out, lo)
                )

            def on_dispatched(fut):
                try:
                    handle, lo, _hi = fut.result()
                except Exception as e:  # noqa: BLE001
                    deliver_resp(to_error(e))
                    return
                drainer.register(handle, partial(on_out, lo))

            _attach_done(w._fut, on_dispatched)
        else:
            # LocalBatcher future (GLOBAL lane) / resolved Gregorian
            # error: resolves to a RateLimitResponse on the flush
            # thread; per-item error conversion like _route's.  The
            # future resolves INSIDE the try and delivery happens once
            # outside it — a raising edge callback must not re-enter
            # (the _deliver_future invariant).
            def on_done(fut):
                try:
                    resp = fut.result()
                except Exception as e:  # noqa: BLE001
                    resp = to_error(e)
                deliver_resp(resp)

            _attach_done(w, on_done)
        return True

    def get_peer_rate_limits_columns_async(
        self, cols: IngressColumns, callback: "Callable",
        max_lanes: int = MAX_BATCH_SIZE,
    ) -> None:
        """Async twin of get_peer_rate_limits_columns (the owner-side
        receive of forwarded batches — the OTHER device-bound endpoint a
        native-edge worker must not block on)."""
        try:
            if len(cols) > max_lanes:
                raise ApiError(
                    "OutOfRange",
                    f"'PeerRequest.rate_limits' list too large; max size is '{max_lanes}'",
                )
            n = len(cols)
            result = ColumnarResult.empty(n)
            if n == 0:
                callback(result, None)
                return
            if not getattr(self.store, "supports_columns", False):
                fut = self._slow_pool.submit(
                    self.get_peer_rate_limits_columns, cols
                )
                _attach_done(fut, partial(_deliver_future, callback))
                return
            audit_mod.note("peer_ingress_hits", int(cols.hits.sum()))
            plan = self._submit_peer_columns(cols, result)
        except Exception as e:  # noqa: BLE001
            callback(None, e)
            return
        _ColumnsJoin(self, plan, result, callback).start()

    def update_peer_globals(self, updates: Sequence[UpdatePeerGlobal]) -> None:
        """gubernator.go:259-272.  With the columnar GLOBAL plane on,
        even a classic (per-item encoded) broadcast commits as ONE
        batched replica scatter; the GUBER_GLOBAL_COLUMNS=0 interop
        mode keeps the pre-columns per-item dispatches."""
        now = self.clock.now_ms()
        if updates and self.serves_global_columns:
            self.store.set_replica_batch(
                GlobalsColumns.from_updates(list(updates)), now
            )
            return
        for u in updates:
            self.store.set_replica(u, now)

    def update_peer_globals_columns(self, cols: GlobalsColumns) -> None:
        """Columnar receive side of the GLOBAL broadcast (the
        GlobalsColumns wire decodes straight into one batched replica
        commit — O(1) device dispatches for an N-item broadcast).
        Capped like the forwarded-hits columns edge: the sender chunks
        at the same bound, so an oversized batch is a bug or abuse —
        and an uncapped one could churn the whole gslot table under
        the store lock in a single RPC."""
        if len(cols) > PEER_COLUMNS_MAX_LANES:
            raise ApiError(
                "OutOfRange",
                f"'UpdatePeerGlobals' columns list too large; "
                f"max size is '{PEER_COLUMNS_MAX_LANES}'",
            )
        now = self.clock.now_ms()
        batch = getattr(self.store, "set_replica_batch", None)
        if batch is not None:
            batch(cols, now)
            return
        for u in cols.to_updates():
            self.store.set_replica(u, now)

    def update_region_columns(self, cols) -> int:
        """Receive side of the multi-region federation plane
        (federation.py): one cross-region hit batch (RegionColumnsReq /
        the GUBC region frame) applied locally through the SAME
        columnar receive path a classic per-item GetPeerRateLimits
        send lands in — so the columnar and classic encodings are
        behavior-identical by construction, only the wire differs.

        The sender already stripped MULTI_REGION from the behavior
        column (the no-amplification rule: applying must not re-queue
        the hits toward other regions), and the receiver TRUSTS that
        contract defensively: any lane still flagged is re-stripped
        here, because an echo loop between two regions is strictly
        worse than one misbehaving sender.

        Conservation ledger (audit.py): the batch's hits note
        `region_recv_hits` at decode and `region_applied_hits` for the
        lanes that applied without error — `region_apply` keeps
        applied <= recv.  Returns the applied lane count."""
        n = len(cols)
        if n > PEER_COLUMNS_MAX_LANES:
            raise ApiError(
                "OutOfRange",
                f"'UpdateRegionColumns' columns list too large; "
                f"max size is '{PEER_COLUMNS_MAX_LANES}'",
            )
        if n == 0:
            return 0
        hits = np.asarray(cols.hits, dtype=np.int64)
        audit_mod.note("region_recv_hits", int(hits.sum()))
        beh = np.asarray(cols.behavior, dtype=np.int32)
        mr = int(Behavior.MULTI_REGION)
        if bool((beh & mr).any()):
            beh = beh & ~np.int32(mr)
        ic = IngressColumns(
            names=list(cols.names),
            unique_keys=list(cols.unique_keys),
            algorithm=np.asarray(cols.algorithm, dtype=np.int32),
            behavior=beh,
            hits=hits,
            limit=np.asarray(cols.limit, dtype=np.int64),
            duration=np.asarray(cols.duration, dtype=np.int64),
        )
        result = self.get_peer_rate_limits_columns(
            ic, max_lanes=PEER_COLUMNS_MAX_LANES
        )
        errored = [
            i for i, r in result.overrides.items()
            if getattr(r, "error", "")
        ]
        applied = n - len(errored)
        applied_hits = int(hits.sum()) - sum(int(hits[i]) for i in errored)
        if applied_hits > 0:
            audit_mod.note("region_applied_hits", applied_hits)
        return applied

    def transfer_ownership(self, cols: "TransferColumns") -> "tuple[int, int]":
        """Receive side of an ownership transfer (elastic membership,
        reshard.py): fence the epoch, drop lanes this daemon does not
        own under its CURRENT ring, and merge-commit the rest through
        the store's batched transfer commit (O(1) device programs).
        Returns (committed, rejected)."""
        n = len(cols)
        if n > PEER_COLUMNS_MAX_LANES:
            raise ApiError(
                "OutOfRange",
                f"'TransferOwnership' columns list too large; "
                f"max size is '{PEER_COLUMNS_MAX_LANES}'",
            )
        if n == 0:
            return 0, 0
        # Conservation ledger: transfer lanes received; committed +
        # rejected below must never exceed this (reshard_in).
        audit_mod.note("reshard_received_lanes", n)
        with self._peer_mutex:
            cur_hash = self.ring_hash
            picker = self.local_picker
            psize = picker.size()
        if cols.ring_hash and cur_hash and cols.ring_hash != cur_hash:
            # Epoch fence: this batch was routed under a ring this
            # daemon no longer runs — committing it could resurrect
            # state for keys that moved AGAIN.  The sender sees a
            # distinct non-retryable answer and aborts.
            self.reshard.note_fenced(n)
            raise ApiError(
                "FailedPrecondition",
                f"transfer fenced: batch ring {cols.ring_hash:#018x} != "
                f"current ring {cur_hash:#018x}",
                http_status=409,
            )
        keep = np.arange(n)
        if psize > 1:
            codes, code_ids = picker.get_batch_codes(cols.keys)
            own = np.zeros(len(code_ids), dtype=bool)
            for c, pid in enumerate(code_ids):
                peer = picker.get_by_peer_id(pid)
                own[c] = peer is not None and peer.info.is_owner
            keep = np.nonzero(own[codes])[0]
        elif psize == 1:
            (only,) = picker.peers()
            if not only.info.is_owner:
                keep = np.zeros(0, dtype=np.int64)
        committed = 0
        if keep.size:
            sub = cols if keep.size == n else cols.subset(keep)
            committed = self.store.commit_transfer(sub, self.clock.now_ms())
        rejected = n - int(keep.size)
        self.reshard.note_received(committed, rejected)
        return committed, rejected

    # ------------------------------------------------------------------
    def health_check(self) -> HealthCheckResponse:
        """gubernator.go:295-333.  Counted + timed at the transport
        edges like every RPC (grpc_stats.go:95-118 parity)."""
        return self._health_check()

    def _health_check(self) -> HealthCheckResponse:
        errs: List[str] = []
        breaker_open = 0
        with self._peer_mutex:
            for peer in list(self.local_picker.peers()) + list(
                self.region_picker.peers()
            ):
                errs.extend(peer.get_last_err())
                breaker = getattr(peer, "breaker", None)
                if breaker is not None and breaker.is_open:
                    breaker_open += 1
            self._health.status = HEALTHY
            self._health.message = ""
            self._health.peer_count = self.local_picker.size()
            self._health.breaker_open_count = breaker_open
            if errs:
                self._health.status = UNHEALTHY
                self._health.message = "|".join(errs)
            from . import __version__

            return HealthCheckResponse(
                status=self._health.status,
                message=self._health.message,
                peer_count=self._health.peer_count,
                breaker_open_count=self._health.breaker_open_count,
                version=__version__,
            )

    # ------------------------------------------------------------------
    def ingress_queued_lanes(self) -> int:
        """Lanes currently admitted into the bounded ingress gates
        (both batchers share the GUBER_INGRESS_QUEUE_LANES budget but
        account separately)."""
        return (
            self.local_batcher._gate.queued
            + self.columnar_batcher._gate.queued
        )

    _BREAKER_NAMES = {0: "closed", 1: "half-open", 2: "open"}

    def debug_status(self) -> dict:
        """The cluster-status surface (GET /debug/status): one JSON doc
        aggregating version, health, per-peer breaker state, bucket-
        table occupancy, ingress-queue depth, and SLO burn — what
        scripts/cluster_status.py polls and the soak harness asserts
        against.  Reads only host-side state: zero device programs."""
        from . import __version__

        hc = self._health_check()
        peers = []
        with self._peer_mutex:
            peer_list = list(self.local_picker.peers()) + list(
                self.region_picker.peers()
            )
            region_rings = {
                dc: list(ring.peers())
                for dc, ring in self.region_picker.regions.items()
            }
            handoff_active = self._handoff_prev_picker() is not None
            ring = {
                "generation": self.ring_generation,
                "hash": format(self.ring_hash, "016x"),
                "handoffActive": handoff_active,
                "handoffRemainingS": (
                    round(max(self._handoff_deadline - time.monotonic(), 0.0), 3)
                    if handoff_active else 0.0
                ),
                "reshardEnabled": self.serves_reshard,
            }
        for p in peer_list:
            breaker = getattr(p, "breaker", None)
            info = getattr(p, "info", None)
            if info is None:
                continue
            peers.append({
                "peer": info.grpc_address,
                "isOwner": bool(info.is_owner),
                "breaker": self._BREAKER_NAMES.get(
                    breaker.state_code if breaker is not None else 0,
                    "closed",
                ),
            })
        store = self.store
        occupancy = getattr(store, "occupancy_stats", None)
        shards = occupancy() if occupancy is not None else []
        used_total = sum(r["used"] for r in shards)
        cap_total = sum(r["capacity"] for r in shards)
        ev_total = sum(r["evictions"] for r in shards)
        gate_cap = getattr(
            self.conf.behaviors, "ingress_queue_lanes", 0
        )
        status = {
            "version": __version__,
            "uptimeS": round(time.monotonic() - self._started_monotonic, 1),
            "health": {
                "status": hc.status,
                "message": hc.message,
                "peerCount": hc.peer_count,
                "breakerOpenCount": hc.breaker_open_count,
            },
            "peers": peers,
            "occupancy": {
                "used": used_total,
                "capacity": cap_total,
                "evictions": ev_total,
                "ratio": round(used_total / cap_total, 4) if cap_total else 0.0,
                "shards": shards,
            },
            "ingress": {
                "queuedLanes": self.ingress_queued_lanes(),
                "capLanes": gate_cap,
                "shedLanes": int(
                    self.metrics.ingress_shed._value.get()  # noqa: SLF001
                ),
                "depth": saturation.queue_depth_snapshot(),
                "windowWaitS": round(
                    self.columnar_batcher._window.effective_wait_s(), 6
                ),
            },
            "dispatch": {
                "inflight": int(getattr(store, "pipeline_depth", lambda: 0)()),
                "deviceDispatches": int(
                    getattr(store, "device_dispatches", 0)
                ),
            },
            "slo": self.slo.snapshot(),
            # Express lane: knobs + hit rate + the host scalar slot's
            # apply count (zero device programs by construction).
            "express": {
                "enabled": bool(
                    getattr(self.conf.behaviors, "express", False)
                ),
                "queueDepth": int(
                    getattr(self.conf.behaviors, "express_queue_depth", 0)
                ),
                "maxLanes": int(
                    getattr(self.conf.behaviors, "express_max_lanes", 0)
                ),
                "scalarApplies": int(
                    getattr(store, "scalar_applies", 0)
                ),
                **saturation.express_snapshot(),
            },
            "hotkeys": self.hotkeys.snapshot()["topk"][:5],
            # Cost observatory (profiling.py): top tenants by cost and
            # the host-profiler vitals — the fleet poller's per-daemon
            # "who is spending the capacity" cells.
            "tenants": self.tenants.snapshot(top=5),
            "profile": {
                "enabled": profiling.enabled(),
                "hz": profiling.hz(),
                "samples": profiling.sample_count(),
            },
            "ring": {**ring, "reshard": self.reshard.snapshot()},
            "audit": {
                "enabled": self.auditor.enabled,
                "checks": self.auditor.checks,
                "violations": dict(self.auditor.violations),
                "violationTotal": sum(self.auditor.violations.values()),
            },
            "xla": {
                "enabled": telemetry.enabled(),
                "compiles": telemetry.compile_count(),
                "steadyRecompiles": telemetry.steady_recompile_count(),
            },
            "snapshot": self.snapshots.snapshot(),
            # Incident black box (blackbox.py): ring fill, bundle
            # counts, last-trigger age — scripts/cluster_status.py's
            # blackbox column reads this.
            "blackbox": self.blackbox.snapshot(),
            # Multi-region federation plane (federation.py): this
            # daemon's data center, the accumulator/carry state, and
            # per-remote-region peer + breaker counts — what the soak's
            # 2x2 topology and scripts/cluster_status.py read.
            "region": {
                **self.multi_region_mgr.snapshot(),
                "regions": {
                    dc: {
                        "peers": len(plist),
                        "breakerOpen": sum(
                            1 for p in plist
                            if getattr(p, "breaker", None) is not None
                            and p.breaker.is_open
                        ),
                    }
                    for dc, plist in region_rings.items()
                },
            },
        }
        return status

    # ------------------------------------------------------------------
    def set_peers(self, peer_infos: Sequence[PeerInfo]) -> None:
        """Rebuild pickers, reusing existing clients by address; drain
        dropped peers through the bounded reshard pool
        (gubernator.go:357-437).  A MEMBERSHIP change additionally bumps
        the ring generation + fingerprint, opens the double-dispatch
        handoff window (the previous ring is retained so reads can peek
        the old owner), and — when the reshard plane is on — schedules
        the columnar state handoff: moved resident keys drain off the
        device and ship to their new owners (reshard.py)."""
        local = [p for p in peer_infos if not p.data_center or p.data_center == self.conf.data_center]
        regional = [p for p in peer_infos if p.data_center and p.data_center != self.conf.data_center]

        with self._peer_mutex:
            old_clients = {
                c.info.grpc_address: c
                for c in list(self.local_picker.peers()) + list(self.region_picker.peers())
                if isinstance(c, PeerClient)
            }
            old_ids = set(self.local_picker.peer_ids())
            new_local = self.local_picker.new()
            for info in local:
                client = old_clients.pop(info.grpc_address, None)
                if client is None:
                    client = PeerClient(
                        info, self.conf.behaviors,
                        tls_context=self.conf.peer_tls_context,
                        channel_credentials=self.conf.peer_channel_credentials,
                        metrics=self.metrics,
                        faults=self.conf.fault_plan,
                        blackbox=self.blackbox,
                    )
                client.info = info
                new_local.add(info.grpc_address, client)
            new_region = self.region_picker.new()
            for info in regional:
                client = old_clients.pop(info.grpc_address, None)
                if client is None:
                    client = PeerClient(
                        info, self.conf.behaviors,
                        tls_context=self.conf.peer_tls_context,
                        channel_credentials=self.conf.peer_channel_credentials,
                        metrics=self.metrics,
                        faults=self.conf.fault_plan,
                        blackbox=self.blackbox,
                    )
                client.info = info
                new_region.add(client)
            prev_picker = self.local_picker
            self.local_picker = new_local
            self.region_picker = new_region
            new_ids = set(new_local.peer_ids())
            # Ring delta only on a real MEMBERSHIP change: re-pushes of
            # the same list (discovery heartbeats, is_owner restamps)
            # must not bump the epoch or churn a handoff.
            membership_changed = new_ids != old_ids
            handoff = False
            if membership_changed:
                self.ring_generation += 1
                self.ring_hash = new_local.fingerprint()
                if old_ids and self.serves_reshard:
                    # Not the bootstrap call (and the reshard plane is
                    # on — GUBER_RESHARD=0 must be exactly the legacy
                    # metadata-only behavior, peeks included): open the
                    # double-dispatch window against the OLD ring.
                    # (prev_picker holds
                    # the surviving clients by reference — they are
                    # reused in the new picker — and shut-down dropped
                    # clients fast-fail, which the peek path tolerates.)
                    self._prev_picker = prev_picker
                    self._handoff_deadline = (
                        time.monotonic()
                        + getattr(self.conf.behaviors, "reshard_handoff_s", 2.0)
                    )
                    handoff = True
                elif (
                    self.serves_reshard
                    and self.snapshots.restored_ring_hash
                    and self.snapshots.restored_ring_hash != self.ring_hash
                ):
                    # BOOTSTRAP call, but the restored snapshot was
                    # saved under a DIFFERENT membership (snapshot.py
                    # ring fencing): the restore kept every key, so
                    # drain the ones this daemon no longer owns and ship
                    # them through the ordinary transfer path.  No
                    # double-dispatch window — there is no previous
                    # picker; the handoff itself is the ordinary
                    # drain -> transfer pass against the new ring.
                    self.snapshots.restored_ring_hash = None
                    handoff = True
            gen, rh = self.ring_generation, self.ring_hash

        # Native service loop (gateway.NativeIngressPump): push the new
        # ring snapshot so the GIL-free route check tracks membership —
        # a membership change with a double-dispatch window DISABLES
        # the fast lane until the window closes (moved keys owe the old
        # owner a peek only the Python router performs).
        pump = getattr(self, "native_ingress", None)
        if pump is not None:
            pump.update_ring()

        # Handoff FIRST, then dropped-peer shutdowns: both ride the
        # same bounded FIFO pool, and a delta dropping several peers
        # must not park every worker in blocking client drains while
        # the state transfer waits out its double-dispatch window.
        if handoff and self.serves_reshard and not self._closed:
            self.reshard.schedule_handoff(new_local, rh, gen)
        # Shutdown dropped peers without blocking — through the bounded
        # drain pool, tracked so close() can't race a half-shutdown
        # client (previously one unbounded daemon thread per peer).
        for client in old_clients.values():
            self.reshard.submit_shutdown(client)

    # ------------------------------------------------------------------
    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        # Native service loop first: the pump's in-flight dispatches
        # must resolve against a live store, and its queued frames get
        # their 503s while the edge still accepts staged responses.
        pump = getattr(self, "native_ingress", None)
        if pump is not None:
            pump.stop()
        self.local_batcher.stop()
        self.columnar_batcher.stop()
        # After the batchers stop every pending future is resolved, so
        # all handles are registered; the drainer resolves them (device
        # rounds complete) before the store/pools go away.
        with self._drainer_lock:
            drainer = self._drainer
        if drainer is not None:
            drainer.stop()
        self.global_mgr.stop()
        self.multi_region_mgr.stop()
        self.auditor.stop()
        # Drain the membership pool BEFORE tearing down peers/store: an
        # in-flight handoff or dropped-peer shutdown must finish (or
        # abort cleanly) rather than race the teardown below.
        self.reshard.close(timeout_s=5.0)
        self._forward_pool.shutdown(wait=False)
        self._slow_pool.shutdown(wait=False)
        # Durability plane: stop the interval cadence, then take the
        # final shutdown snapshot while the store is still alive — the
        # SIGTERM/deploy path of the zero-downtime-restart contract
        # (cmd/server.py routes SIGTERM through Daemon.close to here).
        self.snapshots.stop()
        self.snapshots.save_now("close")
        # Black box last among the observability planes: the final
        # snapshot above is still capturable evidence, and the default
        # recorder's hook must be unhooked or a dead service would keep
        # writing bundles on other daemons' triggers.
        try:
            tracing.default_recorder().dump_hooks.remove(
                self.blackbox.on_trigger
            )
        except ValueError:
            pass
        self.blackbox.close()
        if self.conf.loader is not None:
            self.conf.loader.save(self.store.snapshot_items())
        for peer in self.get_peer_list() + list(self.region_picker.peers()):
            if isinstance(peer, PeerClient):
                peer.shutdown(timeout_s=1.0)


def _n_local_devices(devices) -> int:
    if devices is not None:
        return max(len(devices), 1)
    import jax

    return max(len(jax.devices()), 1)


class GlobalManager:
    """Host-tier GLOBAL pipelines (global.go:32-243) on top of the
    device-tier collective sync: every GlobalSyncWait, run the on-mesh
    sync; fan out the resulting owner broadcasts (UpdatePeerGlobals) to
    every peer daemon and forward aggregated hits for remotely-owned
    keys (GetPeerRateLimits) to their owner daemons.

    Both legs are COLUMNAR and CONCURRENT (architecture.md "GLOBAL
    plane"): the sync emits column batches, the broadcast is encoded
    once (wire.BroadcastBatch) and fanned to all peers through a
    bounded pool — tick wall-time stops scaling as peers x RTT — and
    aggregated hits ride the columnar GetPeerRateLimits path as
    per-owner sub-batches.  Hits whose send provably never applied
    (unroutable owner, breaker fast-fail, connection-level not-ready)
    requeue into the next tick instead of being dropped."""

    # Requeue-carry bound (distinct keys): hits for a peer that stays
    # down accumulate here between ticks; past the cap new keys drop
    # (counted in gubernator_global_dropped_hits) — matching the
    # reference's bounded-loss posture under prolonged partition.
    HIT_CARRY_MAX = 16_384

    # Auto-sizing policy: one sync pass (device collective + host
    # fan-out) should cost <=10% of its window, clamped to [5ms, 1s].
    # The reference hardcodes 500us because its sync is a map drain
    # (config.go:113); here the honest basis is the measured in-situ
    # cost of the REAL sync passes — no synthetic measurement, no
    # extra collectives, no stall of serving traffic.  The estimator is
    # the MIN over the last SYNC_COST_SAMPLES work ticks (the bench
    # suite's best-of-N philosophy): a sync's true cost is its
    # least-contended run, and an estimator that averages in outliers
    # is unstable here because the window feeds back into the sample
    # rate — round 4 observed a single contaminated ~300ms startup
    # sample seeding an EMA whose 1s window then starved itself of the
    # work ticks needed to decay (convergence pinned at the clamp).
    # Cost increases (more keys, slower peers) still track: when every
    # recent sample rises, the min rises with the window of samples.
    SYNC_OVERHEAD_TARGET = 0.1
    SYNC_WAIT_MIN_S = 0.005
    SYNC_WAIT_MAX_S = 1.0
    SYNC_WAIT_FALLBACK_S = 0.1
    SYNC_COST_SAMPLES = 8

    @classmethod
    def window_for_cost(cls, cost_s: float) -> float:
        """The sync window this policy derives from a measured per-sync
        cost (single source of truth for the service, the bench suite,
        and the tests)."""
        return min(
            max(cost_s / cls.SYNC_OVERHEAD_TARGET, cls.SYNC_WAIT_MIN_S),
            cls.SYNC_WAIT_MAX_S,
        )

    def __init__(self, service: V1Service):
        self.service = service
        self._stopped = False
        configured = service.conf.behaviors.global_sync_wait_s
        self._auto = configured is None
        self.sync_wait_s = (
            self.SYNC_WAIT_FALLBACK_S if configured is None else configured
        )
        from collections import deque

        self.measured_sync_cost_s: Optional[float] = None
        self._sync_cost_samples: "deque[float]" = deque(
            maxlen=self.SYNC_COST_SAMPLES
        )
        self._last_sync_cost_s: Optional[float] = None
        # Requeued hit lanes awaiting the next tick: hash_key ->
        # [name, unique_key, algorithm, behavior, hits, limit,
        # duration], hits summed on merge.  Tick-thread-only state (the
        # Interval serializes run_once), so no lock.
        self._hit_carry: Dict[str, list] = {}
        # Bounded fan-out pool, created on first use (idle daemons and
        # non-GLOBAL deployments spawn no threads).
        self._fanout_pool: "Optional[ThreadPoolExecutor]" = None
        self._interval = Interval(self.sync_wait_s, self._tick)
        self._interval.next()

    def _tick(self) -> None:
        try:
            did_work = self.run_once()
            if did_work and self._auto and self._last_sync_cost_s is not None:
                self._observe_sync_cost(self._last_sync_cost_s)
        finally:
            if not self._stopped:
                self._interval.next()

    def _observe_sync_cost(self, cost_s: float) -> None:
        self._sync_cost_samples.append(cost_s)
        self.measured_sync_cost_s = min(self._sync_cost_samples)
        self.sync_wait_s = self.window_for_cost(self.measured_sync_cost_s)
        self._interval.duration_s = self.sync_wait_s

    def run_once(self) -> bool:
        """One sync pass; returns whether the sync produced host-tier
        work (the auto-tuner's signal that GLOBAL is in real use).

        Only the store sync (device collective + decode) counts as
        "sync cost" for window sizing — the peer fan-out legs below are
        dominated by network timeouts under failure, and a dead peer
        must not inflate the window for every healthy peer."""
        svc = self.service
        t0 = time.perf_counter()
        t0_ns = time.monotonic_ns()
        res = svc.store.sync_globals(svc.clock.now_ms())
        # The store reports the in-lock cost of the pass (collective +
        # decode/commit).  The wall time around the call additionally
        # contains the drain-then-lock wait — serving-pipeline
        # backpressure, not sync cost — which under load inflates the
        # auto window ~10x (it pinned cfg6's window at the 1s cap on
        # the contended CPU host).  Fall back to wall time only for
        # stores that don't report.
        cost = getattr(svc.store, "last_sync_cost_s", None)
        self._last_sync_cost_s = (
            cost if cost is not None else (time.perf_counter() - t0)
        )
        did_work = bool(res.broadcast_cols or res.remote_hit_cols)
        if res.remote_hit_cols is not None and len(res.remote_hit_cols):
            # Conservation ledger (audit.py): GLOBAL hits AGGREGATED by
            # this tick's collective — new lanes only, BEFORE the carry
            # merge below (requeued lanes were counted the tick they
            # first aggregated; counting them again would mask a
            # double-send).
            audit_mod.note(
                "global_agg_hits", int(res.remote_hit_cols.hits.sum())
            )
        # global.sync batch trace per WORK tick (PR 4 taxonomy): child
        # spans for the collective and the two fan-out legs, with the
        # per-peer peer.rpc client spans span-linked to the tick's ctx.
        tick = (
            tracing.BatchTrace(())
            if (did_work or self._hit_carry) and tracing.sampled()
            else None
        )
        tracing.batch_span(
            "global.collective", tick, t0_ns, time.monotonic_ns(),
            broadcasts=res.broadcast_count,
            hit_lanes=(
                0 if res.remote_hit_cols is None else len(res.remote_hit_cols)
            ),
        )
        hit_cols = self._take_carry_merged(res.remote_hit_cols)
        if hit_cols is not None and len(hit_cols):
            self._forward_hits(hit_cols, tick)
        if res.broadcast_cols is not None and len(res.broadcast_cols):
            self._broadcast(res.broadcast_cols, tick)
        if tick is not None:
            tracing.record_span(
                "global.sync", tick.ctx,
                start_ns=t0_ns, end_ns=time.monotonic_ns(),
                broadcasts=res.broadcast_count,
            )
        return did_work

    # ------------------------------------------------------------------
    def _get_fanout_pool(self) -> "ThreadPoolExecutor":
        # Tick-thread-only (like _hit_carry): no lock needed.
        if self._fanout_pool is None:
            self._fanout_pool = ThreadPoolExecutor(
                max_workers=max(
                    1, getattr(self.service.conf.behaviors, "global_fanout", 8)
                ),
                thread_name_prefix="global-fanout",
            )
        return self._fanout_pool

    def _broadcast(self, bcols, tick) -> None:
        """Encode the sync pass's broadcasts ONCE (wire.BroadcastBatch
        caches every encoding) and fan them out to all peers
        CONCURRENTLY through the bounded pool.  Per-peer breaker /
        backoff semantics ride unchanged inside each send
        (service._peer_send -> PeerClient._guarded_call); a peer that
        exhausts its budget triggers the flight-recorder dump path."""
        svc = self.service
        peers = [
            p for p in svc.get_peer_list()
            if not p.info.is_owner  # exclude ourselves (global.go:223-226)
        ]
        if not peers:
            return
        t0 = time.perf_counter()
        t0_ns = time.monotonic_ns()
        # Chunk at the receive-side lane cap (a full 65536-gslot table
        # going dirty in one tick outsizes one RPC); each chunk is
        # still ONE encoded batch shared by every peer.
        batches = [
            wire.BroadcastBatch(bcols.slice(lo, lo + PEER_COLUMNS_MAX_LANES))
            for lo in range(0, len(bcols), PEER_COLUMNS_MAX_LANES)
        ]
        pool = self._get_fanout_pool()
        svc.metrics.global_fanout_concurrency.set(
            min(len(peers), getattr(svc.conf.behaviors, "global_fanout", 8))
        )
        ctx = tick.ctx if tick is not None else None
        timeout = svc.conf.behaviors.global_timeout_s

        def send_all(peer) -> bool:
            ok = True
            for batch in batches:
                ok = svc._peer_send(
                    "global_broadcast",
                    partial(
                        peer.update_peer_globals_batch, batch,
                        timeout_s=timeout, trace_ctx=ctx,
                    ),
                ) and ok
            return ok

        futs = [(peer, pool.submit(send_all, peer)) for peer in peers]
        for peer, fut in futs:
            if not fut.result():
                # Flight-recorder dump (tracing._DUMP_KINDS): a peer
                # that missed a broadcast serves stale replicas until
                # the next successful tick — preserve the context.
                tracing.record_event(
                    "global-send-failed", op="global_broadcast",
                    peer=peer.info.grpc_address, items=len(bcols),
                )
        svc.metrics.broadcast_durations.observe(time.perf_counter() - t0)
        tracing.batch_span(
            "global.broadcast", tick, t0_ns, time.monotonic_ns(),
            items=len(bcols), peers=len(peers),
        )

    def _forward_hits(self, cols: "HitColumns", tick) -> None:
        """Forward aggregated hits to their remote owners as columnar
        sub-batches over the existing GetPeerRateLimits columnar path
        (sendHits, global.go:120-160), one concurrent send per owner.
        BUGFIX vs the pre-columns sender: an unroutable owner (pool
        churn mid-tick) or a provably-unapplied send failure requeues
        the lanes into the next tick instead of silently dropping
        them."""
        svc = self.service
        t0 = time.perf_counter()
        t0_ns = time.monotonic_ns()
        by_owner: Dict[str, list] = {}
        clients: Dict[str, PeerClient] = {}
        requeue: list = []
        for i in range(len(cols)):
            try:
                peer = svc.get_peer(cols.hash_key_at(i))
            except PeerError:
                requeue.append(i)
                continue
            addr = peer.info.grpc_address
            by_owner.setdefault(addr, []).append(i)
            clients[addr] = peer
        pool = self._get_fanout_pool()
        ctx = tick.ctx if tick is not None else None
        futs = {
            addr: pool.submit(
                self._send_hits, clients[addr], cols.subset(lanes), ctx
            )
            for addr, lanes in by_owner.items()
        }
        dropped = 0
        for addr, fut in futs.items():
            rq_rel, dr = fut.result()
            lanes = by_owner[addr]
            requeue.extend(lanes[j] for j in rq_rel)
            dropped += dr
            if rq_rel or dr:
                tracing.record_event(
                    "global-send-failed", op="global_hits", peer=addr,
                    requeued=len(rq_rel), dropped=dr,
                )
        if requeue:
            self._requeue_hits(cols, requeue)
        if dropped:
            svc.metrics.global_dropped_hits.inc(dropped)
        # Carry size is the documented GLOBAL bounded-loss slack; the
        # audit's global_slack invariant checks it against HIT_CARRY_MAX.
        audit_mod.set_gauge(audit_mod.GLOBAL_CARRY_GAUGE, len(self._hit_carry))
        svc.metrics.async_durations.observe(time.perf_counter() - t0)
        tracing.batch_span(
            "global.hits", tick, t0_ns, time.monotonic_ns(),
            lanes=len(cols), owners=len(by_owner),
        )

    def _send_hits(self, peer: PeerClient, sub: "HitColumns", ctx):
        """Send one owner's hit columns, chunked at the columnar lane
        cap (the client re-chunks classic-negotiated sends itself).
        Returns (lanes to requeue, lanes dropped): a chunk whose
        failure provably never applied — breaker fast-fail or a
        connection-level not-ready error — requeues; a timeout-shaped
        failure may have applied server-side, so requeueing would
        double-count and the chunk drops (counted)."""
        svc = self.service
        n = len(sub)
        pc = sub.peer_columns()
        timeout = svc.conf.behaviors.global_timeout_s
        requeue: list = []
        dropped = 0
        for lo in range(0, n, PEER_COLUMNS_MAX_LANES):
            hi = min(lo + PEER_COLUMNS_MAX_LANES, n)
            chunk = wire.peer_columns_slice(pc, lo, hi)
            t0_ns = time.monotonic_ns()
            ok, err = svc._peer_send_ex(
                "global_hits",
                partial(
                    peer.send_columns_direct, chunk,
                    timeout_s=timeout, trace_ctx=ctx,
                ),
            )
            if ctx is not None:
                bt = tracing.new_batch([ctx])
                if bt is not None:
                    attrs = dict(
                        peer=peer.info.grpc_address,
                        op="GetPeerRateLimits", leg="global_hits",
                        lanes=hi - lo,
                    )
                    if not ok:
                        attrs["error"] = str(err)
                    tracing.record_span(
                        "peer.rpc", bt.ctx,
                        start_ns=t0_ns, end_ns=time.monotonic_ns(),
                        links=bt.links, **attrs,
                    )
            chunk_hits = int(sub.hits[lo:hi].sum())
            if ok:
                # Conservation ledger: GLOBAL hits DELIVERED owner-ward
                # (sent + dropped must stay <= aggregated).
                audit_mod.note("global_sent_hits", chunk_hits)
                continue
            if is_circuit_open(err) or is_not_ready(err):
                requeue.extend(range(lo, hi))
            else:
                audit_mod.note("global_dropped_hits", chunk_hits)
                dropped += hi - lo
        return requeue, dropped

    def _requeue_hits(self, cols: "HitColumns", lanes) -> None:
        """Fold failed lanes into the carry (hits summed per key),
        bounded at HIT_CARRY_MAX distinct keys."""
        carry = self._hit_carry
        dropped = 0
        for i in lanes:
            hk = cols.hash_key_at(i)
            cur = carry.get(hk)
            if cur is not None:
                cur[4] += int(cols.hits[i])
                continue
            if len(carry) >= self.HIT_CARRY_MAX:
                dropped += 1
                audit_mod.note("global_dropped_hits", int(cols.hits[i]))
                continue
            carry[hk] = [
                cols.names[i], cols.unique_keys[i],
                int(cols.algorithm[i]), int(cols.behavior[i]),
                int(cols.hits[i]), int(cols.limit[i]),
                int(cols.duration[i]),
            ]
        requeued = len(lanes) - dropped
        if requeued:
            self.service.metrics.global_requeued_hits.inc(requeued)
        if dropped:
            self.service.metrics.global_dropped_hits.inc(dropped)

    def _take_carry_merged(
        self, new_cols: "Optional[HitColumns]"
    ) -> "Optional[HitColumns]":
        """Previous ticks' requeued hits merged with this tick's
        accumulator output: hits sum per key, config fields take the
        newest lane (last-writer-wins, like the gtable mirror)."""
        if not self._hit_carry:
            return new_cols
        carry, self._hit_carry = self._hit_carry, {}
        if new_cols is not None:
            for i in range(len(new_cols)):
                hk = new_cols.hash_key_at(i)
                cur = carry.get(hk)
                if cur is None:
                    carry[hk] = [
                        new_cols.names[i], new_cols.unique_keys[i],
                        int(new_cols.algorithm[i]), int(new_cols.behavior[i]),
                        int(new_cols.hits[i]), int(new_cols.limit[i]),
                        int(new_cols.duration[i]),
                    ]
                else:
                    cur[2] = int(new_cols.algorithm[i])
                    cur[3] = int(new_cols.behavior[i])
                    cur[4] += int(new_cols.hits[i])
                    cur[5] = int(new_cols.limit[i])
                    cur[6] = int(new_cols.duration[i])
        vals = list(carry.values())
        n = len(vals)
        return HitColumns(
            names=[v[0] for v in vals],
            unique_keys=[v[1] for v in vals],
            algorithm=np.fromiter((v[2] for v in vals), np.int32, count=n),
            behavior=np.fromiter((v[3] for v in vals), np.int32, count=n),
            hits=np.fromiter((v[4] for v in vals), np.int64, count=n),
            limit=np.fromiter((v[5] for v in vals), np.int64, count=n),
            duration=np.fromiter((v[6] for v in vals), np.int64, count=n),
        )

    def stop(self) -> None:
        self._stopped = True
        self._interval.stop()
        if self._fanout_pool is not None:
            self._fanout_pool.shutdown(wait=False)
