"""SWIM gossip membership — the member-list discovery backend.

The reference's `memberlist.go` delegates the actual membership protocol
to hashicorp/memberlist (SWIM: Scalable Weakly-consistent Infection-style
Process-group Membership) and only adapts its join/leave/update events
into `[]PeerInfo` pushes (`memberlist.go:160-233`).  That library does
not exist here, so this module implements the protocol itself over
stdlib sockets:

  * failure detection — periodic randomized probe (UDP ping -> ack) with
    indirect probes through k peers on timeout, then suspicion, then
    death (the SWIM probe cycle);
  * dissemination — membership updates (alive / suspect / dead / left)
    piggybacked on every protocol packet, each retransmitted a bounded
    number of times (infection-style broadcast);
  * refutation — a node that hears itself suspected or declared dead
    bumps its incarnation number and gossips a fresh alive;
  * anti-entropy — TCP push-pull of the full member table on join and
    periodically with a random peer, so partitions and missed gossip
    converge (memberlist's TCP state sync).

Node metadata carries the advertised `PeerInfo` as JSON, exactly like
the reference stuffs marshaled PeerInfo into node meta
(`memberlist.go:126-139`).  `GossipPool` at the bottom is the
`MemberListPool` equivalent: same config surface
(advertise/address/known-nodes/node-name, `memberlist.go:44-66`), same
300ms join retry (`memberlist.go:135-142`), and an `on_update` callback
receiving the full peer list on every membership change
(`memberlist.go:223-233`).
"""

from __future__ import annotations

import json
import logging
import random
import socket
import socketserver
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from . import faults as faults_mod
from .types import PeerInfo

log = logging.getLogger("gubernator.gossip")

ALIVE = "alive"
SUSPECT = "suspect"
DEAD = "dead"
LEFT = "left"

# How many piggybacked updates fit in one packet, and how many times each
# update is retransmitted (hashicorp scales this by log(n); a constant is
# plenty at rate-limiter cluster sizes).
MAX_PIGGYBACK = 8
RETRANSMIT = 5

# Gossip wire version, stamped on every UDP packet and push-pull frame.
# INTEROP CONTRACT (see README "Peer discovery"): this JSON/UDP wire is
# NOT hashicorp/memberlist-compatible — a node here cannot join a
# reference cluster's port-7946 gossip (memberlist.go:68-151 uses
# msgpack framing + gob/JSON node meta).  Membership migration between
# the two therefore goes through the static/etcd/k8s backends, not
# mixed gossip.  Within THIS wire, compatibility is by tolerance:
# receivers ignore unknown top-level message types, unknown update
# states, and unknown fields (version skew between nodes must never
# break membership — pinned by tests/test_gossip.py version-skew tests).
# Bump only for semantic changes; never gate handling on an exact match.
WIRE_VERSION = 1


@dataclass
class Member:
    name: str
    host: str
    port: int
    incarnation: int = 0
    state: str = ALIVE
    meta: dict = field(default_factory=dict)
    state_at: float = 0.0  # monotonic time of the last state change

    @property
    def addr(self) -> Tuple[str, int]:
        return (self.host, self.port)

    def to_update(self) -> dict:
        u = {
            "s": self.state,
            "name": self.name,
            "addr": [self.host, self.port],
            "inc": self.incarnation,
        }
        if self.state == ALIVE:
            u["meta"] = self.meta
        return u


class Gossip:
    """One SWIM node: UDP probe/gossip plane + TCP push-pull plane."""

    def __init__(
        self,
        bind_address: str,
        name: str = "",
        meta: Optional[dict] = None,
        on_change: Optional[Callable[[List[Member]], None]] = None,
        probe_interval_s: float = 1.0,
        probe_timeout_s: float = 0.5,
        suspect_timeout_s: float = 3.0,
        sync_interval_s: float = 10.0,
        k_indirect: int = 3,
        seed: Optional[int] = None,
        faults: Optional["faults_mod.FaultPlan"] = None,
    ):
        host, _, port = bind_address.partition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port or 7946)
        self.meta = dict(meta or {})
        self.on_change = on_change
        # Probe-order / helper-pick / sync-pick RNG.  Seeded, the SWIM
        # probe schedule replays deterministically, so chaos tests of
        # suspect/confirm transitions are reproducible (faults.py).
        # None keeps the historical per-node unseeded behavior.
        self._rng = random.Random(seed)
        # Fault-injection hook (faults.FaultPlan, op "gossip.probe"):
        # None = honor the process-wide faults.install() plan.
        self.faults = faults
        self.probe_interval_s = probe_interval_s
        self.probe_timeout_s = probe_timeout_s
        self.suspect_timeout_s = suspect_timeout_s
        self.sync_interval_s = sync_interval_s
        self.k_indirect = k_indirect

        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._seq = 0
        self._acks: Dict[int, threading.Event] = {}
        self._piggyback: List[List] = []  # [update, transmits_left]
        self._probe_ring: List[str] = []

        # The gossip plane needs the SAME port on UDP (probe/gossip) and
        # TCP (push-pull).  With port 0 the kernel picks the UDP port
        # first and the TCP bind can lose a race against an unrelated
        # process, so retry with a fresh ephemeral pair.
        for attempt in range(16):
            self._udp = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            self._udp.bind((self.host, self.port))
            port = self._udp.getsockname()[1]  # resolve port 0
            try:
                self._tcp = socketserver.ThreadingTCPServer(
                    (self.host, port), _PushPullHandler, bind_and_activate=False
                )
                self._tcp.allow_reuse_address = True
                self._tcp.daemon_threads = True
                self._tcp.server_bind()
                self._tcp.server_activate()
                break
            except OSError:
                self._udp.close()
                if self.port != 0 or attempt == 15:
                    raise
        self.port = port
        self.name = name or f"{self.host}:{self.port}"

        self._me = Member(
            name=self.name, host=self.host, port=self.port,
            incarnation=1, meta=self.meta, state_at=time.monotonic(),
        )
        self._members: Dict[str, Member] = {self.name: self._me}
        self._tcp.gossip = self  # type: ignore[attr-defined]

        self._threads = [
            threading.Thread(target=self._udp_loop, daemon=True),
            threading.Thread(target=self._tcp.serve_forever, daemon=True,
                             kwargs={"poll_interval": 0.1}),
            threading.Thread(target=self._probe_loop, daemon=True),
            threading.Thread(target=self._sync_loop, daemon=True),
        ]
        for t in self._threads:
            t.start()

    # ------------------------------------------------------------------
    # Public surface
    # ------------------------------------------------------------------
    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def members(self) -> List[Member]:
        """Alive + suspect members (suspects are still members until the
        suspicion timeout expires, as in SWIM)."""
        with self._lock:
            return [
                Member(**{**m.__dict__}) for m in self._members.values()
                if m.state in (ALIVE, SUSPECT)
            ]

    def join(self, seeds: Sequence[str], timeout_s: float = 10.0) -> int:
        """Push-pull with each seed until one answers; retry every 300ms
        until the deadline (memberlist.go:135-142).  Returns how many
        seeds answered."""
        deadline = time.monotonic() + timeout_s
        while not self._stop.is_set():
            joined = 0
            for seed in seeds:
                h, _, p = seed.partition(":")
                try:
                    self._push_pull((h, int(p or 7946)))
                    joined += 1
                except OSError as e:
                    log.debug("join %s failed: %s", seed, e)
            if joined:
                return joined
            if time.monotonic() >= deadline:
                raise TimeoutError(f"unable to join any of {list(seeds)}")
            time.sleep(0.3)
        return 0

    def set_meta(self, meta: dict) -> None:
        """Update advertised metadata: bump incarnation, gossip alive
        (memberlist UpdateNode)."""
        with self._lock:
            self.meta = dict(meta)
            self._me.meta = self.meta
            self._me.incarnation += 1
            self._queue_update(self._me.to_update())
        self._notify()

    def leave(self) -> None:
        """Broadcast a graceful leave before shutdown."""
        with self._lock:
            self._me.state = LEFT
            self._me.incarnation += 1
            update = self._me.to_update()
            self._queue_update(update)
            targets = [m for m in self._members.values()
                       if m.state == ALIVE and m.name != self.name]
        # Push the leave explicitly in every datagram — the piggyback
        # queue carries only RETRANSMIT credits, so in clusters larger
        # than that the later targets would receive an empty packet and
        # only learn of the departure via the probe/suspect/dead cycle.
        payload = json.dumps({"t": "gossip", "g": [update]}).encode()
        for m in targets:
            try:
                self._udp.sendto(payload, m.addr)
            except OSError:
                pass

    def close(self) -> None:
        if self._stop.is_set():
            return
        self._stop.set()
        try:
            self._tcp.shutdown()
            self._tcp.server_close()
        except OSError:
            pass
        try:
            # Unblock the UDP recv loop (send to the actual bound
            # address — loopback would miss a socket bound elsewhere).
            self._udp.sendto(b"{}", self._udp.getsockname())
        except OSError:
            pass
        self._udp.close()

    # ------------------------------------------------------------------
    # Wire helpers
    # ------------------------------------------------------------------
    def _send(self, addr: Tuple[str, int], msg: dict) -> None:
        msg = dict(msg, v=WIRE_VERSION)
        with self._lock:
            gossip = []
            for entry in self._piggyback[:MAX_PIGGYBACK]:
                gossip.append(entry[0])
                entry[1] -= 1
            self._piggyback = [e for e in self._piggyback if e[1] > 0]
        if gossip:
            msg["g"] = gossip
        try:
            self._udp.sendto(json.dumps(msg).encode(), addr)
        except OSError:
            pass

    def _queue_update(self, update: dict) -> None:
        # Replace any queued update about the same node: the newest state
        # supersedes older gossip.
        self._piggyback = [e for e in self._piggyback if e[0]["name"] != update["name"]]
        self._piggyback.append([update, RETRANSMIT])

    # ------------------------------------------------------------------
    # UDP plane
    # ------------------------------------------------------------------
    def _udp_loop(self) -> None:
        while not self._stop.is_set():
            try:
                data, addr = self._udp.recvfrom(65536)
            except OSError:
                return
            try:
                msg = json.loads(data.decode())
            except (UnicodeDecodeError, json.JSONDecodeError):
                continue
            for update in msg.get("g", []):
                self._handle_update(update)
            t = msg.get("t")
            if t == "ping":
                self._send(addr, {"t": "ack", "seq": msg.get("seq", 0)})
            elif t == "ack":
                ev = self._acks.get(msg.get("seq", 0))
                if ev is not None:
                    ev.set()
            elif t == "ping-req":
                # Probe the target on behalf of the asker (SWIM indirect).
                # Must NOT block this loop: _ping waits for an ack that
                # only this loop can deliver.
                target = tuple(msg.get("target", ()))
                if len(target) == 2:
                    threading.Thread(
                        target=self._indirect_probe,
                        args=(addr, target, msg.get("seq", 0)),
                        daemon=True,
                    ).start()

    def _indirect_probe(self, asker: Tuple[str, int], target: Tuple[str, int], seq: int) -> None:
        if self._ping(target):
            self._send(asker, {"t": "ack", "seq": seq})

    def _ping(self, addr: Tuple[str, int], timeout_s: Optional[float] = None) -> bool:
        # Fault-injection point (faults.OP_GOSSIP_PROBE): a DROP/ERROR
        # rule makes the ping count as lost — the caller proceeds to
        # indirect probe / suspicion exactly as if the packet vanished
        # on the wire.  DELAY models a slow link, so it EATS the ack
        # budget: an injected delay >= the probe timeout is a timed-out
        # probe (returned lost immediately, no real sleep — chaos tests
        # of latency-induced suspicion stay deterministic-fast), and a
        # smaller delay leaves only the remainder for the ack wait.
        timeout = timeout_s or self.probe_timeout_s
        fp = self.faults if self.faults is not None else faults_mod.active()
        if fp is not None:
            # DUPLICATE rules are aimed at hit-carrying data-plane RPCs
            # (a duplicated ping is indistinguishable from a ping);
            # excluded BEFORE matching so a probe can't burn the rule's
            # fired_count/rate accounting.
            act = fp.intercept(
                f"{addr[0]}:{addr[1]}", faults_mod.OP_GOSSIP_PROBE,
                exclude=(faults_mod.DUPLICATE,),
            )
            if act is not None:
                if act.kind != faults_mod.DELAY:
                    return False
                if act.delay_s >= timeout:
                    return False
                time.sleep(act.delay_s)
                timeout -= act.delay_s
        with self._lock:
            self._seq += 1
            seq = self._seq
        ev = threading.Event()
        self._acks[seq] = ev
        try:
            self._send(addr, {"t": "ping", "seq": seq})
            return ev.wait(timeout)
        finally:
            self._acks.pop(seq, None)

    # ------------------------------------------------------------------
    # Probe cycle
    # ------------------------------------------------------------------
    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            self._expire_suspects()
            target = self._next_probe_target()
            if target is None:
                continue
            if self._ping(target.addr):
                continue
            # Indirect probe through k random other members.
            with self._lock:
                others = [
                    m for m in self._members.values()
                    if m.state == ALIVE and m.name not in (self.name, target.name)
                ]
            helpers = self._rng.sample(others, min(self.k_indirect, len(others)))
            with self._lock:
                self._seq += 1
                seq = self._seq
            ev = threading.Event()
            self._acks[seq] = ev
            try:
                for h in helpers:
                    self._send(
                        h.addr,
                        {"t": "ping-req", "seq": seq, "target": list(target.addr)},
                    )
                if helpers and ev.wait(self.probe_timeout_s * 2):
                    continue
            finally:
                self._acks.pop(seq, None)
            self._suspect(target)

    def _next_probe_target(self) -> Optional[Member]:
        """Randomized round-robin over the membership (SWIM's shuffled
        ring gives bounded detection time)."""
        with self._lock:
            while self._probe_ring:
                name = self._probe_ring.pop()
                m = self._members.get(name)
                if m is not None and m.state in (ALIVE, SUSPECT) and name != self.name:
                    return m
            names = [
                n for n, m in self._members.items()
                if m.state in (ALIVE, SUSPECT) and n != self.name
            ]
            self._rng.shuffle(names)
            self._probe_ring = names
            if not self._probe_ring:
                return None
            return self._members.get(self._probe_ring.pop())

    def _suspect(self, target: Member) -> None:
        changed = False
        with self._lock:
            m = self._members.get(target.name)
            if m is not None and m.state == ALIVE:
                m.state = SUSPECT
                m.state_at = time.monotonic()
                self._queue_update(m.to_update())
                changed = True
        if changed:
            log.debug("%s: suspect %s", self.name, target.name)

    def _expire_suspects(self) -> None:
        now = time.monotonic()
        expired = []
        with self._lock:
            for m in self._members.values():
                if m.state == SUSPECT and now - m.state_at > self.suspect_timeout_s:
                    m.state = DEAD
                    m.state_at = now
                    self._queue_update(m.to_update())
                    expired.append(m.name)
        if expired:
            log.debug("%s: dead %s", self.name, expired)
            self._notify()

    # ------------------------------------------------------------------
    # Update dissemination
    # ------------------------------------------------------------------
    def _handle_update(self, u: dict) -> None:
        try:
            state = u["s"]
            name = u["name"]
            inc = int(u["inc"])
            host, port = u["addr"]
        except (KeyError, ValueError, TypeError):
            return
        changed = False
        with self._lock:
            if name == self.name:
                # Refute rumors about ourselves (SWIM refutation).  LEFT
                # must be refuted too: a restarted node that reuses its
                # name hears its own stale leave echoed back in push-pull
                # state and must out-increment it to become visible again.
                if state in (SUSPECT, DEAD, LEFT) and inc >= self._me.incarnation:
                    self._me.incarnation = inc + 1
                    self._queue_update(self._me.to_update())
                return
            m = self._members.get(name)
            if state == ALIVE:
                if m is None:
                    m = Member(
                        name=name, host=host, port=int(port), incarnation=inc,
                        state=ALIVE, meta=u.get("meta", {}), state_at=time.monotonic(),
                    )
                    self._members[name] = m
                    self._queue_update(m.to_update())
                    changed = True
                elif inc > m.incarnation:
                    revived = m.state != ALIVE
                    meta_changed = u.get("meta", m.meta) != m.meta
                    m.incarnation = inc
                    m.state = ALIVE
                    m.host, m.port = host, int(port)
                    m.meta = u.get("meta", m.meta)
                    m.state_at = time.monotonic()
                    self._queue_update(m.to_update())
                    changed = revived or meta_changed
            elif state == SUSPECT:
                if m is not None and m.state == ALIVE and inc >= m.incarnation:
                    m.state = SUSPECT
                    m.incarnation = inc
                    m.state_at = time.monotonic()
                    self._queue_update(m.to_update())
            elif state in (DEAD, LEFT):
                if m is not None and m.state in (ALIVE, SUSPECT) and inc >= m.incarnation:
                    m.state = state
                    m.incarnation = inc
                    m.state_at = time.monotonic()
                    self._queue_update(m.to_update())
                    changed = True
        if changed:
            self._notify()

    def _notify(self) -> None:
        if self.on_change is None:
            return
        try:
            self.on_change(self.members())
        except Exception:  # noqa: BLE001 — a bad callback must not kill the protocol
            log.exception("on_change callback failed")

    # ------------------------------------------------------------------
    # TCP push-pull (anti-entropy)
    # ------------------------------------------------------------------
    def _state_snapshot(self) -> List[dict]:
        with self._lock:
            return [m.to_update() for m in self._members.values()]

    def merge_state(self, updates: Sequence[dict]) -> None:
        for u in updates:
            self._handle_update(u)

    def _push_pull(self, addr: Tuple[str, int]) -> None:
        with socket.create_connection(addr, timeout=2.0) as sock:
            f = sock.makefile("rw", encoding="utf-8")
            f.write(json.dumps(
                {"t": "push-pull", "v": WIRE_VERSION, "m": self._state_snapshot()}
            ) + "\n")
            f.flush()
            line = f.readline()
        if line:
            msg = json.loads(line)
            self.merge_state(msg.get("m", []))

    def _sync_loop(self) -> None:
        while not self._stop.wait(self.sync_interval_s):
            with self._lock:
                others = [m for m in self._members.values()
                          if m.state == ALIVE and m.name != self.name]
            if not others:
                continue
            pick = self._rng.choice(others)
            try:
                self._push_pull(pick.addr)
            except (OSError, json.JSONDecodeError):
                continue


class _PushPullHandler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        gossip: Gossip = self.server.gossip  # type: ignore[attr-defined]
        try:
            line = self.rfile.readline()
            if not line:
                return
            msg = json.loads(line)
            self.wfile.write(
                (json.dumps({
                    "t": "push-pull", "v": WIRE_VERSION,
                    "m": gossip._state_snapshot(),
                }) + "\n").encode()
            )
            gossip.merge_state(msg.get("m", []))
        except (OSError, json.JSONDecodeError, UnicodeDecodeError):
            return


# ----------------------------------------------------------------------
# The discovery pool (MemberListPool equivalent)
# ----------------------------------------------------------------------
class GossipPool:
    """member-list discovery backend (reference MemberListPool,
    memberlist.go:38-151): gossip node metadata = advertised PeerInfo;
    every membership change pushes the full `[]PeerInfo` (self included)
    through `on_update`, mirroring the event handler's peers-map rebuild
    (memberlist.go:160-233)."""

    def __init__(
        self,
        advertise: PeerInfo,
        member_list_address: str,
        on_update: Callable[[List[PeerInfo]], None],
        known_nodes: Sequence[str] = (),
        node_name: str = "",
        join_timeout_s: float = 10.0,
        probe_interval_s: float = 1.0,
        probe_timeout_s: float = 0.5,
        suspect_timeout_s: float = 3.0,
        sync_interval_s: float = 10.0,
        seed: Optional[int] = None,
        faults: Optional["faults_mod.FaultPlan"] = None,
    ):
        self.on_update = on_update
        self.gossip = Gossip(
            bind_address=member_list_address,
            name=node_name,
            meta=advertise.to_json(),
            on_change=self._on_change,
            probe_interval_s=probe_interval_s,
            probe_timeout_s=probe_timeout_s,
            suspect_timeout_s=suspect_timeout_s,
            sync_interval_s=sync_interval_s,
            seed=seed,
            faults=faults,
        )
        # Normalize seeds (default port 7946) BEFORE the self-filter: a
        # portless seed naming this host would otherwise pass the string
        # compare and "join" by push-pulling with ourselves.
        def norm(s: str) -> str:
            h, _, p = s.partition(":")
            return f"{h}:{p or 7946}"

        seeds = [norm(s) for s in known_nodes if s]
        seeds = [s for s in seeds if s != self.gossip.address]
        if seeds:
            try:
                self.gossip.join(seeds, timeout_s=join_timeout_s)
            except TimeoutError:
                self.gossip.close()
                raise
        self._on_change(self.gossip.members())

    @property
    def address(self) -> str:
        """host:port of the gossip plane (for seeding other nodes)."""
        return self.gossip.address

    def _on_change(self, members: List[Member]) -> None:
        peers = []
        for m in members:
            if m.meta.get("grpcAddress") or m.meta.get("grpc_address"):
                peers.append(PeerInfo.from_json(m.meta))
        peers.sort(key=lambda p: p.grpc_address)
        try:
            self.on_update(peers)
        except Exception:  # noqa: BLE001
            log.exception("on_update callback failed")

    def close(self) -> None:
        """Graceful leave then shutdown (memberlist.go:153-158)."""
        try:
            self.gossip.leave()
            time.sleep(0.05)  # let the leave datagrams flush
        finally:
            self.gossip.close()
