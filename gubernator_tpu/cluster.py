"""In-process test cluster: N real daemons on loopback ports.

Parity with cluster/cluster.go:82-131: every daemon gets the FULL peer
list (discovery bypassed), behavior windows are shortened for tests, and
daemons can be restarted in place.  Supports data-center labels for
multi-region tests (cluster.DataCenterNone / DataCenterOne).
"""

from __future__ import annotations

import random
from typing import List, Optional

from .config import BehaviorConfig, DaemonConfig
from .daemon import Daemon
from .types import PeerInfo
from .utils.clock import Clock

DATA_CENTER_NONE = ""
DATA_CENTER_ONE = "datacenter-1"


def fast_test_behaviors() -> BehaviorConfig:
    """Shortened windows (cluster/cluster.go:104-110).

    reshard_handoff_s=0: the double-dispatch read window after a
    membership change is OFF by default in tests — every cluster
    fixture's startup (spawn -> feed full peer list) is a membership
    change, and a 2s window of peeked reads would shadow what most
    tests mean to measure.  State transfers still run; suites that
    exercise the window set their own value
    (tests/test_reshard_chaos.py)."""
    return BehaviorConfig(
        global_sync_wait_s=0.05,
        global_timeout_s=5.0,
        batch_timeout_s=5.0,
        multi_region_sync_wait_s=0.05,
        multi_region_timeout_s=5.0,
        reshard_handoff_s=0.0,
    )


class Cluster:
    def __init__(self):
        self.daemons: List[Daemon] = []
        self.peers: List[PeerInfo] = []

    def start(self, n: int, clock: Optional[Clock] = None) -> "Cluster":
        return self.start_with([DATA_CENTER_NONE] * n, clock=clock)

    def start_with(
        self,
        data_centers: List[str],
        clock: Optional[Clock] = None,
        cache_size: int = 4096,
        g_capacity: int = 256,
        behaviors: Optional[BehaviorConfig] = None,
        native_http: Optional[bool] = None,
    ) -> "Cluster":
        """cluster/cluster.go:96-131: spawn every daemon, then feed the
        full converged peer list to all of them.  `behaviors` overrides
        the shortened test windows (e.g. benchmarks on a tunnel-attached
        device need peer RPC deadlines sized to its 100-400ms rounds,
        the same GUBER_BATCH_TIMEOUT tuning a real deployment does)."""
        for dc in data_centers:
            conf = DaemonConfig(
                listen_address="127.0.0.1:0",
                grpc_listen_address="127.0.0.1:0",
                cache_size=cache_size,
                global_cache_size=g_capacity,
                data_center=dc,
                behaviors=behaviors or fast_test_behaviors(),
                peer_discovery_type="static",
                native_http=native_http,
            )
            d = Daemon(conf, clock=clock).start()
            self.daemons.append(d)
        self.peers = [d.peer_info for d in self.daemons]
        for d in self.daemons:
            d.set_peers(self.peers)
        return self

    # ------------------------------------------------------------------
    def peer_at(self, idx: int) -> PeerInfo:
        return self.peers[idx]

    def daemon_at(self, idx: int) -> Daemon:
        return self.daemons[idx]

    def get_random_peer(self, data_center: str = DATA_CENTER_NONE) -> PeerInfo:
        """cluster/cluster.go:40-54."""
        candidates = [p for p in self.peers if p.data_center == data_center]
        if not candidates:
            raise RuntimeError(f"no peers in data center '{data_center}'")
        return random.choice(candidates)

    def daemon_for(self, peer: PeerInfo) -> Daemon:
        for d in self.daemons:
            if d.peer_info.grpc_address == peer.grpc_address:
                return d
        raise KeyError(peer.grpc_address)

    def restart(self, idx: int, clock: Optional[Clock] = None) -> None:
        """cluster/cluster.go:87-93: close and respawn at the same addresses."""
        import dataclasses

        old = self.daemons[idx]
        info = old.peer_info
        old.close()
        # replace() carries EVERY config field (a field-by-field rebuild
        # silently dropped native_http/back_cache_size on restart).
        conf = dataclasses.replace(
            old.conf,
            listen_address=info.http_address,
            grpc_listen_address=info.grpc_address,
            peer_discovery_type="static",
        )
        d = Daemon(conf, clock=clock or old.clock).start()
        self.daemons[idx] = d
        self.peers[idx] = d.peer_info
        for dm in self.daemons:
            dm.set_peers(self.peers)

    def stop(self) -> None:
        for d in self.daemons:
            d.close()
        self.daemons = []
        self.peers = []
