"""Host-side key -> device-slot table with LRU eviction and expiry recycling.

This replaces the reference's LRU cache (`cache.go:52-218`) for the TPU
design: the *values* (bucket states) live on device as integer columns;
the host keeps only the string-key -> dense-slot mapping, an expiry
mirror (refreshed from kernel outputs each batch), and LRU order for
eviction when the slot pool is exhausted.

Semantics parity:
  * expired item == miss, slot recycled in place     (cache.go:138-163)
  * LRU eviction when at capacity                    (cache.go:115-130)
  * hit/miss/size accounting for metrics             (cache.go:88-92,205-218)

The C++ twin (native/host_runtime.cpp) additionally tracks in-flight
pipelined device writes (pending_write) and skips those slots when
evicting.  This table has no such state because the pipelined columnar
path requires the native runtime — on every state reachable through
this class the two implementations behave identically (verified by the
parity tests in tests/test_native.py).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class SlotTable:
    def __init__(self, capacity: int):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self._key_to_slot: Dict[str, int] = {}
        self._slot_to_key: List[Optional[str]] = [None] * capacity
        # Host mirror of device expire_at, updated from kernel outputs.
        self.expire_ms = np.zeros(capacity, dtype=np.int64)
        self._free: List[int] = list(range(capacity - 1, -1, -1))
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        # Mapping-change generation (C++ twin: Table::map_generation):
        # bumped on every key->slot mapping change (assign, remap,
        # evict, remove) but NOT on in-place expiry reuse or expire
        # writes.  Equal reads across two points in time guarantee the
        # mapping is unchanged between them (the GLOBAL sync fast path).
        self.generation = 0

    def __len__(self) -> int:
        return len(self._key_to_slot)

    def key_of(self, slot: int) -> Optional[str]:
        return self._slot_to_key[slot]

    def get_slot(self, key: str) -> Optional[int]:
        return self._key_to_slot.get(key)

    def lookup_or_assign(self, key: str, now_ms: int) -> Tuple[int, bool]:
        """Return (slot, exists).  exists=False means the kernel should treat
        the slot as a fresh create (miss or expired-in-place)."""
        slot = self._key_to_slot.get(key)
        if slot is not None:
            self._lru.move_to_end(slot)
            # Strict expiry: an item at exactly ExpireAt is still a hit
            # (cache.go:151 `ExpireAt < now`).
            if self.expire_ms[slot] >= now_ms:
                self.hits += 1
                return slot, True
            # Expired: same key recycles its own slot (cache.go:138-163).
            self.misses += 1
            return slot, False
        self.misses += 1
        if self._free:
            slot = self._free.pop()
        else:
            # Evict least-recently-used (cache.go:115-130).
            slot, _ = self._lru.popitem(last=False)
            old_key = self._slot_to_key[slot]
            if old_key is not None:
                del self._key_to_slot[old_key]
            self.evictions += 1
        self._key_to_slot[key] = slot
        self._slot_to_key[slot] = key
        self.expire_ms[slot] = 0
        self._lru[slot] = None
        self._lru.move_to_end(slot)
        self.generation += 1
        return slot, False

    def commit(
        self,
        slots: Sequence[int],
        new_expire_ms: Sequence[int],
        removed: Sequence[bool],
        keys: Optional[Sequence[str]] = None,
    ) -> None:
        """Fold kernel outputs back into the host mirror; free removed slots.

        `keys` guards against stale lanes: if eviction during the same
        batch remapped a slot to a different key after this lane was
        scheduled, the lane's result must NOT touch the slot's new owner
        (the evicted lane's state is simply dropped, matching sequential
        evict semantics).
        """
        for i, (slot, exp, rm) in enumerate(zip(slots, new_expire_ms, removed)):
            if slot < 0:
                continue
            if keys is not None and self._slot_to_key[slot] != keys[i]:
                if self._slot_to_key[slot] is None and not rm:
                    # Remove-then-recreate chain: an earlier lane's
                    # RESET_REMAINING freed the slot and a later round
                    # recreated this key on device — re-map it (the C++
                    # twin does the same, gt_batch_commit_plan).
                    if keys[i] in self._key_to_slot:
                        continue  # key meanwhile mapped elsewhere
                    self._key_to_slot[keys[i]] = slot
                    self._slot_to_key[slot] = keys[i]
                    self.expire_ms[slot] = exp
                    self.generation += 1
                    # The slot was appended to _free by this very
                    # commit loop's remove leg — O(1) pop from the end
                    # in the common case, cold linear scan otherwise.
                    if self._free and self._free[-1] == slot:
                        self._free.pop()
                    else:
                        try:
                            self._free.remove(slot)
                        except ValueError:
                            pass
                    self._lru[slot] = None
                    self._lru.move_to_end(slot)
                continue  # otherwise: slot remapped mid-batch; lane is stale
            if rm:
                self.remove_slot(slot)
            else:
                self.expire_ms[slot] = exp

    def set_expire(self, slot: int, expire_ms: int) -> None:
        self.expire_ms[slot] = expire_ms

    def remove_slot(self, slot: int) -> None:
        key = self._slot_to_key[slot]
        if key is None:
            return
        del self._key_to_slot[key]
        self._slot_to_key[slot] = None
        self.expire_ms[slot] = 0
        self._lru.pop(slot, None)
        self._free.append(slot)
        self.generation += 1

    def remove(self, key: str) -> None:
        slot = self._key_to_slot.get(key)
        if slot is not None:
            self.remove_slot(slot)

    def keys(self) -> List[str]:
        return list(self._key_to_slot.keys())
