"""Single-shard bucket store: host slot table + device state columns.

One ShardStore is the TPU-native unit that replaces a reference peer's
`LRUCache` + mutex + per-request algorithm call (`gubernator.go:335-354`):
a whole batch of requests is resolved to device slots host-side, then
evaluated in one jitted kernel call per duplicate-round.

Request order within a batch is preserved for duplicate keys (the k-th
request for a key sees the state left by the (k-1)-th), matching the
reference's mutex serialization (gubernator.go:336-337).
"""

from __future__ import annotations

import datetime as _dt
from dataclasses import dataclass
from typing import List, Optional, Sequence

import jax
import numpy as np

from ..ops import buckets
from ..types import (
    Algorithm,
    Behavior,
    RateLimitRequest,
    RateLimitResponse,
    has_behavior,
)
from ..utils import gregorian
from .slot_table import SlotTable

# Batches are padded to one of these lane counts to bound XLA recompiles.
_PAD_SIZES = (64, 256, 1024, 4096, 16384, 65536, 262144, 1048576)


def _pad_size(n: int) -> int:
    for p in _PAD_SIZES:
        if n <= p:
            return p
    return ((n + _PAD_SIZES[-1] - 1) // _PAD_SIZES[-1]) * _PAD_SIZES[-1]


@dataclass
class _Prepared:
    """A request resolved host-side, ready for kernel dispatch."""

    pos: int
    slot: int
    exists: bool
    req: RateLimitRequest
    greg_expire: int = 0
    greg_duration: int = 0


class ShardStore:
    """Bucket table for one shard, pinned to (at most) one device."""

    def __init__(self, capacity: int = 50_000, device: Optional[jax.Device] = None):
        self.capacity = capacity
        self.table = SlotTable(capacity)
        self.device = device
        state = buckets.init_state(capacity)
        if device is not None:
            state = jax.device_put(state, device)
        self.state = state
        # host mirror of per-slot algorithm, for store-SPI removal detection
        self.algo_mirror = np.zeros(capacity, dtype=np.int32)

    # ------------------------------------------------------------------
    def apply(
        self, requests: Sequence[RateLimitRequest], now_ms: int
    ) -> List[RateLimitResponse]:
        """Evaluate a batch; responses come back in request order."""
        n = len(requests)
        responses: List[Optional[RateLimitResponse]] = [None] * n
        prepared: List[_Prepared] = []
        now_dt = _dt.datetime.fromtimestamp(now_ms / 1000.0, tz=_dt.timezone.utc)

        # now_dt is fixed for the whole batch, so Gregorian math depends
        # only on req.duration — memoize the (at most 6) distinct values.
        greg_cache: dict = {}

        for pos, req in enumerate(requests):
            p = _Prepared(pos=pos, slot=-1, exists=False, req=req)
            if has_behavior(req.behavior, Behavior.DURATION_IS_GREGORIAN):
                if req.duration not in greg_cache:
                    try:
                        greg_cache[req.duration] = (
                            gregorian.gregorian_expiration(now_dt, req.duration),
                            gregorian.gregorian_duration(now_dt, req.duration),
                        )
                    except gregorian.GregorianError as e:
                        greg_cache[req.duration] = e
                cached = greg_cache[req.duration]
                if isinstance(cached, gregorian.GregorianError):
                    responses[pos] = RateLimitResponse(error=str(cached))
                    continue
                p.greg_expire, p.greg_duration = cached
            prepared.append(p)

        # Build rounds incrementally in request order.  A round must have
        # unique keys AND unique slots (the scatter is race-free only
        # then); a duplicate flushes the pending round first so the k-th
        # request for a key observes the (k-1)-th's committed state —
        # the vectorized equivalent of the reference's mutex
        # serialization (gubernator.go:336-337).  A slot collision can
        # only happen when LRU eviction under capacity pressure reuses a
        # slot already scheduled this round; flushing first preserves
        # sequential evict-then-create semantics.
        cur: List[_Prepared] = []
        seen_keys: set = set()
        used_slots: set = set()

        def flush():
            nonlocal cur, seen_keys, used_slots
            if cur:
                self._run_round(cur, now_ms, responses)
            cur, seen_keys, used_slots = [], set(), set()

        for p in prepared:
            key = p.req.hash_key()
            if key in seen_keys:
                flush()
            p.slot, p.exists = self.table.lookup_or_assign(key, now_ms)
            if p.slot in used_slots:
                flush()
            cur.append(p)
            seen_keys.add(key)
            used_slots.add(p.slot)
        flush()

        return [r if r is not None else RateLimitResponse() for r in responses]

    # ------------------------------------------------------------------
    def _run_round(
        self, chunk: List[_Prepared], now_ms: int, responses: List[Optional[RateLimitResponse]]
    ) -> None:
        b = len(chunk)
        padded = _pad_size(b)
        slot = np.full(padded, -1, dtype=np.int32)
        exists = np.zeros(padded, dtype=bool)
        algo = np.zeros(padded, dtype=np.int32)
        behavior = np.zeros(padded, dtype=np.int32)
        hits = np.zeros(padded, dtype=np.int64)
        limit = np.zeros(padded, dtype=np.int64)
        duration = np.zeros(padded, dtype=np.int64)
        greg_expire = np.zeros(padded, dtype=np.int64)
        greg_duration = np.zeros(padded, dtype=np.int64)

        for i, p in enumerate(chunk):
            slot[i] = p.slot
            exists[i] = p.exists
            algo[i] = int(p.req.algorithm)
            behavior[i] = int(p.req.behavior)
            hits[i] = p.req.hits
            limit[i] = p.req.limit
            duration[i] = p.req.duration
            greg_expire[i] = p.greg_expire
            greg_duration[i] = p.greg_duration

        batch = buckets.make_batch(
            slot, exists, algo, behavior, hits, limit, duration, greg_expire, greg_duration
        )
        self.state, out = buckets.apply_batch_jit(self.state, batch, now_ms)

        out_status = np.asarray(out.status)
        out_rem = np.asarray(out.remaining)
        out_reset = np.asarray(out.reset_time)
        out_exp = np.asarray(out.new_expire)
        out_removed = np.asarray(out.removed)

        self.table.commit(slot[:b], out_exp[:b], out_removed[:b])
        for i, p in enumerate(chunk):
            self.algo_mirror[p.slot] = int(p.req.algorithm)
            responses[p.pos] = RateLimitResponse(
                status=int(out_status[i]),
                limit=int(p.req.limit),
                remaining=int(out_rem[i]),
                reset_time=int(out_reset[i]),
            )

    # ------------------------------------------------------------------
    def size(self) -> int:
        return len(self.table)
