"""Single-shard bucket store: host slot table + device state columns.

One ShardStore is the TPU-native unit that replaces a reference peer's
`LRUCache` + mutex + per-request algorithm call (`gubernator.go:335-354`):
a whole batch of requests is resolved to device slots host-side, then
evaluated in one jitted kernel call per duplicate-round.

Request order within a batch is preserved for duplicate keys (the k-th
request for a key sees the state left by the (k-1)-th), matching the
reference's mutex serialization (gubernator.go:336-337).
"""

from __future__ import annotations

import datetime as _dt
import threading
import time
from collections import deque
from dataclasses import dataclass
from functools import partial
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from .. import audit
from .. import native
from .. import profiling
from .. import saturation
from .. import telemetry
from .. import tracing
from ..ops import buckets
from ..ops import scalar as scalar_ops
from ..types import (
    Algorithm,
    Behavior,
    RateLimitRequest,
    RateLimitResponse,
    has_behavior,
)
from ..utils import gregorian
from .slot_table import SlotTable

# Batches pad to a small set of bucket sizes: each bucket is its own
# XLA program, and a program's FIRST dispatch pays a compile (or, on a
# remote device, a multi-second executable load) — so few distinct
# shapes beats tight padding.  Below 1024 buckets grow 4x (64, 256,
# 1024: padded lanes cost microseconds, and these are the sizes the
# service/peer planes hit, where a cold bucket can blow an RPC
# deadline); above 1024 they grow 2x (wasting up to half a large
# batch's scatter time would be real money).
_PAD_MIN = 64
_PAD_COARSE_MAX = 1024
_PAD_MAX = 1 << 20


def pad_size(n: int) -> int:
    p = _PAD_MIN
    while p < n and p < _PAD_COARSE_MAX:
        p <<= 2
    while p < n and p < _PAD_MAX:
        p <<= 1
    if n <= p:
        return p
    return ((n + _PAD_MAX - 1) // _PAD_MAX) * _PAD_MAX


@dataclass
class _Prepared:
    """A request resolved host-side, ready for kernel dispatch.

    gslot / cached_hint are used by the GLOBAL path (parallel/mesh.py):
    cached_hint lanes answer from the replica columns, touch no local
    bucket state, and scatter-add their hits — so they bypass the
    round-uniqueness rules entirely.
    """

    pos: int
    slot: int
    exists: bool
    req: RateLimitRequest
    key: str
    greg_expire: int = 0
    greg_duration: int = 0
    resolved: bool = False
    gslot: int = -1
    cached_hint: bool = False


class GregResolver:
    """Memoized Gregorian expiry/duration for one batch timestamp.

    now is fixed for the whole batch, so the calendar math depends only
    on req.duration — at most the 6 Gregorian interval kinds recur (the
    host analogue of algorithms.go:90-95,140-145).  `resolve` returns
    (expire_ms, duration_ms) or the GregorianError the reference would
    surface as a per-request error.
    """

    def __init__(self, now_ms: int):
        self.now_ms = now_ms
        self._now_dt: Optional[_dt.datetime] = None
        self._cache: Dict[int, object] = {}

    def resolve(self, duration: int):
        if self._now_dt is None:
            self._now_dt = _dt.datetime.fromtimestamp(
                self.now_ms / 1000.0, tz=_dt.timezone.utc
            )
        cached = self._cache.get(duration)
        if cached is None:
            try:
                cached = (
                    gregorian.gregorian_expiration(self._now_dt, duration),
                    gregorian.gregorian_duration(self._now_dt, duration),
                )
            except gregorian.GregorianError as e:
                cached = e
            self._cache[duration] = cached
        return cached


def prepare_requests(
    requests: Sequence[RateLimitRequest],
    now_ms: int,
    responses: List[Optional[RateLimitResponse]],
    positions: Optional[Sequence[int]] = None,
) -> List[_Prepared]:
    """Precompute per-request host-side values (hash key, Gregorian
    expiry/duration).  Requests with invalid Gregorian durations get
    error responses directly (reference returns the error per-request)."""
    greg = GregResolver(now_ms)
    prepared: List[_Prepared] = []

    for i, req in enumerate(requests):
        pos = positions[i] if positions is not None else i
        p = _Prepared(pos=pos, slot=-1, exists=False, req=req, key=req.hash_key())
        if has_behavior(req.behavior, Behavior.DURATION_IS_GREGORIAN):
            cached = greg.resolve(req.duration)
            if isinstance(cached, gregorian.GregorianError):
                responses[pos] = RateLimitResponse(error=str(cached))
                continue
            p.greg_expire, p.greg_duration = cached
        prepared.append(p)
    return prepared


def plan_grouped_python(table, prepared: Sequence[_Prepared], now_ms: int):
    """Full-plan twin of the C++ gt_batch_plan_grouped over a Python
    SlotTable: uniform duplicate groups (same key, identical config, no
    RESET_REMAINING) collapse into round 0 with per-lane occurrence
    indices and a single scattering (write) lane; everything else takes
    the round scheme from round 1 with the same chaining/deferral rules
    as RoundPlanner.  Mutates each _Prepared's slot/exists; returns
    (round_id, occ, write, n_rounds) arrays aligned to `prepared`.

    Used by the mesh store's fused dispatch: ALL rounds of ALL shards
    run inside one jitted program instead of one dispatch per round.
    """
    n = len(prepared)
    round_id = np.zeros(n, dtype=np.int32)
    occ = np.zeros(n, dtype=np.int32)
    write = np.zeros(n, dtype=bool)

    groups: "Dict[str, List[int]]" = {}
    for j, p in enumerate(prepared):
        if p.cached_hint:
            # Replica-cache lane: no local state touched; hits
            # accumulate by scatter-add, so no round/uniqueness rules.
            p.slot, p.exists, p.resolved = -1, False, True
            continue
        groups.setdefault(p.key, []).append(j)

    used0: set = set()
    slow: List[int] = []
    # Last key to write each slot in scheduled device order: round-0
    # groups seed it; slow lanes consult it for BOTH exists-chaining
    # and slot-takeover detection.
    slot_owner: Dict[int, str] = {}
    for key, lanes in groups.items():
        f = prepared[lanes[0]]
        uniform = not has_behavior(f.req.behavior, Behavior.RESET_REMAINING)
        for j in lanes[1:]:
            if not uniform:
                break
            q = prepared[j]
            uniform = (
                q.req.algorithm == f.req.algorithm
                and q.req.behavior == f.req.behavior
                and q.req.hits == f.req.hits
                and q.req.limit == f.req.limit
                and q.req.duration == f.req.duration
                and q.greg_expire == f.greg_expire
                and q.greg_duration == f.greg_duration
            )
        ev_before = table.evictions
        slot, exists = table.lookup_or_assign(key, now_ms)
        evicted = table.evictions != ev_before
        for j in lanes:
            prepared[j].slot = slot
            prepared[j].exists = exists
            prepared[j].resolved = True
        # An eviction may have stolen a slot from a key with earlier
        # lanes in this batch; the slow path's deferral orders it.
        if uniform and not evicted and slot not in used0:
            used0.add(slot)
            slot_owner[slot] = key
            for o, j in enumerate(lanes):
                occ[j] = o
                write[j] = o + 1 == len(lanes)
        else:
            slow.extend(lanes)

    if not slow:
        return round_id, occ, write, 1

    slow.sort()
    rnd = 1
    pending = slow
    while pending:
        seen: set = set()
        used: set = set()
        deferred: List[int] = []
        for j in pending:
            p = prepared[j]
            if p.key in seen:
                deferred.append(j)
                continue
            owner = slot_owner.get(p.slot)
            if owner is not None and owner != p.key:
                # The captured slot was taken over by ANOTHER key's
                # create (mid-batch eviction) scheduled before this
                # lane.  Running here — with either exists value —
                # would corrupt the new owner's device state.
                # Re-resolve: the table no longer maps this key, so it
                # gets a fresh slot (or evicts a different one).
                p.slot, p.exists = table.lookup_or_assign(p.key, now_ms)
            if p.slot in used:  # eviction collision: defer as-is
                deferred.append(j)
                seen.add(p.key)
                continue
            round_id[j] = rnd
            write[j] = True
            if slot_owner.get(p.slot) == p.key:
                p.exists = True  # chained: device state authoritative
            slot_owner[p.slot] = p.key
            seen.add(p.key)
            used.add(p.slot)
        pending = deferred
        rnd += 1
    return round_id, occ, write, rnd


class RoundPlanner:
    """Splits a prepared request stream into kernel rounds.

    A round must have unique keys AND unique slots (the scatter is
    race-free only then).  Duplicates are skipped-and-deferred to a later
    round so the k-th request for a key observes the (k-1)-th's committed
    state — the vectorized equivalent of the reference's mutex
    serialization (gubernator.go:336-337).  Cross-key order is NOT
    preserved (matching the reference's arbitrary goroutine fan-out
    order, gubernator.go:131-218), which keeps hot-key batches at
    max-multiplicity rounds instead of one round per duplicate.  A slot
    collision can only happen when LRU eviction under capacity pressure
    reuses a slot already scheduled in the current round; the colliding
    request keeps its captured (slot, exists) — re-resolving after the
    round would see the stale mirror the evicted lane wrote — and runs
    next round, preserving sequential evict-then-create semantics.
    """

    def __init__(
        self,
        table: SlotTable,
        prepared: Sequence[_Prepared],
        now_ms: int,
        resolver=None,
    ):
        self.table = table
        self.queue = deque(prepared)
        self.now_ms = now_ms
        # Pluggable (slot, exists) resolution — the Store SPI path wraps
        # the table lookup with store.get / remove side effects.
        self.resolver = resolver or (lambda p: table.lookup_or_assign(p.key, now_ms))

    def next_chunk(self) -> List[_Prepared]:
        cur: List[_Prepared] = []
        seen_keys: set = set()
        used_slots: set = set()
        deferred: deque = deque()
        while self.queue:
            p = self.queue.popleft()
            if p.cached_hint:
                # Replica-cache lane: no local state touched, hit
                # accumulation is scatter-add (duplicate-safe) — exempt
                # from key/slot uniqueness.
                p.slot, p.exists, p.resolved = -1, False, True
                cur.append(p)
                continue
            if p.key in seen_keys:
                deferred.append(p)  # k-th occurrence waits for commit
                continue
            if not p.resolved:
                p.slot, p.exists = self.resolver(p)
                p.resolved = True
            if p.slot in used_slots:
                # Eviction collision: defer as-is; same-key successors
                # must stay behind it.
                deferred.append(p)
                seen_keys.add(p.key)
                continue
            cur.append(p)
            seen_keys.add(p.key)
            used_slots.add(p.slot)
        self.queue = deferred
        return cur


class _Columns:
    """Request fields as contiguous arrays (one slot per valid lane)."""

    __slots__ = ("algo", "behavior", "hits", "limit", "duration",
                 "greg_expire", "greg_duration")

    def __init__(self, n: int):
        self.algo = np.empty(n, dtype=np.int32)
        self.behavior = np.empty(n, dtype=np.int32)
        self.hits = np.empty(n, dtype=np.int64)
        self.limit = np.empty(n, dtype=np.int64)
        self.duration = np.empty(n, dtype=np.int64)
        self.greg_expire = np.zeros(n, dtype=np.int64)
        self.greg_duration = np.zeros(n, dtype=np.int64)

    def set(self, j: int, req: RateLimitRequest, ge: int, gd: int) -> None:
        self.algo[j] = int(req.algorithm)
        self.behavior[j] = int(req.behavior)
        self.hits[j] = req.hits
        self.limit[j] = req.limit
        self.duration[j] = req.duration
        self.greg_expire[j] = ge
        self.greg_duration[j] = gd

    def trim(self, m: int) -> None:
        for f in self.__slots__:
            setattr(self, f, getattr(self, f)[:m])


_I32_MAX = (1 << 31) - 1


def _pad(src: np.ndarray, padded: int, dtype) -> np.ndarray:
    out = np.zeros(padded, dtype=dtype)
    out[: len(src)] = src
    return out


def narrow_ok(cols: "_Columns", now_ms: int) -> bool:
    """True when every value column fits the int32 wire
    (buckets.apply_rounds32 preconditions)."""
    hi = _I32_MAX
    for a in (cols.hits, cols.limit, cols.duration):
        if a.size and (int(a.min()) < 0 or int(a.max()) > hi):
            return False
    mask = cols.greg_duration != 0
    if mask.any():
        d = cols.greg_expire[mask] - now_ms
        if int(d.min()) < 0 or int(d.max()) > hi or int(cols.greg_duration.max()) > hi:
            return False
    return True


def decode_narrow(table, keys, slots, pn, now_ms: int, passthrough_exp):
    """Decode one narrow-wire packed result (i32[4, n] lanes).

    -2 keep-sentinel lanes reconstruct the device's pre-THIS-batch
    expiry.  A sentinel value is unrepresentable (>i32 delta), which
    requires a stored duration the narrow wire also can't carry — so no
    in-flight NARROW batch can have written it, and any narrow request
    on such a key triggers duration-change re-expiry instead of a
    pass-through.  Hence the value always predates every in-flight
    batch and the dispatch-time snapshot is correct even if a later
    batch's all-pending eviction fallback steals the slot and zeroes
    the mirror before this resolve.  Defense in depth: when the slot
    still maps this batch's key, prefer the resolve-time table value
    (older in-flight commits have folded in by now via the FIFO drain).
    """
    te = passthrough_exp
    sent = np.nonzero(pn[2] == -2)[0]
    if sent.size:
        te = passthrough_exp.copy()
        cur = table.get_expire_bulk(slots)
        for j in sent:
            if table.get_slot(keys[j]) == slots[j]:
                te[j] = cur[j]
    return buckets.unpack_output32(pn, now_ms, te)


def make_columns(algorithm, behavior, hits, limit, duration, n,
                 greg_expire=None, greg_duration=None) -> "_Columns":
    """Coerce caller-provided arrays into contiguous kernel columns."""
    cols = _Columns(0)
    cols.algo = np.ascontiguousarray(algorithm, dtype=np.int32)
    cols.behavior = np.ascontiguousarray(behavior, dtype=np.int32)
    cols.hits = np.ascontiguousarray(hits, dtype=np.int64)
    cols.limit = np.ascontiguousarray(limit, dtype=np.int64)
    cols.duration = np.ascontiguousarray(duration, dtype=np.int64)
    z = np.zeros(n, dtype=np.int64)
    cols.greg_expire = (
        z if greg_expire is None else np.ascontiguousarray(greg_expire, np.int64)
    )
    cols.greg_duration = (
        z if greg_duration is None else np.ascontiguousarray(greg_duration, np.int64)
    )
    return cols


# ---------------------------------------------------------------------
# Device->host readback with the known-flake quarantine: under heavy
# suite load jax 0.4.x CPU occasionally raises a spurious IndexError
# ("list index out of range") from _copy_single_device_array_to_host_async
# inside np.asarray of a device array.  The array is intact — an
# immediate retry succeeds — so the dispatch readback sites retry ONCE
# and count, instead of failing a whole batch (and a tier-1 run) on a
# runtime race that is not ours.  Anything else (or a second failure)
# propagates unchanged.
_readback_lock = threading.Lock()
_readback_retries_total = 0


def readback_retries_total() -> int:
    """Cumulative retry count (scraped into
    gubernator_readback_retries_total)."""
    with _readback_lock:
        return _readback_retries_total


def host_readback(arr) -> np.ndarray:
    """np.asarray(device_array) with the single-retry quarantine."""
    global _readback_retries_total
    try:
        return np.asarray(arr)
    except IndexError:
        with _readback_lock:
            _readback_retries_total += 1
        return np.asarray(arr)


def _wire_donate_ok(device) -> bool:
    """Whether a freshly uploaded wire buffer is donatable on this
    device.  CPU device_put zero-copies host numpy (the device array
    ALIASES the staging buffer), so donation is unusable there and
    would warn per compile; accelerators copy on upload, so donating
    lets XLA recycle the wire's bytes into the outputs."""
    try:
        d = device if device is not None else jax.devices()[0]
        return d.platform != "cpu"
    except Exception:  # noqa: BLE001 — backend quirks: lose the optimization only
        return False


def _prefetch_async(arr) -> None:
    """Start the device->host copy of `arr` without blocking (the
    launch stage calls this right after the dispatch, so the readback
    overlaps the NEXT batch's host work instead of serializing behind
    it — on a remote device the transfer is a full network RTT)."""
    try:
        arr.copy_to_host_async()
    except (AttributeError, NotImplementedError):  # pragma: no cover
        pass  # backend without async host copies: fetch pays the wait


class _FusedFetch:
    """One shared readback for a FUSED launch group: the k batches'
    packed results ride one stacked device array, transferred ONCE
    (whichever waiter arrives first pays it); each handle reads its
    slice.  Slicing per batch keeps the commit closures unchanged."""

    __slots__ = ("_arr", "_lock", "_np")

    def __init__(self, arr):
        self._arr = arr
        self._lock = threading.Lock()
        self._np = None

    def get(self, i: int):
        with self._lock:
            if self._np is None:
                self._np = host_readback(self._arr)
                self._arr = None  # drop the device reference
            return self._np[i]


@dataclass
class _Staged:
    """A prepared batch between the stage and launch steps: the packed
    wire's H2D upload is already in flight; `solo` launches it alone,
    while same-`fuse_key` neighbors waiting at the launch gate can ride
    one fused program instead (ColumnarPipeline._launch_in_order)."""

    solo: "Optional[Callable]"  # state -> (state, packed); None = scalar
    fuse_key: object = None   # None = not fuse-eligible (fallback wire)
    wire_dev: object = None   # uploaded packed wire (dict-wire path)
    n_rounds: int = 1
    now_ms: int = 0
    wide: bool = False
    # Express scalar slot (ops/scalar.py): a host-side closure that
    # evaluates the single lane and writes its bucket row in place,
    # returning the packed output array the ordinary commit closure
    # decodes.  Runs at this ticket's launch turn under the store lock
    # — no device program, no fusion, ticket-order commit unchanged.
    scalar: "Optional[Callable]" = None


@dataclass
class _ShardPrep:
    """Output of ShardStore's prepare stage: the plan columns plus the
    commit closure, handed to the unlocked stage step."""

    cols: "_Columns"
    now_ms: int
    force_wire: Optional[str]
    n: int
    padded: int
    n_rounds: int
    narrow: bool
    slot_col: np.ndarray
    rid_col: np.ndarray
    ex_col: np.ndarray
    occ_col: np.ndarray
    wr_col: np.ndarray
    commit: "Callable"


class ColumnsHandle:
    """Deferred result of one pipelined columnar batch
    (ShardStore.apply_columns_async).  Commits apply strictly in
    dispatch order — result() drains every older in-flight batch —
    but the device->host READBACK runs outside the ordering locks:
    concurrent waiters overlap their transfers (on a remote device each
    readback is a full network RTT, so serializing them caps the whole
    service at 1/RTT batches per second).

    The handle is created at the END of the prepare stage (its `ticket`
    is the batch's reservation in the plan-order journal) and becomes
    fetchable once the launch stage ran: `_fetch` blocks on the launch
    event, so a drain that overtakes a not-yet-launched batch simply
    waits for its dispatcher thread to reach the launch gate."""

    def __init__(self, store, commit_fn, limit_col, hits_col=None):
        self._store = store
        self._fetch_fn: "Optional[Callable]" = None  # set by the launch
        self._commit_fn = commit_fn
        self._fetched = None
        self._fetch_lock = threading.Lock()
        self._launched = threading.Event()
        self._launch_exc: "Optional[BaseException]" = None
        self._exc: "Optional[BaseException]" = None
        self._limit = limit_col
        self._hits = hits_col  # conservation-ledger twin of the decode
        self._value = None
        self.ticket = -1  # plan-order reservation (set by the pipeline)
        self.done = False
        # tracing.BatchTrace of the submitting batcher (None when the
        # batch carried no sampled lanes): stage spans for this batch
        # parent under its window span and link its member lanes.
        self._trace = None

    # -- launch side (dispatcher threads) ------------------------------
    def _launch_ok(self, fetch_fn) -> None:
        self._fetch_fn = fetch_fn
        self._launched.set()

    def _launch_fail(self, exc: BaseException) -> None:
        self._launch_exc = exc
        self._launched.set()

    # -- resolve side --------------------------------------------------
    def _fetch(self):
        """Blocking device readback; idempotent and safe to call from
        any thread (no store/drain lock held).  Returns None when the
        handle already resolved (a racing waiter's courtesy fetch)."""
        with self._fetch_lock:
            if self.done:
                return None
            if self._fetched is None:
                self._launched.wait()
                if self._launch_exc is not None:
                    raise self._launch_exc
                self._fetched = self._fetch_fn()
                self._fetch_fn = None
            return self._fetched

    def _do_resolve(self) -> None:
        t0 = time.perf_counter()
        try:
            with profiling.scope("dispatch.fetch"):
                packed_np = self._fetch()
        except Exception as e:  # noqa: BLE001 — launch failure
            self._finish_exc(e)
            return
        dt = time.perf_counter() - t0
        self._store._observe_stage("fetch", dt)
        tracing.stage_span("fetch", dt, self._trace)
        t1 = time.perf_counter()
        try:
            with profiling.scope("dispatch.commit"):
                status, remaining, reset = self._commit_fn(packed_np)
        except Exception as e:  # noqa: BLE001 — surfaced at result()
            self._finish_exc(e)
            return
        dt = time.perf_counter() - t1
        self._store._observe_stage("commit", dt)
        tracing.stage_span("commit", dt, self._trace)
        # Conservation ledger (audit.py), fed from the decode the commit
        # just produced: hits GRANTED by the device (UNDER_LIMIT lanes)
        # and the negative-remaining tripwire — two vectorized reductions
        # per batch, the applied-side twin of the dispatch-side count in
        # _submit_pipelined.
        hits = self._hits
        if hits is not None:
            st = np.asarray(status)
            n = min(len(hits), len(st))
            audit.note(
                "applied_hits",
                int(np.asarray(hits[:n])[st[:n] == 0].sum()),  # 0 = UNDER_LIMIT
            )
            rem = np.asarray(remaining)
            neg = int((rem < 0).sum())
            if neg:
                audit.note("negative_remaining", neg)
        self._value = {
            "status": status,
            "limit": self._limit,
            "remaining": remaining,
            "reset_time": reset,
        }
        # Drop the closures: they pin the planner (C++ batch + key
        # buffer), the device output array, and the padded columns.
        # done flips under the fetch lock so a racing waiter's _fetch
        # never sees half-cleared state.
        self._commit_fn = None
        with self._fetch_lock:
            self._fetched = None
            self.done = True

    def _finish_exc(self, exc: BaseException) -> None:
        """Record a launch/commit failure as this handle's outcome so
        the FIFO drain can keep resolving younger batches; result()
        re-raises."""
        self._exc = exc
        self._commit_fn = None
        with self._fetch_lock:
            self._fetched = None
            self.done = True

    def prefetch(self) -> None:
        """Nonblocking hint from the drainer's backlog path.  The
        launch stage already requested the async device->host copy, so
        there is nothing further to do without blocking; kept as an
        explicit extension point for transports whose launch-side
        prefetch is unavailable.  MUST NOT touch `_fetch_lock` — a
        resolver holds it across the blocking readback, and this hint
        fires from service threads that must never stall an RTT."""

    def result(self) -> dict:
        if not self.done:
            try:
                self._fetch()  # overlap readbacks across waiter threads
            except Exception:  # noqa: BLE001
                pass  # the ordered drain records it as this handle's outcome
            self._store._drain_until(self)
        if self._exc is not None:
            raise self._exc
        return self._value


class ColumnarPipeline:
    """Mixin: the three-stage overlapped dispatch pipeline for columnar
    batches (architecture.md "Dispatch pipeline").

    Each batch moves through:

      1. PREPARE — slot-table planning (the only table-mutating step),
         under `_plan_lock`.  The batch's position in the plan order is
         its reservation TICKET; the `_inflight` FIFO appended here is
         the reservation journal — commit order is defined at plan
         time, before any device work.
      2. STAGE — pack the wire and START the H2D upload.  No locks:
         batch N+1's packing runs while batch N computes on device.
      3. LAUNCH — ticket order, under `_lock`, reduced to the
         state-threading jit call (state and wire donated).  Consecutive
         same-shape batches already staged at the gate launch FUSED —
         one program applies them sequentially — so the fixed
         per-dispatch cost amortizes under backlog.
      4. FETCH (no locks; the launch pre-requested the async copy) and
         COMMIT (FIFO under `_drain_lock`, table writes guarded by the
         per-table native mutex + `_lock` for host mirrors).

    Locks, in acquisition order (never the reverse):
      * `_plan_lock` — serializes prepares; owns ticket assignment.
      * `_drain_lock` — serializes resolvers; held across the blocking
        device readback so results commit strictly in dispatch order.
      * `_lock` (the store mutation RLock) — guards the donated device
        buffers; taken by launches and by resolvers ONLY for the
        post-readback decode/commit.

    Batch N+1's PREPARE overlaps batch N's COMMIT: the two hold
    different Python locks, and the C++ slot tables carry their own
    per-table mutex (host_runtime.cpp), so call-level interleaving is
    safe.  The semantics are the pipelined-staleness contract unchanged:
    planning reads table expiry that may lag by the unresolved depth,
    the kernel revalidates expiry device-side, and per-slot
    pending-write counts keep in-flight slots uneviction-able.
    """

    # Launch-fusion cap: group sizes are restricted to {1, 2, 4} — each
    # (size, wire shape) is a distinct XLA program, and on a remote
    # device every program's first dispatch pays an executable load.
    MAX_FUSE = 4

    def _init_pipeline(self) -> None:
        self._inflight: "deque[ColumnsHandle]" = deque()
        self._drain_lock = threading.Lock()
        self._plan_lock = threading.Lock()
        self._launch_cv = threading.Condition()
        self._next_ticket = 0
        self._next_launch = 0
        self._launch_gate: "Dict[int, tuple]" = {}  # ticket -> (_Staged, handle)
        self._launch_aborted: set = set()  # tombstoned tickets (abort path)
        self._stage_stats: "Dict[str, list]" = {}
        self._stats_lock = threading.Lock()
        self._depth_hwm = 0
        self._seen_wire_shapes: set = set()  # (W, narrow) staged so far
        # Device programs launched by this store's columnar pipeline —
        # the "telemetry adds zero device dispatches" contract is
        # pinned by COUNTING this (tests/test_observability.py), the
        # replica_commit_dispatches playbook.
        self.device_dispatches = 0
        # Express scalar applies (ops/scalar.py): batches answered by
        # the host-side singleton path — counted separately so the
        # zero-extra-device-programs pins keep holding (a scalar apply
        # is NOT a device dispatch) and /debug/status can report the
        # express hit rate.
        self.scalar_applies = 0
        # Master switch for the scalar singleton path, default OFF at
        # the store level: the SERVICE enables it from GUBER_EXPRESS
        # (config.py), so bare-store users and every pre-express test
        # see exactly the old dispatch behavior unless they opt in.
        self.scalar_fast_path = False
        # Widest batch the scalar slot serves (the service syncs this
        # with GUBER_EXPRESS_MAX_LANES).  Lanes apply SEQUENTIALLY in
        # submission order — the semantics the kernel's round/group
        # machinery exists to reproduce — so the slot stays
        # oracle-equivalent at any width; the cap keeps the host loop
        # to the small interactive shapes where it beats a program.
        self.scalar_max_lanes = 4
        self._scalar_ok: "Optional[bool]" = None  # lazy capability probe

    # -- observability (metrics.observe_dispatch scrapes these) --------
    def _observe_stage(self, stage: str, dt: float) -> None:
        # Always-on latency attribution (saturation.py): the same
        # number feeds the per-scrape stage gauge below and the
        # gubernator_latency_attribution_seconds{phase} reservoir.
        saturation.observe_phase(f"dispatch.{stage}", dt)
        with self._stats_lock:
            st = self._stage_stats.setdefault(stage, [0, 0.0, 0.0])
            st[0] += 1
            st[1] += dt
            st[2] = max(st[2], dt)

    def pipeline_depth(self) -> int:
        """Batches dispatched but not yet resolved (gauge value)."""
        return len(self._inflight)

    def occupancy_stats(self) -> "List[dict]":
        """Per-shard occupancy from the HOST slot tables the dispatch
        commits already maintain — THE one occupancy read of the
        saturation plane (zero device programs; consumed by
        Metrics.observe_saturation and V1Service.debug_status).  Works
        for both stores: ShardStore exposes `table`, the mesh store
        `tables` (+ the optional back tier)."""
        tables = getattr(self, "tables", None) or [self.table]
        back_cap = int(getattr(self, "back_capacity_per_shard", 0) or 0)
        out = []
        for s, t in enumerate(tables):
            row = {
                "shard": s,
                "used": len(t),
                "capacity": int(t.capacity),
                "evictions": int(t.evictions),
            }
            if back_cap:
                # tier_stats: (total, back_keys, demotions, promotions,
                # back_evictions).
                row["back_used"] = int(t.tier_stats[1])
                row["back_capacity"] = back_cap
            out.append(row)
        return out

    def take_pipeline_stats(self):
        """Drain the per-stage timing aggregates accumulated since the
        last call: ({stage: (count, total_s, max_s)}, depth, depth_hwm).
        Cleared per scrape, like the breaker gauges (PR 1 convention)."""
        with self._stats_lock:
            out = {k: tuple(v) for k, v in self._stage_stats.items()}
            self._stage_stats.clear()
            hwm = self._depth_hwm
            self._depth_hwm = len(self._inflight)
        return out, len(self._inflight), hwm

    # -- the three-stage dispatch driver -------------------------------
    def _submit_pipelined(self, keys, cols, now_ms: int,
                          force_wire: Optional[str] = None) -> "ColumnsHandle":
        """Run prepare -> stage -> launch for one batch and return its
        enqueued handle.  Subclasses provide `_prepare_columns` (table
        planning, returns a prep object with a `.commit` closure),
        `_stage_columns` (pack + upload, returns a _Staged), and
        `_launch_group` (the locked jit call for 1..MAX_FUSE staged
        batches)."""
        bt = tracing.take_batch_trace()  # staged by the batcher (if sampled)
        t0 = time.perf_counter()
        # Conservation ledger (audit.py): hits entering the device
        # dispatch — the earlier-layer twin of the applied-hits count at
        # commit decode (applied <= dispatched is the device invariant).
        audit.note("dispatched_hits", int(cols.hits.sum()))
        # Express scalar slot: a singleton on a capable CPU backend
        # skips device dispatch — planned, ticketed and committed like
        # any batch (the wide commit decode), but its "launch" is the
        # host-side evaluation in ops/scalar.py.  Decided BEFORE the
        # plan so the prepare can pin the wide decode path.
        use_scalar = force_wire is None and self._scalar_eligible(cols)
        with self._plan_lock, profiling.scope("dispatch.prepare"):
            prep = self._prepare_columns(
                keys, cols, now_ms, "wide" if use_scalar else force_wire
            )
            handle = ColumnsHandle(self, prep.commit, cols.limit, cols.hits)
            handle._trace = bt
            handle.ticket = self._next_ticket
            self._next_ticket += 1
            self._inflight.append(handle)
            with self._stats_lock:
                self._depth_hwm = max(self._depth_hwm, len(self._inflight))
        dt = time.perf_counter() - t0
        self._observe_stage("prepare", dt)
        tracing.stage_span("prepare", dt, bt, ticket=handle.ticket,
                           lanes=prep.n)
        # Lane utilization: real lanes vs the pow2-padded shape the
        # launch will scatter (saturation plane; drained per scrape).
        saturation.lane_util.add(prep.n, self._padded_lanes(prep))
        try:
            t1 = time.perf_counter()
            with profiling.scope("dispatch.stage"):
                staged = (
                    self._stage_scalar(prep) if use_scalar
                    else self._stage_columns(prep)
                )
            dt = time.perf_counter() - t1
            self._observe_stage("stage", dt)
            tracing.stage_span("stage", dt, bt)
        except BaseException as e:
            self._abort_launch_turn(handle, e)
            raise
        self._launch_in_order(handle, staged)
        return handle

    def _retire_aborted_locked(self) -> None:
        """Advance past tombstoned (aborted) tickets; `_launch_cv` held."""
        while self._next_launch in self._launch_aborted:
            self._launch_aborted.discard(self._next_launch)
            self._next_launch += 1
        # Tombstones of already-passed tickets (a waiter aborted while
        # a fusing launcher swept it up) can never retire: drop them.
        self._launch_aborted = {
            t for t in self._launch_aborted if t > self._next_launch
        }

    def _abort_launch_turn(self, group_or_handle, exc: BaseException) -> None:
        """A failure after tickets were reserved — staging raised, or an
        asynchronous exception (KeyboardInterrupt) landed while waiting
        at the gate: mark the handle(s) failed and retire their launch
        turns WITHOUT blocking.  If the turn is current it advances now;
        otherwise a tombstone makes whichever launcher next advances
        skip it — so an interrupted dispatcher can never wedge younger
        tickets or the resolvers waiting on their launch events."""
        handles = (
            [h for _, h in group_or_handle]
            if isinstance(group_or_handle, list) else [group_or_handle]
        )
        for h in handles:
            h._launch_fail(exc)
        with self._launch_cv:
            for h in handles:
                self._launch_gate.pop(h.ticket, None)
                self._launch_aborted.add(h.ticket)
            self._retire_aborted_locked()
            self._launch_cv.notify_all()

    def _launch_in_order(self, handle: "ColumnsHandle",
                         staged: "_Staged") -> None:
        ticket = handle.ticket
        group = None
        try:
            with self._launch_cv:
                if self._next_launch != ticket:
                    self._launch_gate[ticket] = (staged, handle)
                    while (self._next_launch != ticket
                           and not handle._launched.is_set()):
                        self._launch_cv.wait(0.1)
                    self._launch_gate.pop(ticket, None)
                    if handle._launched.is_set():
                        return  # an older launcher fused this batch into its group
                group = [(staged, handle)]
                if staged.fuse_key is not None:
                    # Collect contiguous already-staged successors of the
                    # same wire shape/kind.  Contiguity is required — the
                    # launch turn advances past exactly this group, so a
                    # gap ticket must not be skipped.
                    avail = []
                    nt = ticket + 1
                    while (len(avail) < self.MAX_FUSE - 1
                           and nt in self._launch_gate
                           and self._launch_gate[nt][0].fuse_key == staged.fuse_key):
                        avail.append(nt)
                        nt += 1
                    take = 3 if len(avail) >= 3 else (1 if avail else 0)
                    for t2 in avail[:take]:
                        group.append(self._launch_gate.pop(t2))
        except BaseException as e:  # async interrupt mid-wait/collect
            self._abort_launch_turn(group or handle, e)
            raise
        exc: "Optional[BaseException]" = None
        t0 = time.perf_counter()
        try:
            with self._lock, profiling.scope("dispatch.launch"):
                self._launch_group(group)
        except BaseException as e:  # noqa: BLE001
            exc = e
        dt = time.perf_counter() - t0
        self._observe_stage("launch", dt)
        # Lane-time pool (profiling.py): these lanes rode a launch of
        # this wall cost — the tenant ledger's proportional-share
        # denominator (the per-launch timing telemetry also drains).
        profiling.note_lane_time(
            sum(len(h._limit) for _, h in group), dt
        )
        for _, h in group:
            # One launch span per batch (a fused group launches several
            # batches in one program; each batch's trace sees it).
            tracing.stage_span("launch", dt, h._trace, fused=len(group))
        if exc is not None:
            for _, h in group:
                h._launch_fail(exc)
        with self._launch_cv:
            self._next_launch = ticket + len(group)
            self._retire_aborted_locked()
            self._launch_cv.notify_all()
        if exc is not None:
            raise exc

    def _padded_lanes(self, prep) -> int:
        """Total padded lanes one launch of `prep` scatters (the mesh
        store overrides: its pad is per shard)."""
        return prep.padded

    # -- launch implementations (shared by ShardStore / MeshBucketStore)
    def _pre_launch(self) -> None:
        """Hook: device work that must precede the group's programs
        (the mesh drains its queued tier moves here)."""

    def _fused_launch_fn(self, k: int, wide: bool):
        """Hook: the jitted K-batch fused program for this store's
        device topology."""
        raise NotImplementedError

    # -- express scalar hooks (ops/scalar.py; stores override) ---------
    def _scalar_eligible(self, cols) -> bool:
        """Whether this batch may take the host-side scalar slot
        instead of a device program.  Default: never (stores with a
        scalar implementation override)."""
        return False

    def _stage_scalar(self, prep) -> "_Staged":
        raise NotImplementedError

    def _program_label(self, group) -> str:
        """XLA-telemetry program identity for one launch group: store
        topology (mesh twin vs single shard), solo vs fused-K, and the
        wire width — the axes along which distinct programs compile."""
        kind = "mesh" if getattr(self, "tables", None) is not None else "shard"
        staged = group[0][0]
        shape = "solo" if len(group) == 1 else f"fused{len(group)}"
        width = "wide" if staged.wide else "narrow"
        return f"{kind}:dispatch:{shape}:{width}"

    def _launch_group(self, group) -> None:
        """Stage 3 (ticket order, under `_lock`): just the
        state-threading jit call.  A multi-batch group rides ONE fused
        program; each handle's fetch reads its slice of the shared
        stacked result, transferred once.

        A scalar-staged batch (the express singleton slot) never fuses
        (fuse_key None) and launches as a host-side evaluation instead:
        no device program, no XLA — the bucket row mutates in place
        under this same lock, at this same ticket turn, so interleaved
        scalar and device batches commit in plan order exactly like two
        device batches would."""
        if len(group) == 1 and group[0][0].scalar is not None:
            staged, h = group[0]
            # Dispatch is ASYNC on every backend (CPU included): an
            # older ticket's program may still be executing on the XLA
            # thread pool even though its launch returned and released
            # the lock.  The scalar slot mutates the state buffers
            # directly, so it must wait for the arrays to be DEFINED —
            # a no-op when the pipeline already quiesced (the express
            # shallow-queue case), the correctness wait otherwise.
            jax.block_until_ready(self.state)
            packed = staged.scalar()
            self.scalar_applies += 1
            saturation.note_express("scalar", len(h._limit))
            h._launch_ok(lambda: packed)
            return
        self._pre_launch()
        # One program per group (fused or solo) — counted, not timed:
        # the zero-extra-dispatch telemetry contract asserts on this.
        self.device_dispatches += 1
        # lazy=wide: warmup deliberately defers the wide int64 wire
        # programs ("compile lazily" in mesh warmup), so their first
        # post-steady compile is by design, not shape churn.
        with telemetry.program(self._program_label(group),
                               lazy=group[0][0].wide):
            if len(group) == 1:
                staged, h = group[0]
                self.state, packed = staged.solo(self.state)
                h._launch_ok(partial(host_readback, packed))
                _prefetch_async(packed)
                return
            fn = self._fused_launch_fn(len(group), group[0][0].wide)
            nr = np.asarray([s.n_rounds for s, _ in group], np.int32)
            nowv = np.asarray([s.now_ms for s, _ in group], np.int64)
            self.state, stacked = fn(
                self.state, *[s.wire_dev for s, _ in group], nr, nowv
            )
            shared = _FusedFetch(stacked)
            for i, (_, h) in enumerate(group):
                h._launch_ok(partial(shared.get, i))
            _prefetch_async(stacked)

    # -- resolve / drain ordering --------------------------------------
    def _drain_until(self, handle: "ColumnsHandle") -> None:
        with self._drain_lock:
            if handle.done:
                return  # a concurrent drain already resolved it
            while self._inflight:
                h = self._inflight.popleft()
                h._do_resolve()
                if h is handle:
                    return
            if not handle.done:  # not in the deque (already popped elsewhere)
                handle._do_resolve()

    def _drain_all(self) -> None:
        with self._drain_lock:
            while self._inflight:
                self._inflight.popleft()._do_resolve()

    def _drain_then_lock(self) -> None:
        """Acquire the plan + store locks with the pipeline empty:
        non-columnar mutators (dataclass apply, snapshot, loader,
        GLOBAL sync) must observe every older batch's table commits
        first, and must block new prepares while they hold the state.
        Release with `_unlock_drained`.  Loops defensively, though with
        `_plan_lock` held no new handle can enter the FIFO."""
        self._plan_lock.acquire()
        while True:
            self._drain_all()
            self._lock.acquire()
            if not self._inflight:
                return
            self._lock.release()

    def _unlock_drained(self) -> None:
        self._lock.release()
        self._plan_lock.release()


def build_round_arrays(chunk: Sequence[_Prepared], padded: int) -> Tuple[np.ndarray, ...]:
    """Columnize one round of prepared requests into kernel input arrays."""
    slot = np.full(padded, -1, dtype=np.int32)
    exists = np.zeros(padded, dtype=bool)
    algo = np.zeros(padded, dtype=np.int32)
    behavior = np.zeros(padded, dtype=np.int32)
    hits = np.zeros(padded, dtype=np.int64)
    limit = np.zeros(padded, dtype=np.int64)
    duration = np.zeros(padded, dtype=np.int64)
    greg_expire = np.zeros(padded, dtype=np.int64)
    greg_duration = np.zeros(padded, dtype=np.int64)
    for i, p in enumerate(chunk):
        slot[i] = p.slot
        exists[i] = p.exists
        algo[i] = int(p.req.algorithm)
        behavior[i] = int(p.req.behavior)
        hits[i] = p.req.hits
        limit[i] = p.req.limit
        duration[i] = p.req.duration
        greg_expire[i] = p.greg_expire
        greg_duration[i] = p.greg_duration
    return slot, exists, algo, behavior, hits, limit, duration, greg_expire, greg_duration


class ShardStore(ColumnarPipeline):
    """Bucket table for one shard, pinned to (at most) one device.

    `store` is the optional persistence SPI (gubernator_tpu.store.Store):
    get() fulfills misses, on_change() observes every applied request,
    remove() fires on explicit removals — the call pattern of
    algorithms.go:26-33,64-68,176-177.
    """

    def __init__(
        self,
        capacity: int = 50_000,
        device: Optional[jax.Device] = None,
        store=None,
        use_native: bool = True,
    ):
        self.capacity = capacity
        # The C++ host runtime (native/host_runtime.cpp) handles key
        # resolution + round planning at C speed; Python twin is the
        # compiler-less fallback.
        self._native = use_native and native.available()
        self.table = (
            native.NativeSlotTable(capacity) if self._native else SlotTable(capacity)
        )
        self.device = device
        self.store = store
        # Serializes buffer-donating mutators for multi-threaded callers.
        self._lock = threading.RLock()
        state = buckets.init_state(capacity)
        if device is not None:
            state = jax.device_put(state, device)
        self.state = state
        # host mirror of per-slot algorithm, for store-SPI removal detection
        self.algo_mirror = np.zeros(capacity, dtype=np.int32)
        self._init_pipeline()  # FIFO of unresolved pipelined batches

    def describe_topology(self) -> "Tuple[str, str]":
        """(backend platform, mesh shape) for gubernator_build_info —
        a single-shard store reports a 1-wide mesh."""
        try:
            d = self.device if self.device is not None else jax.devices()[0]
            return d.platform, "1"
        except Exception:  # noqa: BLE001
            return "unknown", "1"

    # ------------------------------------------------------------------
    def apply(
        self, requests: Sequence[RateLimitRequest], now_ms: int
    ) -> List[RateLimitResponse]:
        """Evaluate a batch; responses come back in request order."""
        responses: List[Optional[RateLimitResponse]] = [None] * len(requests)
        if self._native and self.store is None:
            # Rides the columnar pipeline: dispatch under the store
            # lock, resolve outside it (ColumnarPipeline ordering).
            self._apply_native(requests, now_ms, responses)
            return [r if r is not None else RateLimitResponse() for r in responses]
        # Store-SPI / fallback path: interleaved per-round host
        # callbacks need the lock across the whole batch.
        self._drain_then_lock()
        try:
            prepared = prepare_requests(requests, now_ms, responses)
            resolver = self._store_resolver(now_ms) if self.store is not None else None
            planner = RoundPlanner(self.table, prepared, now_ms, resolver=resolver)
            while True:
                chunk = planner.next_chunk()
                if not chunk:
                    break
                self._run_round(chunk, now_ms, responses)
            return [r if r is not None else RateLimitResponse() for r in responses]
        finally:
            self._unlock_drained()

    # ------------------------------------------------------------------
    # Native (C++) fast path: resolve + round-plan in host_runtime.cpp,
    # column math in numpy, responses in one pass.
    # ------------------------------------------------------------------
    def _apply_native(self, requests, now_ms: int, responses) -> None:
        n = len(requests)
        if n == 0:
            return
        greg_bit = int(Behavior.DURATION_IS_GREGORIAN)
        behavior = np.fromiter((r.behavior for r in requests), np.int32, count=n)
        if not (behavior & greg_bit).any():
            # Common case: no calendar lanes — extract each field in one
            # tight comprehension pass instead of a per-request loop
            # (the dataclass API's host cost is exactly this extraction).
            keys = [r.hash_key() for r in requests]
            cols = make_columns(
                np.fromiter((r.algorithm for r in requests), np.int32, count=n),
                behavior,
                np.fromiter((r.hits for r in requests), np.int64, count=n),
                np.fromiter((r.limit for r in requests), np.int64, count=n),
                np.fromiter((r.duration for r in requests), np.int64, count=n),
                n,
            )
            status, remaining, reset = self._run_columns(keys, cols, now_ms)
            limit = cols.limit
            for j in range(n):
                responses[j] = RateLimitResponse(
                    status=int(status[j]),
                    limit=int(limit[j]),
                    remaining=int(remaining[j]),
                    reset_time=int(reset[j]),
                )
            return
        keys: List[str] = []
        vidx = np.empty(n, dtype=np.int64)
        cols = _Columns(n)
        greg = GregResolver(now_ms)
        m = 0
        for i, req in enumerate(requests):
            ge = gd = 0
            if has_behavior(req.behavior, Behavior.DURATION_IS_GREGORIAN):
                cached = greg.resolve(req.duration)
                if isinstance(cached, gregorian.GregorianError):
                    responses[i] = RateLimitResponse(error=str(cached))
                    continue
                ge, gd = cached
            keys.append(req.hash_key())
            vidx[m] = i
            cols.set(m, req, ge, gd)
            m += 1
        if m == 0:
            return
        cols.trim(m)
        status, remaining, reset = self._run_columns(keys, cols, now_ms)
        limit = cols.limit
        for j in range(m):
            responses[int(vidx[j])] = RateLimitResponse(
                status=int(status[j]),
                limit=int(limit[j]),
                remaining=int(remaining[j]),
                reset_time=int(reset[j]),
            )

    def _run_columns(self, keys: List[str], cols: "_Columns", now_ms: int):
        """Single-dispatch kernel path over pre-validated columns: the
        C++ planner assigns every lane a (round, slot, exists) upfront,
        the whole duplicate-round loop runs inside one jitted program
        (buckets.apply_rounds), and all outputs come back in ONE packed
        device->host transfer.  Returns (status, remaining, reset_time)
        arrays aligned to keys."""
        r = self._submit_pipelined(keys, cols, now_ms).result()
        return r["status"], r["remaining"], r["reset_time"]

    def _prepare_columns(self, keys: List[str], cols: "_Columns", now_ms: int,
                         force_wire: Optional[str] = None) -> "_ShardPrep":
        """Stage 1 (under `_plan_lock`): everything that touches the
        slot table — the C++ grouped plan, the pass-through expiry
        snapshot — plus the cheap padded plan-column scatters.  No
        device work and no packing: those run unlocked in stage 2, so
        batch N+1's planning starts the moment batch N's plan is done,
        regardless of where batch N is in its flight."""
        n = len(keys)
        planner = native.NativeBatchPlanner(self.table, keys, now_ms)
        round_id, slots, exists, occ, write, n_rounds = planner.plan_grouped(
            cols, int(Behavior.RESET_REMAINING)
        )
        padded = pad_size(n)
        slot_col = np.full(padded, -1, dtype=np.int32)
        slot_col[:n] = slots
        rid_col = np.zeros(padded, dtype=np.int32)
        rid_col[:n] = round_id
        ex_col = np.zeros(padded, dtype=bool)
        ex_col[:n] = exists
        occ_col = np.zeros(padded, dtype=np.int32)
        occ_col[:n] = occ
        wr_col = np.zeros(padded, dtype=bool)
        wr_col[:n] = write
        narrow = narrow_ok(cols, now_ms) and force_wire != "wide"
        # Snapshot the pass-through expiry NOW: the -2 keep-sentinel means
        # "the kernel left this slot's pre-batch expiry unchanged", and
        # pre-batch is defined at plan time.  A later pipelined batch's
        # planning can evict/reassign these slots (zeroing expire_ms)
        # before resolve() runs, so reading the table at resolve time
        # would reconstruct a wrong reset_time for far-future
        # pass-through lanes.
        passthrough_exp = self.table.get_expire_bulk(slots) if narrow else None

        def commit(packed_np):
            with self._lock:
                if narrow:
                    status, removed, remaining, reset, new_exp = decode_narrow(
                        self.table, keys, slots, packed_np[:, :n], now_ms,
                        passthrough_exp,
                    )
                else:
                    status, removed, remaining, reset, new_exp = buckets.unpack_output(
                        packed_np[:, :n]
                    )
                planner.commit_plan(new_exp, removed)
                self.algo_mirror[slots] = cols.algo
                return status, remaining, reset

        return _ShardPrep(
            cols=cols, now_ms=now_ms, force_wire=force_wire, n=n,
            padded=padded, n_rounds=n_rounds, narrow=narrow,
            slot_col=slot_col, rid_col=rid_col, ex_col=ex_col,
            occ_col=occ_col, wr_col=wr_col, commit=commit,
        )

    def _stage_columns(self, prep: "_ShardPrep") -> "_Staged":
        """Stage 2 (no locks): encode the wire and START the H2D
        upload.  The dict-wire path uploads ONE buffer and is
        fuse-eligible; the fallback array wires launch solo."""
        cols, now_ms, padded = prep.cols, prep.now_ms, prep.padded
        n_rounds, narrow = prep.n_rounds, prep.narrow
        dict_enc = None
        if (prep.force_wire is None and n_rounds <= 255
                and int(prep.occ_col.max(initial=0)) <= 65535):
            # The dict wire carries values in its 256-row i64 table, so
            # it works at ANY magnitude — wide batches (monthly/yearly
            # Gregorian, big limits) only switch the OUTPUT width.
            dict_enc = buckets.build_config_dict(cols, now_ms)
        if dict_enc is not None:
            cfg_idx, table = dict_enc
            # Single-buffer wire: one host->device transfer per batch
            # instead of 12 (per-call overhead dominates at service
            # batch sizes).
            wire = buckets.pack_dict_wire(
                prep.slot_col[None, :], prep.ex_col[None, :],
                prep.wr_col[None, :],
                _pad(cfg_idx, padded, np.uint8)[None, :],
                prep.occ_col[None, :], prep.rid_col[None, :], table,
            )[0]
            wire_dev = (
                jax.device_put(wire, self.device)
                if self.device is not None else jax.device_put(wire)
            )
            if _wire_donate_ok(self.device):
                kern = (
                    buckets.apply_rounds_packed_donated
                    if narrow
                    else buckets.apply_rounds_packed_wide_donated
                )
            else:
                kern = (
                    buckets.apply_rounds_packed_jit
                    if narrow
                    else buckets.apply_rounds_packed_wide_jit
                )
            return _Staged(
                solo=lambda state: kern(state, wire_dev, n_rounds, now_ms),
                fuse_key=("dict", narrow, wire.shape[0]),
                wire_dev=wire_dev, n_rounds=n_rounds, now_ms=now_ms,
                wide=not narrow,
            )
        if narrow:
            greg_delta = np.where(
                cols.greg_duration != 0, cols.greg_expire - now_ms, 0
            ).astype(np.int32)
            batch = buckets.make_batch32(
                prep.slot_col,
                prep.ex_col,
                _pad(cols.algo, padded, np.int32),
                _pad(cols.behavior, padded, np.int32),
                _pad(cols.hits, padded, np.int32),
                _pad(cols.limit, padded, np.int32),
                _pad(cols.duration, padded, np.int32),
                _pad(greg_delta, padded, np.int32),
                _pad(cols.greg_duration, padded, np.int32),
                occ=prep.occ_col,
                write=prep.wr_col,
            )
            return _Staged(
                solo=lambda state: buckets.apply_rounds32_jit(
                    state, batch, prep.rid_col, n_rounds, now_ms
                )
            )
        batch = buckets.make_batch(
            prep.slot_col,
            prep.ex_col,
            _pad(cols.algo, padded, np.int32),
            _pad(cols.behavior, padded, np.int32),
            _pad(cols.hits, padded, np.int64),
            _pad(cols.limit, padded, np.int64),
            _pad(cols.duration, padded, np.int64),
            _pad(cols.greg_expire, padded, np.int64),
            _pad(cols.greg_duration, padded, np.int64),
            occ=prep.occ_col,
            write=prep.wr_col,
        )
        return _Staged(
            solo=lambda state: buckets.apply_rounds_jit(
                state, batch, prep.rid_col, n_rounds, now_ms
            )
        )

    def _fused_launch_fn(self, k: int, wide: bool):
        return buckets.fused_packed_jit(
            k, wide, donate_wires=_wire_donate_ok(self.device)
        )

    # -- express scalar slot (ops/scalar.py) ---------------------------
    def _scalar_eligible(self, cols) -> bool:
        """Small batches on a CPU backend take the host scalar path
        when the service enabled it (scalar_fast_path) and the one-time
        writable-buffer capability probe passed.  Lanes apply
        sequentially in submission order — exactly the semantics the
        kernel's round/duplicate-group machinery reproduces — so width
        is a cost cap, not a correctness bound."""
        if not self.scalar_fast_path:
            return False
        if not 1 <= len(cols.hits) <= self.scalar_max_lanes:
            return False
        if not (self._native and self.store is None):
            return False
        if self._scalar_ok is None:
            with self._lock:
                # In-flight async programs must finish before the probe
                # writes a spare lane of the live buffer.
                jax.block_until_ready(self.state)
                self._scalar_ok = scalar_ops.device_is_cpu(
                    self.device
                ) and scalar_ops.probe(self.state.hot, sharded=False)
        return self._scalar_ok

    def _stage_scalar(self, prep: "_ShardPrep") -> "_Staged":
        """Express stage: capture the plan's slot rows and return the
        host-evaluation closure.  The closure runs at the launch turn
        under `_lock` (ColumnarPipeline._launch_group) and returns a
        packed [4, n] wide output the ordinary commit decodes."""
        cols = prep.cols
        n = prep.n
        slots = prep.slot_col[:n].copy()
        exists = prep.ex_col[:n].copy()
        occ = prep.occ_col[:n].copy()
        now_ms = prep.now_ms

        def run():
            hot = scalar_ops.single_view(self.state.hot)
            cold = scalar_ops.single_view(self.state.cold)
            if hot is None or cold is None:
                raise RuntimeError("scalar fast path: state view unavailable")
            packed = np.zeros((4, n), dtype=np.int64)
            for i in range(n):
                slot = int(slots[i])
                # Exists per lane: the planner's claim, EXCEPT that a
                # later occurrence of an analytic duplicate group
                # (occ > 0) shares the FIRST occurrence's pre-group
                # claim — sequentially, the prior occurrence's write
                # made the row live.  Round-1+ same-key lanes already
                # carry exists=True from the planner, and a mid-batch
                # slot TAKEOVER (different key, occ == 0,
                # exists=False) must keep creating.
                ex = bool(exists[i]) or int(occ[i]) > 0
                st, rem, reset, n_exp, removed = scalar_ops.apply_one(
                    hot[slot], cold[slot],
                    exists=ex,
                    algorithm=int(cols.algo[i]),
                    behavior=int(cols.behavior[i]),
                    hits=int(cols.hits[i]),
                    limit=int(cols.limit[i]),
                    duration=int(cols.duration[i]),
                    greg_expire=int(cols.greg_expire[i]),
                    greg_duration=int(cols.greg_duration[i]),
                    now_ms=now_ms,
                )
                packed[0, i] = st | (int(removed) << 1)
                packed[1, i] = rem
                packed[2, i] = reset
                packed[3, i] = n_exp
            return packed

        return _Staged(solo=None, scalar=run)

    @property
    def supports_columns(self) -> bool:
        """True when the zero-dataclass bulk path is usable."""
        return self._native and self.store is None

    def apply_columns(
        self,
        keys: List[str],
        algorithm,
        behavior,
        hits,
        limit,
        duration,
        now_ms: int,
        greg_expire=None,
        greg_duration=None,
        force_wire=None,
    ):
        """Columnar bulk API: the zero-dataclass ingress path.

        `keys` are full hash keys (name + '_' + unique_key); the array
        args align with them.  Gregorian expiry/duration must be
        precomputed by the caller when DURATION_IS_GREGORIAN is set
        (utils.gregorian).  Returns a dict of numpy arrays:
        status/limit/remaining/reset_time.  Requires the native runtime
        and no Store SPI (use `apply` otherwise).
        """
        cols = self._make_columns(algorithm, behavior, hits, limit, duration,
                                  len(keys), greg_expire, greg_duration)
        return self._submit_pipelined(keys, cols, now_ms, force_wire).result()

    def apply_columns_async(
        self,
        keys: List[str],
        algorithm,
        behavior,
        hits,
        limit,
        duration,
        now_ms: int,
        greg_expire=None,
        greg_duration=None,
        force_wire=None,
    ) -> ColumnsHandle:
        """Pipelined apply_columns: plans and enqueues the batch, then
        returns immediately with a ColumnsHandle; `handle.result()`
        blocks on the device readback.  Dispatching batch i+1 before
        resolving batch i overlaps host planning and transfer with
        device compute — the throughput shape of a batching ingress
        pipeline (the reference's interval-drained queues,
        peer_client.go:272-312, feeding a device instead of a socket).

        Pipelined planning reads slot-table expiry that is stale by the
        unresolved depth; the kernel revalidates expiry device-side, so
        the only observable effect is eviction under pressure acting on
        slightly old expire times."""
        cols = self._make_columns(algorithm, behavior, hits, limit, duration,
                                  len(keys), greg_expire, greg_duration)
        return self._submit_pipelined(keys, cols, now_ms, force_wire)

    def _make_columns(self, algorithm, behavior, hits, limit, duration, n,
                      greg_expire, greg_duration) -> "_Columns":
        if not (self._native and self.store is None):
            raise RuntimeError(
                "apply_columns requires the native host runtime and no Store SPI"
            )
        return make_columns(algorithm, behavior, hits, limit, duration, n,
                            greg_expire, greg_duration)

    # ------------------------------------------------------------------
    # Store SPI integration
    # ------------------------------------------------------------------
    def _store_resolver(self, now_ms: int):
        return make_store_resolver(
            self.table, self.algo_mirror, self.store, self._inject, now_ms
        )

    def _inject(self, slot: int, item) -> None:
        """Write one CacheItem into the device row + host mirrors."""
        rows = item_to_rows(item)
        self.algo_mirror[slot] = int(rows.algo[0])
        self.state = buckets.write_rows(self.state, np.array([slot], np.int32), rows)
        self.table.set_expire(slot, item.expire_at)

    def load_item(self, item) -> None:
        """Loader.Load path: place one persisted item (gubernator.go:78-90)."""
        self._drain_then_lock()
        try:
            slot, _ = self.table.lookup_or_assign(item.key, 0)
            self._inject(slot, item)
        finally:
            self._unlock_drained()

    def snapshot_items(self):
        """Loader.Save path: every mapped slot as a CacheItem
        (gubernator.go:93-111); drains in-flight batches first so the
        snapshot reflects every dispatched batch's committed state."""
        self._drain_then_lock()
        try:
            keys = self.table.keys()
            if not keys:
                return []
            slots = [self.table.get_slot(k) for k in keys]
            rows = buckets.read_rows(self.state, np.asarray(slots, np.int32))
            return _rows_to_items(keys, rows)
        finally:
            self._unlock_drained()

    # ------------------------------------------------------------------
    # Elastic membership: columnar state handoff (reshard.py) — the
    # single-shard twin of MeshBucketStore.drain_keys/commit_transfer.
    # ------------------------------------------------------------------
    def resident_keys(self) -> List[str]:
        """Keys currently resident in the slot table (ring-delta scan
        input).  Host-only, no device programs — held under the plan
        lock (like snapshot_items): the native key enumeration is a
        size-then-fill marshal that a concurrent planner growing the
        table would overrun."""
        self._drain_then_lock()
        try:
            return list(self.table.keys())
        finally:
            self._unlock_drained()

    def resident_mask(self, keys) -> np.ndarray:
        """Which keys currently map to a slot (the handoff peek's
        observe-don't-create filter; see MeshBucketStore)."""
        out = np.zeros(len(keys), dtype=bool)
        for j, k in enumerate(keys):
            out[j] = self.table.get_slot(k) is not None
        return out

    def drain_keys(self, keys, now_ms: int, remove: bool = True):
        """Drain moved keys: ONE gather program for the whole batch
        (atomic w.r.t. dispatches — the pipeline is drained and the
        plan lock held).  remove=False leaves the table untouched (the
        handoff's gather-then-forget-on-ack protocol); expired rows are
        never shipped."""
        self._drain_then_lock()
        try:
            return self._gather_transfer_locked(keys, now_ms, remove)
        finally:
            self._unlock_drained()

    def snapshot_columns(self, now_ms: int):
        """Durability dump (snapshot.py): every resident key's full
        bucket row in ONE gather program — drain_keys' all-keys variant
        (gather-only, nothing removed).  Warmup keys are synthetic
        compile fodder and stay out of the file."""
        self._drain_then_lock()
        try:
            keys = [
                k for k in self.table.keys()
                if not k.startswith("__warmup__")
            ]
            return self._gather_transfer_locked(keys, now_ms, remove=False)
        finally:
            self._unlock_drained()

    def _gather_transfer_locked(self, keys, now_ms: int, remove: bool):
        from ..reshard import TransferColumns

        found = [
            (k, s) for k in keys
            if (s := self.table.get_slot(k)) is not None
        ]
        if not found:
            return TransferColumns.empty()
        slots = np.asarray([s for _, s in found], np.int32)
        rows = jax.tree.map(
            np.asarray, buckets.read_rows(self.state, slots)
        )
        self.device_dispatches += 1
        if remove:
            for k, _ in found:
                self.table.remove(k)
        live = np.nonzero(np.asarray(rows.expire_at) >= now_ms)[0]
        return TransferColumns(
            keys=[found[int(i)][0] for i in live],
            algorithm=np.asarray(rows.algo)[live].astype(np.int32),
            status=np.asarray(rows.status)[live].astype(np.int32),
            limit=np.asarray(rows.limit)[live].astype(np.int64),
            remaining=np.asarray(rows.remaining)[live].astype(np.int64),
            duration=np.asarray(rows.duration)[live].astype(np.int64),
            stamp=np.asarray(rows.stamp)[live].astype(np.int64),
            expire_at=np.asarray(rows.expire_at)[live].astype(np.int64),
        )

    def forget_keys(self, keys) -> None:
        """Drop keys from the table after a transfer ACK (no device
        program; see MeshBucketStore.forget_keys)."""
        self._drain_then_lock()
        try:
            for k in keys:
                self.table.remove(k)
        finally:
            self._unlock_drained()

    def commit_transfer(self, cols, now_ms: int) -> int:
        """Receive side of an ownership transfer: assign slots, gather
        the CURRENT rows for already-resident keys, merge monotonically
        (reshard.merge_transfer_rows — idempotent under re-delivery),
        and scatter back.  O(1) device programs per batch (gather +
        scatter), counted in `device_dispatches`."""
        from ..reshard import merge_transfer_rows

        n = len(cols)
        if n == 0:
            return 0
        self._drain_then_lock()
        try:
            fresh = np.nonzero(np.asarray(cols.expire_at) >= now_ms)[0]
            seen: Dict[str, int] = {}
            for j in fresh:
                seen[cols.keys[int(j)]] = int(j)
            idx = np.fromiter(seen.values(), np.int64, count=len(seen))
            if not idx.size:
                return 0
            slots = np.empty(idx.size, np.int32)
            exists = np.zeros(idx.size, dtype=bool)
            for j, i in enumerate(idx):
                slots[j], exists[j] = self.table.lookup_or_assign(
                    cols.keys[int(i)], now_ms
                )
            cur = jax.tree.map(
                np.asarray, buckets.read_rows(self.state, slots)
            )
            merged = merge_transfer_rows(
                {
                    "algo": cur.algo, "status": cur.status,
                    "limit": cur.limit, "remaining": cur.remaining,
                    "stamp": cur.stamp, "expire_at": cur.expire_at,
                },
                cols, idx, now_ms, exists,
            )
            self.state = buckets.write_rows(
                self.state, slots,
                buckets.BucketRows(
                    algo=merged["algo"], limit=merged["limit"],
                    remaining=merged["remaining"],
                    duration=merged["duration"], stamp=merged["stamp"],
                    expire_at=merged["expire_at"], status=merged["status"],
                ),
            )
            self.device_dispatches += 2
            self.algo_mirror[slots] = merged["algo"]
            for j in range(idx.size):
                self.table.set_expire(
                    int(slots[j]), int(merged["expire_at"][j])
                )
            return int(idx.size)
        finally:
            self._unlock_drained()

    # ------------------------------------------------------------------
    def _run_round(
        self,
        chunk: List[_Prepared],
        now_ms: int,
        responses: List[Optional[RateLimitResponse]],
    ) -> None:
        b = len(chunk)
        arrays = build_round_arrays(chunk, pad_size(b))
        batch = buckets.make_batch(*arrays)
        self.state, out = buckets.apply_batch_jit(self.state, batch, now_ms)

        # device_get on the whole pytree overlaps the transfers (one
        # round-trip instead of five sequential blocking readbacks).
        out = jax.device_get(out)
        out_status = out.status
        out_rem = out.remaining
        out_reset = out.reset_time
        out_exp = out.new_expire
        out_removed = out.removed

        slot = arrays[0]
        self.table.commit(
            slot[:b], out_exp[:b], out_removed[:b], keys=[p.key for p in chunk]
        )
        for i, p in enumerate(chunk):
            self.algo_mirror[p.slot] = int(p.req.algorithm)
            responses[p.pos] = RateLimitResponse(
                status=int(out_status[i]),
                limit=int(p.req.limit),
                remaining=int(out_rem[i]),
                reset_time=int(out_reset[i]),
            )
        if self.store is not None:
            self._fire_store_callbacks(chunk, out_removed)

    # ------------------------------------------------------------------
    def _fire_store_callbacks(self, chunk, out_removed) -> None:
        """Post-round Store calls: remove for freed slots
        (algorithms.go:38-40), on_change with the post-apply item for
        everything else (the deferred s.OnChange, algorithms.go:64-68)."""
        live = [(i, p) for i, p in enumerate(chunk) if not out_removed[i]]
        for i, p in enumerate(chunk):
            if out_removed[i]:
                self.store.remove(p.key)
        if not live:
            return
        rows = buckets.read_rows(
            self.state, np.asarray([p.slot for _, p in live], np.int32)
        )
        items = _rows_to_items([p.key for _, p in live], rows)
        for (_, p), item in zip(live, items):
            self.store.on_change(p.req, item)

    # ------------------------------------------------------------------
    def size(self) -> int:
        return len(self.table)


def make_store_resolver(table, algo_mirror, store, inject_fn, now_ms: int):
    """Slot resolution wrapped with the reference's Store call pattern:
    cache miss -> store.get -> inject (algorithms.go:26-33); cached item
    with switched algorithm -> store.remove + re-get
    (algorithms.go:54-62,196-204).  Shared by ShardStore and
    MeshBucketStore (per-shard tables, one store)."""

    def resolve(p):
        slot, exists = table.lookup_or_assign(p.key, now_ms)
        req = p.req
        if exists and algo_mirror[slot] != int(req.algorithm):
            # Algorithm switch: reference removes from cache AND store,
            # then re-reads the store on the retry pass.
            store.remove(p.key)
            item, ok = store.get(req)
            if ok and item is not None and int(item.algorithm) == int(req.algorithm):
                inject_fn(slot, item)
                return slot, True
            return slot, False
        if not exists:
            item, ok = store.get(req)
            if ok and item is not None and int(item.algorithm) != int(req.algorithm):
                # c.Add + failed type-cast -> remove both + re-get.
                store.remove(p.key)
                item, ok = store.get(req)
            if ok and item is not None:
                inject_fn(slot, item)
                # Note: an already-expired store item is recreated by the
                # kernel's expiry check rather than resurrected
                # (divergence: the reference trusts store items without
                # re-checking ExpireAt for one request).
                return slot, True
        return slot, exists

    return resolve


def item_to_rows(item) -> "buckets.BucketRows":
    """Convert one SPI CacheItem to a single-row BucketRows."""
    from ..store import LeakyBucketItem

    v = item.value
    if isinstance(v, LeakyBucketItem):
        return buckets.BucketRows(
            algo=np.array([int(Algorithm.LEAKY_BUCKET)], np.int32),
            limit=np.array([v.limit], np.int64),
            remaining=np.array([int(v.remaining * buckets.LEAKY_SCALE)], np.int64),
            duration=np.array([v.duration], np.int64),
            stamp=np.array([v.updated_at], np.int64),
            expire_at=np.array([item.expire_at], np.int64),
            status=np.array([0], np.int32),
        )
    return buckets.BucketRows(
        algo=np.array([int(Algorithm.TOKEN_BUCKET)], np.int32),
        limit=np.array([v.limit], np.int64),
        remaining=np.array([v.remaining], np.int64),
        duration=np.array([v.duration], np.int64),
        stamp=np.array([v.created_at], np.int64),
        expire_at=np.array([item.expire_at], np.int64),
        status=np.array([int(v.status)], np.int32),
    )


def _rows_to_items(keys, rows):
    """Convert gathered device rows to SPI CacheItems (store.go:11-24)."""
    from ..store import CacheItem, LeakyBucketItem, TokenBucketItem

    algo = np.asarray(rows.algo)
    limit = np.asarray(rows.limit)
    remaining = np.asarray(rows.remaining)
    duration = np.asarray(rows.duration)
    stamp = np.asarray(rows.stamp)
    expire = np.asarray(rows.expire_at)
    status = np.asarray(rows.status)
    items = []
    for i, key in enumerate(keys):
        if algo[i] == int(Algorithm.LEAKY_BUCKET):
            value = LeakyBucketItem(
                limit=int(limit[i]),
                duration=int(duration[i]),
                remaining=remaining[i] / buckets.LEAKY_SCALE,
                updated_at=int(stamp[i]),
            )
        else:
            value = TokenBucketItem(
                limit=int(limit[i]),
                duration=int(duration[i]),
                remaining=int(remaining[i]),
                created_at=int(stamp[i]),
                status=int(status[i]),
            )
        items.append(
            CacheItem(algorithm=int(algo[i]), key=key, value=value, expire_at=int(expire[i]))
        )
    return items
