"""Kubernetes discovery pool — Endpoints/Pods list+watch membership.

Reference behavior (kubernetes.go): a SharedIndexInformer watches either
the Endpoints of a Service or Pods by label selector
(kubernetes.go:44-62, 155-181); every add/update/delete rebuilds the
peer list from the informer store — endpoint subset addresses or
running-and-ready pod IPs, each as `ip:pod_port`, with IsOwner matched
by PodIP (kubernetes.go:183-237).

The reference depends on client-go; this build implements the informer
pattern directly over the Kubernetes HTTP API with the stdlib: an
initial LIST captures state + resourceVersion, a chunked WATCH stream
applies JSON events from that version, and any stream failure (timeout,
410 Gone) falls back to relist-then-rewatch — the same list/watch
contract client-go's Reflector implements.  In-cluster credentials come
from the standard service-account mount, like client-go's
rest.InClusterConfig (kubernetesconfig.go:1-11).
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import socket
import ssl
import threading
import urllib.parse
from typing import Callable, Dict, List, Optional, Tuple

from .types import PeerInfo

log = logging.getLogger("gubernator.k8s")

SERVICE_ACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"
BACKOFF_S = 5.0

WATCH_ENDPOINTS = "endpoints"
WATCH_PODS = "pods"


def watch_mechanism_from_string(mechanism: str) -> str:
    """kubernetes.go:51-62: empty defaults to endpoints."""
    if mechanism in ("", WATCH_ENDPOINTS):
        return WATCH_ENDPOINTS
    if mechanism == WATCH_PODS:
        return WATCH_PODS
    raise ValueError(f"unknown watch mechanism specified: {mechanism}")


class K8sApiClient:
    """Minimal Kubernetes API client (list + watch) over stdlib HTTP.

    Defaults to in-cluster config: KUBERNETES_SERVICE_HOST/PORT env plus
    the service-account token and CA from the standard mount.  Tests
    and out-of-cluster use pass `api_url` (http:// or https://) and an
    optional token/ca_file directly.
    """

    def __init__(
        self,
        api_url: str = "",
        token: str = "",
        ca_file: str = "",
        client_cert_file: str = "",
        client_key_file: str = "",
        skip_tls_verify: bool = False,
    ):
        if not api_url:
            host = os.environ.get("KUBERNETES_SERVICE_HOST", "")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise RuntimeError(
                    "not running in-cluster (no KUBERNETES_SERVICE_HOST) and "
                    "no api_url was provided"
                )
            api_url = f"https://{host}:{port}"
        self.api_url = api_url.rstrip("/")
        if not token:
            token_path = os.path.join(SERVICE_ACCOUNT_DIR, "token")
            if os.path.exists(token_path):
                with open(token_path) as f:
                    token = f.read().strip()
        self.token = token
        if not ca_file:
            default_ca = os.path.join(SERVICE_ACCOUNT_DIR, "ca.crt")
            if os.path.exists(default_ca):
                ca_file = default_ca
        self._ssl_ctx: Optional[ssl.SSLContext] = None
        if self.api_url.startswith("https://"):
            self._ssl_ctx = ssl.create_default_context(
                cafile=ca_file or None
            )
            if client_cert_file:
                self._ssl_ctx.load_cert_chain(
                    client_cert_file, client_key_file or None
                )
            if skip_tls_verify:
                self._ssl_ctx.check_hostname = False
                self._ssl_ctx.verify_mode = ssl.CERT_NONE

    @classmethod
    def auto(cls) -> "K8sApiClient":
        """In-cluster config when the service-account env is present,
        otherwise the local kubeconfig — the reference's build-tag pair
        (kubernetesconfig.go:1-11 in-cluster /
        kubernetesconfig_local.go:1-38 ~/.kube/config)."""
        if os.environ.get("KUBERNETES_SERVICE_HOST"):
            return cls()
        try:
            return cls.from_kubeconfig()
        except FileNotFoundError as e:
            raise RuntimeError(
                "not running in-cluster (no KUBERNETES_SERVICE_HOST) and no "
                f"kubeconfig found ({e.filename}); set KUBECONFIG or mount "
                "the service account"
            ) from e

    @classmethod
    def from_kubeconfig(cls, path: str = "", context: str = "") -> "K8sApiClient":
        """Out-of-cluster client from a kubeconfig file
        (kubernetesconfig_local.go:1-38 equivalent: clientcmd loading
        rules — $KUBECONFIG, then ~/.kube/config).  Supports server +
        CA (file or inline base64 data), bearer token, and client
        cert/key auth; `context` overrides current-context."""
        import base64
        import tempfile

        try:
            import yaml
        except ImportError as e:  # pragma: no cover
            raise RuntimeError(
                "kubeconfig support requires PyYAML "
                "(pip install 'gubernator-tpu[k8s]')"
            ) from e

        path = (
            path
            or os.environ.get("KUBECONFIG", "")
            or os.path.expanduser("~/.kube/config")
        )
        with open(path) as f:
            cfg = yaml.safe_load(f) or {}
        base_dir = os.path.dirname(os.path.abspath(path))

        def by_name(section, name):
            for entry in cfg.get(section, []) or []:
                if entry.get("name") == name:
                    return entry.get(section.rstrip("s"), {})
            raise ValueError(f"kubeconfig: no {section} entry named {name!r}")

        ctx_name = context or cfg.get("current-context", "")
        if not ctx_name:
            raise ValueError("kubeconfig: no current-context set")
        ctx = by_name("contexts", ctx_name)
        cluster = by_name("clusters", ctx.get("cluster", ""))
        user = by_name("users", ctx.get("user", ""))
        for unsupported in ("exec", "auth-provider"):
            if user.get(unsupported):
                # Silently ignoring these would yield an unauthenticated
                # client that 401s at runtime with no hint why.
                raise ValueError(
                    f"kubeconfig: user {ctx.get('user')!r} uses "
                    f"'{unsupported}' auth, which this client does not "
                    "support; use a token or client certificate"
                )

        def materialize(file_key: str, data_key: str, source: dict) -> str:
            """Inline base64 *-data wins over the file path variant.
            Materialized files (which may hold a client PRIVATE KEY)
            are 0600 and removed at interpreter exit.  Relative file
            paths resolve against the kubeconfig's own directory
            (clientcmd semantics)."""
            data = source.get(data_key, "")
            if data:
                import atexit

                tmp = tempfile.NamedTemporaryFile(
                    prefix="guber-kubeconfig-", delete=False
                )
                tmp.write(base64.b64decode(data))
                tmp.close()
                atexit.register(
                    lambda p=tmp.name: os.path.exists(p) and os.remove(p)
                )
                return tmp.name
            file_path = source.get(file_key, "")
            if file_path and not os.path.isabs(file_path):
                file_path = os.path.join(base_dir, file_path)
            return file_path

        return cls(
            api_url=cluster.get("server", ""),
            token=user.get("token", ""),
            ca_file=materialize(
                "certificate-authority", "certificate-authority-data", cluster
            ),
            client_cert_file=materialize(
                "client-certificate", "client-certificate-data", user
            ),
            client_key_file=materialize("client-key", "client-key-data", user),
            skip_tls_verify=bool(cluster.get("insecure-skip-tls-verify")),
        )

    def _connect(self, timeout: Optional[float]):
        scheme, _, rest = self.api_url.partition("://")
        hostname, _, port = rest.partition(":")
        if scheme == "https":
            return http.client.HTTPSConnection(
                hostname, int(port or 443), timeout=timeout, context=self._ssl_ctx
            )
        return http.client.HTTPConnection(hostname, int(port or 80), timeout=timeout)

    def _request(self, conn, path: str, params: Dict[str, str]):
        if params:
            path += "?" + urllib.parse.urlencode(params)
        headers = {}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        conn.request("GET", path, headers=headers)
        resp = conn.getresponse()
        if resp.status != 200:
            body = resp.read(200)
            raise OSError(f"k8s API returned HTTP {resp.status}: {body!r}")
        return resp

    # LIST page size: apiservers cap very large lists and the reflector
    # contract is chunked reads (metadata.continue tokens); 500 matches
    # client-go's default reflector page size.
    LIST_LIMIT = 500

    def list(
        self, namespace: str, resource: str, selector: str = ""
    ) -> Tuple[List[dict], str]:
        """Chunked LIST of a namespaced resource (limit= + continue=
        pagination, the client-go reflector contract); returns
        (all items, resourceVersion of the FINAL chunk — the version
        the subsequent watch must start from)."""
        items: List[dict] = []
        cont = ""
        conn = self._connect(timeout=10.0)  # one connection for all chunks
        try:
            while True:
                params = {"limit": str(self.LIST_LIMIT)}
                if selector:
                    params["labelSelector"] = selector
                if cont:
                    params["continue"] = cont
                body = json.load(
                    self._request(
                        conn, f"/api/v1/namespaces/{namespace}/{resource}", params
                    )
                )
                items.extend(body.get("items", []))
                meta = body.get("metadata", {})
                cont = meta.get("continue", "")
                if not cont:
                    return items, meta.get("resourceVersion", "")
        finally:
            conn.close()

    def watch(
        self,
        namespace: str,
        resource: str,
        resource_version: str,
        selector: str = "",
        stop: Optional[threading.Event] = None,
    ):
        """WATCH stream from resource_version: yields (type, object)
        dicts until the server closes the stream, an error arrives, or
        `stop` is set.  The connection is parked on the instance so
        close_watch() can unblock the reader from another thread via a
        socket shutdown — HTTPResponse.close() would deadlock on the
        buffer lock the blocked readline holds."""
        params = {"watch": "true", "resourceVersion": resource_version}
        if selector:
            params["labelSelector"] = selector
        conn = self._connect(timeout=None)
        self._watch_conn = conn
        try:
            resp = self._request(
                conn, f"/api/v1/namespaces/{namespace}/{resource}", params
            )
            for line in resp:
                if stop is not None and stop.is_set():
                    return
                line = line.strip()
                if not line:
                    continue
                event = json.loads(line)
                yield event.get("type", ""), event.get("object", {})
        finally:
            self._watch_conn = None
            try:
                if conn.sock is not None:
                    conn.sock.close()
            except OSError:
                pass

    def close_watch(self) -> None:
        """Unblock a watch() reader stuck in readline: TCP-shutdown the
        socket so the read returns EOF; the watch thread then tears the
        connection down itself."""
        conn = getattr(self, "_watch_conn", None)
        if conn is not None and conn.sock is not None:
            try:
                conn.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass


class K8sPool:
    """Peer discovery over the Kubernetes API (reference K8sPool,
    kubernetes.go:35-241)."""

    def __init__(
        self,
        on_update: Callable[[List[PeerInfo]], None],
        namespace: str = "default",
        selector: str = "",
        pod_ip: str = "",
        pod_port: str = "81",
        mechanism: str = WATCH_ENDPOINTS,
        api_client: Optional[K8sApiClient] = None,
        backoff_s: float = BACKOFF_S,
    ):
        self.on_update = on_update
        self.namespace = namespace
        self.selector = selector
        self.pod_ip = pod_ip
        self.pod_port = pod_port
        self.mechanism = watch_mechanism_from_string(mechanism)
        self.backoff_s = backoff_s
        # In-cluster service account or local kubeconfig, like the
        # reference's build-tag pair (kubernetesconfig*.go).
        self.client = api_client or K8sApiClient.auto()
        self._store: Dict[str, dict] = {}  # namespace/name -> object
        self._stop = threading.Event()
        # The informer loop: list -> watch -> (on failure) relist.
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    # ------------------------------------------------------------------
    @staticmethod
    def _key(obj: dict) -> str:
        meta = obj.get("metadata", {})
        return f"{meta.get('namespace', '')}/{meta.get('name', '')}"

    def _run(self) -> None:
        resource = self.mechanism  # "endpoints" | "pods"
        while not self._stop.is_set():
            try:
                items, rv = self.client.list(self.namespace, resource, self.selector)
                self._store = {self._key(o): o for o in items}
                self._update_peers()
                for etype, obj in self.client.watch(
                    self.namespace, resource, rv, self.selector, self._stop
                ):
                    if self._stop.is_set():
                        return
                    if etype == "ERROR":
                        break  # e.g. 410 Gone: relist from scratch
                    if etype == "DELETED":
                        self._store.pop(self._key(obj), None)
                    elif etype in ("ADDED", "MODIFIED"):
                        self._store[self._key(obj)] = obj
                    else:
                        continue  # BOOKMARK etc.
                    self._update_peers()
            except (OSError, ValueError, http.client.HTTPException) as e:
                # HTTPException covers mid-stream truncation
                # (IncompleteRead etc.), which is neither an OSError nor
                # a ValueError — the informer must relist, not die.
                if not self._stop.is_set():
                    log.warning("k8s watch failed, will relist: %s", e)
            if self._stop.is_set():
                return
            self._stop.wait(self.backoff_s)

    # ------------------------------------------------------------------
    def _update_peers(self) -> None:
        if self.mechanism == WATCH_PODS:
            peers = self._peers_from_pods()
        else:
            peers = self._peers_from_endpoints()
        try:
            self.on_update(peers)
        except Exception:  # noqa: BLE001
            log.exception("on_update callback failed")

    def _peers_from_pods(self) -> List[PeerInfo]:
        """kubernetes.go:187-210: skip pods with any container not ready
        or not running; IsOwner by PodIP match."""
        peers = []
        for obj in self._store.values():
            status = obj.get("status", {})
            ip = status.get("podIP", "")
            if not ip:
                continue
            statuses = status.get("containerStatuses", [])
            if any(
                not cs.get("ready") or "running" not in cs.get("state", {})
                for cs in statuses
            ):
                continue
            peers.append(
                PeerInfo(
                    grpc_address=f"{ip}:{self.pod_port}",
                    is_owner=(ip == self.pod_ip),
                )
            )
        return sorted(peers, key=lambda p: p.grpc_address)

    def _peers_from_endpoints(self) -> List[PeerInfo]:
        """kubernetes.go:212-237: every ready subset address."""
        peers = []
        for obj in self._store.values():
            for subset in obj.get("subsets", []) or []:
                for addr in subset.get("addresses", []) or []:
                    ip = addr.get("ip", "")
                    if not ip:
                        continue
                    peers.append(
                        PeerInfo(
                            grpc_address=f"{ip}:{self.pod_port}",
                            is_owner=(ip == self.pod_ip),
                        )
                    )
        return sorted(peers, key=lambda p: p.grpc_address)

    # ------------------------------------------------------------------
    def close(self) -> None:
        self._stop.set()
        self.client.close_watch()
        self._thread.join(timeout=2.0)
