"""Persistence SPI: Store (continuous) and Loader (startup/shutdown).

Parity with store.go:29-58: `Store.on_change/get/remove` are called
synchronously around every rate-limit evaluation for keys it covers;
`Loader.load/save` run once at daemon start/stop.  Mock implementations
ship in the production package exactly like the reference's
(store.go:60-130) so user test suites can count calls.

Item shapes mirror TokenBucketItem / LeakyBucketItem (store.go:11-24);
leaky `remaining` is a float (the device keeps it fixed-point, the SPI
converts), so user stores written against the reference port directly.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Protocol, Tuple, Union

from .types import Algorithm, RateLimitRequest, Status


@dataclass
class TokenBucketItem:
    """store.go:18-24"""

    limit: int = 0
    duration: int = 0
    remaining: int = 0
    created_at: int = 0
    status: int = Status.UNDER_LIMIT


@dataclass
class LeakyBucketItem:
    """store.go:11-16"""

    limit: int = 0
    duration: int = 0
    remaining: float = 0.0
    updated_at: int = 0


@dataclass
class CacheItem:
    """cache.go:64-76"""

    algorithm: int = Algorithm.TOKEN_BUCKET
    key: str = ""
    value: Union[TokenBucketItem, LeakyBucketItem, None] = None
    expire_at: int = 0


class Store(Protocol):
    """store.go:29-45.  OnChange receives the item state AFTER the
    request was applied; Get fulfills cache misses; Remove is called on
    explicit removal (RESET_REMAINING, algorithm switch), never on
    expiry."""

    def on_change(self, r: RateLimitRequest, item: CacheItem) -> None: ...

    def get(self, r: RateLimitRequest) -> Tuple[Optional[CacheItem], bool]: ...

    def remove(self, key: str) -> None: ...


class Loader(Protocol):
    """store.go:49-58."""

    def load(self) -> Iterable[CacheItem]: ...

    def save(self, items: Iterator[CacheItem]) -> None: ...


class MockStore:
    """store.go:60-92 — call-counting in-memory store."""

    def __init__(self):
        self.called: Dict[str, int] = {"OnChange()": 0, "Remove()": 0, "Get()": 0}
        self.cache_items: Dict[str, CacheItem] = {}

    def on_change(self, r: RateLimitRequest, item: CacheItem) -> None:
        self.called["OnChange()"] += 1
        self.cache_items[item.key] = item

    def get(self, r: RateLimitRequest) -> Tuple[Optional[CacheItem], bool]:
        self.called["Get()"] += 1
        item = self.cache_items.get(r.hash_key())
        return item, item is not None

    def remove(self, key: str) -> None:
        self.called["Remove()"] += 1
        self.cache_items.pop(key, None)


class MockLoader:
    """store.go:94-130 — call-counting loader."""

    def __init__(self):
        self.called: Dict[str, int] = {"Load()": 0, "Save()": 0}
        self.cache_items: List[CacheItem] = []

    def load(self) -> Iterable[CacheItem]:
        self.called["Load()"] += 1
        return list(self.cache_items)

    def save(self, items: Iterator[CacheItem]) -> None:
        self.called["Save()"] += 1
        self.cache_items.extend(items)
