"""Request tracing + flight recorder (Dapper, Sigelman et al. 2010).

The system has three layers of concurrency machinery — the columnar
coalescer, the PREPARE/STAGE/LAUNCH/FETCH/COMMIT dispatch pipeline and
the batched peer hop — and aggregate gauges cannot say WHERE one slow
request lost its time.  This module adds:

* **Spans** — monotonic-ns intervals with a 128-bit trace id / 64-bit
  span id, W3C `traceparent` interop at the edges.  Context is
  per-thread (`current()`); sampling is decided ONCE per request at
  ingress (`GUBER_TRACE_SAMPLE`, a 0..1 rate).  When tracing is off —
  or the request lost the sampling dice roll — every entry point
  returns the shared `_NOOP` singleton: no allocation, no id
  generation, one float compare on the hot path.

* **Span links, not nesting, for batches.**  Coalescing means one
  device dispatch / one peer RPC carries MANY traces; a batch gets its
  own trace (the `batch.window` span) and every per-stage span LINKS
  the member lanes' contexts (the Dapper/OpenTelemetry span-link rule
  for fan-in).  `/debug/traces?trace_id=X` therefore matches spans
  whose own id is X *or* that link X.

* **Flight recorder** — a lock-free ring buffer of the last N spans
  and N events.  CPython makes `next(itertools.count())` and a list
  slot assignment atomic, so writers never take a lock and a reader's
  snapshot is at worst one record torn-at-the-edges (it sorts by
  sequence number and drops holes).  Dumped via the gateway's
  `GET /debug/traces` / `GET /debug/events` and automatically (to the
  structured log, rate-limited) on breaker-open / ingress-shed /
  injected-fault events.

Cross-daemon: the peer hop carries a sparse trace-context column (lane
ranges -> trace/span ids) in both columnar encodings, so a forwarded
check produces ONE trace spanning both daemons (wire.py).
"""

from __future__ import annotations

import itertools
import json
import os
import random
import threading
import time
import weakref
from typing import Dict, List, Optional, Sequence, Tuple

from .utils.logging import category_logger

logger = category_logger("tracing")

# Sampling rate (0..1).  0 disables tracing entirely: every hook
# degrades to a single comparison and the wire carries no trace bytes
# (the GUBER_TRACE_SAMPLE=0 wire-parity contract).
_SAMPLE: float = 0.0
# Bench-only "compiled out" switch: the overhead gate compares the
# sample-rate-0 guards against this fully-disabled baseline.
_FORCE_DISABLED: bool = False

def _env_ring(default: int = 4096) -> int:
    """GUBER_TRACE_RING, warn-and-default on garbage — module import
    must never raise (every layer imports this module)."""
    v = os.environ.get("GUBER_TRACE_RING", "")
    if not v:
        return default
    try:
        return max(int(v), 1)
    except ValueError:
        import warnings

        warnings.warn(
            f"GUBER_TRACE_RING must be an integer, got {v!r}; "
            f"using {default}",
            stacklevel=2,
        )
        return default


SPAN_RING_CAPACITY = _env_ring()
EVENT_RING_CAPACITY = 1024

_tls = threading.local()


def _env_sample() -> float:
    """Import-time env default.  Out-of-range/unparsable values fall
    back to 0 (OFF) with a warning — the safe direction; clamping 5 to
    1.0 would be the 100%-sampling surprise config.setup_daemon_config
    loudly rejects.  Import time cannot raise, so warn-and-disable is
    the library-embedding equivalent of that validation."""
    v = os.environ.get("GUBER_TRACE_SAMPLE", "")
    if not v:
        return 0.0
    try:
        rate = float(v)
    except ValueError:
        rate = -1.0
    if not 0.0 <= rate <= 1.0:
        import warnings

        warnings.warn(
            f"GUBER_TRACE_SAMPLE must be a float in [0, 1], got {v!r}; "
            "tracing disabled",
            stacklevel=2,
        )
        return 0.0
    return rate


def set_sample_rate(rate: float) -> None:
    global _SAMPLE
    _SAMPLE = min(max(float(rate), 0.0), 1.0)


def sample_rate() -> float:
    return _SAMPLE


def force_disable(flag: bool) -> None:
    """Bench hook: behave as if the module did not exist (the
    'tracing-compiled-out' baseline of the overhead gate)."""
    global _FORCE_DISABLED
    _FORCE_DISABLED = bool(flag)


def enabled() -> bool:
    """One branch — THE hot-path guard every layer uses."""
    return _SAMPLE > 0.0 and not _FORCE_DISABLED


def sampled() -> bool:
    """Roll the sampling dice for work that is not an ingress request
    (the GlobalManager's sync ticks): same rate, same single-compare
    fast path when tracing is off."""
    return enabled() and _rng().random() < _SAMPLE


def _rng() -> random.Random:
    r = getattr(_tls, "rng", None)
    if r is None:
        r = _tls.rng = random.Random(os.urandom(16))
    return r


class SpanContext:
    """An active (trace, span) pair — what propagates."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: int, span_id: int):
        self.trace_id = trace_id
        self.span_id = span_id

    @property
    def trace_hex(self) -> str:
        return format(self.trace_id, "032x")

    @property
    def span_hex(self) -> str:
        return format(self.span_id, "016x")

    def __repr__(self) -> str:  # debugging only
        return f"SpanContext({self.trace_hex}, {self.span_hex})"


def current() -> Optional[SpanContext]:
    """The calling thread's active span context (None = no sampled
    trace on this thread)."""
    return getattr(_tls, "ctx", None)


# ---------------------------------------------------------------------
# W3C traceparent (https://www.w3.org/TR/trace-context/)
# ---------------------------------------------------------------------
def format_traceparent(ctx: SpanContext) -> str:
    return f"00-{ctx.trace_hex}-{ctx.span_hex}-01"


def parse_traceparent(value: str) -> Optional[Tuple[int, int, bool]]:
    """-> (trace_id, span_id, sampled_flag) or None on any malformed
    input (a bad header must never fail the request)."""
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_hex, span_hex, flags = parts
    if len(version) != 2 or len(trace_hex) != 32 or len(span_hex) != 16:
        return None
    if version == "ff":
        return None
    try:
        trace_id = int(trace_hex, 16)
        span_id = int(span_hex, 16)
        sampled = bool(int(flags, 16) & 0x01)
    except ValueError:
        return None
    if trace_id == 0 or span_id == 0:
        return None
    return trace_id, span_id, sampled


# ---------------------------------------------------------------------
# Flight recorder: lock-free rings
# ---------------------------------------------------------------------
class _Ring:
    """Fixed-capacity ring written without locks.  `next()` on an
    itertools.count and a list-slot store are each atomic under the
    GIL; a reader snapshot copies the slot list, sorts by sequence and
    tolerates the (rare) slot being overwritten mid-copy."""

    def __init__(self, capacity: int):
        self._cap = max(int(capacity), 1)
        self._buf: List[Optional[tuple]] = [None] * self._cap
        self._seq = itertools.count()

    def record(self, item: dict) -> None:
        i = next(self._seq)
        self._buf[i % self._cap] = (i, item)

    def snapshot(self) -> List[dict]:
        entries = [e for e in list(self._buf) if e is not None]
        entries.sort(key=lambda e: e[0])
        return [item for _, item in entries]

    def clear(self) -> None:
        self._buf = [None] * self._cap


# Event kinds that trigger an automatic flight-recorder dump to the
# structured log (rate-limited so an open breaker can't storm it).
# global-send-failed: a GLOBAL broadcast/hit-forward send exhausted its
# retry budget — the same lost-progress signal a breaker trip is.
# slo-fast-burn: the SLO engine (saturation.py) measured a page-level
# error-budget burn on its short window — dump while the evidence of
# WHERE the latency went is still in the ring.
# reshard-aborted: an ownership transfer failed/was fenced and its
# lanes degraded to reset-on-move (reshard.py) — the state-loss moment
# the recorder exists to preserve.
_DUMP_KINDS = frozenset({"breaker-open", "shed", "fault",
                         "global-send-failed", "slo-fast-burn",
                         "reshard-aborted", "recompile-storm",
                         "audit-violation", "snapshot-rejected"})
_DUMP_MIN_INTERVAL_S = 5.0

# Every live Recorder (weakly — a closed service's recorder must not be
# pinned by this registry).  Module-level snapshots/reset operate on
# the union, which preserves the one-global-ring semantics bare-store
# users had before per-service recorders existed.
_recorders: "weakref.WeakSet[Recorder]" = weakref.WeakSet()


class Recorder:
    """One flight recorder: a span ring + event ring + the auto-dump
    rate limiter, keyed per daemon/service instance so co-resident
    daemons' incidents no longer interleave (the PR 9 shared-ring
    wart).  Threads owned by a service bind its recorder via
    `bind_recorder`; unbound threads fall back to the module default,
    and readers MERGE (spans_snapshot/events_snapshot take an explicit
    recorder list), so spans recorded off an unbound helper thread are
    never lost to a per-service view.

    `dump_hooks` is the incident trigger surface: callables
    `(trigger_kind, fields) -> None` invoked on EVERY _DUMP_KINDS event
    BEFORE the log dump's rate limit — the black box (blackbox.py) does
    its own coalescing/rate limiting and must see every trigger."""

    __slots__ = ("name", "_spans", "_events", "dump_hooks", "_last_dump",
                 "_dump_lock", "__weakref__")

    def __init__(self, span_capacity: int = 0, event_capacity: int = 0,
                 name: str = ""):
        self.name = name
        self._spans = _Ring(span_capacity or SPAN_RING_CAPACITY)
        self._events = _Ring(event_capacity or EVENT_RING_CAPACITY)
        self.dump_hooks: List = []
        self._last_dump = 0.0
        self._dump_lock = threading.Lock()
        _recorders.add(self)

    def spans(self) -> List[dict]:
        return self._spans.snapshot()

    def events(self) -> List[dict]:
        return self._events.snapshot()

    def clear(self) -> None:
        self._spans.clear()
        self._events.clear()

    def _auto_dump(self, trigger: str, fields: dict) -> None:
        # Hooks BEFORE the rate limit: the black box coalesces trigger
        # storms itself and must count every one; each hook is fenced —
        # diagnostics must never fail the path that fired the event.
        for hook in list(self.dump_hooks):
            try:
                hook(trigger, fields)
            except Exception:  # noqa: BLE001
                logger.exception("flight-recorder dump hook failed")
        now = time.monotonic()
        with self._dump_lock:
            if now - self._last_dump < _DUMP_MIN_INTERVAL_S:
                return
            self._last_dump = now
        try:
            payload = {
                "trigger": trigger,
                "events": self._events.snapshot()[-20:],
                "spans": self._spans.snapshot()[-50:],
            }
            logger.warning(
                "flight-recorder dump trigger=%s %s",
                trigger,
                json.dumps(payload, separators=(",", ":"), default=str),
            )
        except Exception:  # noqa: BLE001 — diagnostics must never fail the path
            logger.exception("flight-recorder dump failed")


_DEFAULT = Recorder(name="process")
# Back-compat aliases: library code and tests reach for the module
# rings directly (tracing._spans.record(...)); they are the DEFAULT
# recorder's rings.
_spans = _DEFAULT._spans
_events = _DEFAULT._events


def default_recorder() -> Recorder:
    return _DEFAULT


def bind_recorder(rec: Optional[Recorder]) -> None:
    """Bind `rec` as this thread's flight recorder (None = back to the
    module default).  Service-owned threads (gateway workers, pools,
    the auditor, the native pump) bind their service's recorder so
    incidents are attributable per daemon."""
    _tls.recorder = rec


def current_recorder() -> Recorder:
    return getattr(_tls, "recorder", None) or _DEFAULT


def all_recorders() -> List[Recorder]:
    return list(_recorders)


def record_span(
    name: str,
    ctx: SpanContext,
    parent_id: int = 0,
    start_ns: int = 0,
    end_ns: int = 0,
    links: Sequence[SpanContext] = (),
    **attrs,
) -> None:
    """Append one COMPLETED span to the flight recorder.  `wall_ns`
    stamps the span's END on the wall clock (time.time_ns) — spans'
    start_ns are MONOTONIC and therefore incomparable across daemons;
    the wall stamp is what lets scripts/trace_collect.py order one
    trace's spans from several processes and measure hop latencies
    (NTP-grade skew applies, which is fine for hop-scale deltas)."""
    current_recorder()._spans.record(
        {
            "name": name,
            "trace_id": ctx.trace_hex,
            "span_id": ctx.span_hex,
            "parent_id": format(parent_id, "016x") if parent_id else "",
            "start_ns": start_ns,
            "dur_ns": max(end_ns - start_ns, 0),
            "wall_ns": time.time_ns(),
            "thread": threading.current_thread().name,
            "links": [
                {"trace_id": l.trace_hex, "span_id": l.span_hex}
                for l in links
            ],
            "attrs": attrs,
        }
    )


def record_event(kind: str, **fields) -> None:
    """Append one event; breaker-open / shed / fault events also dump
    the recorder to the log (the 'automatic on failure' contract) —
    cheap enough to call unconditionally from failure paths even when
    tracing is sampled out, since failures are rare by definition."""
    fields["kind"] = kind
    fields["ts_ns"] = time.monotonic_ns()
    rec = current_recorder()
    rec._events.record(fields)
    if kind in _DUMP_KINDS:
        rec._auto_dump(kind, fields)


def spans_snapshot(trace_id_hex: str = "", since_ns: int = 0,
                   limit: int = 0,
                   recorders: "Optional[Sequence[Recorder]]" = None
                   ) -> List[dict]:
    """Recorded spans, optionally filtered to one trace: a span matches
    when its own trace_id is the target OR it links the target (the
    batch span-link rule — a coalesced dispatch's stage spans belong to
    every lane's trace).  `since_ns` keeps only spans whose wall-clock
    end stamp is strictly newer (the incremental-poll cursor
    scripts/trace_collect.py advances per daemon); `limit` keeps the
    OLDEST N after filtering — the pagination order: a poller whose
    cursor tracks the max wall_ns it received gets the NEXT window on
    its next poll instead of skipping everything between its cursor
    and a newest-N slice.

    `recorders` restricts the read to an explicit recorder list (the
    gateway passes [service recorder, default] so a daemon's view is
    its own work plus unbound-thread spillover); None reads the union
    of every live recorder — the pre-refactor whole-process view."""
    spans: List[dict] = []
    for rec in (recorders if recorders is not None else all_recorders()):
        spans.extend(rec._spans.snapshot())
    if trace_id_hex:
        want = trace_id_hex.lower().lstrip("0x")
        want = want.zfill(32)
        spans = [
            s
            for s in spans
            if s["trace_id"] == want
            or any(l["trace_id"] == want for l in s["links"])
        ]
    if since_ns:
        spans = [s for s in spans if s.get("wall_ns", 0) > since_ns]
    if limit and len(spans) > limit:
        # Ring order is record order, which tracks wall order closely
        # but not exactly (wall_ns is stamped inside record_span);
        # sort by wall stamp so the oldest-N window and the caller's
        # max-wall cursor agree.  A page never ends MID-TIE: concurrent
        # record_span calls can stamp identical wall_ns, and cutting
        # between two equal stamps would let the poller's strict
        # `since >` cursor skip the tied remainder forever — so the
        # page extends through every span sharing the boundary stamp
        # (limit is a soft cap, exceeded only by the tie count).
        spans = sorted(spans, key=lambda s: s.get("wall_ns", 0))
        cut = spans[limit - 1].get("wall_ns", 0)
        spans = [s for s in spans if s.get("wall_ns", 0) <= cut]
    return spans


def events_snapshot(
    recorders: "Optional[Sequence[Recorder]]" = None,
) -> List[dict]:
    """Recorded events, merged across `recorders` (None = every live
    recorder) in monotonic-stamp order — ts_ns is process-monotonic, so
    cross-recorder merge order is exact."""
    recs = recorders if recorders is not None else all_recorders()
    if len(recs) == 1:
        return recs[0]._events.snapshot()
    events: List[dict] = []
    for rec in recs:
        events.extend(rec._events.snapshot())
    events.sort(key=lambda e: e.get("ts_ns", 0))
    return events


def reset() -> None:
    """Test hook: clear every live recorder's rings and this thread's
    context/binding."""
    for rec in all_recorders():
        rec.clear()
    _tls.ctx = None
    _tls.staged = None
    _tls.emitted = None
    _tls.recorder = None


# ---------------------------------------------------------------------
# Spans
# ---------------------------------------------------------------------
class _NoopSpan:
    """Shared do-nothing span: the zero-alloc disabled/unsampled path.
    Every method is a no-op; `bool(_NOOP)` is False so callers can
    branch on it."""

    __slots__ = ()
    ctx = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def activate(self):
        return self

    def deactivate(self):
        pass

    def end(self, **attrs):
        pass

    def traceparent(self):
        return None

    def __bool__(self):
        return False


_NOOP = _NoopSpan()


class _Span:
    """A live sampled span.  Context-manager use (sync paths) pairs
    activate/deactivate with end; async paths call them explicitly —
    activate/deactivate on the submitting thread, end() from whatever
    completion thread finishes the request."""

    __slots__ = ("name", "ctx", "parent_id", "start_ns", "attrs", "links",
                 "_prev", "_prev_set", "_ended")

    def __init__(self, name: str, ctx: SpanContext, parent_id: int = 0,
                 links: Sequence[SpanContext] = (), **attrs):
        self.name = name
        self.ctx = ctx
        self.parent_id = parent_id
        self.links = tuple(links)
        self.attrs = attrs
        self.start_ns = time.monotonic_ns()
        self._prev = None
        self._prev_set = False
        self._ended = False

    def activate(self) -> "_Span":
        self._prev = getattr(_tls, "ctx", None)
        self._prev_set = True
        _tls.ctx = self.ctx
        _tls.emitted = format_traceparent(self.ctx)
        return self

    def deactivate(self) -> None:
        if self._prev_set:
            _tls.ctx = self._prev
            self._prev = None
            self._prev_set = False

    def traceparent(self) -> str:
        return format_traceparent(self.ctx)

    def end(self, **attrs) -> None:
        if self._ended:  # exactly-once: async finish paths can race
            return
        self._ended = True
        if attrs:
            self.attrs.update(attrs)
        record_span(
            self.name, self.ctx, parent_id=self.parent_id,
            start_ns=self.start_ns, end_ns=time.monotonic_ns(),
            links=self.links, **self.attrs,
        )

    def __enter__(self) -> "_Span":
        return self.activate()

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.deactivate()
        if exc_type is not None:
            self.attrs["error"] = str(exc)
        self.end()
        return False


def ingress_span(edge: str, name: str, traceparent: Optional[str] = None,
                 **attrs):
    """Root/continuation span for one ingress request.  The ONE place
    the sampling dice is rolled — and the LOCAL rate always decides:
    an upstream `traceparent` contributes the trace id and parent span
    (so sampled requests still correlate with the caller's ids), but
    its sampled flag neither forces nor suppresses recording here.
    Headers arrive from untrusted clients: honoring flag=01 would let
    any caller stamp itself into 100% sampling (recorder flooding,
    trace bytes on every peer RPC — the overhead the bench gate
    bounds), and honoring flag=00 would let a proxy blind an operator
    running at sample 1.0."""
    if not enabled() or _rng().random() >= _SAMPLE:
        return _NOOP
    parent = parse_traceparent(traceparent) if traceparent else None
    if parent is not None:
        trace_id, parent_span, _flag = parent
    else:
        trace_id, parent_span = _rng().getrandbits(128) or 1, 0
    ctx = SpanContext(trace_id, _rng().getrandbits(64) or 1)
    return _Span(f"ingress.{edge}", ctx, parent_id=parent_span,
                 path=name, **attrs)


def take_emitted_traceparent() -> Optional[str]:
    """The traceparent the most recent ingress span on THIS thread
    emitted (survives span end — the stdlib gateway reads it after
    handle_request returns to stamp the response header)."""
    tp = getattr(_tls, "emitted", None)
    _tls.emitted = None
    return tp


# ---------------------------------------------------------------------
# Batch traces (the span-link machinery for coalesced work)
# ---------------------------------------------------------------------
class BatchTrace:
    """One coalesced unit of work (a window flush / device dispatch)
    carrying links to the member lanes' contexts.  `ctx` is the batch's
    own trace: the window span uses it directly and the per-stage
    dispatch spans parent under it."""

    __slots__ = ("ctx", "links")

    def __init__(self, links: Sequence[SpanContext]):
        self.ctx = SpanContext(
            _rng().getrandbits(128) or 1, _rng().getrandbits(64) or 1
        )
        self.links = tuple(links)


def new_batch(links: Sequence[SpanContext]) -> Optional[BatchTrace]:
    """BatchTrace for `links`, or None when there is nothing to link
    (the unsampled fast path: callers pass the None straight through)."""
    if not links or not enabled():
        return None
    return BatchTrace(links)


def stage_batch_trace(bt: Optional[BatchTrace]) -> None:
    """Hand a BatchTrace to the store pipeline through thread-local
    storage: apply_columns_async runs synchronously on the calling
    thread, and threading an argument through its (stable) signature
    would touch every store implementation."""
    _tls.staged = bt


def take_batch_trace() -> Optional[BatchTrace]:
    bt = getattr(_tls, "staged", None)
    _tls.staged = None
    return bt


def stage_span(stage: str, dur_s: float, bt: Optional[BatchTrace],
               **attrs) -> None:
    """One completed dispatch-pipeline stage span
    (dispatch.prepare/stage/launch/fetch/commit), parented under the
    batch's window span and linked to every member lane."""
    if bt is None:
        return
    end = time.monotonic_ns()
    record_span(
        f"dispatch.{stage}",
        SpanContext(bt.ctx.trace_id, _rng().getrandbits(64) or 1),
        parent_id=bt.ctx.span_id,
        start_ns=end - int(dur_s * 1e9),
        end_ns=end,
        links=bt.links,
        **attrs,
    )


def batch_span(name: str, bt: Optional[BatchTrace], start_ns: int,
               end_ns: int, **attrs) -> None:
    """One completed child span of a batch trace (the GlobalManager's
    global.collective / global.broadcast / global.hits legs), parented
    under the batch root and carrying its links."""
    if bt is None:
        return
    record_span(
        name,
        SpanContext(bt.ctx.trace_id, _rng().getrandbits(64) or 1),
        parent_id=bt.ctx.span_id,
        start_ns=start_ns,
        end_ns=end_ns,
        links=bt.links,
        **attrs,
    )


def request_links(cols) -> List[SpanContext]:
    """Links for a dispatch built from `cols`: the thread's ambient
    context (local ingress) plus any wire trace-context column a peer
    frame/proto carried (cols.trace_ctx: (lane_lo, lane_hi, trace_id,
    span_id) ranges)."""
    if not enabled():
        return []
    links: List[SpanContext] = []
    cur = current()
    if cur is not None:
        links.append(cur)
    entries = getattr(cols, "trace_ctx", None)
    if entries:
        seen = {(cur.trace_id, cur.span_id)} if cur is not None else set()
        for _lo, _hi, tid, sid in entries:
            if (tid, sid) not in seen:
                seen.add((tid, sid))
                links.append(SpanContext(tid, sid))
    return links


def links_to_entries(
    links: Sequence[SpanContext], lo: int, hi: int
) -> List[Tuple[int, int, int, int]]:
    """Wire trace-context entries covering lanes [lo, hi) for every
    linked context (peer_client packs these into the frame trailer /
    proto column)."""
    return [(lo, hi, l.trace_id, l.span_id) for l in links]


# Module init: honor the environment (daemons call set_sample_rate from
# their parsed config as well; library users get the env default).
set_sample_rate(_env_sample())
