"""Load-generation CLI (reference cmd/gubernator-cli/main.go:48-108):
generate random token-bucket limits and hammer an endpoint, printing
OVER_LIMIT responses."""

from __future__ import annotations

import argparse
import random
from concurrent.futures import ThreadPoolExecutor


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="gubernator-tpu load generator")
    parser.add_argument("endpoint", nargs="?", default="127.0.0.1:1050")
    parser.add_argument("--limits", type=int, default=2000)
    parser.add_argument("--concurrency", type=int, default=10)
    parser.add_argument(
        "--columns", action="store_true",
        help="drive the columnar front door (ColumnsV1Client: checks "
        "coalesce into GUBC frames; falls back to classic JSON against "
        "an old daemon)",
    )
    args = parser.parse_args(argv)

    from ..client import ColumnsV1Client, V1Client, random_string
    from ..types import Algorithm, GetRateLimitsRequest, RateLimitRequest, Status, SECOND

    if args.columns:
        client = ColumnsV1Client(args.endpoint, timeout_s=0.5)
    else:
        client = V1Client(args.endpoint, timeout_s=0.5)
    rng = random.Random()
    limits = [
        RateLimitRequest(
            name=f"ID-{i:04d}",
            unique_key=random_string("id-", 10),
            hits=1,
            limit=rng.randint(1, 10),
            duration=rng.randint(1, 10) * SECOND,
            algorithm=Algorithm.TOKEN_BUCKET,
        )
        for i in range(args.limits)
    ]

    over = 0

    def send(req):
        nonlocal over
        resp = client.get_rate_limits(GetRateLimitsRequest(requests=[req]))
        rl = resp.responses[0]
        if rl.status == Status.OVER_LIMIT:
            over += 1
            print(f"OVER_LIMIT {req.name} {req.unique_key} remaining={rl.remaining}")

    with ThreadPoolExecutor(max_workers=args.concurrency) as pool:
        list(pool.map(send, limits))
    if args.columns:
        client.close()
    print(f"done: {args.limits} requests, {over} over limit")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
