"""Server binary (reference cmd/gubernator/main.go): flags -> daemon."""

from __future__ import annotations

import argparse
import signal
import sys
import threading


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="gubernator-tpu rate-limit daemon")
    parser.add_argument("-config", dest="config", default="", help="env config file")
    parser.add_argument("-debug", dest="debug", action="store_true", help="debug logging")
    parser.add_argument(
        "-version", "--version", dest="version", action="store_true",
        help="print version and exit",
    )
    args = parser.parse_args(argv)

    if args.version:
        from .. import __version__

        print(f"gubernator-tpu {__version__}")
        return 0

    from . import apply_jax_platform_env

    apply_jax_platform_env()

    from ..config import setup_daemon_config
    from ..daemon import spawn_daemon
    from ..utils.logging import setup_logging

    conf = setup_daemon_config(config_file=args.config)
    if args.debug:
        conf.debug = True
    setup_logging(debug=conf.debug)
    daemon = spawn_daemon(conf)
    addr = daemon.gateway.address
    print(f"gubernator-tpu listening on http://{addr} (advertise {daemon.peer_info.grpc_address})")
    sys.stdout.flush()

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    daemon.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
