"""Local cluster binary (reference cmd/gubernator-cluster/main.go:30-56):
start an in-process loopback cluster for client-library testing; prints
"Ready" once all daemons accept connections."""

from __future__ import annotations

import argparse
import signal
import sys
import threading


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="gubernator-tpu local cluster")
    parser.add_argument("--nodes", type=int, default=6)
    args = parser.parse_args(argv)

    from . import apply_jax_platform_env

    apply_jax_platform_env()

    from ..cluster import Cluster

    cl = Cluster().start(args.nodes)
    for p in cl.peers:
        print(f"peer: http://{p.http_address} grpc://{p.grpc_address}")
    print("Ready")
    sys.stdout.flush()

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    cl.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
