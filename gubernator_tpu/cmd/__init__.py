"""CLI entry points.

Shared env handling: some hosts pre-register an accelerator platform in
`sitecustomize`, which overrides `JAX_PLATFORMS` set in the environment
before the interpreter started.  The binaries re-assert the env var via
`jax.config` so `JAX_PLATFORMS=cpu gubernator-server ...` (and the
subprocess test fixtures that rely on it) behave the same everywhere.
"""

from __future__ import annotations

import os


def apply_jax_platform_env() -> None:
    """Force jax onto the platform named by $JAX_PLATFORMS, if set."""
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        import jax

        jax.config.update("jax_platforms", platforms)
