"""Wire-schema types: enums, request/response dataclasses, JSON codec.

Parity with the reference protos (`proto/gubernator.proto:57-189`,
`proto/peers.proto:36-57`): same field names, enum values, and bit-flag
behavior semantics.  The JSON codec mirrors grpc-gateway conventions
(accepts both snake_case and camelCase keys; emits camelCase).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional


class Algorithm(enum.IntEnum):
    """proto/gubernator.proto:57-62"""

    TOKEN_BUCKET = 0
    LEAKY_BUCKET = 1


class Behavior(enum.IntFlag):
    """Bit flags controlling rate-limit behavior (proto/gubernator.proto:65-131).

    BATCHING is the zero value (default, no bit set).
    """

    BATCHING = 0
    NO_BATCHING = 1
    GLOBAL = 2
    DURATION_IS_GREGORIAN = 4
    RESET_REMAINING = 8
    MULTI_REGION = 16


class Status(enum.IntEnum):
    """proto/gubernator.proto:161-164"""

    UNDER_LIMIT = 0
    OVER_LIMIT = 1


def has_behavior(flags: int, flag: Behavior) -> bool:
    """Reference `HasBehavior` (gubernator.go:476-481)."""
    return bool(int(flags) & int(flag))


def set_behavior(flags: int, flag: Behavior, on: bool) -> int:
    """Reference `SetBehavior` (gubernator.go:483-488)."""
    if on:
        return int(flags) | int(flag)
    return int(flags) & ~int(flag)


# Duration helpers in milliseconds (client.go:30-34).
MILLISECOND = 1
SECOND = 1000 * MILLISECOND
MINUTE = 60 * SECOND
HOUR = 60 * MINUTE


@dataclass
class RateLimitRequest:
    """Mirror of `RateLimitReq` (proto/gubernator.proto:133-159)."""

    name: str = ""
    unique_key: str = ""
    hits: int = 0
    limit: int = 0
    duration: int = 0
    algorithm: int = Algorithm.TOKEN_BUCKET
    behavior: int = Behavior.BATCHING

    def hash_key(self) -> str:
        """The cache/shard key: Name + "_" + UniqueKey (client.go:36-38)."""
        return f"{self.name}_{self.unique_key}"

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "uniqueKey": self.unique_key,
            "hits": str(self.hits),
            "limit": str(self.limit),
            "duration": str(self.duration),
            "algorithm": Algorithm(self.algorithm).name,
            "behavior": int(self.behavior),
        }

    @classmethod
    def from_json(cls, d: dict) -> "RateLimitRequest":
        return cls(
            name=d.get("name", ""),
            unique_key=_pick(d, "unique_key", "uniqueKey", default=""),
            hits=_to_int(d.get("hits", 0)),
            limit=_to_int(d.get("limit", 0)),
            duration=_to_int(d.get("duration", 0)),
            algorithm=_parse_enum(d.get("algorithm", 0), Algorithm),
            behavior=_parse_behavior(d.get("behavior", 0)),
        )


@dataclass
class RateLimitResponse:
    """Mirror of `RateLimitResp` (proto/gubernator.proto:166-179)."""

    status: int = Status.UNDER_LIMIT
    limit: int = 0
    remaining: int = 0
    reset_time: int = 0
    error: str = ""
    metadata: Dict[str, str] = field(default_factory=dict)

    def to_json(self) -> dict:
        out = {
            "status": Status(self.status).name,
            "limit": str(self.limit),
            "remaining": str(self.remaining),
            "resetTime": str(self.reset_time),
        }
        if self.error:
            out["error"] = self.error
        if self.metadata:
            out["metadata"] = dict(self.metadata)
        return out

    @classmethod
    def from_json(cls, d: dict) -> "RateLimitResponse":
        return cls(
            status=_parse_enum(d.get("status", 0), Status),
            limit=_to_int(d.get("limit", 0)),
            remaining=_to_int(d.get("remaining", 0)),
            reset_time=_to_int(_pick(d, "reset_time", "resetTime", default=0)),
            error=d.get("error", ""),
            metadata=d.get("metadata", {}) or {},
        )


@dataclass
class GetRateLimitsRequest:
    """Mirror of `GetRateLimitsReq` (proto/gubernator.proto:48-50)."""

    requests: List[RateLimitRequest] = field(default_factory=list)

    def to_json(self) -> dict:
        return {"requests": [r.to_json() for r in self.requests]}

    @classmethod
    def from_json(cls, d: dict) -> "GetRateLimitsRequest":
        return cls(requests=[RateLimitRequest.from_json(r) for r in d.get("requests", [])])


@dataclass
class GetRateLimitsResponse:
    """Mirror of `GetRateLimitsResp` (proto/gubernator.proto:53-55)."""

    responses: List[RateLimitResponse] = field(default_factory=list)

    def to_json(self) -> dict:
        return {"responses": [r.to_json() for r in self.responses]}

    @classmethod
    def from_json(cls, d: dict) -> "GetRateLimitsResponse":
        return cls(responses=[RateLimitResponse.from_json(r) for r in d.get("responses", [])])


@dataclass
class HealthCheckResponse:
    """Mirror of `HealthCheckResp` (proto/gubernator.proto:182-189)."""

    status: str = "healthy"
    message: str = ""
    peer_count: int = 0
    # Peers whose circuit breaker is currently open/half-open (not yet
    # re-trusted); forwarded keys they own are served by degraded local
    # evaluation (faults.py).  JSON-only extension: the reference proto
    # has no such field, so the gRPC wire omits it.
    breaker_open_count: int = 0
    # Daemon build version (gubernator_tpu.__version__).  JSON-only
    # extension like breaker_open_count: the reference HealthCheckResp
    # proto has no version field, so the gRPC wire omits it.
    version: str = ""

    def to_json(self) -> dict:
        out = {
            "status": self.status,
            "peerCount": self.peer_count,
            "breakerOpenCount": self.breaker_open_count,
        }
        if self.version:
            out["version"] = self.version
        if self.message:
            out["message"] = self.message
        return out

    @classmethod
    def from_json(cls, d: dict) -> "HealthCheckResponse":
        return cls(
            status=d.get("status", ""),
            message=d.get("message", ""),
            peer_count=_to_int(_pick(d, "peer_count", "peerCount", default=0)),
            breaker_open_count=_to_int(
                _pick(d, "breaker_open_count", "breakerOpenCount", default=0)
            ),
            version=d.get("version", ""),
        )


@dataclass
class UpdatePeerGlobal:
    """Mirror of `UpdatePeerGlobal` (proto/peers.proto:52-56)."""

    key: str = ""
    status: RateLimitResponse = field(default_factory=RateLimitResponse)
    algorithm: int = Algorithm.TOKEN_BUCKET

    def to_json(self) -> dict:
        return {
            "key": self.key,
            "status": self.status.to_json(),
            "algorithm": Algorithm(self.algorithm).name,
        }

    @classmethod
    def from_json(cls, d: dict) -> "UpdatePeerGlobal":
        return cls(
            key=d.get("key", ""),
            status=RateLimitResponse.from_json(d.get("status", {}) or {}),
            algorithm=_parse_enum(d.get("algorithm", 0), Algorithm),
        )


@dataclass
class PeerInfo:
    """Mirror of `PeerInfo` (config.go:135-149)."""

    grpc_address: str = ""
    http_address: str = ""
    data_center: str = ""
    is_owner: bool = False  # stamped by the daemon, never serialized

    def to_json(self) -> dict:
        return {
            "grpcAddress": self.grpc_address,
            "httpAddress": self.http_address,
            "dataCenter": self.data_center,
        }

    @classmethod
    def from_json(cls, d: dict) -> "PeerInfo":
        return cls(
            grpc_address=_pick(d, "grpc_address", "grpcAddress", default=""),
            http_address=_pick(d, "http_address", "httpAddress", default=""),
            data_center=_pick(d, "data_center", "dataCenter", default=""),
        )


def _pick(d: dict, *names: str, default=None):
    for n in names:
        if n in d:
            return d[n]
    return default


def _to_int(v) -> int:
    if v is None:
        return 0
    return int(v)


def _parse_enum(v, enum_cls):
    if isinstance(v, str):
        try:
            return enum_cls[v]
        except KeyError:
            return enum_cls(int(v))
    return enum_cls(int(v))


def _parse_behavior(v) -> int:
    # Behavior may arrive as an int bitmask, a flag name, or a list of names.
    if isinstance(v, list):
        out = 0
        for item in v:
            out |= _parse_behavior(item)
        return out
    if isinstance(v, str):
        try:
            return int(v)
        except ValueError:
            return int(Behavior[v])
    return int(v)
