"""HTTP/JSON gateway — the client-facing edge.

Parity with the reference's grpc-gateway mux + metrics endpoint
(daemon.go:194-239): POST /v1/GetRateLimits, GET /v1/HealthCheck,
GET /metrics, plus the peer data plane (PeersV1) as
POST /v1/peer.GetPeerRateLimits and POST /v1/peer.UpdatePeerGlobals.
Errors render grpc-gateway style: {"code": N, "message": "..."}.
TLS (including mTLS client auth) wraps the listener when configured
(tls.go:118-263 equivalent via ssl.SSLContext).
"""

from __future__ import annotations

import itertools
import json
import os
import socket
import ssl
import threading
import time
from functools import partial
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlsplit

import numpy as np

from . import audit as audit_mod
from . import native as _native
from . import profiling
from . import saturation
from . import telemetry
from . import tracing
from . import wire
from .config import (
    INGRESS_COLUMNS_MAX_LANES,
    MAX_BATCH_SIZE,
    PEER_COLUMNS_MAX_LANES,
)
from .service import ApiError, ColumnarResult, IngressColumns, V1Service
from .types import Algorithm, RateLimitRequest, UpdatePeerGlobal, _parse_behavior



_GRPC_CODES = {"InvalidArgument": 3, "OutOfRange": 11, "Internal": 13,
               "FailedPrecondition": 9}

_STATUS_NAMES = ("UNDER_LIMIT", "OVER_LIMIT")


class LazyIngressColumns:
    """IngressColumns twin built from the native JSON parse
    (native.parse_json_batch): kernel-ready columns + PACKED hash keys
    + per-lane validation codes, with name/unique_key strings
    materialized lazily — the hot path never creates 2n string objects
    per batch."""

    __slots__ = ("_pj", "algorithm", "behavior", "hits", "limit",
                 "duration", "_names", "_uks")

    def __init__(self, pj):
        self._pj = pj
        self.algorithm = pj.algo
        self.behavior = pj.behavior
        self.hits = pj.hits
        self.limit = pj.limit
        self.duration = pj.duration
        self._names = None
        self._uks = None

    def __len__(self) -> int:
        return self._pj.n

    @property
    def prevalidated(self):
        """(PackedKeys hash keys, err codes u8[n]: 1 empty unique_key,
        2 empty name) — lets the service skip its per-lane validation
        and hash-key loop (service.py _route_columns)."""
        return self._pj.hash_keys, self._pj.err

    @property
    def names(self):
        if self._names is None:
            self._names = [self._pj.name_at(i) for i in range(self._pj.n)]
        return self._names

    @property
    def unique_keys(self):
        if self._uks is None:
            self._uks = [
                self._pj.unique_key_at(i) for i in range(self._pj.n)
            ]
        return self._uks

    def request_at(self, i: int) -> RateLimitRequest:
        return RateLimitRequest(
            name=self._pj.name_at(i),
            unique_key=self._pj.unique_key_at(i),
            hits=int(self.hits[i]),
            limit=int(self.limit[i]),
            duration=int(self.duration[i]),
            algorithm=int(self.algorithm[i]),
            behavior=int(self.behavior[i]),
        )


def parse_body_native(raw: bytes):
    """Native fast path for a /v1/GetRateLimits body; None falls back
    to json.loads + parse_columns (exotic JSON, bad enum values — the
    Python path reproduces the exact historical error behavior)."""
    pj = _native.parse_json_batch(raw)
    if pj is None or (pj.err >= 3).any():
        return None
    return LazyIngressColumns(pj)


def render_result_native(result: ColumnarResult):
    """Native response rendering; overrides pre-render in Python (they
    carry metadata/errors), forwarded lanes pre-render their
    metadata.owner straight from the arrays (no per-lane dataclass).
    None when the native runtime is absent."""
    ov = None
    if result.overrides:
        ov = {
            i: json.dumps(r.to_json(), separators=(",", ":")).encode("utf-8")
            for i, r in result.overrides.items()
        }
    if result.owner_of is not None:
        ov = ov or {}
        owner_json = [json.dumps(a) for a in result.owner_addrs]
        status, limit = result.status, result.limit
        remaining, reset = result.remaining, result.reset_time
        for i in np.nonzero(result.owner_of >= 0)[0]:
            i = int(i)
            if i in ov:
                continue
            ov[i] = (
                '{"status":"%s","limit":"%d","remaining":"%d",'
                '"resetTime":"%d","metadata":{"owner":%s}}'
                % (
                    _STATUS_NAMES[status[i]], limit[i], remaining[i],
                    reset[i], owner_json[result.owner_of[i]],
                )
            ).encode("utf-8")
    return _native.render_json(
        result.status, result.limit, result.remaining, result.reset_time,
        ov or {},
    )


def parse_columns(items: list) -> IngressColumns:
    """Parse a JSON `requests` array straight into ingress columns (no
    per-request dataclasses — the gateway's half of the zero-dataclass
    hot path)."""
    n = len(items)
    names: list = [""] * n
    uks: list = [""] * n
    algo = np.zeros(n, dtype=np.int32)
    behavior = np.zeros(n, dtype=np.int32)
    hits = np.zeros(n, dtype=np.int64)
    limit = np.zeros(n, dtype=np.int64)
    duration = np.zeros(n, dtype=np.int64)
    for i, d in enumerate(items):
        names[i] = d.get("name", "")
        uks[i] = d.get("uniqueKey") or d.get("unique_key") or ""
        v = d.get("hits")
        if v:
            hits[i] = int(v)
        v = d.get("limit")
        if v:
            limit[i] = int(v)
        v = d.get("duration")
        if v:
            duration[i] = int(v)
        v = d.get("algorithm")
        if v:
            # Same validation as the dataclass path (_parse_enum): an
            # out-of-range value must fail identically at every batch size.
            if isinstance(v, str) and v in Algorithm.__members__:
                algo[i] = int(Algorithm[v])
            else:
                algo[i] = int(Algorithm(int(v)))
        v = d.get("behavior")
        if v:
            behavior[i] = v if isinstance(v, int) else _parse_behavior(v)
    return IngressColumns(
        names=names, unique_keys=uks, algorithm=algo, behavior=behavior,
        hits=hits, limit=limit, duration=duration,
    )


def render_columns(result: ColumnarResult) -> dict:
    """Serialize a ColumnarResult to the gateway JSON payload directly
    from the arrays."""
    status = result.status
    limit = result.limit
    remaining = result.remaining
    reset = result.reset_time
    ov = result.overrides
    owner_of = result.owner_of
    out = []
    for i in range(result.n):
        r = ov.get(i)
        if r is not None:
            out.append(r.to_json())
        else:
            d = {
                "status": _STATUS_NAMES[status[i]],
                "limit": str(limit[i]),
                "remaining": str(remaining[i]),
                "resetTime": str(reset[i]),
            }
            if owner_of is not None and owner_of[i] >= 0:
                d["metadata"] = {"owner": result.owner_addrs[owner_of[i]]}
            out.append(d)
    return {"responses": out}


def handle_request(service: V1Service, method: str, path: str, raw: bytes,
                   headers=None):
    """Transport-independent request handler: the single routing +
    metrics + error surface behind BOTH edges (the stdlib ThreadingHTTP
    server below and the native epoll edge, NativeGatewayServer).
    Returns (http_status, content_type, body_bytes).  `headers` (any
    mapping with .get, or None) feeds traceparent extraction and
    /metrics content negotiation; the native edge passes None — its
    requests root fresh traces."""
    # Per-service flight recorder + incident black box: bind this
    # daemon's recorder for the handler's duration (co-resident daemons
    # stop interleaving their rings), and tap every GUBC frame at the
    # gateway edge, both directions (bb.tap sniffs the frame magic, so
    # JSON bodies cost one length/prefix check each way).
    tracing.bind_recorder(getattr(service, "recorder", None))
    bb = getattr(service, "blackbox", None)
    if bb is not None and raw:
        bb.tap("in", "", raw)
    status, ctype, body = _handle_request(service, method, path, raw, headers)
    if bb is not None and body:
        bb.tap("out", "", body)
    return status, ctype, body


def _handle_request(service: V1Service, method: str, path: str, raw: bytes,
                    headers=None):
    try:
        if method == "GET":
            # /healthz is an alias so stock k8s liveness/readiness
            # probes work without a rewrite rule; the payload includes
            # breakerOpenCount (peers currently fast-failed by their
            # circuit breaker, faults.py).
            if path in ("/v1/HealthCheck", "/healthz"):
                with service.metrics.observe_rpc("/pb.gubernator.V1/HealthCheck"):
                    hc = service.health_check()
                return 200, "application/json", _json_bytes(hc.to_json())
            if path == "/metrics":
                # Collect-on-scrape: refresh the cache gauges from the
                # store (the reference's prometheus Collector pattern,
                # cache.go:205-218) and the per-peer circuit-breaker
                # state gauges from the live PeerClients.  The WHOLE
                # refresh+render runs under the scrape lock: two racing
                # scrapers must not interleave a take_pipeline_stats
                # drain with the other's clear()/set() — an unlucky
                # interleaving would render a per-scrape sample as if
                # it never happened.
                with service.metrics.scrape_lock:
                    service.metrics.observe_cache(service.store)
                    service.metrics.observe_dispatch(service.store)
                    service.metrics.observe_saturation(service)
                    service.metrics.observe_telemetry()
                    service.metrics.observe_audit(service)
                    service.metrics.observe_cost(service)
                    service.metrics.observe_native_ingress(service)
                    service.metrics.observe_blackbox(service)
                    service.metrics.observe_peers(
                        service.get_peer_list()
                        + list(service.get_region_picker().peers())
                    )
                    ctype, payload = service.metrics.render_negotiated(
                        headers.get("Accept", "") if headers else ""
                    )
                return 200, ctype, payload
            qpath = urlsplit(path).path
            if qpath in ("/debug/traces", "/debug/events"):
                return _debug_dump(service, path)
            if qpath == "/debug/status":
                # The cluster-status surface: one JSON doc per daemon
                # (scripts/cluster_status.py polls these).
                return 200, "application/json", _json_bytes(
                    service.debug_status()
                )
            if qpath == "/debug/latency":
                # Live per-phase percentile snapshots from the always-on
                # attribution reservoirs (saturation.py).  `express` is
                # the express-vs-batched split: per-path lane counts +
                # hit rate, with the bypass's own submit wall under
                # phases["express.submit"] beside the windowed path's
                # batch.window/queue.wait.
                return 200, "application/json", _json_bytes({
                    "phases": saturation.phase_snapshot(),
                    "express": saturation.express_snapshot(),
                    "slo": service.slo.snapshot(),
                })
            if qpath == "/debug/hotkeys":
                return 200, "application/json", _json_bytes(
                    service.hotkeys.snapshot()
                )
            if qpath == "/debug/device":
                # XLA/device telemetry (telemetry.py): compile table,
                # steady-state recompiles, per-program timings, device
                # memory / live-buffer samples.
                doc = telemetry.snapshot()
                doc["devices"] = telemetry.device_snapshot()
                return 200, "application/json", _json_bytes(doc)
            if qpath == "/debug/audit":
                # Conservation audit (audit.py): ledger deltas +
                # invariant verdicts; the soak harness's pass/fail gate.
                return 200, "application/json", _json_bytes(
                    service.auditor.snapshot()
                )
            if qpath == "/debug/tenants":
                # Cost observatory (profiling.py): per-tenant cost
                # ledger — top-K exact rows + the `other` rollup;
                # scripts/cluster_status.py --tenants aggregates these
                # fleet-wide.
                return 200, "application/json", _json_bytes(
                    service.tenants.snapshot()
                )
            if qpath == "/debug/pprof":
                return _debug_pprof(path)
            return 404, "application/json", _json_bytes(
                {"code": 5, "message": f"no handler for {path}"}
            )
        if method != "POST":
            return 404, "application/json", _json_bytes(
                {"code": 5, "message": f"no handler for {method} {path}"}
            )
        tp = headers.get("traceparent") if headers else None
        if path == "/v1/GetRateLimits":
            # Span OUTSIDE the metrics timer: observe_rpc's exit hook
            # attaches a trace exemplar from the still-active context.
            with tracing.ingress_span("http", path, tp):
                with service.metrics.observe_rpc("/pb.gubernator.V1/GetRateLimits"):
                    if service.serves_ingress_columns and wire.is_ingress_frame(raw):
                        # Columnar front door: GUBC kind-5 frame in,
                        # kind-6 frame out (no JSON either way).  With
                        # the knob off this branch is never reached —
                        # the frame falls into json.loads below and
                        # 400s exactly like a pre-columns build, which
                        # is the client's version probe.
                        t_parse = time.perf_counter()
                        with profiling.scope("ingress.parse"):
                            cols = _decode_ingress_frame_or_400(raw)
                        saturation.observe_phase(
                            "ingress.parse", time.perf_counter() - t_parse
                        )
                        result = service.get_rate_limits_columns(
                            cols, max_lanes=INGRESS_COLUMNS_MAX_LANES
                        )
                        t_enc = time.perf_counter()
                        with profiling.scope("response.encode"):
                            rendered = wire.encode_ingress_result_frame(result)
                        saturation.observe_phase(
                            "response.encode", time.perf_counter() - t_enc
                        )
                        service.metrics.ingress_columns_batches.labels(
                            encoding="frame"
                        ).inc()
                        return 200, wire.COLUMNS_CONTENT_TYPE, rendered
                    t_parse = time.perf_counter()
                    with profiling.scope("ingress.parse"):
                        cols = parse_body_native(raw) if raw else None
                        native = cols is not None
                        if not native:
                            body = json.loads(raw) if raw else {}
                            cols = parse_columns(body.get("requests", []))
                    saturation.observe_phase(
                        "ingress.parse", time.perf_counter() - t_parse
                    )
                    result = service.get_rate_limits_columns(cols)
                    t_enc = time.perf_counter()
                    with profiling.scope("response.encode"):
                        rendered = (
                            render_result_native(result) if native else None
                        )
                        if rendered is None:
                            rendered = _json_bytes(render_columns(result))
                    saturation.observe_phase(
                        "response.encode", time.perf_counter() - t_enc
                    )
            return 200, "application/json", rendered
        if path == "/v1/peer.GetPeerRateLimits":
            # Body parsing happens INSIDE the metrics span on BOTH
            # gateway paths: a malformed peer body counts as a
            # status="1" request in request_counts here exactly like on
            # the async edge (architecture.md "Columnar pipeline: the
            # peer hop" documents the parity rule).
            with tracing.ingress_span("http", path, tp):
                with service.metrics.observe_rpc(
                    "/pb.gubernator.PeersV1/GetPeerRateLimits"
                ):
                    if service.serves_peer_columns and wire.is_columns_frame(raw):
                        # Columnar peer hop: binary frame in, frame out.
                        result = service.get_peer_rate_limits_columns(
                            _decode_frame_or_400(raw),
                            max_lanes=PEER_COLUMNS_MAX_LANES,
                        )
                        return (200, wire.COLUMNS_CONTENT_TYPE,
                                wire.encode_result_frame(result))
                    body = json.loads(raw) if raw else {}
                    cols = parse_columns(body.get("requests", []))
                    result = service.get_peer_rate_limits_columns(cols)
            # PeersV1 response field is rate_limits (peers.proto:42-45).
            return 200, "application/json", _json_bytes(
                {"rateLimits": render_columns(result)["responses"]}
            )
        if path == "/debug/profile":
            return _debug_profile(raw)
        if path == "/debug/incident":
            return _debug_incident(service, raw)
        if (path == "/v1/peer.UpdateRegionColumns"
                and service.serves_region_columns):
            # Cross-region federation receive (federation.py): GUBC
            # region frame in, ONE columnar apply.  A daemon with the
            # plane off (GUBER_REGION_COLUMNS=0) never reaches here —
            # it falls through to the 404 below, exactly what a
            # pre-federation build answers, which is the sender's
            # version probe (sticky classic fallback to the per-item
            # GetPeerRateLimits path).
            with service.metrics.observe_rpc(
                "/pb.gubernator.PeersV1/UpdateRegionColumns"
            ):
                if not wire.is_region_frame(raw):
                    raise ApiError(
                        "InvalidArgument",
                        "UpdateRegionColumns expects a GUBC region frame",
                    )
                try:
                    cols = wire.decode_region_frame(raw)
                except ValueError as e:
                    raise ApiError(
                        "InvalidArgument", f"invalid region frame: {e}"
                    ) from e
                applied = service.update_region_columns(cols)
            return 200, "application/json", _json_bytes(
                {"applied": applied}
            )
        if path == "/v1/peer.TransferOwnership" and service.serves_reshard:
            # Ownership-transfer receive (elastic membership): GUBC
            # transfer frame in, ONE batched merge-commit.  A daemon
            # with the plane off (GUBER_RESHARD=0) never reaches here —
            # it falls through to the 404 below, exactly what a
            # pre-reshard build answers, which is the sender's version
            # probe (sticky classic fallback).
            with service.metrics.observe_rpc(
                "/pb.gubernator.PeersV1/TransferOwnership"
            ):
                if not wire.is_transfer_frame(raw):
                    raise ApiError(
                        "InvalidArgument",
                        "TransferOwnership expects a GUBC transfer frame",
                    )
                try:
                    cols = wire.decode_transfer_frame(raw)
                except ValueError as e:
                    raise ApiError(
                        "InvalidArgument", f"invalid transfer frame: {e}"
                    ) from e
                committed, rejected = service.transfer_ownership(cols)
            return 200, "application/json", _json_bytes(
                {"committed": committed, "rejected": rejected}
            )
        if path == "/v1/peer.UpdatePeerGlobals":
            with service.metrics.observe_rpc(
                "/pb.gubernator.PeersV1/UpdatePeerGlobals"
            ):
                if service.serves_global_columns and wire.is_globals_frame(raw):
                    # Columnar GLOBAL broadcast: GUBC globals frame in,
                    # ONE batched replica commit.  A daemon with the
                    # plane off never reaches here — the json.loads
                    # below rejects the frame exactly like a
                    # pre-columns build (the sender's version answer).
                    try:
                        cols = wire.decode_globals_frame(raw)
                    except ValueError as e:
                        raise ApiError(
                            "InvalidArgument", f"invalid globals frame: {e}"
                        ) from e
                    service.update_peer_globals_columns(cols)
                    return 200, "application/json", b"{}"
                body = json.loads(raw) if raw else {}
                updates = [
                    UpdatePeerGlobal.from_json(u)
                    for u in body.get("globals", [])
                ]
                service.update_peer_globals(updates)
            return 200, "application/json", b"{}"
        return 404, "application/json", _json_bytes(
            {"code": 5, "message": f"no handler for {path}"}
        )
    except Exception as e:  # noqa: BLE001
        return _error_triplet(e)


def _json_bytes(payload) -> bytes:
    return json.dumps(payload).encode("utf-8")


def _debug_dump(service, path: str):
    """GET /debug/traces[?trace_id=<32-hex>][&since=<wall-ns>]
    [&limit=<n>] and GET /debug/events: dump the flight recorder
    (tracing.py).  The trace filter matches a span's own trace id OR
    its links — the batch span-link rule, so a lane's trace finds the
    coalesced window/stage spans it rode.  `since` filters on each
    span's wall-clock end stamp (wall_ns) so a stitcher
    (scripts/trace_collect.py) can poll incrementally instead of
    re-reading the whole ring; `limit` keeps the OLDEST N after the
    filter (pagination order — the poller's next `since` cursor picks
    up exactly where this page ended).  Reads across EVERY live
    recorder: per-service recorders exist so incident bundles stay
    attributable per daemon (blackbox.py snapshots only its service's
    ring), but the debug READ surface keeps the one-ring view — a
    cross-daemon trace in a co-resident cluster must be visible from
    ANY daemon's debug port (the two-daemon trace-stitching contract)."""
    recorders = None
    parts = urlsplit(path)
    if parts.path == "/debug/events":
        return 200, "application/json", _json_bytes(
            {"events": tracing.events_snapshot(recorders=recorders)}
        )
    q = parse_qs(parts.query)
    trace_id = (q.get("trace_id") or [""])[0]

    def _int_q(name: str) -> int:
        try:
            return max(int((q.get(name) or ["0"])[0]), 0)
        except ValueError:
            return 0

    return 200, "application/json", _json_bytes(
        {
            "sampleRate": tracing.sample_rate(),
            "spans": tracing.spans_snapshot(
                trace_id, since_ns=_int_q("since"), limit=_int_q("limit"),
                recorders=recorders,
            ),
        }
    )


def _debug_pprof(path: str):
    """GET /debug/pprof?seconds=N[&format=collapsed|json][&top=N]: the
    continuous host profiler's window (profiling.py).  Default output
    is flamegraph collapsed text ('phase;frame;...;frame count' lines —
    pipe into flamegraph.pl / speedscope); format=json serves the
    top-N + phase/program attribution view the integration gate
    asserts against (>= 80% of samples on a loaded daemon must
    attribute to a named phase)."""
    q = parse_qs(urlsplit(path).query)

    def _int_q(name: str, default: int) -> int:
        try:
            return int((q.get(name) or [str(default)])[0])
        except ValueError:
            return default

    seconds = _int_q("seconds", 10)
    if (q.get("format") or ["collapsed"])[0] == "json":
        return 200, "application/json", _json_bytes(
            profiling.profile_snapshot(seconds, top=_int_q("top", 30))
        )
    return (200, "text/plain; charset=utf-8",
            profiling.collapsed(seconds).encode("utf-8"))


_profile_state = {"thread": None, "dirs": [], "run_id": "", "log_dir": ""}
_profile_seq = itertools.count(1)
_profile_lock = threading.Lock()
# Retention cap on profile dumps this daemon created: a client looping
# POST /debug/profile must not fill the temp filesystem of a long-lived
# daemon (each dump is a multi-MB TensorBoard trace).
PROFILE_KEEP = 5


def _debug_profile(raw: bytes):
    """POST /debug/profile {"durationMs": N}: run an on-demand
    jax.profiler device trace for N ms (default 1000, cap 60s) in the
    background, writing a TensorBoard-loadable dump to a fresh
    mkdtemp-created directory (mode 0700, unpredictable name — the
    caller must NOT choose the path, and a predictable fixed path in
    /tmp could be pre-planted by another local user).  Gated on tracing
    being enabled (GUBER_TRACE_SAMPLE > 0) — a daemon with
    observability off must not let callers start device-wide profiles.
    One at a time; answers 202 immediately (a profile must not park a
    gateway worker for its whole duration; the first call also pays
    jax.profiler's lazy tensorflow import, several seconds)."""
    if not tracing.enabled():
        raise ApiError(
            "InvalidArgument",
            "profiling requires tracing enabled (GUBER_TRACE_SAMPLE > 0)",
            http_status=403,
        )
    body = json.loads(raw) if raw else {}
    if not isinstance(body, dict):
        raise ApiError("InvalidArgument", "body must be a JSON object")
    try:
        duration_s = min(max(float(body.get("durationMs", 1000)) / 1000.0, 0.01), 60.0)
    except (TypeError, ValueError):
        raise ApiError("InvalidArgument", "durationMs must be a number") from None
    with _profile_lock:
        t = _profile_state["thread"]
        if t is not None and t.is_alive():
            # Concurrent-run guard: the second caller learns WHICH run
            # holds the device (its id + artifact path) instead of just
            # a refusal — two operators racing a profile can converge
            # on the same artifact.
            return 409, "application/json", _json_bytes(
                {
                    "code": 10,
                    "message": "a device profile is already running",
                    "runId": _profile_state["run_id"],
                    "logDir": _profile_state["log_dir"],
                }
            )
        import shutil
        import tempfile

        log_dir = tempfile.mkdtemp(prefix="gubernator-profile-")
        run_id = f"profile-{next(_profile_seq)}"
        _profile_state["run_id"] = run_id
        _profile_state["log_dir"] = log_dir
        _profile_state["dirs"].append(log_dir)
        while len(_profile_state["dirs"]) > PROFILE_KEEP:
            shutil.rmtree(_profile_state["dirs"].pop(0), ignore_errors=True)

        def run():
            import jax

            try:
                jax.profiler.start_trace(log_dir)
                time.sleep(duration_s)
            finally:
                try:
                    jax.profiler.stop_trace()
                except Exception:  # noqa: BLE001 — best-effort teardown
                    pass
            # Cost-observatory pairing: the continuous host profiler's
            # window covering the SAME interval lands beside the device
            # trace, so one call yields device trace + host flamegraph
            # for the same seconds (collapsed text, flamegraph.pl /
            # speedscope ready).
            if profiling.enabled():
                try:
                    with open(
                        os.path.join(log_dir, "host_profile.collapsed"),
                        "w",
                    ) as f:
                        f.write(
                            profiling.collapsed(max(int(duration_s), 1))
                        )
                except OSError:
                    pass

        t = threading.Thread(target=run, daemon=True, name="debug-profile")
        _profile_state["thread"] = t
        t.start()
    host_seconds = max(int(duration_s), 1)
    return 202, "application/json", _json_bytes(
        {
            "runId": run_id, "logDir": log_dir,
            "durationMs": duration_s * 1000.0,
            # Written when the run completes (the 202 answers before the
            # trace finishes); the live equivalent is the pprof URL.
            "hostProfile": (
                f"{log_dir}/host_profile.collapsed"
                if profiling.enabled() else None
            ),
            "hostPprof": f"/debug/pprof?seconds={host_seconds}",
        }
    )


def _debug_incident(service, raw: bytes):
    """POST /debug/incident [{"reason": "..."}]: operator-requested
    incident bundle (blackbox.py) — freeze the wire rings + debug
    surfaces into an on-disk bundle exactly as an auto-dump trigger
    would, but exempt from the writer's rate limit.  403 when the
    black box is disabled (GUBER_BLACKBOX=0 must not let callers
    re-arm capture), 409 when no bundle directory is configured (the
    rings run but there is nowhere to freeze them), 202 otherwise —
    the write happens off-thread (the /debug/profile shape: evidence
    collection must not park a gateway worker)."""
    from . import blackbox as blackbox_mod

    bb = getattr(service, "blackbox", None)
    if bb is None or not (blackbox_mod.enabled() and bb._on):  # noqa: SLF001
        raise ApiError(
            "InvalidArgument",
            "incident capture requires the black box enabled "
            "(GUBER_BLACKBOX=1)",
            http_status=403,
        )
    if not bb.path:
        return 409, "application/json", _json_bytes(
            {
                "code": 9,
                "message": "no bundle directory configured "
                           "(GUBER_BLACKBOX_DIR)",
            }
        )
    body = json.loads(raw) if raw else {}
    if not isinstance(body, dict):
        raise ApiError("InvalidArgument", "body must be a JSON object")
    doc = bb.trigger_manual(str(body.get("reason", "")))
    return 202, "application/json", _json_bytes(doc)


def _decode_frame_or_400(raw: bytes):
    """Frame decode for the peer endpoint: a malformed/truncated frame
    is the CLIENT's fault — surface it as a 400 (ApiError), not a 500,
    on both gateway paths."""
    try:
        return wire.decode_columns_frame(raw)
    except ValueError as e:
        raise ApiError("InvalidArgument", f"invalid columns frame: {e}") from e


def _decode_ingress_frame_or_400(raw: bytes):
    """Public-ingress twin of _decode_frame_or_400 (kind-5 frames,
    untrusted-client validation inside the decode)."""
    try:
        return wire.decode_ingress_frame(raw)
    except ValueError as e:
        raise ApiError("InvalidArgument", f"invalid columns frame: {e}") from e


def _error_triplet(e: BaseException):
    """Map a handler exception to (status, content_type, body) — the
    same arms as handle_request's except clauses, shared with the async
    path so the two edges answer errors identically."""
    if isinstance(e, ApiError):
        return e.http_status, "application/json", _json_bytes(
            {"code": _GRPC_CODES.get(e.code, 2), "message": e.message}
        )
    if isinstance(e, (json.JSONDecodeError, UnicodeDecodeError)):
        # UnicodeDecodeError: json.loads auto-detects utf-16/32 from a
        # leading NUL and raises it for binary garbage — a malformed
        # REQUEST, not a server fault (and the columns-negotiation
        # probe relies on old peers answering 4xx to non-JSON bodies).
        return 400, "application/json", _json_bytes(
            {"code": 3, "message": f"invalid JSON: {e}"}
        )
    return 500, "application/json", _json_bytes(
        {"code": 13, "message": str(e)}
    )


def handle_request_async(service: V1Service, method: str, path: str,
                         raw: bytes, respond, headers=None) -> None:
    """Async twin of handle_request for the device-bound POST paths:
    parse + submit on the calling thread, deliver via
    respond(status, content_type, body) exactly once from a completion
    thread.  Everything else (GET, globals push, unknown paths) answers
    synchronously — those never wait on a device round.  Used by the
    native epoll edge so its workers return to the ingress queue
    instead of parking one thread per in-flight request."""
    if method != "POST" or path not in (
        "/v1/GetRateLimits", "/v1/peer.GetPeerRateLimits"
    ):
        respond(*handle_request(service, method, path, raw, headers))
        return
    # Recorder binding + black-box edge taps, the handle_request
    # discipline (the early branch above already taps inside
    # handle_request): request on the submitting worker here, response
    # in finish() where the rendered triplet exists.
    tracing.bind_recorder(getattr(service, "recorder", None))
    bb = getattr(service, "blackbox", None)
    if bb is not None and raw:
        bb.tap("in", "", raw)
    rpc = (
        "/pb.gubernator.V1/GetRateLimits"
        if path == "/v1/GetRateLimits"
        else "/pb.gubernator.PeersV1/GetPeerRateLimits"
    )
    metrics = service.metrics
    start = time.perf_counter()
    # Ingress span, async form: active on THIS thread only while the
    # request is parsed/submitted (that is where routing captures the
    # context into batch links and peer forwards); ended exactly once
    # by finish(), from whichever completion thread delivers.
    span = tracing.ingress_span(
        "http", path, headers.get("traceparent") if headers else None
    )
    span.activate()
    # Exactly-once guard: an inline callback that raised must not
    # re-enter through the outer except and answer the same token
    # twice (round-5 review finding).  The check-then-set is LOCKED: a
    # completion thread and the submitting thread can race into
    # finish() concurrently (e.g. a drainer callback firing while the
    # submit path converts a late exception), and an unlocked flag
    # would let both pass the check and double-respond / double-count.
    finished = [False]
    finished_lock = threading.Lock()

    def finish(status_label: str, triplet) -> None:
        with finished_lock:
            if finished[0]:
                return
            finished[0] = True
        # Manual observe_rpc: the span covers parse -> response-ready,
        # like the sync context manager covers parse -> render.
        dt = time.perf_counter() - start
        metrics.request_counts.labels(status=status_label, method=rpc).inc()
        metrics.request_duration.labels(method=rpc).observe(dt)
        metrics.observe_latency(rpc, dt, ctx=span.ctx if span else None)
        span.end(status=status_label)
        if bb is not None and triplet[2]:
            bb.tap("out", "", triplet[2])
        respond(*triplet)

    try:
        if path == "/v1/GetRateLimits":
            ingress_frame = (
                service.serves_ingress_columns and wire.is_ingress_frame(raw)
            )
            t_parse = time.perf_counter()
            if ingress_frame:
                # Columnar front door, async edge: the native worker
                # hands ready column buffers (gt_frame_parse ran with
                # the GIL released) to the submit path and returns to
                # the ingress queue; the kind-6 response renders on the
                # completion thread straight from the result arrays.
                with profiling.scope("ingress.parse"):
                    cols = _decode_ingress_frame_or_400(raw)
                native = False
            else:
                with profiling.scope("ingress.parse"):
                    cols = parse_body_native(raw) if raw else None
                    native = cols is not None
                    if cols is None:
                        body = json.loads(raw) if raw else {}
                        cols = parse_columns(body.get("requests", []))
            saturation.observe_phase(
                "ingress.parse", time.perf_counter() - t_parse
            )

            def cb(result, exc):
                # Guarded like the sync catch-all: a render failure on a
                # completion thread must become a 500, not a swallowed
                # exception that leaves the client hanging.
                try:
                    if exc is not None:
                        finish("1", _error_triplet(exc))
                        return
                    t_enc = time.perf_counter()
                    if ingress_frame:
                        with profiling.scope("response.encode"):
                            rendered = wire.encode_ingress_result_frame(result)
                        saturation.observe_phase(
                            "response.encode", time.perf_counter() - t_enc
                        )
                        metrics.ingress_columns_batches.labels(
                            encoding="frame"
                        ).inc()
                        finish("0", (200, wire.COLUMNS_CONTENT_TYPE, rendered))
                        return
                    with profiling.scope("response.encode"):
                        rendered = (
                            render_result_native(result) if native else None
                        )
                        if rendered is None:  # native render unavailable/cap
                            rendered = _json_bytes(render_columns(result))
                    saturation.observe_phase(
                        "response.encode", time.perf_counter() - t_enc
                    )
                    finish("0", (200, "application/json", rendered))
                except Exception as e:  # noqa: BLE001
                    finish("1", _error_triplet(e))

            service.get_rate_limits_columns_async(
                cols, cb,
                max_lanes=(
                    INGRESS_COLUMNS_MAX_LANES if ingress_frame
                    else MAX_BATCH_SIZE
                ),
            )
        else:
            frame = service.serves_peer_columns and wire.is_columns_frame(raw)
            if frame:
                cols = _decode_frame_or_400(raw)
            else:
                body = json.loads(raw) if raw else {}
                cols = parse_columns(body.get("requests", []))

            def cb(result, exc):
                try:
                    if exc is not None:
                        finish("1", _error_triplet(exc))
                        return
                    if frame:
                        finish("0", (200, wire.COLUMNS_CONTENT_TYPE,
                                     wire.encode_result_frame(result)))
                        return
                    finish("0", (200, "application/json", _json_bytes(
                        {"rateLimits": render_columns(result)["responses"]}
                    )))
                except Exception as e:  # noqa: BLE001
                    finish("1", _error_triplet(e))

            service.get_peer_rate_limits_columns_async(
                cols, cb,
                max_lanes=PEER_COLUMNS_MAX_LANES if frame else MAX_BATCH_SIZE,
            )
    except Exception as e:  # noqa: BLE001 — parse/submit errors, before
        finish("1", _error_triplet(e))  # any callback was registered
    finally:
        # Submit done: drop the context from this worker thread (the
        # span itself stays open until finish()).
        span.deactivate()


_HTTP_REASONS = {200: "OK", 400: "Bad Request", 404: "Not Found",
                 500: "Internal Server Error"}


class NativeIngressPump:
    """Batch-granularity control of the native ingress service loop
    (host_runtime.cpp gt_ingress_*, architecture.md "Native service
    loop").

    Gateway workers feed kind-5 frames into the native ring without
    ever copying their bytes into Python (HttpEdge.next(ingress=...));
    this pump is the ONLY Python in the steady-state hot path: one
    take per coalesced batch (zero-copy column views), the
    batch-granularity observability folds (audit ledger, tenant
    ledger, hot-key sketch, phase attribution — the PR 6/9/12 planes
    stay honest), one store dispatch, and one complete that hands the
    result arrays back to C++ for the per-frame kind-6 response fill
    and socket write.

    Lanes needing Python semantics never reach here — the native
    submit falls back to the ordinary gateway path for them (slow
    behavior bits, validation errors, remote owners, sampled traces,
    malformed frames), so correctness is identical with the pump on or
    off; the pump only removes interpreter time from the
    already-columnar common case."""

    # Behavior bits that demand the Python router (GLOBAL replica
    # path, MULTI_REGION hit queueing, Gregorian resolution — and
    # NO_BATCHING direct dispatch when the express lane is off): any
    # lane carrying one makes the whole frame fall back.  This mask is
    # the PR 13 set; with GUBER_EXPRESS on, NO_BATCHING moves out of
    # the fallback mask and into the native EXPRESS queue instead
    # (frames jump the ring, never the Python path — the bit means
    # "skip coalescing waits", which the native loop satisfies
    # directly).
    FALLBACK_BEHAVIOR = 1 | 2 | 4 | 16
    EXPRESS_FALLBACK_BEHAVIOR = 2 | 4 | 16
    EXPRESS_MASK = 1  # Behavior.NO_BATCHING

    #: Lane ceiling of one coalesced take = the device dispatch
    #: ceiling (ColumnarBatcher.MAX_LANES — an oversized dispatch
    #: would pad into a brand-new XLA bucket and compile mid-traffic).
    TAKE_LANES = 64_000
    #: Overlapping dispatches in flight (the PR 3 pipeline overlaps
    #: host work behind device compute underneath this bound; 6 keeps
    #: the device fed through a host-side hiccup without queueing work
    #: past any useful deadline — the native ring's shed bound still
    #: caps total admitted lanes).
    DEPTH = 6
    #: Take/dispatch threads.  Two, like the headline bench loop: the
    #: PREPARE of take N+1 (the C++ mesh plan, under `_plan_lock`)
    #: overlaps take N's STAGE/LAUNCH (store lock) — on one thread the
    #: two stages serialize and the ~equal-cost halves each idle while
    #: the other runs (measured ~1.6x at 60k-lane takes on the 2-core
    #: dev box).
    N_PUMPS = 2

    def __init__(self, service: V1Service, take_lanes: "Optional[int]" = None):
        from concurrent.futures import ThreadPoolExecutor

        from . import native as _nat

        self.service = service
        self.batcher = _nat.IngressBatcher()
        self.take_lanes = take_lanes or self.TAKE_LANES
        self._sem = threading.Semaphore(self.DEPTH)
        self._stopped = threading.Event()
        self._threads: list = []
        self._done_pool = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="native-ingress-done",
            initializer=tracing.bind_recorder,
            initargs=(getattr(service, "recorder", None),),
        )
        self._ring_lock = threading.Lock()
        self._ring = None
        self._eligible = False
        self._enable_at = 0.0
        self._shed_seen = 0
        self._express_seen = 0
        self._lanes_seen = 0
        # The set_peers hook: the service pushes ring snapshots here.
        service.native_ingress = self

    @property
    def active(self) -> bool:
        """Whether workers should offer frames to the native lane.
        Sampled tracing turns it off wholesale — the Python path owns
        span creation — which keeps GUBER_TRACE_SAMPLE>0 semantics
        identical to PR 8 at the cost of the fast lane."""
        return (
            not self._stopped.is_set()
            and not tracing.enabled()
            and not getattr(self.service, "_closed", False)
        )

    def stats(self) -> dict:
        return self.batcher.stats()

    # -- ring push (service.set_peers -> update_ring) ------------------
    def update_ring(self) -> None:
        """Recompute and push the native route snapshot: sorted vnode
        hashes + per-vnode self bits off the live picker (the
        ownership-code pass of hash_ring.get_batch_codes reduced to
        the one question the fast lane asks).  During a reshard
        double-dispatch window the lane DISABLES — moved keys owe the
        old owner a peek only the Python router performs — and
        re-enables when the window closes."""
        from .parallel import hash_ring as _hr

        svc = self.service
        with svc._peer_mutex:
            picker = svc.local_picker
            handoff_until = (
                svc._handoff_deadline if svc._prev_picker is not None else 0.0
            )
            vh = np.array(picker._vnode_hashes, dtype=np.uint64, copy=True)
            codes = np.array(picker._vnode_code, dtype=np.int32, copy=True)
            ids = list(picker._code_ids)
            self_codes = []
            for c, pid in enumerate(ids):
                peer = picker.get_by_peer_id(pid)
                info = getattr(peer, "info", None)
                if info is not None and info.is_owner:
                    self_codes.append(c)
            hash_fn = picker.hash_fn
        if hash_fn is _hr._fnv1a_str:
            variant = 1
        elif hash_fn is _hr._fnv1_str:
            variant = 0
        else:
            variant = -1  # custom hash: the native route cannot mirror it
        vself = (
            np.isin(codes, np.asarray(self_codes, dtype=np.int32))
            .astype(np.uint8)
            if codes.size else np.zeros(0, np.uint8)
        )
        now = time.monotonic()
        enabled = (
            variant >= 0
            and bool(ids)
            and handoff_until <= now
            and not self._stopped.is_set()
        )
        with self._ring_lock:
            self._ring = (vh, vself, bool(ids) and len(self_codes) == len(ids),
                          max(variant, 0))
            # Eligibility WITHOUT the window: what the deadline re-push
            # may enable (a custom hash_fn or empty ring stays off).
            self._eligible = variant >= 0 and bool(ids)
            self._enable_at = handoff_until if handoff_until > now else 0.0
            self._push(enabled)

    def _push(self, enabled: bool) -> None:
        # _ring_lock held.
        vh, vself, all_self, variant = self._ring
        b = self.service.conf.behaviors
        express = bool(getattr(b, "express", False))
        self.batcher.set_ring(
            vh, vself, all_self=all_self, enabled=enabled,
            cap_lanes=getattr(b, "ingress_queue_lanes", 0),
            max_frame_lanes=INGRESS_COLUMNS_MAX_LANES,
            behavior_mask=(
                self.EXPRESS_FALLBACK_BEHAVIOR if express
                else self.FALLBACK_BEHAVIOR
            ),
            hash_variant=variant,
            express_mask=self.EXPRESS_MASK if express else 0,
        )

    # -- pump loop ------------------------------------------------------
    def start(self) -> "NativeIngressPump":
        for i in range(self.N_PUMPS):
            t = threading.Thread(
                target=self._run, daemon=True, name=f"native-ingress-pump-{i}"
            )
            t.start()
            self._threads.append(t)
        return self

    def _run(self) -> None:
        batcher = self.batcher
        tracing.bind_recorder(getattr(self.service, "recorder", None))
        bb = getattr(self.service, "blackbox", None)
        while not self._stopped.is_set():
            with self._ring_lock:
                # Check-and-push under ONE lock hold: a set_peers that
                # opens a NEW window between a read and the push must
                # not be re-enabled over; and the re-push honors the
                # SAME eligibility update_ring derived (a custom
                # hash_fn or empty ring stays disabled).
                if self._enable_at and time.monotonic() >= self._enable_at:
                    self._enable_at = 0.0
                    self._push(
                        self._eligible and not self._stopped.is_set()
                    )
            with profiling.scope("epoll.wait"):
                tb = batcher.take(self.take_lanes, timeout_ms=200)
            # Overload-signal parity with the Python gate: native sheds
            # happen entirely in C++, so the pump surfaces them into the
            # flight recorder (the automatic-dump trigger shedding
            # exists for) and samples the ring depth for /debug/status.
            st = batcher.stats()
            saturation.observe_queue_depth(st["pendingLanes"])
            # Express-lane attribution: NO_BATCHING frames served by
            # the native express queue (counted in C++ at submit), and
            # the ring's BULK lanes into the batched denominator — the
            # hit-rate gauge must reflect the native edge's coalesced
            # traffic, not just the batchers' windows.
            xl = st.get("expressLanes", 0)
            tl = st.get("lanes", 0)
            d_express = xl - self._express_seen
            d_bulk = (tl - self._lanes_seen) - d_express
            if d_express > 0:
                saturation.note_express("native", d_express)
            if d_bulk > 0:
                saturation.note_express("windowed", d_bulk)
            self._express_seen = xl
            self._lanes_seen = tl
            shed = st["shedLanes"]
            if shed > self._shed_seen:
                tracing.record_event(
                    "shed", lanes=shed - self._shed_seen,
                    queued=st["pendingLanes"],
                    cap=getattr(
                        self.service.conf.behaviors,
                        "ingress_queue_lanes", 0,
                    ),
                )
                self._shed_seen = shed
            if tb is None:
                if batcher.stopped:
                    return
                continue
            if bb is not None:
                # Black-box native tap, BEFORE _submit: the batch's
                # zero-copy views die at complete()/fail(), and this is
                # the only point where the coalesced frames' bytes can
                # still be reconstructed (express-lane singles answered
                # entirely in C++ never surface here — documented
                # capture slack, architecture.md "Incident black box").
                bb.tap_taken(tb)
            self._sem.acquire()
            try:
                args = self._submit(tb)
            except BaseException as e:  # noqa: BLE001
                self._sem.release()
                self._fail(tb, e)
                continue
            self._done_pool.submit(self._complete, *args)

    def _submit(self, tb):
        """One batch through the funnel's batch-granularity duties:
        conservation ledger, tenant fold, hot-key sketch (riding the
        hashes the native route already computed — zero extra
        hashing), phase attribution, then ONE columnar dispatch."""
        svc = self.service
        audit_mod.note("ingress_hits", int(tb.hits.sum()))
        tenant_ctx = svc.tenants.fold_admit(tb)
        svc.hotkeys.update(tb.hashes, tb.hash_keys)
        nf = max(tb.n_frames, 1)
        saturation.observe_phase("ingress.parse", tb.parse_ns_total / 1e9 / nf)
        for age_us in tb.frame_age_us:
            saturation.observe_phase("batch.window", float(age_us) / 1e6)
        t0 = time.perf_counter()
        handle = svc.store.apply_columns_async(
            tb.hash_keys, tb.algorithm, tb.behavior, tb.hits, tb.limit,
            tb.duration, svc.clock.now_ms(),
        )
        return tb, handle, tenant_ctx, t0

    def _complete(self, tb, handle, tenant_ctx, t0) -> None:
        svc = self.service
        m = svc.metrics
        rpc = "/pb.gubernator.V1/GetRateLimits"
        try:
            try:
                out = handle.result()
                nf = tb.n_frames
                # Copies of everything needed past complete() — the
                # batch's views die inside it.
                ages_s = tb.frame_age_us.astype(np.float64) / 1e6
                result = ColumnarResult(
                    n=tb.n,
                    status=np.asarray(out["status"], dtype=np.int32),
                    limit=np.asarray(out["limit"], dtype=np.int64),
                    remaining=np.asarray(out["remaining"], dtype=np.int64),
                    reset_time=np.asarray(out["reset_time"], dtype=np.int64),
                    overrides={},
                )
                svc.tenants.fold_outcome(tenant_ctx, result)
                t_enc = time.perf_counter()
                with profiling.scope("response.encode"):
                    self.batcher.complete(
                        tb, result.status, result.limit, result.remaining,
                        result.reset_time,
                    )
                saturation.observe_phase(
                    "response.encode",
                    (time.perf_counter() - t_enc) / max(nf, 1),
                )
                dt_disp = time.perf_counter() - t0
                m.ingress_columns_batches.labels(encoding="frame").inc(nf)
                m.request_counts.labels(status="0", method=rpc).inc(nf)
                duration = m.request_duration.labels(method=rpc)
                for age in ages_s:
                    dt = float(age) + dt_disp
                    duration.observe(dt)
                    m.observe_latency(rpc, dt)
            except BaseException as e:  # noqa: BLE001
                self._fail(tb, e)
        finally:
            self._sem.release()

    def _fail(self, tb, exc: BaseException) -> None:
        nf = tb.n_frames
        status, ctype, body = _error_triplet(exc)
        self.batcher.fail(
            tb, status, _HTTP_REASONS.get(status, "Error"), ctype, body
        )
        self.service.metrics.request_counts.labels(
            status="1", method="/pb.gubernator.V1/GetRateLimits"
        ).inc(nf)

    def stop(self) -> None:
        if self._stopped.is_set():
            return
        self._stopped.set()
        # Detach from the scrape surface FIRST: a /metrics scrape must
        # not read batcher stats across the free below.
        if getattr(self.service, "native_ingress", None) is self:
            self.service.native_ingress = None
        # Wake the pump + 503 queued frames; in-flight dispatches
        # complete through the done pool.  The batcher is NOT freed
        # here: gateway workers may still be blocked in
        # edge.next(ingress=...) and a submit against freed memory is a
        # use-after-free — a stopped batcher answers every submit with
        # the fallback code instead.  NativeGatewayServer.close calls
        # release() once its workers are joined.
        self.batcher.stop()
        for t in self._threads:
            t.join(timeout=15.0)
        self._done_pool.shutdown(wait=True)

    def release(self) -> None:
        """Free the native batcher.  Only safe after every thread that
        could submit into it (the gateway workers) has exited."""
        if all(not t.is_alive() for t in self._threads):
            self.batcher.free()


class NativeGatewayServer:
    """The C++ epoll edge (host_runtime.cpp gt_http_*): one native
    thread owns accept/read/frame/write for every connection; N Python
    workers pull parsed requests (GIL released while blocked) and run
    the same handle_request path as the stdlib gateway.  Replaces the
    measured ~1.1 ms/request Python HTTP layer and the thread-per-
    connection model that convoys at 100-way concurrency (RESULTS.md
    cfg8/cfg5).  No TLS — the daemon selects the stdlib gateway when
    TLS is configured."""

    # Workers only parse + SUBMIT (handle_request_async): the device
    # round completes through the service's drainer pool and responds
    # from there, so in-flight requests are bounded by the native
    # ingress queue, not this pool — a handful of workers keeps the
    # submit path fed even on a 1-core host.
    N_WORKERS = 4

    def __init__(self, service: V1Service, listen_address: str = "127.0.0.1:0",
                 n_workers: "Optional[int]" = None, acceptors: int = 1,
                 uds_path: str = ""):
        from . import native as _nat

        self.service = service
        if n_workers is not None and n_workers < 1:
            # Fail at startup: 0/negative would accept-but-never-serve.
            raise ValueError(
                f"native_workers must be >= 1, got {n_workers}"
            )
        self.n_workers = self.N_WORKERS if n_workers is None else n_workers
        self._edge = _nat.HttpEdge(  # raises if unavailable
            listen_address, acceptors=acceptors, uds_path=uds_path,
        )
        self._host = listen_address.partition(":")[0] or "127.0.0.1"
        self._threads: list = []
        self._stopped = threading.Event()
        # The native ingress service loop (NativeIngressPump): attached
        # by the daemon when the fast lane is on.  Workers hand kind-5
        # tokens to its batcher via edge.next(ingress=...); close()
        # stops it BEFORE the edge so staged responses never touch a
        # freed server.
        self.pump: "Optional[NativeIngressPump]" = None
        # Per-service scrape surface (metrics.observe_native_ingress).
        service.native_edges = getattr(service, "native_edges", [])
        service.native_edges.append(self._edge)
        # Responses not yet handed back to the C++ edge: free() must
        # wait for this to reach zero — async completions outlive the
        # worker threads, and edge.respond on freed memory is a
        # use-after-free (shutdown() alone is safe: respond after
        # shutdown is an explicit no-op C++-side).
        self._pending = 0
        self._pending_cv = threading.Condition()

    @property
    def address(self) -> str:
        return f"{self._host}:{self._edge.port}"

    def start(self) -> None:
        for i in range(self.n_workers):
            t = threading.Thread(target=self._worker, daemon=True,
                                 name=f"native-gw-{i}")
            t.start()
            self._threads.append(t)

    def _worker(self) -> None:
        from .native import FAST_LANE

        edge, service = self._edge, self.service
        while not self._stopped.is_set():
            # The native fast lane: when the pump is attached, a kind-5
            # ingress frame is validated/hashed/routed/enqueued INSIDE
            # edge.next (one GIL-released native call) and this worker
            # never sees its bytes — Python's per-frame cost is the
            # token round trip.  Fallback reasons fall through to the
            # unchanged path below.
            pump = self.pump
            ingress = pump.batcher if pump is not None and pump.active else None
            # Cost profiler: time blocked in the native queue pull (the
            # GIL is released inside edge.next) folds as epoll.wait —
            # the "GIL-idle in epoll" answer, distinct from parse work.
            with profiling.scope("epoll.wait"):
                got = edge.next(timeout_ms=200, ingress=ingress)
            if got is None:
                if edge.stopped:
                    return
                continue
            if got is FAST_LANE:
                continue
            token, method, path, body = got
            if getattr(service, "_closed", False):
                edge.respond(token, 503, b'{"code": 14, "message": "shutting down"}')
                continue
            with self._pending_cv:
                self._pending += 1
            handle_request_async(
                service, method, path, body, partial(self._respond, token)
            )

    def _respond(self, token: int, status: int, ctype: str,
                 payload: bytes) -> None:
        try:
            self._edge.respond(token, status, payload,
                               reason=_HTTP_REASONS.get(status, "Error"),
                               content_type=ctype)
        finally:
            with self._pending_cv:
                self._pending -= 1
                if self._pending == 0:
                    self._pending_cv.notify_all()

    def close(self) -> None:
        # Teardown order matters (round-5 review: use-after-free):
        # shutdown stops traffic but keeps the native server allocated;
        # the workers — possibly mid-device-round, about to respond() —
        # are joined BEFORE free() releases it.  A worker stuck past the
        # join timeout leaks the server instead of crashing into freed
        # memory.  The pump stops FIRST: its completions stage
        # responses into the edge, so it must drain while the server is
        # still allocated (respond-after-shutdown is a C++-side no-op).
        self._stopped.set()
        if self.pump is not None:
            self.pump.stop()
        self._edge.shutdown()
        deadline = time.monotonic() + 30.0
        for t in self._threads:
            t.join(timeout=max(deadline - time.monotonic(), 0.1))
        # Async completions (service drainer / forward pool) may still
        # owe edge.respond calls after the workers exit; free() only
        # when none remain (a stuck completion leaks the edge instead
        # of crashing into freed memory, same policy as a stuck worker).
        with self._pending_cv:
            self._pending_cv.wait_for(
                lambda: self._pending == 0,
                timeout=max(deadline - time.monotonic(), 0.1),
            )
            drained = self._pending == 0
        workers_done = all(not t.is_alive() for t in self._threads)
        if self.pump is not None and workers_done:
            # Workers are out of edge.next: no submit can reach the
            # batcher anymore.
            self.pump.release()
        # Off the scrape surface before the native server frees: a
        # /metrics scrape must never reach a freed edge.
        edges = getattr(self.service, "native_edges", None)
        if edges is not None and self._edge in edges:
            edges.remove(self._edge)
        if drained and workers_done:
            self._edge.free()


class _GatewayHTTPServer(ThreadingHTTPServer):
    # socketserver's default listen backlog of 5 resets connections under
    # a concurrent client burst; the reference edge accepts thousands of
    # in-flight requests and bounds load at the request level instead
    # (1000-item cap, gubernator.go:118-121).
    request_queue_size = 128


class GatewayServer:
    def __init__(
        self,
        service: V1Service,
        listen_address: str = "127.0.0.1:0",
        tls_context: Optional[ssl.SSLContext] = None,
    ):
        self.service = service
        host, _, port = listen_address.partition(":")
        handler = _make_handler(service)
        self.httpd = _GatewayHTTPServer((host or "127.0.0.1", int(port or 0)), handler)
        self.httpd.daemon_threads = True
        if tls_context is not None:
            self.httpd.socket = tls_context.wrap_socket(self.httpd.socket, server_side=True)
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        host, port = self.httpd.server_address[:2]
        return f"{host}:{port}"

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        self._thread.start()

    def close(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)


def _make_handler(service: V1Service):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # noqa: N802 — silence stdlib logging
            pass

        def _send_bytes(self, status: int, content_type: str, body: bytes,
                        traceparent: "Optional[str]" = None) -> None:
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            if traceparent:
                # W3C trace-context emission: the client learns the
                # trace id its request was sampled under.
                self.send_header("traceparent", traceparent)
            self.end_headers()
            self.wfile.write(body)

        def _refuse_if_closed(self) -> bool:
            """A closed daemon must refuse — keep-alive handler threads
            outlive server shutdown, but the reference's gRPC server
            kills streams on Close (daemon.go:254-274)."""
            if getattr(service, "_closed", False):
                self.close_connection = True
                try:
                    self.connection.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                return True
            return False

        def _read_raw(self) -> bytes:
            length = int(self.headers.get("Content-Length", "0"))
            return self.rfile.read(length) if length else b""

        def do_GET(self):  # noqa: N802
            if self._refuse_if_closed():
                return
            status, ctype, body = handle_request(
                service, "GET", self.path, b"", self.headers
            )
            self._send_bytes(status, ctype, body)

        def do_POST(self):  # noqa: N802
            if self._refuse_if_closed():
                return
            status, ctype, body = handle_request(
                service, "POST", self.path, self._read_raw(), self.headers
            )
            self._send_bytes(
                status, ctype, body,
                traceparent=tracing.take_emitted_traceparent(),
            )

    return Handler
