"""etcd discovery pool — lease-registration + prefix-watch membership.

Reference behavior (etcd.go): each daemon registers itself at
`/gubernator/peers/<grpc_address>` with a 30s lease kept alive in the
background, re-registering with a 5s backoff whenever the keepalive is
lost (etcd.go:222-316); it lists the prefix for the current peer set and
watches it (resuming from the list revision) to rebuild the peer map on
every change (etcd.go:110-220); Close deletes the key and revokes the
lease (etcd.go:296-310, 318-321).

The reference depends on the official Go client; this build talks to
etcd's public gRPC API directly (etcdserverpb KV/Lease/Watch) through a
minimal client over grpcio and wire-subset stubs
(proto/etcd_rpc.proto) — wire-compatible with a real etcd v3 cluster
and with the in-process fake used by tests.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

import grpc

from .proto import etcd_rpc_pb2 as rpc
from .types import PeerInfo

log = logging.getLogger("gubernator.etcd")

ETCD_TIMEOUT_S = 10.0  # etcd.go:31
BACKOFF_TIMEOUT_S = 5.0  # etcd.go:32
LEASE_TTL_S = 30  # etcd.go:34
DEFAULT_BASE_KEY = "/gubernator/peers/"  # etcd.go:35


def prefix_range_end(prefix: bytes) -> bytes:
    """etcd's GetPrefixRangeEnd: the prefix with its last byte
    incremented (carrying over 0xff)."""
    end = bytearray(prefix)
    for i in reversed(range(len(end))):
        if end[i] < 0xFF:
            end[i] += 1
            return bytes(end[: i + 1])
    return b"\0"  # whole keyspace


class EtcdClient:
    """Minimal etcd v3 client: KV Range/Put/DeleteRange, Lease
    Grant/Revoke/KeepAlive, Watch — just the surface the pool needs."""

    def __init__(
        self,
        endpoints: Sequence[str],
        credentials: Optional[grpc.ChannelCredentials] = None,
        timeout_s: float = ETCD_TIMEOUT_S,
        username: str = "",
        password: str = "",
    ):
        if not endpoints:
            raise ValueError("at least one etcd endpoint is required")
        self.endpoints = list(endpoints)
        self.timeout_s = timeout_s
        self._credentials = credentials
        self._username = username
        self._password = password
        self._metadata: "Optional[list]" = None
        self._endpoint_idx = 0
        self._rotate_lock = threading.Lock()
        self._retired_channels: list = []
        self._connect()

    @property
    def endpoint_index(self) -> int:
        return self._endpoint_idx

    def _connect(self) -> None:
        """(Re)build the channel + stubs against the current endpoint.
        The Go client load-balances across all endpoints; here failover
        is explicit — rotate() advances to the next endpoint and the
        pool's retry loops call it on any RPC failure."""
        target = self.endpoints[self._endpoint_idx]
        if self._credentials is not None:
            self._channel = grpc.secure_channel(target, self._credentials)
        else:
            self._channel = grpc.insecure_channel(target)
        u = self._channel.unary_unary
        s = self._channel.stream_stream
        self._range = u(
            "/etcdserverpb.KV/Range",
            request_serializer=rpc.RangeRequest.SerializeToString,
            response_deserializer=rpc.RangeResponse.FromString,
        )
        self._put = u(
            "/etcdserverpb.KV/Put",
            request_serializer=rpc.PutRequest.SerializeToString,
            response_deserializer=rpc.PutResponse.FromString,
        )
        self._delete = u(
            "/etcdserverpb.KV/DeleteRange",
            request_serializer=rpc.DeleteRangeRequest.SerializeToString,
            response_deserializer=rpc.DeleteRangeResponse.FromString,
        )
        self._compact = u(
            "/etcdserverpb.KV/Compact",
            request_serializer=rpc.CompactionRequest.SerializeToString,
            response_deserializer=rpc.CompactionResponse.FromString,
        )
        self._grant = u(
            "/etcdserverpb.Lease/LeaseGrant",
            request_serializer=rpc.LeaseGrantRequest.SerializeToString,
            response_deserializer=rpc.LeaseGrantResponse.FromString,
        )
        self._revoke = u(
            "/etcdserverpb.Lease/LeaseRevoke",
            request_serializer=rpc.LeaseRevokeRequest.SerializeToString,
            response_deserializer=rpc.LeaseRevokeResponse.FromString,
        )
        self._keepalive = s(
            "/etcdserverpb.Lease/LeaseKeepAlive",
            request_serializer=rpc.LeaseKeepAliveRequest.SerializeToString,
            response_deserializer=rpc.LeaseKeepAliveResponse.FromString,
        )
        self._watch = s(
            "/etcdserverpb.Watch/Watch",
            request_serializer=rpc.WatchRequest.SerializeToString,
            response_deserializer=rpc.WatchResponse.FromString,
        )
        self._authenticate = u(
            "/etcdserverpb.Auth/Authenticate",
            request_serializer=rpc.AuthenticateRequest.SerializeToString,
            response_deserializer=rpc.AuthenticateResponse.FromString,
        )
        # GUBER_ETCD_USER/PASSWORD (config.go:309-310): etcd v3 auth is
        # token-based — Authenticate once per connection, then send the
        # token as `token` metadata on every call.  Re-connecting (the
        # rotate() failover path) re-authenticates, which also renews an
        # expired token: callers' retry loops rotate on auth errors the
        # same as on transport errors.
        if self._username:
            try:
                resp = self._authenticate(
                    rpc.AuthenticateRequest(
                        name=self._username, password=self._password
                    ),
                    timeout=self.timeout_s,
                )
                self._metadata = [("token", resp.token)]
            except grpc.RpcError as e:
                code = e.code() if hasattr(e, "code") else None
                # Wrong credentials must fail pool construction (the
                # reference's client refuses too); a TRANSPORT failure
                # must not kill the retry loops that call rotate() from
                # their own except-handlers — leave the stale/absent
                # token, let the next RPC fail, and back off again.
                if self._metadata is None and code == grpc.StatusCode.INVALID_ARGUMENT:
                    raise
                log.warning("etcd re-authentication failed (will retry): %s", e)

    def rotate(self, observed_index: Optional[int] = None) -> None:
        """Fail over to the next configured endpoint.

        `observed_index` is the endpoint the caller saw failing:
        concurrent failures from the keepalive and watch threads then
        advance the index ONCE, not past the fresh endpoint.  The old
        channel is retired, not closed — the other thread's healthy
        stream on it keeps running; retirees close at client close()."""
        with self._rotate_lock:
            if observed_index is not None and observed_index != self._endpoint_idx:
                return  # another thread already rotated away
            if len(self.endpoints) <= 1:
                # Single endpoint: nothing to fail over to, but rebuild
                # the channel anyway — with auth enabled this is the
                # only place an expired token gets renewed (etcd simple
                # tokens expire server-side; every caller reaches here
                # via its failure-retry loop).
                self._retired_channels.append(self._channel)
                while len(self._retired_channels) > 2:
                    self._retired_channels.pop(0).close()
                self._connect()
                return
            self._retired_channels.append(self._channel)
            # Bound the retirement list: only the most recent retirees
            # can still carry another thread's live stream; older ones
            # closed their streams rotations ago — close them now or a
            # long outage leaks a channel per backoff cycle.
            while len(self._retired_channels) > 2:
                self._retired_channels.pop(0).close()
            self._endpoint_idx = (self._endpoint_idx + 1) % len(self.endpoints)
            self._connect()

    # ------------------------------------------------------------------
    def range_prefix(self, prefix: str) -> Tuple[List[Tuple[str, bytes]], int]:
        """All (key, value) under prefix, plus the store revision to
        resume a watch from (etcd.go:141-161)."""
        p = prefix.encode()
        resp = self._range(
            rpc.RangeRequest(key=p, range_end=prefix_range_end(p)),
            timeout=self.timeout_s, metadata=self._metadata,
        )
        kvs = [(kv.key.decode(), kv.value) for kv in resp.kvs]
        return kvs, resp.header.revision

    def compact(self, revision: int) -> None:
        """KV.Compact — not used by the pool itself (etcd compacts on
        its own schedule in production); exposed for the integration
        tests that prove the pool survives watch-resume across a
        compaction (mvcc ErrCompacted -> canceled watch -> re-list)."""
        self._compact(
            rpc.CompactionRequest(revision=revision),
            timeout=self.timeout_s, metadata=self._metadata,
        )

    def put(self, key: str, value: bytes, lease_id: int = 0) -> None:
        self._put(
            rpc.PutRequest(key=key.encode(), value=value, lease=lease_id),
            timeout=self.timeout_s, metadata=self._metadata,
        )

    def delete(self, key: str) -> None:
        self._delete(
            rpc.DeleteRangeRequest(key=key.encode()),
            timeout=self.timeout_s, metadata=self._metadata,
        )

    def lease_grant(self, ttl_s: int) -> int:
        resp = self._grant(
            rpc.LeaseGrantRequest(TTL=ttl_s),
            timeout=self.timeout_s, metadata=self._metadata,
        )
        if resp.error:
            raise RuntimeError(f"lease grant failed: {resp.error}")
        return resp.ID

    def lease_revoke(self, lease_id: int) -> None:
        self._revoke(
            rpc.LeaseRevokeRequest(ID=lease_id),
            timeout=self.timeout_s, metadata=self._metadata,
        )

    def lease_keepalive(self, lease_id: int, interval_s: float, stop: threading.Event):
        """Generator of keepalive responses, sending a ping every
        `interval_s` until `stop` is set or the stream dies.  The caller
        treats StopIteration/RpcError as 'keepalive lost'."""

        def requests():
            while not stop.is_set():
                yield rpc.LeaseKeepAliveRequest(ID=lease_id)
                stop.wait(interval_s)

        return self._keepalive(requests(), metadata=self._metadata)

    def watch_prefix(self, prefix: str, start_revision: int, stop: threading.Event):
        """Returns (response_iterator, done_event) for a prefix watch
        from `start_revision`.  The caller MUST set `done` when it stops
        consuming the stream: the request-side generator parks in a
        bounded wait on (done | stop), so gRPC's request-consumer thread
        exits promptly instead of leaking one blocked thread per watch
        attempt."""
        p = prefix.encode()
        done = threading.Event()

        def requests():
            yield rpc.WatchRequest(
                create_request=rpc.WatchCreateRequest(
                    key=p,
                    range_end=prefix_range_end(p),
                    start_revision=start_revision,
                )
            )
            while not stop.is_set() and not done.is_set():
                done.wait(0.5)

        return self._watch(requests(), metadata=self._metadata), done

    def close(self) -> None:
        with self._rotate_lock:
            for ch in self._retired_channels:
                ch.close()
            self._retired_channels.clear()
            self._channel.close()


def credentials_from_config(conf) -> Optional[grpc.ChannelCredentials]:
    """setupEtcdTLS equivalent (config.go:390-433): build channel
    credentials from the GUBER_ETCD_TLS_* surface.

      * GUBER_ETCD_TLS_CA           — verify against this CA
      * GUBER_ETCD_TLS_CERT/KEY     — client certificate (mTLS)
      * GUBER_ETCD_TLS_ENABLE       — TLS with system roots
      * GUBER_ETCD_TLS_SKIP_VERIFY  — TLS pinning each endpoint's own
        certificate fetched at startup (Python gRPC cannot disable
        verification outright; trust-on-first-use is the closest
        faithful semantic to the reference's InsecureSkipVerify)

    Returns None when no TLS knob is set (plaintext)."""
    ca = getattr(conf, "etcd_tls_ca", "")
    cert = getattr(conf, "etcd_tls_cert", "")
    key = getattr(conf, "etcd_tls_key", "")
    enable = getattr(conf, "etcd_tls_enable", False)
    skip = getattr(conf, "etcd_tls_skip_verify", False)
    if not (ca or (cert and key) or enable or skip):
        return None
    root_pem = None
    if ca:
        with open(ca, "rb") as f:
            root_pem = f.read()
    elif skip:
        import ssl as _ssl

        pins = []
        for ep in getattr(conf, "etcd_endpoints", []):
            host, _, port = ep.partition(":")
            try:
                pins.append(
                    _ssl.get_server_certificate(
                        (host, int(port or 2379)), timeout=ETCD_TIMEOUT_S
                    )
                )
            except OSError as e:  # endpoint down: pin the others
                log.warning("etcd skip-verify pin failed for %s: %s", ep, e)
        if pins:
            root_pem = "".join(pins).encode()
    key_pem = chain_pem = None
    if cert and key:
        with open(key, "rb") as f:
            key_pem = f.read()
        with open(cert, "rb") as f:
            chain_pem = f.read()
    return grpc.ssl_channel_credentials(
        root_certificates=root_pem,
        private_key=key_pem,
        certificate_chain=chain_pem,
    )


class EtcdPool:
    """Peer discovery over etcd (reference EtcdPool, etcd.go:42-334)."""

    def __init__(
        self,
        advertise: PeerInfo,
        on_update: Callable[[List[PeerInfo]], None],
        endpoints: Sequence[str] = ("127.0.0.1:2379",),
        key_prefix: str = DEFAULT_BASE_KEY,
        client: Optional[EtcdClient] = None,
        credentials: Optional[grpc.ChannelCredentials] = None,
        lease_ttl_s: int = LEASE_TTL_S,
        backoff_s: float = BACKOFF_TIMEOUT_S,
        username: str = "",
        password: str = "",
    ):
        if not advertise.grpc_address:
            raise ValueError("Advertise.GRPCAddress is required")  # etcd.go:78
        self.advertise = advertise
        self.on_update = on_update
        self.key_prefix = key_prefix
        self.lease_ttl_s = lease_ttl_s
        self.backoff_s = backoff_s
        self.client = client or EtcdClient(
            endpoints, credentials=credentials,
            username=username, password=password,
        )
        self._instance_key = key_prefix + advertise.grpc_address
        self._peers: dict = {}
        self._peers_lock = threading.Lock()
        self._stop = threading.Event()
        self._lease_id: Optional[int] = None

        # Initial registration is synchronous like the reference
        # (etcd.go:262-264: failure fails pool construction), trying
        # each configured endpoint before giving up.
        for attempt in range(len(self.client.endpoints)):
            try:
                self._register_once()
                break
            except grpc.RpcError:
                if attempt == len(self.client.endpoints) - 1:
                    raise
                self.client.rotate()
        self._collect_and_notify()

        self._threads = [
            threading.Thread(target=self._keepalive_loop, daemon=True),
            threading.Thread(target=self._watch_loop, daemon=True),
        ]
        for t in self._threads:
            t.start()

    # ------------------------------------------------------------------
    def _register_once(self) -> None:
        """Grant lease + put our PeerInfo under it (etcd.go:240-259)."""
        payload = json.dumps(self.advertise.to_json()).encode()
        self._lease_id = self.client.lease_grant(self.lease_ttl_s)
        self.client.put(self._instance_key, payload, lease_id=self._lease_id)

    def _keepalive_loop(self) -> None:
        """Consume keepalives; on loss, re-register with backoff
        (etcd.go:266-295)."""
        while not self._stop.is_set():
            ep = self.client.endpoint_index
            try:
                stream = self.client.lease_keepalive(
                    self._lease_id, max(self.lease_ttl_s / 3.0, 0.05), self._stop
                )
                for resp in stream:
                    if self._stop.is_set():
                        return
                    if resp.TTL <= 0:
                        # Real etcd keeps the stream open and answers an
                        # expired lease with TTL=0; treat it like a
                        # stream loss (the Go client closes its channel
                        # on TTL<=0, which etcd.go re-registers on).
                        break
            except grpc.RpcError:
                self.client.rotate(ep)
            if self._stop.is_set():
                return
            log.warning("keep alive lost, attempting to re-register peer")
            while not self._stop.is_set():
                ep = self.client.endpoint_index
                try:
                    self._register_once()
                    break
                except grpc.RpcError as e:
                    log.error("while attempting to re-register peer: %s", e)
                    self.client.rotate(ep)
                    self._stop.wait(self.backoff_s)

    # ------------------------------------------------------------------
    def _collect_and_notify(self) -> int:
        """List the prefix, rebuild the peer map, push an update;
        returns the revision to watch from (etcd.go:141-161)."""
        kvs, revision = self.client.range_prefix(self.key_prefix)
        peers = {}
        for key, value in kvs:
            info = self._unmarshal(value)
            if info is not None:
                peers[key] = info
        with self._peers_lock:
            self._peers = peers
        self._call_on_update()
        return revision

    def _watch_loop(self) -> None:
        """Watch the prefix from the collect revision; any event mutates
        the peer map and re-notifies; stream failure re-collects with
        backoff (etcd.go:96-139, 174-220)."""
        revision = None
        while not self._stop.is_set():
            done = None
            ep = self.client.endpoint_index
            try:
                if revision is None:
                    revision = self._collect_and_notify() + 1
                stream, done = self.client.watch_prefix(
                    self.key_prefix, revision, self._stop
                )
                for resp in stream:
                    if self._stop.is_set():
                        return
                    if resp.canceled:
                        break
                    changed = False
                    for ev in resp.events:
                        key = ev.kv.key.decode()
                        if ev.type == 1:  # DELETE
                            changed = self._peers.pop(key, None) is not None or changed
                        else:  # PUT
                            info = self._unmarshal(ev.kv.value)
                            if info is not None:
                                self._peers[key] = info
                                changed = True
                        revision = max(revision, ev.kv.mod_revision + 1)
                    if changed:
                        self._call_on_update()
            except grpc.RpcError:
                self.client.rotate(ep)
            finally:
                if done is not None:
                    done.set()  # release the request-side generator
            if self._stop.is_set():
                return
            revision = None  # full re-collect after any stream failure
            self._stop.wait(self.backoff_s)

    @staticmethod
    def _unmarshal(value: bytes) -> Optional[PeerInfo]:
        try:
            return PeerInfo.from_json(json.loads(value.decode()))
        except (ValueError, UnicodeDecodeError):
            log.error("unable to unmarshal PeerInfo from etcd value %r", value[:100])
            return None

    def _call_on_update(self) -> None:
        """etcd.go:323-334 (IsOwner stamped by the daemon's set_peers;
        the reference stamps here, but the daemon re-stamps anyway)."""
        with self._peers_lock:
            peers = sorted(self._peers.values(), key=lambda p: p.grpc_address)
        try:
            self.on_update(peers)
        except Exception:  # noqa: BLE001
            log.exception("on_update callback failed")

    # ------------------------------------------------------------------
    def close(self) -> None:
        """Deregister then shut down (etcd.go:296-310, 318-321)."""
        if self._stop.is_set():
            return
        self._stop.set()
        try:
            self.client.delete(self._instance_key)
            if self._lease_id is not None:
                self.client.lease_revoke(self._lease_id)
        except grpc.RpcError as e:
            log.warning("during etcd deregistration: %s", e)
        for t in self._threads:
            t.join(timeout=2.0)
        self.client.close()
