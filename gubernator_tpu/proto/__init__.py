"""Wire schema (reference proto/gubernator.proto, proto/peers.proto).

`gubernator_pb2` / `peers_pb2` are protoc-generated from the .proto
files in this directory (regenerate with scripts/proto.sh).  Service
and message names are wire-compatible with the reference so stock
Gubernator gRPC clients interoperate unchanged.
"""

from . import gubernator_pb2, peers_pb2  # noqa: F401

V1_SERVICE = "pb.gubernator.V1"
PEERS_V1_SERVICE = "pb.gubernator.PeersV1"
