"""Elastic membership: live resharding with columnar state handoff.

A ring change used to be metadata-only (`V1Service.set_peers` rebuilt
the pickers, mirroring gubernator.go:357-437) — every device-resident
counter whose ownership moved was silently orphaned, so a scale-out
event was a cluster-wide rate-limit reset.  This module makes
membership changes *stateful*:

  * On a ring delta, the old owner DRAINS the moved keys off the
    device (one mesh-wide gather program per drain batch — the PR 5
    readback playbook in reverse, `MeshBucketStore.drain_keys`) and
    ships them to each new owner as a TransferColumns batch (GUBC
    frame kind 4 / proto `TransferColumnsReq`, wire.py).  The gather
    does not remove the keys: the local copy is forgotten only after
    the transfer is ACKED (`forget_keys`), so it stays readable — the
    double-dispatch peek target — for the whole in-flight window.
  * The new owner commits the batch through the batched replica-commit
    playbook (`MeshBucketStore.commit_transfer`: one gather + one
    scatter, O(1) device programs per batch) with MONOTONE merge
    semantics, so duplicate delivery and concurrent traffic can never
    double-count a hit.
  * Epoch fencing: every transfer frame is stamped with the
    destination ring's fingerprint (`ring_fingerprint`, an
    order-independent FNV-1 fold of the membership).  A receiver whose
    ring has since changed again rejects the batch (FailedPrecondition
    — "a late transfer from a dead epoch"), and the sender aborts
    instead of committing state under the wrong ring.
  * During the handoff window reads DOUBLE-DISPATCH: the routing
    daemon serves the hit from the key's NEW owner and issues a
    zero-hit peek at the OLD owner, merging monotonically (see
    V1Service._merge_handoff) so no request observes a reset bucket
    while the transfer is in flight.

Merge semantics (the documented monotone rule, architecture.md
"Membership & resharding"): for a live resident row of the same
algorithm, remaining = min, status = max (OVER_LIMIT wins), stamp /
reset / expire = max; an expired or algorithm-switched resident row is
overwritten by the incoming row wholesale.  min/max are idempotent and
order-free, which is what makes transfer retries and the
double-dispatch window safe.

Documented slack (the exactly-once contract the chaos oracle pins,
tests/test_reshard_chaos.py): hits admitted by the NEW owner against a
fresh bucket *during* the handoff window are not reflected in the
transferred row (and vice versa: hits the old owner admits between the
drain gather and the transfer ACK never reach the new owner), so a key
may over-admit by at most min(hits-before-drain, hits-during-window).
If a transfer ABORTS (frames dropped past the retry budget, epoch
fenced, unsupported peer), the local copy was never removed — reads
still peek it for the rest of the window — but the new owner starts
the key fresh, so the key over-admits by at most the old owner's
consumption: exactly the pre-PR reset behavior, now bounded to the
failure case and counted
(gubernator_reshard_transfers{result="aborted"} + a `reshard-aborted`
flight-recorder event).  An old owner that DIES mid-transfer loses its
unshipped consumption the same way.  Hits are never double-counted in
any path: the commit merge is idempotent (min/max), a timeout-shaped
send failure leaves both copies but only the current ring's owner
takes hits, and the peek leg is zero-hit by construction.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np

from . import audit
from . import tracing
from .utils import hashing

log = logging.getLogger("gubernator.reshard")

# Lane cap per transfer RPC: ride the columnar peer-hop bound (a
# transfer is the same wire weight class as a coalesced forward).
TRANSFER_MAX_LANES = 16384


def ring_fingerprint(peer_ids: Sequence[str], replicas: int = 512) -> int:
    """Order-independent 64-bit identity of a ring MEMBERSHIP — the
    shared epoch stamp for transfer fencing.  Computed identically on
    every daemon from the peer-id strings (gRPC addresses) alone, so no
    coordination is needed for two daemons to agree on "the same ring".
    XOR-fold of per-peer FNV-1 hashes (order-free), mixed with the
    vnode count (a replicas change moves ownership without changing
    membership, so it must change the epoch too)."""
    h = hashing.fnv1_64(f"replicas={replicas}".encode("utf-8"))
    for pid in peer_ids:
        h ^= hashing.fnv1_64(pid.encode("utf-8"))
    return h & 0xFFFFFFFFFFFFFFFF


@dataclass
class TransferColumns:
    """One ownership-transfer batch in column form: lane i of every
    column is one moved key's FULL device bucket row (the BucketRows
    shape, ops/buckets.py) — enough state for the new owner to continue
    the bucket exactly where the old owner left it."""

    keys: List[str]
    algorithm: np.ndarray  # i32[n]
    status: np.ndarray  # i32[n]
    limit: np.ndarray  # i64[n]
    remaining: np.ndarray  # i64[n]
    duration: np.ndarray  # i64[n]
    stamp: np.ndarray  # i64[n]  (token created_at / leaky updated_at)
    expire_at: np.ndarray  # i64[n]
    # Destination-epoch fence: ring_fingerprint of the ring this batch
    # was routed under.  0 = unfenced (accepted anywhere; tests only).
    ring_hash: int = 0

    def __len__(self) -> int:
        return len(self.keys)

    @classmethod
    def empty(cls, ring_hash: int = 0) -> "TransferColumns":
        return cls(
            keys=[],
            algorithm=np.zeros(0, np.int32),
            status=np.zeros(0, np.int32),
            limit=np.zeros(0, np.int64),
            remaining=np.zeros(0, np.int64),
            duration=np.zeros(0, np.int64),
            stamp=np.zeros(0, np.int64),
            expire_at=np.zeros(0, np.int64),
            ring_hash=ring_hash,
        )

    def subset(self, idx) -> "TransferColumns":
        """Lane subset (receiver-side ownership filtering / sender-side
        chunking)."""
        idx = np.asarray(idx, dtype=np.int64)
        return TransferColumns(
            keys=[self.keys[int(i)] for i in idx],
            algorithm=self.algorithm[idx],
            status=self.status[idx],
            limit=self.limit[idx],
            remaining=self.remaining[idx],
            duration=self.duration[idx],
            stamp=self.stamp[idx],
            expire_at=self.expire_at[idx],
            ring_hash=self.ring_hash,
        )

    def slice(self, lo: int, hi: int) -> "TransferColumns":
        return TransferColumns(
            keys=self.keys[lo:hi],
            algorithm=self.algorithm[lo:hi],
            status=self.status[lo:hi],
            limit=self.limit[lo:hi],
            remaining=self.remaining[lo:hi],
            duration=self.duration[lo:hi],
            stamp=self.stamp[lo:hi],
            expire_at=self.expire_at[lo:hi],
            ring_hash=self.ring_hash,
        )


def merge_transfer_rows(cur, incoming: TransferColumns, idx, now_ms: int,
                        exists: np.ndarray):
    """Monotone merge of incoming transferred rows against the
    receiver's CURRENT device rows (both as parallel arrays; `cur` is a
    dict of gathered columns aligned with `idx` lanes of `incoming`).

    live = the receiver already holds an unexpired row of the same
    algorithm for the key (it admitted traffic during the handoff
    window).  For live lanes the SIDE with the lower `remaining` wins
    and contributes BOTH its remaining and its stamp — the pair moves
    together, because a field-wise min(remaining)/max(stamp) mix would
    fabricate a state that never existed (a stale low remaining paired
    with a fresh stamp denies a leaky bucket all leak credit accrued
    since the stale drain).  status/expire merge max.  Equal remaining
    keeps the current side, so duplicate delivery (transfer retries)
    is a no-op and interleavings converge.  Dead/absent lanes take the
    incoming row wholesale.  Returns the merged column dict to
    scatter."""
    inc_algo = incoming.algorithm[idx]
    live = (
        exists
        & (cur["expire_at"] >= now_ms)
        & (cur["algo"] == inc_algo)
    )
    # Which side supplies the (remaining, stamp) pair: the incoming row
    # when the lane is dead/absent, or when it is STRICTLY more
    # consumed than the resident one.
    take_inc = np.logical_not(live) | (
        incoming.remaining[idx] < cur["remaining"]
    )
    out = {
        "algo": inc_algo.astype(np.int32),
        "limit": incoming.limit[idx].astype(np.int64),
        "duration": incoming.duration[idx].astype(np.int64),
        "remaining": np.where(
            take_inc, incoming.remaining[idx], cur["remaining"]
        ).astype(np.int64),
        "stamp": np.where(
            take_inc, incoming.stamp[idx], cur["stamp"]
        ).astype(np.int64),
        "status": np.where(
            live,
            np.maximum(cur["status"], incoming.status[idx]),
            incoming.status[idx],
        ).astype(np.int32),
        "expire_at": np.where(
            live,
            np.maximum(cur["expire_at"], incoming.expire_at[idx]),
            incoming.expire_at[idx],
        ).astype(np.int64),
    }
    return out


class ReshardManager:
    """The sender side of the state-migration plane, plus the bounded
    membership maintenance pool.

    One small pool serves both membership duties set_peers used to do
    inline or on unbounded daemon threads: shutting down dropped peers'
    clients (tracked, so close() can't race a half-shutdown client) and
    running the drain -> transfer handoff for a ring delta.  Handoffs
    are generation-checked: a newer set_peers supersedes an in-flight
    handoff between batches."""

    POOL_WORKERS = 4

    def __init__(self, service):
        self.service = service
        self._pool = ThreadPoolExecutor(
            max_workers=self.POOL_WORKERS, thread_name_prefix="reshard"
        )
        self._lock = threading.Lock()
        self._tasks: List[Future] = []
        self._closed = False
        # Host-side counters (exported as gubernator_reshard_* via the
        # per-scrape observe pass and served raw in /debug/status).
        self.transfers_started = 0
        self.transfers_committed = 0
        self.transfers_aborted = 0
        self.transfers_fenced_in = 0  # receive-side epoch rejections
        self.lanes_moved = 0
        self.lanes_received = 0
        self.lanes_rejected = 0  # receive-side not-owned-here lanes
        self.last_handoff_seconds = 0.0

    # -- bounded submission -------------------------------------------
    def _submit(self, fn, *args) -> Optional[Future]:
        with self._lock:
            if self._closed:
                return None
            try:
                fut = self._pool.submit(fn, *args)
            except RuntimeError:  # pool shut down under us
                return None
            self._tasks.append(fut)
            # Completed futures retire lazily; the list stays bounded
            # by churn rate, not daemon lifetime.
            if len(self._tasks) > 64:
                self._tasks = [t for t in self._tasks if not t.done()]
            return fut

    def submit_shutdown(self, client) -> None:
        """Shut a dropped peer's client down off the caller's thread —
        through the bounded pool, TRACKED, so `close()` drains them
        instead of racing a half-shutdown client (gubernator.go:398-428
        drains dropped peers in the background too, but bounded)."""
        if self._submit(self._safe_shutdown, client) is None:
            # Closing/closed: shut down inline — the client must not
            # leak its window thread just because we are.
            self._safe_shutdown(client)

    @staticmethod
    def _safe_shutdown(client) -> None:
        try:
            client.shutdown()
        except Exception as e:  # noqa: BLE001 — best-effort teardown
            log.debug("dropped-peer shutdown failed: %s", e)

    # -- handoff ------------------------------------------------------
    def schedule_handoff(self, picker, ring_hash: int, generation: int) -> None:
        """Queue the drain -> transfer pass for a ring delta (called by
        V1Service.set_peers AFTER the new picker is installed, outside
        the peer mutex)."""
        self._submit(self._run_handoff, picker, ring_hash, generation)

    def _current_generation(self) -> int:
        return self.service.ring_generation

    def _run_handoff(self, picker, ring_hash: int, generation: int) -> None:
        svc = self.service
        store = svc.store
        t0 = time.monotonic()
        did_work = False
        try:
            if self._current_generation() != generation or self._closed:
                # Superseded before we even started (membership churn
                # queues handoffs faster than they run): the newest
                # handoff owns whatever still resides here — stale ones
                # must cost one integer compare, not a table scan.
                return
            # Warmup keys ("__warmup__*") are synthetic compile fodder,
            # resident on EVERY daemon by construction — shipping them
            # would be pure churn (and under a frozen test clock they
            # never expire out of the live filter).
            keys = [
                k for k in store.resident_keys()
                if not k.startswith("__warmup__")
            ]
            if not keys:
                return
            codes, code_ids = picker.get_batch_codes(keys)
            moved: Dict[str, List[str]] = {}
            for c, pid in enumerate(code_ids):
                peer = picker.get_by_peer_id(pid)
                if peer is None or peer.info.is_owner:
                    continue  # stays local (or churned away mid-pass)
                sel = np.nonzero(codes == c)[0]
                if sel.size:
                    moved[pid] = [keys[int(i)] for i in sel]
            if not moved:
                return
            did_work = True
            n_total = sum(len(v) for v in moved.values())
            log.info(
                "reshard gen=%d: %d resident keys moved to %d new owner(s)",
                generation, n_total, len(moved),
            )
            for pid, mkeys in moved.items():
                for lo in range(0, len(mkeys), TRANSFER_MAX_LANES):
                    if self._current_generation() != generation or self._closed:
                        # A newer ring superseded this handoff: stop
                        # between batches — nothing drained yet for this
                        # chunk, so nothing is lost; the newer handoff
                        # re-routes what still resides here.
                        return
                    self._transfer_chunk(
                        picker, pid, mkeys[lo:lo + TRANSFER_MAX_LANES],
                        ring_hash,
                    )
        except Exception as e:  # noqa: BLE001 — a handoff failure must
            # never take the serving path down; it degrades to the
            # pre-PR reset behavior for the affected keys, counted.
            log.warning("reshard handoff gen=%d failed: %s", generation, e)
            self._abort(None, 0, f"handoff-error: {e}")
        finally:
            if did_work:
                # Superseded/no-op passes cost an integer compare and
                # would rewrite the gauge to ~0, hiding the wall time
                # of the last REAL drain->transfer pass.
                self.last_handoff_seconds = time.monotonic() - t0

    def _transfer_chunk(self, picker, pid: str, keys: List[str],
                        ring_hash: int) -> None:
        """Gather -> send -> forget-on-ack.  The gather does NOT remove
        the keys: the old owner's copy stays readable (the
        double-dispatch peek target) for the whole in-flight window,
        and only a successful ACK forgets it — so an aborted transfer
        loses nothing locally, and a timeout-shaped failure (the RPC
        may have applied server-side) leaves both copies, which the
        monotone merge + current-ring routing keep from ever
        double-counting."""
        svc = self.service
        cols = svc.store.drain_keys(keys, svc.clock.now_ms(), remove=False)
        if len(cols) == 0:
            return
        cols.ring_hash = ring_hash
        self.transfers_started += 1
        self._count("started")
        # Conservation ledger (audit.py): acked lanes must never exceed
        # drained lanes (reshard_out) — counted at the two distinct
        # points of the gather -> send -> forget-on-ack protocol.
        audit.note("reshard_drained_lanes", len(cols))
        peer = picker.get_by_peer_id(pid)
        if peer is None:
            self._abort(cols, len(cols), f"peer {pid} gone from ring")
            return
        ok, err = svc._peer_send_ex(  # noqa: SLF001 — shared retry envelope
            "TransferOwnership",
            lambda: self._send_one(peer, cols),
        )
        if ok:
            svc.store.forget_keys(cols.keys)
            self.transfers_committed += 1
            self.lanes_moved += len(cols)
            self._count("committed")
            audit.note("reshard_acked_lanes", len(cols))
            if self.service.metrics is not None:
                self.service.metrics.reshard_lanes.labels(
                    direction="out"
                ).inc(len(cols))
        else:
            self._abort(cols, len(cols), str(err))

    def _send_one(self, peer, cols: TransferColumns) -> None:
        """One transfer send; raises on transport failure.  A peer that
        negotiated down to classic (no transfer surface) or fenced the
        epoch raises a terminal ValueError so the retry envelope stops
        — both are deterministic answers, not transient faults."""
        status = peer.transfer_ownership(cols)
        if status == "unsupported":
            raise ValueError(
                f"peer {peer.info.grpc_address} does not speak the "
                "transfer plane (classic fallback: moved keys reset "
                "there, pre-reshard semantics)"
            )
        if status == "fenced":
            raise ValueError(
                f"peer {peer.info.grpc_address} fenced the transfer "
                "(its ring changed again; dead-epoch batch)"
            )

    def _abort(self, cols: Optional[TransferColumns], lanes: int,
               reason: str) -> None:
        """Abort leg: the local copy was never removed (gather-only
        drain), so nothing is reinstalled — the keys stay readable at
        the old owner for the rest of the double-dispatch window, after
        which they behave as the pre-PR reset did (fresh buckets at the
        new owner) — bounded to this failure case and counted."""
        self.transfers_aborted += 1
        self._count("aborted")
        # Flight-recorder event + automatic dump (tracing.py): an
        # aborted transfer is exactly the state-loss moment the
        # recorder exists to preserve — same rate-limited path as
        # breaker-open.
        tracing.record_event("reshard-aborted", lanes=lanes, reason=reason)
        log.warning("reshard transfer aborted (%d lanes): %s", lanes, reason)

    def _count(self, result: str) -> None:
        m = self.service.metrics
        if m is not None:
            m.reshard_transfers.labels(result=result).inc()

    # -- receive-side bookkeeping (V1Service.transfer_ownership) -------
    def note_received(self, committed: int, rejected: int) -> None:
        self.lanes_received += committed
        self.lanes_rejected += rejected
        audit.note("reshard_committed_lanes", committed)
        audit.note("reshard_rejected_lanes", rejected)
        m = self.service.metrics
        if m is not None:
            if committed:
                m.reshard_lanes.labels(direction="in").inc(committed)
            if rejected:
                m.reshard_lanes.labels(direction="rejected").inc(rejected)

    def note_fenced(self, lanes: int) -> None:
        self.transfers_fenced_in += 1
        m = self.service.metrics
        if m is not None:
            m.reshard_transfers.labels(result="fenced").inc()

    def snapshot(self) -> dict:
        """The /debug/status "reshard" section."""
        return {
            "transfersStarted": self.transfers_started,
            "transfersCommitted": self.transfers_committed,
            "transfersAborted": self.transfers_aborted,
            "transfersFencedIn": self.transfers_fenced_in,
            "lanesMoved": self.lanes_moved,
            "lanesReceived": self.lanes_received,
            "lanesRejected": self.lanes_rejected,
            "lastHandoffSeconds": round(self.last_handoff_seconds, 4),
        }

    def wait_idle(self, timeout_s: float = 10.0) -> bool:
        """Block until every tracked task finished (tests + close())."""
        deadline = time.monotonic() + timeout_s
        with self._lock:
            tasks = list(self._tasks)
        for t in tasks:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            try:
                t.result(timeout=remaining)
            except Exception:  # noqa: BLE001 — task errors logged at site
                pass
        return True

    def close(self, timeout_s: float = 10.0) -> None:
        with self._lock:
            self._closed = True
        self.wait_idle(timeout_s)
        self._pool.shutdown(wait=False)
