"""Peer fault tolerance: circuit breakers, jittered backoff, and a
deterministic fault-injection harness.

The reference Gubernator survives peer churn as routine — k8s pods
cycle, gossip detects failures, and the data plane keeps serving.  The
three pieces here give this build the same property:

  * `CircuitBreaker` — per-peer closed -> open -> half-open state
    machine wrapped around every PeerClient RPC.  A threshold of
    consecutive transport failures opens the circuit; while open every
    call fails fast (no connect timeout burned per request); after the
    open interval ONE probe is let through (half-open), and its outcome
    closes or re-opens the circuit.

  * `Backoff` — exponential backoff with full jitter (delay drawn
    uniformly from [0, min(max, base * mult^attempt)]), used by the
    forward re-pick loop and the global/multi-region send loops instead
    of bare immediate retries.

  * `FaultPlan` — a seedable, ordered list of `FaultRule`s that can
    drop, delay, or error the Nth (or every, or a seeded fraction of)
    RPC per peer.  PeerClient and the gossip probe path consult the
    installed plan at their transport call sites, so chaos scenarios
    are injected through a supported hook — no monkeypatching — and are
    reproducible in CI: the same seed yields the same decision
    sequence.

Install a plan process-wide with `install(plan)` / `uninstall()` (the
in-process cluster harness path) or per-client via the `faults=`
constructor argument on PeerClient / Gossip.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

# ----------------------------------------------------------------------
# Circuit breaker
# ----------------------------------------------------------------------
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"

# Numeric encoding for the state gauge (metrics.py): closed < half-open
# < open so alert thresholds can use a simple `> 0` / `== 2` compare.
STATE_CODES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    """closed -> open -> half-open failure-count breaker.

    * CLOSED: calls flow; `failure_threshold` consecutive failures
      (successes reset the count) transition to OPEN.
    * OPEN: `allow()` is False until `open_interval_s` elapses, then
      the breaker moves to HALF_OPEN and reserves ONE probe slot.
    * HALF_OPEN: exactly one in-flight probe; its success closes the
      circuit (counters reset), its failure re-opens it for another
      interval.  Concurrent callers see False while the probe is out.

    Callers MUST pair every True `allow()` with exactly one
    `record_success()` or `record_failure()` — that releases the
    half-open probe slot.  `clock` is injectable for deterministic
    tests (defaults to time.monotonic).
    """

    def __init__(
        self,
        failure_threshold: int = 5,
        open_interval_s: float = 2.0,
        clock: Callable[[], float] = time.monotonic,
        on_transition: Optional[Callable[[str], None]] = None,
    ):
        self.failure_threshold = max(1, int(failure_threshold))
        self.open_interval_s = float(open_interval_s)
        self._clock = clock
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False

    # -- observers ------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._peek_state()

    @property
    def state_code(self) -> int:
        return STATE_CODES[self.state]

    @property
    def is_open(self) -> bool:
        """Non-mutating: True while calls would fast-fail (the probe
        window counts as open for routing decisions — a half-open peer
        is not yet trusted with traffic)."""
        return self.state != CLOSED

    def _peek_state(self) -> str:
        # Lock held.  An expired OPEN reads as HALF_OPEN so observers
        # (health, metrics) never report a stale open past the interval.
        if self._state == OPEN and (
            self._clock() - self._opened_at >= self.open_interval_s
        ):
            return HALF_OPEN
        return self._state

    # -- the call-site protocol ----------------------------------------
    def allow(self) -> bool:
        """Gate one call.  Mutating: an expired OPEN transitions to
        HALF_OPEN here and this caller becomes the probe."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at < self.open_interval_s:
                    return False
                self._transition(HALF_OPEN)
                self._probe_inflight = True
                return True
            # HALF_OPEN: one probe at a time.
            if self._probe_inflight:
                return False
            self._probe_inflight = True
            return True

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_inflight = False
            if self._state != CLOSED:
                self._transition(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._probe_inflight = False
            if self._state == HALF_OPEN:
                self._open()
                return
            if self._state == OPEN:
                # Failures while open (late completions of calls that
                # started before the trip) keep the window fresh.
                self._opened_at = self._clock()
                return
            self._failures += 1
            if self._failures >= self.failure_threshold:
                self._open()

    def _open(self) -> None:
        self._opened_at = self._clock()
        self._failures = 0
        self._transition(OPEN)

    def _transition(self, state: str) -> None:
        self._state = state
        if self._on_transition is not None:
            try:
                self._on_transition(state)
            except Exception:  # noqa: BLE001 — metrics must not break the breaker
                pass


# ----------------------------------------------------------------------
# Backoff
# ----------------------------------------------------------------------
class Backoff:
    """Exponential backoff with full jitter (delay ~ U[0, cap(attempt)]
    where cap = min(max_s, base_s * multiplier**attempt)).

    Full jitter beats equal-jitter for the re-pick loop's purpose:
    concurrent requests that all saw the same peer die must not retry
    in lockstep.  `rng` is injectable for reproducible chaos runs.
    """

    def __init__(
        self,
        base_s: float = 0.02,
        max_s: float = 1.0,
        multiplier: float = 2.0,
        rng: Optional[random.Random] = None,
    ):
        self.base_s = float(base_s)
        self.max_s = float(max_s)
        self.multiplier = float(multiplier)
        self._rng = rng or random.Random()

    def cap(self, attempt: int) -> float:
        return min(self.max_s, self.base_s * (self.multiplier ** max(attempt, 0)))

    def delay(self, attempt: int) -> float:
        return self._rng.uniform(0.0, self.cap(attempt))

    def sleep(self, attempt: int) -> float:
        d = self.delay(attempt)
        if d > 0:
            time.sleep(d)
        return d


# ----------------------------------------------------------------------
# Fault injection
# ----------------------------------------------------------------------
DROP = "drop"
DELAY = "delay"
ERROR = "error"
# DUPLICATE: let the call proceed, then deliver it AGAIN — the network
# or a misbehaving proxy re-delivering an RPC that already applied.
# The peer hop's hit-carrying RPCs are increments, NOT idempotent, so a
# duplicated delivery is a true double-commit on the wire: the seeded
# fault the conservation audit (audit.py forward_conservation) must
# catch.  PeerClient applies it by invoking the transport twice inside
# one guarded call (breaker sees one call; the duplicate's own failure
# is swallowed — a dropped duplicate is just a clean network again).
DUPLICATE = "duplicate"
# WAN: the wide-area link shape — every matching call pays a seeded
# normal-ish latency (mean latency_s, stddev jitter_s, clamped at 0)
# and a seeded fraction `loss` of calls is lost outright.  A lost call
# presents timeout-shaped (DROP: the request — or its RESPONSE — died
# in transit, so the RPC may have applied remotely and the caller must
# not blind-retry).  The surviving calls resolve to ordinary DELAY
# actions, so every existing interception point applies a WAN rule
# with no new handling (gossip's delay-eats-ack-budget rule included).
# All draws come from the plan's per-(peer, op) seeded streams: the
# same seed yields the same loss pattern AND the same latency series,
# which is what lets the 2x2 region soak replay a WAN weather system
# deterministically.
WAN = "wan"

# Known interception points (the `op` a rule matches against):
#   GetPeerRateLimits / UpdatePeerGlobals  — PeerClient data-plane RPCs
#   gossip.probe                            — SWIM UDP ping sends
OP_GOSSIP_PROBE = "gossip.probe"


@dataclass
class FaultRule:
    """One match-and-act rule.

    peer/op match by exact string or "*".  The rule fires on matching
    calls number `after+1 .. after+count` (per (peer, op) pair, 1-based;
    count=None means forever), and only when the plan's seeded RNG draw
    is < `rate`.  `kind`:

      * ERROR — raise a connection-shaped failure (not_ready=True by
        default: the caller's re-pick/breaker path engages, like a real
        UNAVAILABLE).
      * DROP  — raise a timeout-shaped failure (not_ready=False: the
        RPC may have executed server-side, so callers must NOT retry —
        the DEADLINE_EXCEEDED caveat, peer_client.py:44-49).  No real
        sleep: deterministic-fast for CI.
      * DELAY — sleep `delay_s`, then let the call proceed.  On gossip
        probes the delay eats the ack budget instead: delay_s >= the
        probe timeout counts the probe as lost (without a real sleep),
        so injected latency can drive suspicion (gossip._ping).
    """

    peer: str = "*"
    op: str = "*"
    kind: str = ERROR
    after: int = 0
    count: Optional[int] = None
    rate: float = 1.0
    delay_s: float = 0.0
    not_ready: bool = True
    message: str = ""
    # WAN-shape parameters (kind=WAN only): per-call latency drawn
    # from N(latency_s, jitter_s) clamped at 0, and `loss` = seeded
    # probability the call is lost (timeout-shaped DROP).
    latency_s: float = 0.0
    jitter_s: float = 0.0
    loss: float = 0.0
    # Times this rule decided a call's fate (FaultPlan.intercept bumps
    # it under the plan lock).  Lives on the rule itself so the count
    # can never be confused with another rule's after heal() frees one.
    fired_count: int = 0

    def __post_init__(self) -> None:
        # DROP is timeout-shaped by definition: the RPC may have
        # executed server-side, so it must never present as a safely
        # retryable connection failure (the DEADLINE_EXCEEDED caveat,
        # peer_client.py:44-49).
        if self.kind == DROP:
            self.not_ready = False

    def matches(self, peer: str, op: str) -> bool:
        return self.peer in ("*", peer) and self.op in ("*", op)


@dataclass
class FaultAction:
    kind: str
    delay_s: float = 0.0
    not_ready: bool = True
    message: str = ""


class FaultPlan:
    """A seedable, ordered fault plan.

    Rules are evaluated MOST-SPECIFIC-FIRST: an exact `peer` beats
    peer="*", then an exact `op` beats op="*"; equally specific rules
    keep insertion order.  Within that order the first rule whose
    (peer, op) matches, whose per-(rule, peer, op) call window is
    active, and whose seeded RNG draw passes `rate` decides the call's
    fate — so a per-victim storm or `partition(victim)` laid over a
    steady peer="*" WAN shape takes effect instead of being shadowed
    by the earlier wildcard (the 2x2 region soak's layering), and
    healing the specific rule falls back to the steady shape.
    Per-(peer, op) call counters advance on EVERY intercepted call, so
    "the Nth RPC to peer X" is well-defined regardless of how many
    rules exist.  All state is behind one lock: a plan is shared by
    every PeerClient in the process when installed globally.
    """

    def __init__(self, seed: Optional[int] = None):
        self.seed = seed
        self._lock = threading.Lock()
        self._rules: List[FaultRule] = []
        self._calls: Dict[Tuple[str, str], int] = {}
        # One RNG stream per (peer, op), derived from the plan seed:
        # the Nth call to a given (peer, op) sees the Nth draw of its
        # own stream no matter how concurrent calls to OTHER peers/ops
        # interleave — without this, rate-gated rules in a multi-daemon
        # cluster would consume one shared sequence in thread-schedule
        # order and "same seed, same decisions" would not hold.
        self._rngs: Dict[Tuple[str, str], random.Random] = {}

    # -- authoring ------------------------------------------------------
    def add(self, rule: FaultRule) -> FaultRule:
        with self._lock:
            self._rules.append(rule)
        return rule

    def partition(self, peer: str, op: str = "*") -> FaultRule:
        """Every matching RPC fails connection-shaped (UNAVAILABLE-like)
        until healed — the client-side view of a network partition."""
        return self.add(FaultRule(peer=peer, op=op, kind=ERROR, not_ready=True))

    def drop_nth(self, peer: str, n: int, op: str = "*") -> FaultRule:
        """Time out exactly the Nth matching RPC (1-based)."""
        return self.add(FaultRule(peer=peer, op=op, kind=DROP, after=n - 1, count=1))

    def drop(self, peer: str = "*", op: str = "*", rate: float = 1.0) -> FaultRule:
        """Time out matching RPCs (timeout-shaped: the call may have
        executed server-side, so callers must not blind-retry) at the
        seeded `rate` until healed — lossy-network chaos, e.g. DROP on
        the resharding transfer frames."""
        return self.add(FaultRule(peer=peer, op=op, kind=DROP, rate=rate))

    def error_nth(self, peer: str, n: int, op: str = "*", count: int = 1) -> FaultRule:
        """Fail connection-shaped starting at the Nth matching RPC."""
        return self.add(
            FaultRule(peer=peer, op=op, kind=ERROR, after=n - 1, count=count)
        )

    def delay(self, peer: str, delay_s: float, op: str = "*",
              rate: float = 1.0) -> FaultRule:
        return self.add(
            FaultRule(peer=peer, op=op, kind=DELAY, delay_s=delay_s, rate=rate)
        )

    def duplicate(self, peer: str = "*", op: str = "*", rate: float = 1.0,
                  after: int = 0, count: Optional[int] = None) -> FaultRule:
        """Deliver matching RPCs TWICE (byzantine-network chaos): the
        seeded double-commit that must trip the conservation audit's
        forward_conservation invariant on the sender."""
        return self.add(
            FaultRule(peer=peer, op=op, kind=DUPLICATE, rate=rate,
                      after=after, count=count)
        )

    def wan(self, peer: str = "*", op: str = "*", latency_s: float = 0.05,
            jitter_s: float = 0.01, loss: float = 0.0,
            rate: float = 1.0) -> FaultRule:
        """Shape matching RPCs like a wide-area link until healed:
        every call pays a seeded normal-ish delay (mean `latency_s`,
        stddev `jitter_s`, clamped at 0) and a seeded `loss` fraction
        is lost outright (timeout-shaped — the call may have applied
        remotely, so callers must not blind-retry; the federation
        sender drops those hits COUNTED).  The 2x2 region soak installs
        one of these per inter-region (peer, op) pair and heals it to
        model a WAN partition ending."""
        if not 0.0 <= loss <= 1.0:
            raise ValueError(f"loss must be within [0, 1], got {loss}")
        if latency_s < 0.0 or jitter_s < 0.0:
            raise ValueError("latency_s/jitter_s must be >= 0")
        return self.add(FaultRule(
            peer=peer, op=op, kind=WAN, rate=rate,
            latency_s=latency_s, jitter_s=jitter_s, loss=loss,
        ))

    def heal(self, peer: str = "*", op: str = "*") -> int:
        """Remove matching rules (the partition ends, the peer returns).
        Returns how many rules were removed.  Call counters are kept:
        healing must not rewind "Nth RPC" bookkeeping for other rules."""
        with self._lock:
            before = len(self._rules)
            self._rules = [
                r for r in self._rules
                if not (peer in ("*", r.peer) and op in ("*", r.op))
            ]
            return before - len(self._rules)

    # -- interception ---------------------------------------------------
    def intercept(self, peer: str, op: str,
                  exclude: tuple = ()) -> Optional[FaultAction]:
        """Decide one call's fate.  Returns None (proceed) or a
        FaultAction.  The caller applies the action — sleeps for DELAY,
        raises for ERROR/DROP — so the plan itself never blocks while
        holding its lock.  `exclude` skips rules of the named kinds
        BEFORE they match (no fired_count / rate-draw consumption): a
        caller that cannot honor a kind (gossip probes and DUPLICATE)
        must not silently burn the rule's accounting."""
        with self._lock:
            key = (peer, op)
            n = self._calls.get(key, 0) + 1
            self._calls[key] = n
            rng = self._rngs.get(key)
            if rng is None:
                # str seeds hash stably (sha512, not PYTHONHASHSEED),
                # so the stream replays across processes too.
                rng = self._rngs[key] = random.Random(
                    f"{self.seed}:{peer}:{op}" if self.seed is not None else None
                )
            # Most-specific-first (stable, so equal specificity keeps
            # insertion order): exact peer beats "*", then exact op —
            # a per-victim storm/partition layered over a steady
            # peer="*" WAN rule must win, not be shadowed by it.
            ordered = sorted(
                self._rules,
                key=lambda r: (r.peer == "*", r.op == "*"),
            )
            for rule in ordered:
                if rule.kind in exclude:
                    continue
                if not rule.matches(peer, op):
                    continue
                if n <= rule.after:
                    continue
                if rule.count is not None and n > rule.after + rule.count:
                    continue
                if rule.rate < 1.0 and rng.random() >= rule.rate:
                    continue
                rule.fired_count += 1
                if rule.kind == WAN:
                    # Resolve the WAN shape to an ordinary DROP/DELAY
                    # action HERE, from the same per-(peer, op) seeded
                    # stream as the rate draw — interception points
                    # need no WAN-specific handling and the loss
                    # pattern + latency series replay under a seed.
                    # Draw ORDER is part of the wire format of a seed:
                    # loss first, then latency only for survivors.
                    if rule.loss > 0.0 and rng.random() < rule.loss:
                        return FaultAction(
                            kind=DROP, not_ready=False,
                            message=rule.message or (
                                f"injected wan loss (peer {peer}, "
                                f"op {op}, call #{n})"
                            ),
                        )
                    return FaultAction(
                        kind=DELAY,
                        delay_s=max(
                            0.0, rng.gauss(rule.latency_s, rule.jitter_s)
                        ),
                        not_ready=rule.not_ready,
                        message=rule.message or (
                            f"injected wan latency (peer {peer}, "
                            f"op {op}, call #{n})"
                        ),
                    )
                msg = rule.message or (
                    f"injected {rule.kind} (peer {peer}, op {op}, call #{n})"
                )
                return FaultAction(
                    kind=rule.kind, delay_s=rule.delay_s,
                    not_ready=rule.not_ready, message=msg,
                )
            return None

    # -- observers (chaos-test assertions) ------------------------------
    def calls(self, peer: str, op: str) -> int:
        with self._lock:
            return self._calls.get((peer, op), 0)

    def fired(self, rule: FaultRule) -> int:
        with self._lock:
            return rule.fired_count


# ----------------------------------------------------------------------
# Process-wide installation (the no-monkeypatch hook)
# ----------------------------------------------------------------------
_active_lock = threading.Lock()
_active_plan: Optional[FaultPlan] = None


def install(plan: FaultPlan) -> FaultPlan:
    """Install `plan` process-wide: every PeerClient/Gossip instance
    without an explicit `faults=` consults it on each RPC/probe."""
    global _active_plan
    with _active_lock:
        _active_plan = plan
    return plan


def uninstall() -> None:
    global _active_plan
    with _active_lock:
        _active_plan = None


def active() -> Optional[FaultPlan]:
    with _active_lock:
        return _active_plan


class injected:
    """Context manager: `with faults.injected(plan): ...` installs the
    plan for the block and uninstalls on exit (even on error) — the
    chaos-test idiom."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan

    def __enter__(self) -> FaultPlan:
        return install(self.plan)

    def __exit__(self, *exc) -> None:
        uninstall()
