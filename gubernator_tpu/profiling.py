"""Cost observatory: always-on host profiling + per-tenant attribution.

PR 6 says how slow the service is (latency attribution), PR 9 says
whether accounting holds (conservation audit) and what the DEVICE is
doing (XLA telemetry).  This module answers the two remaining operator
questions:

* **Where does the host CPU actually go?** — `Sampler`, a
  dependency-free continuous sampling profiler: one daemon thread wakes
  ~`GUBER_PROFILE_HZ` times per second (seeded jitter so the tick can
  never phase-lock with a periodic workload), snapshots every thread's
  stack via `sys._current_frames()`, and folds each stack into
  flamegraph "collapsed" form.  Each sample is TAGGED with the phase of
  the request waterfall the thread was executing (the PR 6 taxonomy —
  `ingress.parse`, `dispatch.launch`, `peer.rpc`, ... — declared by
  lightweight `scope()` hooks at the existing attribution sites) and
  with the PR 9 program label when one is in scope, so "Python decode"
  vs "device scatter" vs "GIL-idle in epoll" is answerable per phase.
  Samples land in a ring of one-second windows; `GET /debug/pprof
  ?seconds=N` merges the last N windows into collapsed text (default)
  or a JSON top-N view.  `GUBER_PROFILE=0` is the compiled-out mode:
  the sampler tick is one branch, every scope hook is one comparison
  returning a shared no-op, and the bench gate pins the enabled-vs-out
  throughput ratio at >= 0.95 (the PR 4/PR 9 discipline).

* **Who is spending the capacity?** — `TenantLedger`, cardinality-
  bounded per-tenant cost attribution keyed by rate-limit NAME (the
  tenant unit).  A count-min sketch over vectorized FNV-1 name hashes
  (the `hash_ring.get_batch_codes` machinery, PR 6) ranks tenants; the
  top `GUBER_TENANT_TOPK` keep EXACT accumulator rows (hits, lanes,
  over-limit, shed lanes, ingress bytes) and everyone else rolls into
  ONE `other` bucket — so 10k distinct names cost K+1 metric series,
  and `rows + other == totals` holds exactly (the audit-style
  conservation the tests pin).  Lane-time and queue-residency are
  PROPORTIONAL shares: the dispatch pipeline and the batchers feed
  process-wide (lanes, seconds) accumulators, and a tenant's share is
  `its lanes x the per-lane cost` — zero per-lane bookkeeping on the
  hot path.  Served at `GET /debug/tenants`, summarized in
  `/debug/status`, exported as bounded `gubernator_tenant_*` families,
  and aggregated fleet-wide by `scripts/cluster_status.py --tenants`.

The SAMPLER and the share accumulators are MODULE-GLOBAL (the
tracing/saturation convention: one daemon per process in production;
in-process multi-daemon tests share one plane).  Each `TenantLedger`
is PER-SERVICE — "which tenant is hot on THIS daemon" is the question
the hot-key defense needs answered — and every fold site sits beside
the matching conservation-ledger note (audit.py), so the sum of a
process's ledgers reconciles exactly against the audit's
`ingress_hits + peer_ingress_hits` at quiesce (the soak asserts it).
"""

from __future__ import annotations

import itertools
import os
import random
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

# ---------------------------------------------------------------------
# Knobs (module-level env reads cover library embeddings; daemons
# re-apply their parsed config via set_enabled/set_hz — config-file ->
# env -> default precedence, like telemetry.set_storm).
# ---------------------------------------------------------------------

DEFAULT_HZ = 67.0  # deliberately not a divisor of common periodic work
RING_SECONDS = 120  # of one-second sample windows kept
MAX_STACK_DEPTH = 48
NUMERIC_LANE_BYTES = 32  # algo/beh i32 + hits/limit/duration i64


def _env_flag(name: str, default: bool) -> bool:
    v = os.environ.get(name, "")
    if not v:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name, "")
    try:
        return float(v) if v else default
    except ValueError:
        return default


_ENABLED: bool = _env_flag("GUBER_PROFILE", True)
_HZ: float = min(max(_env_float("GUBER_PROFILE_HZ", DEFAULT_HZ), 1.0), 1000.0)

# ---------------------------------------------------------------------
# Per-thread tags (read cross-thread by the sampler; plain dict writes
# are GIL-atomic, the tracing._Ring trick)
# ---------------------------------------------------------------------

# thread ident -> active phase tag (scope() hooks at the PR 6 sites)
_scopes: Dict[int, str] = {}
# thread ident -> active program label (mirrored by telemetry.program)
_programs: Dict[int, str] = {}
# thread ident -> static role tag (long-lived daemon threads register
# once at start: epoll loop, batch-window flusher, handle drainer, ...)
_static: Dict[int, str] = {}


class _NoopScope:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopScope()


class _Scope:
    __slots__ = ("tag", "_ident", "_prev")

    def __init__(self, tag: str):
        self.tag = tag

    def __enter__(self):
        ident = threading.get_ident()
        self._ident = ident
        self._prev = _scopes.get(ident)
        _scopes[ident] = self.tag
        # NO piggyback here (Sampler.maybe_tick): dispatch-stage scopes
        # enter INSIDE the pipeline's locked launch/commit critical
        # sections, and stretching those by even a tick's fold widens
        # the donated-device-array window enough to flake tier-1.  The
        # piggyback sites are the lock-free service-level folds.
        return self

    def __exit__(self, *exc):
        if self._prev is None:
            # pop, don't park a None: thread idents recycle, and a dict
            # of dead idents would otherwise grow with pool churn.
            _scopes.pop(self._ident, None)
        else:
            _scopes[self._ident] = self._prev
        return False


def scope(tag: str):
    """Phase scope for the current thread: while active, profiler
    samples of this thread attribute to `tag` (the PR 6 phase
    taxonomy).  Disabled path is one branch returning a shared no-op —
    the tracing/telemetry compiled-out discipline."""
    if not _ENABLED:
        return _NOOP
    return _Scope(tag)


def tag_thread(tag: str) -> None:
    """Register a STATIC role tag for the calling thread (long-lived
    daemon threads: the epoll loop, the batch-window flusher, the
    auditor).  Unlike scope(), the tag covers idle time too — which is
    the point: "GIL-idle in epoll" is an answer, not noise."""
    _static[threading.get_ident()] = tag


def set_program(label: Optional[str]) -> None:
    """Mirror of the telemetry program label for the calling thread
    (telemetry._Program calls this on enter/exit when the profiler is
    on), so samples carry program identity beside the phase."""
    ident = threading.get_ident()
    if label is None:
        _programs.pop(ident, None)
    else:
        _programs[ident] = label


# ---------------------------------------------------------------------
# The sampler
# ---------------------------------------------------------------------


def _strip_worker_suffix(name: str) -> str:
    """ThreadPoolExecutor names workers 'prefix_N' / 'prefix-N';
    collapse the pool index so one pool folds to one tag."""
    base = name.rstrip("0123456789")
    return base.rstrip("-_") or name


class _Window:
    """One second of samples: collapsed-stack counts plus the phase /
    program marginals (so the JSON view never re-parses stacks)."""

    __slots__ = ("sec", "samples", "stacks", "phases", "programs")

    def __init__(self, sec: int):
        self.sec = sec
        self.samples = 0
        self.stacks: Dict[Tuple[str, tuple], int] = {}
        self.phases: Dict[str, int] = {}
        self.programs: Dict[str, int] = {}


class Sampler(threading.Thread):
    """The continuous profiler thread.  Runs forever once started (a
    daemon thread); `GUBER_PROFILE=0` leaves it ticking but each tick
    is ONE branch — so enable/disable is a live toggle, not a thread
    lifecycle."""

    def __init__(self):
        super().__init__(name="cost-profiler", daemon=True)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._ring: "deque[_Window]" = deque(maxlen=RING_SECONDS)
        self._code_labels: Dict[object, str] = {}
        # Idle-stack fold cache: ident -> (frame id, f_lasti, code id,
        # folded).  Most daemon threads are PARKED in a wait between
        # ticks — same frame object, same instruction — so their fold
        # is byte-identical to last tick's; revalidating three ints
        # replaces a 48-frame walk and keeps the per-tick GIL hold
        # near-constant as thread pools grow.  A recycled frame id is
        # paired with f_lasti + code id, and a one-tick stale fold in a
        # statistical profile is noise, not corruption.
        self._fold_cache: Dict[int, tuple] = {}
        self._names: Dict[int, str] = {}
        self._names_at = 0.0
        self.total_samples = 0
        self.total_ticks = 0
        # Seeded jitter: the tick must not phase-lock with periodic
        # work (a 15ms flush timer sampled at exactly 67Hz aliases);
        # seeded so two runs fold comparable profiles.
        self._rng = random.Random(0x9E3779B9)
        # Piggyback pacing (maybe_tick): monotonic deadline for the
        # next sample + a try-acquire gate so exactly one thread folds.
        # Own RNG: the run loop's _rng draws concurrently.
        self._next_due = 0.0
        self._tick_gate = threading.Lock()
        self._due_rng = random.Random(0x85EBCA6B)

    # -- write side ----------------------------------------------------
    def run(self) -> None:  # pragma: no cover - timing loop; body is tested
        while not self._stop.is_set():
            period = 1.0 / max(_HZ, 1.0)
            self._stop.wait(period * (0.7 + 0.6 * self._rng.random()))
            if not _ENABLED:
                continue  # the compiled-out tick: one branch
            try:
                # Pacing fallback, not the primary ticker: under load
                # the scope hooks piggyback the due sample on a thread
                # that already holds the GIL (maybe_tick), and this
                # wake finds the deadline already pushed — it only
                # samples when the process is too idle to piggyback,
                # exactly when a dedicated thread's wake is free.
                self.maybe_tick()
            except Exception:  # noqa: BLE001 — the profiler must never kill itself
                continue

    def maybe_tick(self) -> None:
        """Run the due sample on the CALLING thread, if one is due.
        Called from the LOCK-FREE hot-path folds (the per-batch ledger
        admission fold, the batcher flush's queue-wait note — sites
        that hold no store/pipeline lock) and the run-loop fallback.
        A dedicated sampler thread waking
        at 67 Hz on a saturated box costs ~3x the fold itself in GIL
        handoffs and coalescing disruption (measured on the 2-core
        bench); a thread that is ALREADY running folds for free and
        lands the pause at a phase boundary, where no batch window is
        mid-flush.  Cost when not due: one clock read + one compare.
        The sample skips the calling thread's own stack (sample_once's
        self-exclusion), so trigger timing cannot bias the triggering
        thread's attribution."""
        if not _ENABLED:
            return
        now = time.monotonic()
        if now < self._next_due:
            return
        if not self._tick_gate.acquire(blocking=False):
            return  # another thread is folding this tick
        try:
            if time.monotonic() < self._next_due:
                return
            # Seeded jitter (the run-loop rule): the piggyback cadence
            # must not phase-lock with periodic work either.
            self._next_due = now + (
                (0.7 + 0.6 * self._due_rng.random()) / max(_HZ, 1.0)
            )
            self.sample_once()
        finally:
            self._tick_gate.release()

    def stop(self) -> None:
        self._stop.set()

    def sample_once(self) -> None:
        """One profiling tick: snapshot every thread's stack and fold.
        Public so tests (and the bench) can drive deterministic ticks
        without sleeping."""
        now = time.time()
        if now - self._names_at > 1.0:
            # Thread names refresh at 1Hz, not per tick: enumerate()
            # walks a lock; names only feed the fallback tag.
            self._names = {
                t.ident: t.name for t in threading.enumerate()
                if t.ident is not None
            }
            self._names_at = now
        frames = sys._current_frames()
        own = threading.get_ident()
        sec = int(now)
        with self._lock:
            self.total_ticks += 1
            win = self._ring[-1] if self._ring else None
            if win is None or win.sec != sec:
                win = _Window(sec)
                self._ring.append(win)
            for ident, frame in frames.items():
                if ident == own:
                    continue
                tag = _scopes.get(ident) or _static.get(ident)
                if tag is None:
                    name = self._names.get(ident)
                    tag = (
                        f"thread:{_strip_worker_suffix(name)}"
                        if name else "unknown"
                    )
                cached = self._fold_cache.get(ident)
                sig = (id(frame), frame.f_lasti, id(frame.f_code))
                if cached is not None and cached[0] == sig:
                    stack = cached[1]
                else:
                    stack = self._fold(frame)
                    self._fold_cache[ident] = (sig, stack)
                key = (tag, stack)
                win.stacks[key] = win.stacks.get(key, 0) + 1
                win.phases[tag] = win.phases.get(tag, 0) + 1
                prog = _programs.get(ident)
                if prog is not None:
                    win.programs[prog] = win.programs.get(prog, 0) + 1
                win.samples += 1
                self.total_samples += 1
            if len(self._fold_cache) > 4 * max(len(frames), 1):
                # Pool churn parks dead idents in the cache; prune to
                # the live set once it dominates.
                self._fold_cache = {
                    k: v for k, v in self._fold_cache.items() if k in frames
                }

    def _fold(self, frame) -> tuple:
        """Collapse one stack to a root→leaf TUPLE of frame labels.
        Frame labels cache per code object, so in steady state the walk
        allocates one tuple of already-interned strings — hashing it
        mixes cached per-string hashes (pointer-cheap), where the old
        joined-string key built and hashed ~1KB of fresh text per busy
        thread per tick.  Readers join with ';' at render time
        (flamegraph collapsed order)."""
        labels: List[str] = []
        depth = 0
        while frame is not None and depth < MAX_STACK_DEPTH:
            code = frame.f_code
            label = self._code_labels.get(code)
            if label is None:
                label = self._code_labels[code] = (
                    f"{os.path.basename(code.co_filename)}:{code.co_name}"
                )
            labels.append(label)
            frame = frame.f_back
            depth += 1
        labels.reverse()
        return tuple(labels)

    # -- read side -----------------------------------------------------
    def merged(self, seconds: int) -> _Window:
        """Merge the windows covering the last `seconds` (clamped to
        the ring) into one aggregate window."""
        seconds = min(max(int(seconds), 1), RING_SECONDS)
        cutoff = int(time.time()) - seconds
        out = _Window(cutoff)
        with self._lock:
            for win in self._ring:
                if win.sec < cutoff:
                    continue
                out.samples += win.samples
                for k, v in win.stacks.items():
                    out.stacks[k] = out.stacks.get(k, 0) + v
                for k, v in win.phases.items():
                    out.phases[k] = out.phases.get(k, 0) + v
                for k, v in win.programs.items():
                    out.programs[k] = out.programs.get(k, 0) + v
        return out


_sampler: Optional[Sampler] = None
_sampler_lock = threading.Lock()


def _get_sampler(start: bool = False) -> Optional[Sampler]:
    global _sampler
    with _sampler_lock:
        if _sampler is None and start:
            _sampler = Sampler()
            _sampler.start()
        return _sampler


def ensure_started() -> None:
    """Start the module-global sampler thread if it is not running.
    Called by daemon/service startup when the plane is enabled — module
    import never starts threads (library safety)."""
    _get_sampler(start=True)


def set_enabled(flag: bool) -> None:
    """Process-wide switch (the daemon applies its parsed GUBER_PROFILE
    at startup, both directions — the tracing.set_sample_rate rule)."""
    global _ENABLED
    _ENABLED = bool(flag)
    if _ENABLED:
        ensure_started()


def set_hz(hz: float) -> None:
    global _HZ
    _HZ = min(max(float(hz), 1.0), 1000.0)


def enabled() -> bool:
    return _ENABLED


def hz() -> float:
    return _HZ


def sample_count() -> int:
    s = _get_sampler()
    return s.total_samples if s is not None else 0


def profile_snapshot(seconds: int = 10, top: int = 30) -> dict:
    """The JSON view of GET /debug/pprof: phase/program marginals, the
    top-N collapsed stacks, and the named-attribution fraction (the
    integration gate asserts >= 0.8 of samples attribute to a phase
    that is not 'unknown' on a loaded daemon)."""
    s = _get_sampler()
    if s is None:
        return {
            "enabled": _ENABLED, "hz": _HZ, "seconds": seconds,
            "samples": 0, "phases": {}, "programs": {}, "topStacks": [],
            "namedFraction": 0.0,
        }
    win = s.merged(seconds)
    ranked = sorted(win.stacks.items(), key=lambda kv: kv[1], reverse=True)
    named = sum(v for k, v in win.phases.items() if k != "unknown")
    return {
        "enabled": _ENABLED,
        "hz": _HZ,
        "seconds": seconds,
        "samples": win.samples,
        "totalSamples": s.total_samples,
        "phases": dict(
            sorted(win.phases.items(), key=lambda kv: kv[1], reverse=True)
        ),
        "programs": dict(
            sorted(win.programs.items(), key=lambda kv: kv[1], reverse=True)
        ),
        "topStacks": [
            {"phase": tag, "stack": ";".join(stack), "count": count}
            for (tag, stack), count in ranked[: max(int(top), 1)]
        ],
        "namedFraction": round(named / win.samples, 4) if win.samples else 0.0,
    }


def collapsed(seconds: int = 10) -> str:
    """Flamegraph collapsed text ('phase;frame;...;frame count' per
    line): pipe straight into flamegraph.pl / speedscope."""
    s = _get_sampler()
    if s is None:
        return ""
    win = s.merged(seconds)
    lines = [
        f"{tag};{';'.join(stack)} {count}" if stack else f"{tag} {count}"
        for (tag, stack), count in sorted(
            win.stacks.items(), key=lambda kv: kv[1], reverse=True
        )
    ]
    return "\n".join(lines) + ("\n" if lines else "")


# ---------------------------------------------------------------------
# Proportional-share accumulators (process-wide, fed per BATCH)
# ---------------------------------------------------------------------


class _ShareAccumulator:
    """(lanes, seconds) totals for one cost pool; a tenant's share of
    the pool is its lanes x (seconds / lanes) — proportional
    attribution with zero per-lane work on the hot path."""

    __slots__ = ("_lock", "lanes", "seconds")

    def __init__(self):
        self._lock = threading.Lock()
        self.lanes = 0
        self.seconds = 0.0

    def add(self, lanes: int, seconds: float) -> None:
        with self._lock:
            self.lanes += int(lanes)
            self.seconds += float(seconds)

    def per_lane(self) -> float:
        with self._lock:
            return self.seconds / self.lanes if self.lanes else 0.0


lane_time = _ShareAccumulator()   # device launch wall x lanes (pipeline)
queue_time = _ShareAccumulator()  # coalescing-window wait x lanes (batchers)


def note_lane_time(lanes: int, seconds: float) -> None:
    """One device launch: `lanes` rode a program whose enqueue wall was
    `seconds` (models/shard.py's launch stage feeds this — the same
    per-launch timing the PR 9 telemetry drains)."""
    lane_time.add(lanes, seconds)


def note_queue_wait(lanes: int, seconds: float) -> None:
    """One batcher submission flushed after waiting `seconds` in the
    coalescing window (queue residency; both batchers feed this beside
    their existing batch.window attribution)."""
    queue_time.add(lanes, seconds * lanes)
    # Flushes are frequent and spread across the window timeline — a
    # good piggyback site (Sampler.maybe_tick's rationale).
    if _ENABLED:
        s = _sampler
        if s is not None:
            s.maybe_tick()


# ---------------------------------------------------------------------
# Per-tenant cost ledger
# ---------------------------------------------------------------------

# The count-min row-index derivation: d independent multiply-shift rows
# from ONE 64-bit FNV-1 name hash (the saturation.HotKeySketch salts).
_CMS_SALTS = np.array(
    [0x9E3779B97F4A7C15, 0xC2B2AE3D27D4EB4F, 0x165667B19E3779F9,
     0x27D4EB2F165667C5],
    dtype=np.uint64,
)

_STATS = ("hits", "lanes", "over_limit", "shed", "ingress_bytes")


class _TenantRow:
    __slots__ = ("name", "est", "hits", "lanes", "over_limit", "shed",
                 "ingress_bytes")

    def __init__(self, name: str):
        self.name = name
        self.est = 0
        self.hits = 0
        self.lanes = 0
        self.over_limit = 0
        self.shed = 0
        self.ingress_bytes = 0


class _TenantCtx:
    """Per-batch fold context: the vectorized name aggregation computed
    once at admit and reused by the outcome/shed folds (same arrays,
    zero re-hashing)."""

    __slots__ = ("inv", "uh", "first", "name_at", "m")

    def __init__(self, inv, uh, first, name_at):
        self.inv = inv
        self.uh = uh
        self.first = first
        self.name_at = name_at
        self.m = len(uh)


def _name_columns(cols):
    """(hashable_names, name_at, name_lens, uk_lens) for any ingress
    column shape — list-backed IngressColumns, the native-JSON
    LazyIngressColumns (spans into the request body), or a
    FrameIngressColumns (blob + offsets) — WITHOUT materializing
    per-lane strings on the packed shapes."""
    from . import native

    pj = getattr(cols, "_pj", None)
    if pj is not None:  # LazyIngressColumns: (off, len) spans into body
        body = np.frombuffer(pj.body, dtype=np.uint8)
        nspan = np.asarray(pj.nspan, dtype=np.int64)
        starts, lens = nspan[0::2], nspan[1::2]
        off = np.zeros(len(lens) + 1, dtype=np.int64)
        np.cumsum(lens, out=off[1:])
        total = int(off[-1])
        pos = (
            np.repeat(starts - off[:-1], lens)
            + np.arange(total, dtype=np.int64)
        )
        packed = native.PackedKeys(body[pos], off)
        ukspan = np.asarray(pj.ukspan, dtype=np.int64)
        return packed, pj.name_at, lens, ukspan[1::2]
    nb = getattr(cols, "_nb", None)
    if nb is not None:  # FrameIngressColumns: name blob + offsets
        no = np.asarray(cols._no, dtype=np.int64)
        uo = np.asarray(cols._uo, dtype=np.int64)
        packed = native.PackedKeys(np.frombuffer(nb, dtype=np.uint8), no)
        return packed, cols._name_at, np.diff(no), np.diff(uo)
    names = cols.names  # plain lists (classic JSON / proto decode)
    lens = np.fromiter((len(s) for s in names), dtype=np.int64,
                       count=len(names))
    uk_lens = np.fromiter(
        (len(s) for s in cols.unique_keys), dtype=np.int64, count=len(names)
    )
    return names, names.__getitem__, lens, uk_lens


class TenantLedger:
    """Cardinality-bounded per-tenant cost accounting (see module
    docstring).  All folds are per BATCH and vectorized over lanes;
    Python touches at most `topk` tenants per fold.  Conservation holds
    exactly for every stat: `sum(rows) + other == totals` — promotion
    moves a tenant's CURRENT batch out of `other` into its new row, and
    eviction folds the loser's whole row back into `other`."""

    def __init__(self, topk: int = 16, width: int = 8192, depth: int = 4):
        self.topk = max(int(topk), 1)
        self.width = int(width)
        self.depth = min(int(depth), len(_CMS_SALTS))
        self._lock = threading.Lock()
        self._tab = np.zeros((self.depth, self.width), dtype=np.int64)
        self._salts = _CMS_SALTS[: self.depth]
        self._rows: Dict[int, _TenantRow] = {}  # name hash -> row
        self._row_hashes = np.zeros(0, dtype=np.uint64)  # sorted, for isin
        self._other = dict.fromkeys(_STATS, 0)
        self._totals = dict.fromkeys(_STATS, 0)
        self.batches = 0

    # -- admit-side folds (beside every audit ingress note) ------------
    def fold_admit(self, cols) -> Optional[_TenantCtx]:
        """Fold one ingress batch's admission: per-tenant hits, lanes
        and ingress bytes.  Returns the fold context the outcome/shed
        folds reuse (or None on an empty batch)."""
        n = len(cols)
        if n == 0:
            return None
        # Per-ingress-batch piggyback site (Sampler.maybe_tick): the
        # ledger fold is always-on, so under any load the profiler's
        # cadence rides threads already holding the GIL.
        if _ENABLED:
            s = _sampler
            if s is not None:
                s.maybe_tick()
        from . import native

        names, name_at, name_lens, uk_lens = _name_columns(cols)
        hashes = native.fnv1_batch(names)
        uh, first, inv = np.unique(
            hashes, return_index=True, return_inverse=True
        )
        ctx = _TenantCtx(inv, uh, first, name_at)
        lanes_u = np.bincount(inv, minlength=ctx.m).astype(np.int64)
        hits_u = np.bincount(
            inv, weights=np.asarray(cols.hits, dtype=np.float64),
            minlength=ctx.m,
        ).astype(np.int64)
        lane_bytes = name_lens + uk_lens + NUMERIC_LANE_BYTES
        bytes_u = np.bincount(
            inv, weights=lane_bytes.astype(np.float64), minlength=ctx.m
        ).astype(np.int64)
        with self._lock:
            self.batches += 1
            idx = (
                (uh[None, :] * self._salts[:, None]) >> np.uint64(17)
            ) % np.uint64(self.width)
            for r in range(self.depth):
                np.add.at(self._tab[r], idx[r].astype(np.intp), hits_u)
            est = self._tab[
                np.arange(self.depth)[:, None], idx.astype(np.intp)
            ].min(axis=0)
            self._totals["hits"] += int(hits_u.sum())
            self._totals["lanes"] += int(lanes_u.sum())
            self._totals["ingress_bytes"] += int(bytes_u.sum())
            tracked = np.isin(uh, self._row_hashes)
            for j in np.nonzero(tracked)[0]:
                row = self._rows[int(uh[j])]
                row.est = int(est[j])
                row.hits += int(hits_u[j])
                row.lanes += int(lanes_u[j])
                row.ingress_bytes += int(bytes_u[j])
            un = np.nonzero(~tracked)[0]
            if un.size:
                self._other["hits"] += int(hits_u[un].sum())
                self._other["lanes"] += int(lanes_u[un].sum())
                self._other["ingress_bytes"] += int(bytes_u[un].sum())
                self._promote_locked(
                    un, est, uh, first, name_at,
                    hits_u, lanes_u, bytes_u,
                )
        return ctx

    def _promote_locked(self, un, est, uh, first, name_at,
                        hits_u, lanes_u, bytes_u) -> None:
        """Promote untracked candidates whose count-min estimate beats
        the current top-K floor.  At most `topk` candidates loop in
        Python per batch (the HotKeySketch bound): uniform traffic
        concentrates estimates near the floor, and without the cap a
        10k-unique batch would loop 10k lanes."""
        if len(self._rows) >= self.topk:
            floor = min(r.est for r in self._rows.values())
            cand = un[est[un] > floor]
        else:
            cand = un
        if cand.size > self.topk:
            cand = cand[np.argsort(est[cand])[-self.topk:]]
        changed = False
        for j in cand:
            j = int(j)
            if len(self._rows) >= self.topk:
                # Evict the weakest row; its EXACT stats conserve into
                # `other` (the rollup is a ledger, not a loss).
                evict_h = min(self._rows, key=lambda h: self._rows[h].est)
                if self._rows[evict_h].est >= int(est[j]):
                    continue
                loser = self._rows.pop(evict_h)
                for k in _STATS:
                    self._other[k] += getattr(loser, k)
            row = _TenantRow(str(name_at(int(first[j]))))
            row.est = int(est[j])
            # This batch's contribution moves other -> row (it was
            # summed into `other` above; conservation stays exact).
            row.hits = int(hits_u[j])
            row.lanes = int(lanes_u[j])
            row.ingress_bytes = int(bytes_u[j])
            self._other["hits"] -= row.hits
            self._other["lanes"] -= row.lanes
            self._other["ingress_bytes"] -= row.ingress_bytes
            self._rows[int(uh[j])] = row
            changed = True
        if changed or len(self._rows) != len(self._row_hashes):
            self._row_hashes = np.sort(
                np.fromiter(self._rows, dtype=np.uint64, count=len(self._rows))
            )

    def fold_requests(self, requests) -> Optional[list]:
        """Dataclass-router twin of fold_admit (the slow path already
        pays per-request Python).  Returns the per-request name list as
        the outcome context."""
        if not requests:
            return None
        names = [r.name for r in requests]
        cols = _RequestView(names, requests)
        self.fold_admit(cols)
        return names

    def fold_one(self, name: str, hits: int, nbytes: int) -> None:
        """Single-lane fold (the async single-key fast path, which
        bypasses both routers): scalar twin of fold_admit — identical
        accounting under the same lock, none of the vector machinery
        (unique/bincount/padding string) that exists to amortize over
        a batch this path deliberately skips."""
        from .utils import hashing

        if _ENABLED:
            s = _sampler
            if s is not None:
                s.maybe_tick()
        hits = int(hits)
        nbytes = int(nbytes)
        uh = np.uint64(hashing.fnv1_64(name.encode("utf-8")))
        idx = (uh * self._salts) >> np.uint64(17)
        with self._lock:
            self.batches += 1
            est = None
            for r in range(self.depth):
                j = int(idx[r]) % self.width
                v = int(self._tab[r, j]) + hits
                self._tab[r, j] = v
                est = v if est is None or v < est else est
            self._totals["hits"] += hits
            self._totals["lanes"] += 1
            self._totals["ingress_bytes"] += nbytes
            row = self._rows.get(int(uh))
            if row is not None:
                row.est = est
                row.hits += hits
                row.lanes += 1
                row.ingress_bytes += nbytes
                return
            self._other["hits"] += hits
            self._other["lanes"] += 1
            self._other["ingress_bytes"] += nbytes
            self._promote_locked(
                np.arange(1), np.array([est], dtype=np.int64),
                np.array([uh], dtype=np.uint64),
                np.zeros(1, dtype=np.int64), lambda _i: name,
                np.array([hits], dtype=np.int64),
                np.ones(1, dtype=np.int64),
                np.array([nbytes], dtype=np.int64),
            )

    # -- outcome-side folds --------------------------------------------
    def fold_outcome(self, ctx: Optional[_TenantCtx], result) -> None:
        """Per-tenant OVER_LIMIT attribution from a resolved columnar
        result (arrays + sparse overrides)."""
        if ctx is None:
            return
        over = (np.asarray(result.status) == 1).astype(np.float64)
        for i, ov in result.overrides.items():
            over[i] = 1.0 if (
                getattr(ov, "status", 0) == 1 and not getattr(ov, "error", "")
            ) else 0.0
        if not over.any():
            return
        over_u = np.bincount(ctx.inv, weights=over, minlength=ctx.m)
        self._route_stat_locked("over_limit", ctx, over_u.astype(np.int64))

    def fold_outcome_responses(self, names: Optional[list],
                               responses) -> None:
        """Dataclass-router outcome twin: `names` is fold_requests'
        return, `responses` the per-request RateLimitResponse list."""
        if not names:
            return
        over_names = [
            nm for nm, r in zip(names, responses)
            if r is not None and r.status == 1 and not r.error
        ]
        if not over_names:
            return
        from . import native

        hashes = native.fnv1_batch(over_names)
        uh, first, inv = np.unique(
            hashes, return_index=True, return_inverse=True
        )
        ctx = _TenantCtx(inv, uh, first, over_names.__getitem__)
        self._route_stat_locked(
            "over_limit", ctx,
            np.bincount(inv, minlength=len(uh)).astype(np.int64),
        )

    def fold_shed(self, ctx: Optional[_TenantCtx], lanes) -> None:
        """Per-tenant shed attribution: `lanes` is the index array of
        the batch's lanes the bounded ingress gate refused."""
        if ctx is None:
            return
        lanes = np.asarray(lanes, dtype=np.int64)
        if not lanes.size:
            return
        shed_u = np.bincount(ctx.inv[lanes], minlength=ctx.m).astype(np.int64)
        self._route_stat_locked("shed", ctx, shed_u)

    def _route_stat_locked(self, stat: str, ctx: _TenantCtx, vals) -> None:
        """Add per-unique `vals` to `stat`, routed tenant-row vs other
        by the CURRENT top-K (outcome folds happen after admit; a row
        churn in between shifts attribution, never totals)."""
        total = int(vals.sum())
        if total == 0:
            return
        with self._lock:
            self._totals[stat] += total
            tracked = np.isin(ctx.uh, self._row_hashes)
            for j in np.nonzero(tracked & (vals > 0))[0]:
                row = self._rows.get(int(ctx.uh[j]))
                if row is not None:
                    setattr(row, stat, getattr(row, stat) + int(vals[j]))
            un = tracked == False  # noqa: E712 — elementwise
            self._other[stat] += int(vals[un].sum())

    # -- read side -----------------------------------------------------
    def snapshot(self, top: Optional[int] = None) -> dict:
        """The GET /debug/tenants document.  Lane-time / queue-
        residency are proportional shares computed here (per-lane
        factors from the process-wide accumulators) — the hot path
        never touches them per tenant."""
        lane_s = lane_time.per_lane()
        queue_s = queue_time.per_lane()

        def _render(src, name=None, est=None):
            row = {
                "hits": src["hits"] if isinstance(src, dict) else src.hits,
                "lanes": src["lanes"] if isinstance(src, dict) else src.lanes,
                "overLimit": (
                    src["over_limit"] if isinstance(src, dict)
                    else src.over_limit
                ),
                "shed": src["shed"] if isinstance(src, dict) else src.shed,
                "ingressBytes": (
                    src["ingress_bytes"] if isinstance(src, dict)
                    else src.ingress_bytes
                ),
            }
            row["overLimitRate"] = (
                round(row["overLimit"] / row["lanes"], 4)
                if row["lanes"] else 0.0
            )
            row["laneTimeS"] = round(row["lanes"] * lane_s, 6)
            row["queueS"] = round(row["lanes"] * queue_s, 6)
            if name is not None:
                row["tenant"] = name
            if est is not None:
                row["estimate"] = est
            return row

        with self._lock:
            rows = sorted(
                self._rows.values(), key=lambda r: r.est, reverse=True
            )
            if top is not None:
                rows = rows[: int(top)]
            doc = {
                "topk": [_render(r, name=r.name, est=r.est) for r in rows],
                "other": _render(dict(self._other)),
                "totals": _render(dict(self._totals)),
                "trackedTenants": len(self._rows),
                "topkLimit": self.topk,
                "batches": self.batches,
                "laneTimeSPerLane": round(lane_s, 9),
                "queueSPerLane": round(queue_s, 9),
            }
        return doc

    def totals(self) -> dict:
        with self._lock:
            return dict(self._totals)


class _RequestView:
    """Minimal column view over a dataclass request list so
    fold_requests reuses the one vectorized fold."""

    __slots__ = ("names", "unique_keys", "hits")

    def __init__(self, names, requests):
        self.names = names
        self.unique_keys = [r.unique_key for r in requests]
        self.hits = np.fromiter(
            (int(r.hits) for r in requests), dtype=np.int64,
            count=len(requests),
        )

    def __len__(self) -> int:
        return len(self.names)


# ---------------------------------------------------------------------
def reset() -> None:
    """Test hook: clear the module-global accumulators and the sampler
    ring (mirrors saturation.reset; per-service TenantLedgers are
    per-instance and need no global reset)."""
    global lane_time, queue_time
    lane_time = _ShareAccumulator()
    queue_time = _ShareAccumulator()
    _scopes.clear()
    _programs.clear()
    _static.clear()
    s = _get_sampler()
    if s is not None:
        with s._lock:
            s._ring.clear()
            s.total_samples = 0
            s.total_ticks = 0
