"""Prometheus metrics with reference name parity.

Metric names match the reference exactly so dashboards/alerts port
unchanged: gubernator_cache_size + gubernator_cache_access_count
(cache.go:88-92,205-218), gubernator_grpc_request_counts +
gubernator_grpc_request_duration (grpc_stats.go:45-59),
gubernator_async_durations + gubernator_broadcast_durations
(global.go:40-56).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    Summary,
    generate_latest,
)

from . import audit as audit_mod
from . import profiling, saturation, telemetry, tracing

try:  # OpenMetrics exposition carries trace exemplars; text 0.0.4 cannot
    from prometheus_client.openmetrics.exposition import (
        CONTENT_TYPE_LATEST as OPENMETRICS_CONTENT_TYPE,
    )
    from prometheus_client.openmetrics.exposition import (
        generate_latest as openmetrics_latest,
    )
except ImportError:  # pragma: no cover — ancient prometheus_client
    OPENMETRICS_CONTENT_TYPE = ""
    openmetrics_latest = None


class Metrics:
    def __init__(self):
        self.registry = CollectorRegistry()
        # Serializes collect-on-scrape refresh + render: two racing
        # scrapers must never interleave a take_pipeline_stats drain
        # with another's clear()+set() (a drained-but-not-yet-rendered
        # sample would silently vanish).  Held by the gateway /metrics
        # handler around the whole observe_*+render sequence.
        self.scrape_lock = threading.Lock()
        self.cache_size = Gauge(
            "gubernator_cache_size",
            "The number of items in LRU Cache which holds the rate limits.",
            registry=self.registry,
        )
        self.cache_access_count = Counter(
            "gubernator_cache_access_count",
            "Cache access counts.",
            ["type"],
            registry=self.registry,
        )
        self.request_counts = Counter(
            "gubernator_grpc_request_counts",
            "The count of gRPC requests.",
            ["status", "method"],
            registry=self.registry,
        )
        self.request_duration = Summary(
            "gubernator_grpc_request_duration",
            "The timings of gRPC requests in seconds.",
            ["method"],
            registry=self.registry,
        )
        # Histogram twin of request_duration, bucketed for latency SLOs
        # and carrying TRACE EXEMPLARS (tracing.py): each bucket
        # remembers one recent trace id, rendered on the OpenMetrics
        # exposition so a dashboard latency spike links straight to a
        # recorded trace.  The Summary above keeps reference name
        # parity; this is the observability extension.
        self.request_duration_hist = Histogram(
            "gubernator_request_duration_seconds",
            "RPC latency histogram with trace exemplars.",
            ["method"],
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 2.5, 5.0),
            registry=self.registry,
        )
        self.build_info = Gauge(
            "gubernator_build_info",
            "Constant 1, labeled with the daemon build version, the "
            "jax backend platform, and the device-mesh shape.",
            ["version", "backend", "mesh"],
            registry=self.registry,
        )
        self.async_durations = Summary(
            "gubernator_async_durations",
            "The duration of GLOBAL async sends in seconds.",
            registry=self.registry,
        )
        self.broadcast_durations = Summary(
            "gubernator_broadcast_durations",
            "The duration of GLOBAL broadcasts to peers in seconds.",
            registry=self.registry,
        )
        # -- peer fault tolerance (faults.py) --------------------------
        self.circuit_state = Gauge(
            "gubernator_circuit_breaker_state",
            "Per-peer circuit breaker state (0 closed, 1 half-open, 2 open).",
            ["peer"],
            registry=self.registry,
        )
        self.circuit_transitions = Counter(
            "gubernator_circuit_breaker_transitions",
            "Circuit breaker state transitions per peer.",
            ["peer", "to"],
            registry=self.registry,
        )
        self.peer_retries = Counter(
            "gubernator_peer_retry_count",
            "Retries of peer sends after a transport failure, by loop.",
            ["op"],  # forward | global_hits | global_broadcast | multi_region
            registry=self.registry,
        )
        self.degraded_evals = Counter(
            "gubernator_degraded_local_evals",
            "Forwarded keys served by degraded local evaluation because "
            "the owner's circuit breaker was open.",
            registry=self.registry,
        )
        # -- columnar peer hop (wire.py, peer_client.py) ---------------
        self.peer_columns_batches = Counter(
            "gubernator_peer_columns_batches",
            "Forwarded peer batches by negotiated wire encoding "
            "(columns = zero-dataclass fast path, classic = per-request "
            "JSON/protobuf fallback to a pre-columns peer).",
            ["encoding"],
            registry=self.registry,
        )
        # -- public columnar ingress (wire.py, gateway/grpc edges) -----
        self.ingress_columns_batches = Counter(
            "gubernator_ingress_columns_batches",
            "Public GetRateLimits batches served from the columnar "
            "ingress path by wire encoding (frame = GUBC kind-5 on the "
            "HTTP gateway, proto = V1/GetRateLimitsColumns over gRPC).",
            ["encoding"],
            registry=self.registry,
        )
        # -- native service loop (host_runtime.cpp gt_ingress_*) -------
        self.native_ingress_batches = Counter(
            "gubernator_native_ingress_batches",
            "Coalesced batches the native ingress service loop handed "
            "to the Python pump (stat = frames/lanes/batches/fallbacks; "
            "fallbacks = kind-5 frames that took the Python path for "
            "semantics the fast lane does not serve).",
            ["stat"],
            registry=self.registry,
        )
        # -- millisecond express lane (architecture.md "Express lane") -
        self.express_lanes = Counter(
            "gubernator_express_lanes_total",
            "Ingress lanes by dispatch path (bypass = batcher "
            "shallow-queue bypass, scalar = host-side small-batch "
            "slot, native = NO_BATCHING frames on the native express "
            "queue, windowed = lanes that rode a coalesced batch — a "
            "window flush or the native ring's bulk path).",
            ["path"],
            registry=self.registry,
        )
        self.express_hit_ratio = Gauge(
            "gubernator_express_hit_ratio",
            "Fraction of batcher/native ingress lanes that took an "
            "express path (bypass + native over those plus windowed), "
            "cumulative since start.",
            registry=self.registry,
        )
        self.readback_retries = Counter(
            "gubernator_readback_retries_total",
            "Device->host readbacks retried once for the known jax CPU "
            "IndexError flake (_copy_single_device_array_to_host_async "
            "under load); a retry that also fails propagates.",
            registry=self.registry,
        )
        self.ingress_acceptor_requests = Gauge(
            "gubernator_ingress_acceptor_requests",
            "Requests parsed per native acceptor loop (GUBER_ACCEPTORS "
            "SO_REUSEPORT sharding + the GUBER_UDS_PATH lane; the "
            "fairness surface — all acceptors of a loaded group must "
            "show progress).",
            ["acceptor", "transport"],
            registry=self.registry,
        )
        self.ingress_acceptor_conns = Gauge(
            "gubernator_ingress_acceptor_conns",
            "Connections accepted per native acceptor loop (cumulative).",
            ["acceptor", "transport"],
            registry=self.registry,
        )
        self.ingress_acceptor_frames = Gauge(
            "gubernator_ingress_acceptor_frames",
            "Kind-5 ingress frames consumed by the native fast lane per "
            "acceptor loop (cumulative).",
            ["acceptor", "transport"],
            registry=self.registry,
        )
        self.ingress_acceptor_lanes = Gauge(
            "gubernator_ingress_acceptor_lanes",
            "Rate-limit check lanes consumed by the native fast lane "
            "per acceptor loop (cumulative).",
            ["acceptor", "transport"],
            registry=self.registry,
        )
        # -- columnar GLOBAL replication plane (service.GlobalManager) -
        self.global_broadcast_batches = Counter(
            "gubernator_global_broadcast_batches",
            "GLOBAL broadcast sends by negotiated wire encoding "
            "(columns = encode-once GlobalsColumns fast path, classic "
            "= per-item JSON/protobuf fallback to a pre-columns peer).",
            ["encoding"],
            registry=self.registry,
        )
        self.global_fanout_concurrency = Gauge(
            "gubernator_global_fanout_concurrency",
            "Concurrent peer sends of the last GLOBAL broadcast "
            "fan-out (bounded by GUBER_GLOBAL_FANOUT).",
            registry=self.registry,
        )
        self.global_requeued_hits = Counter(
            "gubernator_global_requeued_hits",
            "Aggregated GLOBAL hit lanes (one per key) requeued into "
            "the next sync tick after an unroutable owner or a "
            "provably-unapplied send failure (the pre-columns sender "
            "silently dropped these).",
            registry=self.registry,
        )
        self.global_dropped_hits = Counter(
            "gubernator_global_dropped_hits",
            "Aggregated GLOBAL hit lanes dropped: timeout-shaped send "
            "failures that may have applied server-side (requeueing "
            "would double-count) or requeue-carry overflow.",
            registry=self.registry,
        )
        # -- multi-region federation plane (federation.py) -------------
        self.region_batches = Counter(
            "gubernator_region_batches",
            "Cross-region hit batches sent by negotiated wire encoding "
            "(columns = encode-once RegionColumns fast path, classic = "
            "per-item GetPeerRateLimits fallback to a pre-federation "
            "peer or GUBER_REGION_COLUMNS=0).",
            ["encoding"],
            registry=self.registry,
        )
        self.region_carry_keys = Gauge(
            "gubernator_region_carry_keys",
            "Distinct keys in the federation requeue carry, summed over "
            "destination regions (bounded at federation.REGION_CARRY_MAX "
            "per region; the region_slack audit invariant checks it).",
            registry=self.registry,
        )
        self.region_requeued_hits = Counter(
            "gubernator_region_requeued_hits",
            "Aggregated cross-region hit lanes (one per key) requeued "
            "into a destination region's next flush after a "
            "provably-unapplied send failure (breaker fast-fail, "
            "connection-level not-ready, unroutable owner).",
            registry=self.registry,
        )
        self.region_dropped_hits = Counter(
            "gubernator_region_dropped_hits",
            "Aggregated cross-region hit lanes dropped counted: "
            "timeout-shaped send failures that may have applied "
            "remotely (re-sending would double-count), requeue-carry "
            "overflow, or a destination region leaving the membership.",
            registry=self.registry,
        )
        # -- bounded ingress queue (service._IngressGate) --------------
        self.ingress_shed = Counter(
            "gubernator_ingress_shed_total",
            "Lanes shed by the bounded ingress queue "
            "(GUBER_INGRESS_QUEUE_LANES) with a 429-style error.",
            registry=self.registry,
        )
        # -- overlapped dispatch pipeline (models/shard.py) ------------
        self.dispatch_inflight = Gauge(
            "gubernator_dispatch_inflight",
            "Columnar batches dispatched to the device but not yet "
            "resolved (the dispatch pipeline's depth at scrape time).",
            registry=self.registry,
        )
        self.dispatch_inflight_hwm = Gauge(
            "gubernator_dispatch_inflight_hwm",
            "High-water mark of the dispatch pipeline depth since the "
            "previous scrape.",
            registry=self.registry,
        )
        self.dispatch_stage_seconds = Gauge(
            "gubernator_dispatch_stage_seconds",
            "Per-stage dispatch pipeline timings since the previous "
            "scrape (prepare/stage/launch/fetch/commit; stat = "
            "count/sum/max).  Cleared and rebuilt per scrape like the "
            "circuit-breaker gauges, so a quiet store reports nothing "
            "rather than a stale distribution.",
            ["stage", "stat"],
            registry=self.registry,
        )
        # -- saturation & SLO observability plane (saturation.py) ------
        self.latency_attribution = Histogram(
            "gubernator_latency_attribution_seconds",
            "Per-phase latency attribution across the request "
            "waterfall (ingress parse -> batch-window wait -> queue "
            "wait -> dispatch prepare/stage/launch/fetch/commit -> "
            "peer-wire RTT -> response encode).  Always-on; the same "
            "observations back GET /debug/latency's percentile "
            "snapshots.",
            ["phase"],
            buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                     0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0),
            registry=self.registry,
        )
        # This instance becomes the plane's histogram sink (last-wins,
        # like the tracing flight recorder: one daemon per process in
        # production).
        saturation.register_sink(self.latency_attribution)
        self.occupancy_slots = Gauge(
            "gubernator_occupancy_slots",
            "Mapped bucket-table slots per shard and tier, read from "
            "the host tables the existing dispatch readbacks maintain "
            "(ZERO extra device programs — pinned by a dispatch-count "
            "test).",
            ["shard", "tier"],
            registry=self.registry,
        )
        self.occupancy_capacity = Gauge(
            "gubernator_occupancy_capacity",
            "Bucket-table slot capacity per shard and tier.",
            ["shard", "tier"],
            registry=self.registry,
        )
        self.occupancy_evictions = Counter(
            "gubernator_occupancy_evictions",
            "LRU evictions per shard (capacity pressure; an eviction "
            "under load is reference-grade state loss).",
            ["shard"],
            registry=self.registry,
        )
        self.ingress_queue_lanes = Gauge(
            "gubernator_ingress_queue_lanes",
            "Lanes currently queued in the bounded ingress gates "
            "(sum of the local and columnar batchers) at scrape time; "
            "GET /debug/status carries the admit-time depth "
            "distribution.",
            registry=self.registry,
        )
        self.batch_window_wait_seconds = Gauge(
            "gubernator_batch_window_wait_seconds",
            "EFFECTIVE coalescing-window wait the next ingress flush "
            "will use (the adaptive window's current estimate; upper-"
            "bounded by GUBER_BATCH_WAIT).",
            registry=self.registry,
        )
        self.lane_utilization = Gauge(
            "gubernator_lane_utilization",
            "Per-launch lane utilization since the previous scrape: "
            "stat=lanes (real), stat=padded (pow2-padded shape "
            "scattered), stat=ratio (fill fraction), stat=launches.  "
            "Cleared per scrape.",
            ["stat"],
            registry=self.registry,
        )
        self.dispatcher_busy_ratio = Gauge(
            "gubernator_dispatcher_busy_ratio",
            "Fraction of wall time the ingress dispatcher (batch-"
            "window flush worker) spent flushing since the previous "
            "scrape — the USE utilization signal for the host "
            "dispatch tier.",
            registry=self.registry,
        )
        self.slo_latency_target_ms = Gauge(
            "gubernator_slo_latency_target_ms",
            "Configured ingress latency SLO target "
            "(GUBER_LATENCY_TARGET_MS; 0 = SLO engine disabled).",
            registry=self.registry,
        )
        self.slo_burn_rate = Gauge(
            "gubernator_slo_burn_rate",
            "Error-budget burn rate per window (bad-fraction / "
            "budget-fraction; 1.0 burns the budget exactly at accrual "
            "rate, >=14.4 on the 5m window trips the flight-recorder "
            "dump).",
            ["window"],
            registry=self.registry,
        )
        self.slo_requests = Counter(
            "gubernator_slo_requests",
            "Ingress requests judged against the latency SLO target.",
            ["verdict"],  # good | bad
            registry=self.registry,
        )
        self._slo_good = self.slo_requests.labels(verdict="good")
        self._slo_bad = self.slo_requests.labels(verdict="bad")
        self.hotkey_lanes = Counter(
            "gubernator_hotkey_lanes",
            "Lanes folded into the hot-key count-min sketch "
            "(hash_ring owner-code hashes; GET /debug/hotkeys serves "
            "the top-K).",
            registry=self.registry,
        )
        self.hotkey_topk = Gauge(
            "gubernator_hotkey_topk",
            "Decayed count-min estimates of the current hot-key "
            "top-K (bounded cardinality; rebuilt per scrape).",
            ["key"],
            registry=self.registry,
        )
        # -- elastic membership / resharding (reshard.py) --------------
        self.reshard_transfers = Counter(
            "gubernator_reshard_transfers",
            "Ownership-transfer batches by outcome: started (drained "
            "and sent), committed (merge-applied by the new owner), "
            "aborted (reinstalled locally after a send failure, "
            "unsupported peer, or epoch fence — the bounded "
            "reset-on-move fallback), fenced (receive-side dead-epoch "
            "rejections).",
            ["result"],
            registry=self.registry,
        )
        self.reshard_lanes = Counter(
            "gubernator_reshard_lanes",
            "Transferred counter lanes by direction: out (drained and "
            "committed at a new owner), in (merge-committed here), "
            "rejected (received but not owned under the current ring).",
            ["direction"],
            registry=self.registry,
        )
        self.reshard_handoff_seconds = Gauge(
            "gubernator_reshard_handoff_seconds",
            "Wall time of the last drain->transfer handoff pass "
            "(set per scrape).",
            registry=self.registry,
        )
        self.ring_generation = Gauge(
            "gubernator_ring_generation",
            "Monotonic membership-change counter of this daemon's peer "
            "ring (bumped by every set_peers that changes membership).",
            registry=self.registry,
        )
        # -- XLA / device telemetry plane (telemetry.py) ---------------
        self.xla_compiles = Counter(
            "gubernator_xla_compiles",
            "XLA backend compiles since start, keyed by the program "
            "identity the launching thread declared (solo/fused-K "
            "dispatches, wide/narrow wires, mesh twins, the GLOBAL "
            "sync collective; 'unlabeled' = a compile outside any "
            "labeled launch site).",
            ["program"],
            registry=self.registry,
        )
        self.xla_compile_seconds = Counter(
            "gubernator_xla_compile_seconds",
            "Cumulative XLA backend compile wall seconds per program "
            "identity.",
            ["program"],
            registry=self.registry,
        )
        self.xla_steady_recompiles = Counter(
            "gubernator_xla_steady_recompiles",
            "Backend compiles AFTER startup warmup completed — shape "
            "churn by definition; a burst fires the recompile-storm "
            "flight-recorder dump.",
            ["program"],
            registry=self.registry,
        )
        self.xla_program_runs = Gauge(
            "gubernator_xla_program_runs",
            "Per-program launch timings since the previous scrape "
            "(stat = count/sum/max seconds; enqueue wall time).  "
            "Cleared per scrape like the dispatch-stage gauges.",
            ["program", "stat"],
            registry=self.registry,
        )
        self.device_memory_bytes = Gauge(
            "gubernator_device_memory_bytes",
            "Per-device memory sampled at scrape time (stat = "
            "bytes_in_use/peak_bytes_in_use/bytes_limit where the "
            "backend reports memory_stats; live_bytes from the "
            "live-array walk everywhere).",
            ["device", "stat"],
            registry=self.registry,
        )
        self.device_live_buffers = Gauge(
            "gubernator_device_live_buffers",
            "Live jax arrays resident per device at scrape time.",
            ["device"],
            registry=self.registry,
        )
        # -- durability plane (snapshot.py) ----------------------------
        self.snapshot_writes = Counter(
            "gubernator_snapshot_writes",
            "Crash-safe snapshot dumps by result: ok (gathered, "
            "encoded, fsync'd, atomically renamed) or error (counted "
            "and logged; the serving path and shutdown never fail on a "
            "failed dump).",
            ["result"],
            registry=self.registry,
        )
        self.snapshot_restores = Counter(
            "gubernator_snapshot_restores",
            "Boot-time snapshot restores by result: ok (merge-"
            "committed), absent (no file — cold start), rejected "
            "(corrupt/truncated/wrong-version/checksum — LOUD cold "
            "start with a snapshot-rejected flight-recorder dump).",
            ["result"],
            registry=self.registry,
        )
        self.snapshot_lanes = Counter(
            "gubernator_snapshot_lanes",
            "Bucket lanes crossing the durability plane by direction: "
            "saved (gathered into a completed dump) or restored "
            "(merge-committed at boot).",
            ["direction"],
            registry=self.registry,
        )
        self.snapshot_age_seconds = Gauge(
            "gubernator_snapshot_age_seconds",
            "Seconds since the last successful snapshot dump (set per "
            "scrape; -1 = no successful dump yet / plane disabled).  "
            "The staleness-slack contract bounds over-admission after "
            "a crash by the hits admitted inside this window.",
            registry=self.registry,
        )
        # -- cost observatory (profiling.py) ---------------------------
        self.tenant_cost = Gauge(
            "gubernator_tenant_cost",
            "Per-tenant cost attribution, TOP-K ONLY (tenant = the "
            "rate-limit name; cardinality bounded at GUBER_TENANT_TOPK "
            "label values, rebuilt per scrape so departed tenants drop "
            "off).  stat = hits/lanes/over_limit/shed/ingress_bytes "
            "(exact accumulators) plus lane_time_seconds/queue_seconds "
            "(proportional shares: tenant lanes x the process-wide "
            "per-lane cost).",
            ["tenant", "stat"],
            registry=self.registry,
        )
        self.tenant_other = Gauge(
            "gubernator_tenant_other",
            "The `other` rollup of every tenant outside the top-K "
            "(same stats as gubernator_tenant_cost; rows + other == "
            "totals exactly — the ledger conserves on eviction).",
            ["stat"],
            registry=self.registry,
        )
        self.tenant_total = Gauge(
            "gubernator_tenant_total",
            "Whole-daemon tenant-ledger totals (the conservation "
            "denominator: hits here reconcile against the audit "
            "ledger's ingress_hits + peer_ingress_hits at quiesce).",
            ["stat"],
            registry=self.registry,
        )
        self.profile_samples = Counter(
            "gubernator_profile_samples",
            "Stack samples folded by the continuous host profiler "
            "(GUBER_PROFILE_HZ ticks x threads; GET /debug/pprof "
            "serves the collapsed windows).",
            registry=self.registry,
        )
        self.profile_hz = Gauge(
            "gubernator_profile_hz",
            "Configured host-profiler sampling rate (0 = the plane is "
            "compiled out, GUBER_PROFILE=0).",
            registry=self.registry,
        )
        # -- conservation audit (audit.py) -----------------------------
        self.audit_violations = Counter(
            "gubernator_audit_violations_total",
            "Conservation-audit invariant violations (device/forward/"
            "global/reshard hit conservation, GLOBAL carry slack, "
            "negative remaining).  Any increment is a double-commit or "
            "lost-hits class bug; each also dumps the flight recorder.",
            ["invariant"],
            registry=self.registry,
        )
        self.audit_checks = Counter(
            "gubernator_audit_checks_total",
            "Conservation-audit reconciliation passes completed.",
            registry=self.registry,
        )
        self.audit_ledger = Gauge(
            "gubernator_audit_ledger",
            "Conservation-ledger counters (baseline-relative deltas "
            "the audit reconciles), exported for dashboards; the "
            "invariant verdicts live in "
            "gubernator_audit_violations_total.",
            ["entry"],
            registry=self.registry,
        )
        # -- incident black box (blackbox.py) --------------------------
        self.blackbox_frames = Counter(
            "gubernator_blackbox_frames",
            "Wire frames captured by the incident black box's traffic "
            "tap, by wire plane (ring eviction does not decrement — "
            "this counts everything that passed the tap).",
            ["wire"],
            registry=self.registry,
        )
        self.blackbox_ring_bytes = Gauge(
            "gubernator_blackbox_ring_bytes",
            "Current bytes held in each black-box capture ring "
            "(byte-budgeted: GUBER_BLACKBOX_MB split across wires).",
            ["wire"],
            registry=self.registry,
        )
        self.blackbox_bundles = Counter(
            "gubernator_blackbox_bundles",
            "Incident bundles written (trigger-coalesced and "
            "rate-limited; retention-pruned bundles still count).",
            registry=self.registry,
        )
        self.blackbox_last_trigger_age = Gauge(
            "gubernator_blackbox_last_trigger_age_seconds",
            "Seconds since the last black-box trigger (auto-dump event "
            "or POST /debug/incident); -1 = never triggered.",
            registry=self.registry,
        )
        # SloEngine (saturation.py), attached by the owning V1Service;
        # observe_latency judges GetRateLimits requests against it.
        self.slo = None

    @contextmanager
    def observe_rpc(self, method: str):
        """Count + time one RPC by fully-qualified method name — the
        per-RPC tagging of the reference's stats handler
        (grpc_stats.go:95-118).  Status label is the WIRE outcome: "0"
        unless the handler raised (an unhealthy HealthCheck payload is
        still a successful RPC)."""
        start = time.perf_counter()
        status = "0"
        try:
            yield
        except BaseException:
            status = "1"
            raise
        finally:
            dt = time.perf_counter() - start
            self.request_counts.labels(status=status, method=method).inc()
            self.request_duration.labels(method=method).observe(dt)
            self.observe_latency(method, dt)

    def observe_latency(self, method: str, dt: float, ctx=None) -> None:
        """Histogram observation with a trace exemplar — shared by the
        sync observe_rpc (ambient per-thread context) and the async
        gateway finish path (which passes its span's context explicitly:
        completion threads have no ambient one)."""
        if method == "/pb.gubernator.V1/GetRateLimits":
            # SLO + attribution accounting for the public ingress RPC:
            # the whole-request wall time is the waterfall's root row,
            # and the SLO engine judges it against the latency target.
            saturation.observe_phase("ingress.total", dt)
            if self.slo is not None:
                good = self.slo.observe(dt)
                if good is not None:
                    (self._slo_good if good else self._slo_bad).inc()
        hist = self.request_duration_hist.labels(method=method)
        if ctx is None and tracing.enabled():
            ctx = tracing.current()
        if ctx is not None:
            try:
                hist.observe(dt, exemplar={"trace_id": ctx.trace_hex})
                return
            except (TypeError, ValueError):  # pragma: no cover
                pass  # prometheus_client without exemplar support
        hist.observe(dt)

    def render(self) -> bytes:
        return generate_latest(self.registry)

    def render_negotiated(self, accept: str) -> "tuple[str, bytes]":
        """(content_type, payload) honoring the scraper's Accept
        header: `application/openmetrics-text` gets the OpenMetrics
        exposition — the only format that carries the trace exemplars —
        everyone else the classic text format."""
        if "application/openmetrics-text" in (accept or "") and (
            openmetrics_latest is not None
        ):
            return OPENMETRICS_CONTENT_TYPE, openmetrics_latest(self.registry)
        return "text/plain; version=0.0.4", self.render()

    def set_build_info(self, store) -> None:
        """Pin the build-info series: version from the package, backend
        and mesh shape from the store's device topology (stores without
        a mesh report their shard layout)."""
        from . import __version__

        describe = getattr(store, "describe_topology", None)
        backend, mesh = ("unknown", "none")
        if describe is not None:
            try:
                backend, mesh = describe()
            except Exception:  # noqa: BLE001 — labels must never fail startup
                pass
        self.build_info.labels(
            version=__version__, backend=backend, mesh=mesh
        ).set(1)

    def observe_cache(self, store) -> None:
        """Refresh cache gauges from a ShardStore/MeshBucketStore."""
        self.cache_size.set(store.size())
        tables = getattr(store, "tables", None) or [store.table]
        hits = sum(t.hits for t in tables)
        misses = sum(t.misses for t in tables)
        # Counters are monotonic: set via inc of the delta.
        self._bump(self.cache_access_count.labels(type="hit"), hits)
        self._bump(self.cache_access_count.labels(type="miss"), misses)

    def observe_peers(self, peers) -> None:
        """Refresh the per-peer breaker state gauge from live
        PeerClients (collect-on-scrape, like observe_cache).  Rebuilt
        from scratch each scrape: a peer that left the cluster must
        drop off the gauge, not freeze at its last state forever."""
        self.circuit_state.clear()
        for p in peers:
            breaker = getattr(p, "breaker", None)
            info = getattr(p, "info", None)
            if breaker is None or info is None:
                continue
            self.circuit_state.labels(peer=info.grpc_address).set(
                breaker.state_code
            )

    def observe_dispatch(self, store) -> None:
        """Refresh the dispatch-pipeline gauges from a store
        (collect-on-scrape).  Per-stage series are cleared first — the
        stats are deltas since the last scrape (the PR 1 breaker-gauge
        convention), so departed stages drop off instead of freezing."""
        take = getattr(store, "take_pipeline_stats", None)
        if take is None:
            return
        stats, depth, hwm = take()
        self.dispatch_inflight.set(depth)
        self.dispatch_inflight_hwm.set(hwm)
        self.dispatch_stage_seconds.clear()
        for stage, (count, total_s, max_s) in stats.items():
            lab = self.dispatch_stage_seconds.labels
            lab(stage=stage, stat="count").set(count)
            lab(stage=stage, stat="sum").set(total_s)
            lab(stage=stage, stat="max").set(max_s)

    def observe_saturation(self, service) -> None:
        """Refresh the saturation/SLO plane gauges (collect-on-scrape,
        under the gateway's scrape lock like every other observer).
        Everything read here is host-side state the dispatch path
        already maintains — the scrape launches no device program."""
        store = service.store
        occupancy = getattr(store, "occupancy_stats", None)
        self.occupancy_slots.clear()
        self.occupancy_capacity.clear()
        if occupancy is not None:
            for row in occupancy():
                sh = str(row["shard"])
                slots, caps = self.occupancy_slots, self.occupancy_capacity
                slots.labels(shard=sh, tier="front").set(row["used"])
                caps.labels(shard=sh, tier="front").set(row["capacity"])
                self._bump(
                    self.occupancy_evictions.labels(shard=sh),
                    row["evictions"],
                )
                if "back_used" in row:
                    slots.labels(shard=sh, tier="back").set(row["back_used"])
                    caps.labels(shard=sh, tier="back").set(
                        row["back_capacity"]
                    )
        self.ingress_queue_lanes.set(service.ingress_queued_lanes())
        self.batch_window_wait_seconds.set(
            service.columnar_batcher._window.effective_wait_s()
        )
        lanes, padded, launches = saturation.lane_util.take()
        self.lane_utilization.clear()
        lab = self.lane_utilization.labels
        lab(stat="lanes").set(lanes)
        lab(stat="padded").set(padded)
        lab(stat="launches").set(launches)
        if padded:
            lab(stat="ratio").set(lanes / padded)
        busy, elapsed = saturation.dispatcher_busy.take()
        self.dispatcher_busy_ratio.set(min(busy / elapsed, 1.0))
        # Express lane: per-path lane deltas since the last scrape plus
        # the cumulative hit rate (saturation.ExpressStats).
        for path, lanes in saturation.express.take().items():
            if lanes:
                self.express_lanes.labels(path=path).inc(lanes)
        self.express_hit_ratio.set(
            saturation.express.snapshot()["hitRate"]
        )
        # Readback-flake quarantine counter (models/shard.py): delta
        # against the cumulative module total, the native-shed pattern.
        from .models import shard as _shard

        retries = _shard.readback_retries_total()
        prev = getattr(self, "_readback_retries_seen", 0)
        if retries > prev:
            self.readback_retries.inc(retries - prev)
            self._readback_retries_seen = retries
        slo = self.slo
        if slo is not None:
            self.slo_latency_target_ms.set(slo.target_ms if slo.enabled else 0)
            for name, w in slo.WINDOWS.items():
                self.slo_burn_rate.labels(window=name).set(slo.burn_rate(w))
        sketch = getattr(service, "hotkeys", None)
        if sketch is not None:
            snap = sketch.snapshot()
            self._bump(self.hotkey_lanes, snap["total_lanes"])
            self.hotkey_topk.clear()
            for row in snap["topk"]:
                self.hotkey_topk.labels(key=row["key"]).set(row["estimate"])
        # Elastic membership: ring generation + last handoff wall time
        # (the counters are incremented live by the ReshardManager).
        self.ring_generation.set(getattr(service, "ring_generation", 0))
        mgr = getattr(service, "reshard", None)
        if mgr is not None:
            self.reshard_handoff_seconds.set(mgr.last_handoff_seconds)
        # Durability plane: snapshot staleness (the slack-contract
        # numerator; counters are incremented live by SnapshotManager).
        snaps = getattr(service, "snapshots", None)
        if snaps is not None:
            self.snapshot_age_seconds.set(
                time.time() - snaps.last_save_unix
                if snaps.last_save_unix else -1.0
            )

    def observe_native_ingress(self, service) -> None:
        """Refresh the native-service-loop families (collect-on-scrape,
        under the scrape lock like every observer): per-acceptor
        counters from the epoll edges (the REUSEPORT fairness surface)
        and the pump's batch/fallback/shed totals.  Native sheds feed
        the SAME gubernator_ingress_shed_total the Python gate
        increments — one overload signal regardless of which tier
        declined the work — via a delta so the two sources compose."""
        for edge in getattr(service, "native_edges", ()):
            try:
                rows = edge.acceptor_stats()
            except (OSError, AttributeError):
                continue
            for i, row in enumerate(rows):
                transport = "uds" if row["uds"] else "tcp"
                lab = {"acceptor": str(i), "transport": transport}
                self.ingress_acceptor_conns.labels(**lab).set(row["accepted"])
                self.ingress_acceptor_requests.labels(**lab).set(
                    row["requests"]
                )
                self.ingress_acceptor_frames.labels(**lab).set(
                    row["ingressFrames"]
                )
                self.ingress_acceptor_lanes.labels(**lab).set(
                    row["ingressLanes"]
                )
        pump = getattr(service, "native_ingress", None)
        if pump is None:
            return
        stats = pump.stats()
        for stat in ("frames", "lanes", "batches", "fallbacks"):
            self._bump(
                self.native_ingress_batches.labels(stat=stat), stats[stat]
            )
        shed = stats["shedLanes"]
        prev = getattr(self, "_native_shed_seen", 0)
        if shed > prev:
            self.ingress_shed.inc(shed - prev)
            self._native_shed_seen = shed

    def observe_telemetry(self) -> None:
        """Refresh the XLA/device telemetry families from the
        process-global telemetry plane (collect-on-scrape, under the
        scrape lock like every observer).  Per-program exec timings are
        drained per scrape; compile counters bump to the cumulative
        plane totals; device memory/live-buffer stats are sampled here
        and nowhere else (the scrape is the only reader that pays the
        live-array walk)."""
        if not telemetry.enabled():
            return
        for label, row in telemetry.compile_snapshot().items():
            self._bump(self.xla_compiles.labels(program=label), row["count"])
            self._bump(
                self.xla_compile_seconds.labels(program=label),
                row["total_s"],
            )
            self._bump(
                self.xla_steady_recompiles.labels(program=label),
                row["steady_recompiles"],
            )
        self.xla_program_runs.clear()
        for label, (count, total_s, max_s) in telemetry.take_exec_stats().items():
            lab = self.xla_program_runs.labels
            lab(program=label, stat="count").set(count)
            lab(program=label, stat="sum").set(total_s)
            lab(program=label, stat="max").set(max_s)
        self.device_memory_bytes.clear()
        self.device_live_buffers.clear()
        for row in telemetry.device_snapshot():
            dev = row["device"]
            for stat in ("bytes_in_use", "peak_bytes_in_use",
                         "bytes_limit", "live_bytes"):
                if stat in row:
                    self.device_memory_bytes.labels(
                        device=dev, stat=stat
                    ).set(row[stat])
            self.device_live_buffers.labels(device=dev).set(
                row.get("live_buffers", 0)
            )

    def observe_cost(self, service) -> None:
        """Refresh the cost-observatory families from the service's
        tenant ledger and the process-global profiler (collect-on-
        scrape, under the scrape lock like every observer).  Per-tenant
        series are REBUILT each scrape from the top-K — the cardinality
        bound the Zipf test pins (<= K tenant label values + the one
        `other` rollup, under any number of distinct names)."""
        tenants = getattr(service, "tenants", None)
        if tenants is not None:
            snap = tenants.snapshot()
            stat_keys = (
                ("hits", "hits"), ("lanes", "lanes"),
                ("overLimit", "over_limit"), ("shed", "shed"),
                ("ingressBytes", "ingress_bytes"),
                ("laneTimeS", "lane_time_seconds"),
                ("queueS", "queue_seconds"),
            )
            self.tenant_cost.clear()
            for row in snap["topk"]:
                for src, stat in stat_keys:
                    self.tenant_cost.labels(
                        tenant=row["tenant"], stat=stat
                    ).set(row[src])
            for family, doc in (
                (self.tenant_other, snap["other"]),
                (self.tenant_total, snap["totals"]),
            ):
                family.clear()
                for src, stat in stat_keys:
                    family.labels(stat=stat).set(doc[src])
        self._bump(self.profile_samples, profiling.sample_count())
        self.profile_hz.set(profiling.hz() if profiling.enabled() else 0)

    def observe_audit(self, service) -> None:
        """Refresh the conservation-ledger gauge from the service's
        auditor (collect-on-scrape; violation/check counters are
        incremented LIVE by the auditor thread at detection time)."""
        auditor = getattr(service, "auditor", None)
        if auditor is None:
            return
        self.audit_ledger.clear()
        for entry, value in auditor.deltas().items():
            self.audit_ledger.labels(entry=entry).set(value)
        for entry, value in audit_mod.gauges_snapshot().items():
            self.audit_ledger.labels(entry=entry).set(value)

    def observe_blackbox(self, service) -> None:
        """Refresh the incident-black-box families from the service's
        BlackBox (collect-on-scrape: the tap itself never touches
        prometheus — one branch + ring append per frame)."""
        bb = getattr(service, "blackbox", None)
        if bb is None:
            return
        for wire_name, ring in bb.rings.items():
            _n, nbytes, frames_total = ring.stats()
            self._bump(self.blackbox_frames.labels(wire=wire_name),
                       frames_total)
            self.blackbox_ring_bytes.labels(wire=wire_name).set(nbytes)
        self._bump(self.blackbox_bundles, bb.bundles_written)
        snap_age = bb.snapshot().get("lastTriggerAgeS")
        self.blackbox_last_trigger_age.set(
            -1 if snap_age is None else snap_age
        )

    def _bump(self, counter, absolute: float) -> None:
        current = counter._value.get()  # noqa: SLF001
        if absolute > current:
            counter.inc(absolute - current)
