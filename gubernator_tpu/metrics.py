"""Prometheus metrics with reference name parity.

Metric names match the reference exactly so dashboards/alerts port
unchanged: gubernator_cache_size + gubernator_cache_access_count
(cache.go:88-92,205-218), gubernator_grpc_request_counts +
gubernator_grpc_request_duration (grpc_stats.go:45-59),
gubernator_async_durations + gubernator_broadcast_durations
(global.go:40-56).
"""

from __future__ import annotations

from prometheus_client import CollectorRegistry, Counter, Gauge, Summary, generate_latest


class Metrics:
    def __init__(self):
        self.registry = CollectorRegistry()
        self.cache_size = Gauge(
            "gubernator_cache_size",
            "The number of items in LRU Cache which holds the rate limits.",
            registry=self.registry,
        )
        self.cache_access_count = Counter(
            "gubernator_cache_access_count",
            "Cache access counts.",
            ["type"],
            registry=self.registry,
        )
        self.request_counts = Counter(
            "gubernator_grpc_request_counts",
            "The count of gRPC requests.",
            ["status", "method"],
            registry=self.registry,
        )
        self.request_duration = Summary(
            "gubernator_grpc_request_duration",
            "The timings of gRPC requests in seconds.",
            ["method"],
            registry=self.registry,
        )
        self.async_durations = Summary(
            "gubernator_async_durations",
            "The duration of GLOBAL async sends in seconds.",
            registry=self.registry,
        )
        self.broadcast_durations = Summary(
            "gubernator_broadcast_durations",
            "The duration of GLOBAL broadcasts to peers in seconds.",
            registry=self.registry,
        )

    def render(self) -> bytes:
        return generate_latest(self.registry)

    def observe_cache(self, store) -> None:
        """Refresh cache gauges from a ShardStore/MeshBucketStore."""
        self.cache_size.set(store.size())
        tables = getattr(store, "tables", None) or [store.table]
        hits = sum(t.hits for t in tables)
        misses = sum(t.misses for t in tables)
        # Counters are monotonic: set via inc of the delta.
        self._bump(self.cache_access_count.labels(type="hit"), hits)
        self._bump(self.cache_access_count.labels(type="miss"), misses)

    def _bump(self, counter, absolute: float) -> None:
        current = counter._value.get()  # noqa: SLF001
        if absolute > current:
            counter.inc(absolute - current)
