"""Prometheus metrics with reference name parity.

Metric names match the reference exactly so dashboards/alerts port
unchanged: gubernator_cache_size + gubernator_cache_access_count
(cache.go:88-92,205-218), gubernator_grpc_request_counts +
gubernator_grpc_request_duration (grpc_stats.go:45-59),
gubernator_async_durations + gubernator_broadcast_durations
(global.go:40-56).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    Summary,
    generate_latest,
)

from . import tracing

try:  # OpenMetrics exposition carries trace exemplars; text 0.0.4 cannot
    from prometheus_client.openmetrics.exposition import (
        CONTENT_TYPE_LATEST as OPENMETRICS_CONTENT_TYPE,
    )
    from prometheus_client.openmetrics.exposition import (
        generate_latest as openmetrics_latest,
    )
except ImportError:  # pragma: no cover — ancient prometheus_client
    OPENMETRICS_CONTENT_TYPE = ""
    openmetrics_latest = None


class Metrics:
    def __init__(self):
        self.registry = CollectorRegistry()
        # Serializes collect-on-scrape refresh + render: two racing
        # scrapers must never interleave a take_pipeline_stats drain
        # with another's clear()+set() (a drained-but-not-yet-rendered
        # sample would silently vanish).  Held by the gateway /metrics
        # handler around the whole observe_*+render sequence.
        self.scrape_lock = threading.Lock()
        self.cache_size = Gauge(
            "gubernator_cache_size",
            "The number of items in LRU Cache which holds the rate limits.",
            registry=self.registry,
        )
        self.cache_access_count = Counter(
            "gubernator_cache_access_count",
            "Cache access counts.",
            ["type"],
            registry=self.registry,
        )
        self.request_counts = Counter(
            "gubernator_grpc_request_counts",
            "The count of gRPC requests.",
            ["status", "method"],
            registry=self.registry,
        )
        self.request_duration = Summary(
            "gubernator_grpc_request_duration",
            "The timings of gRPC requests in seconds.",
            ["method"],
            registry=self.registry,
        )
        # Histogram twin of request_duration, bucketed for latency SLOs
        # and carrying TRACE EXEMPLARS (tracing.py): each bucket
        # remembers one recent trace id, rendered on the OpenMetrics
        # exposition so a dashboard latency spike links straight to a
        # recorded trace.  The Summary above keeps reference name
        # parity; this is the observability extension.
        self.request_duration_hist = Histogram(
            "gubernator_request_duration_seconds",
            "RPC latency histogram with trace exemplars.",
            ["method"],
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 2.5, 5.0),
            registry=self.registry,
        )
        self.build_info = Gauge(
            "gubernator_build_info",
            "Constant 1, labeled with the daemon build version, the "
            "jax backend platform, and the device-mesh shape.",
            ["version", "backend", "mesh"],
            registry=self.registry,
        )
        self.async_durations = Summary(
            "gubernator_async_durations",
            "The duration of GLOBAL async sends in seconds.",
            registry=self.registry,
        )
        self.broadcast_durations = Summary(
            "gubernator_broadcast_durations",
            "The duration of GLOBAL broadcasts to peers in seconds.",
            registry=self.registry,
        )
        # -- peer fault tolerance (faults.py) --------------------------
        self.circuit_state = Gauge(
            "gubernator_circuit_breaker_state",
            "Per-peer circuit breaker state (0 closed, 1 half-open, 2 open).",
            ["peer"],
            registry=self.registry,
        )
        self.circuit_transitions = Counter(
            "gubernator_circuit_breaker_transitions",
            "Circuit breaker state transitions per peer.",
            ["peer", "to"],
            registry=self.registry,
        )
        self.peer_retries = Counter(
            "gubernator_peer_retry_count",
            "Retries of peer sends after a transport failure, by loop.",
            ["op"],  # forward | global_hits | global_broadcast | multi_region
            registry=self.registry,
        )
        self.degraded_evals = Counter(
            "gubernator_degraded_local_evals",
            "Forwarded keys served by degraded local evaluation because "
            "the owner's circuit breaker was open.",
            registry=self.registry,
        )
        # -- columnar peer hop (wire.py, peer_client.py) ---------------
        self.peer_columns_batches = Counter(
            "gubernator_peer_columns_batches",
            "Forwarded peer batches by negotiated wire encoding "
            "(columns = zero-dataclass fast path, classic = per-request "
            "JSON/protobuf fallback to a pre-columns peer).",
            ["encoding"],
            registry=self.registry,
        )
        # -- columnar GLOBAL replication plane (service.GlobalManager) -
        self.global_broadcast_batches = Counter(
            "gubernator_global_broadcast_batches",
            "GLOBAL broadcast sends by negotiated wire encoding "
            "(columns = encode-once GlobalsColumns fast path, classic "
            "= per-item JSON/protobuf fallback to a pre-columns peer).",
            ["encoding"],
            registry=self.registry,
        )
        self.global_fanout_concurrency = Gauge(
            "gubernator_global_fanout_concurrency",
            "Concurrent peer sends of the last GLOBAL broadcast "
            "fan-out (bounded by GUBER_GLOBAL_FANOUT).",
            registry=self.registry,
        )
        self.global_requeued_hits = Counter(
            "gubernator_global_requeued_hits",
            "Aggregated GLOBAL hit lanes (one per key) requeued into "
            "the next sync tick after an unroutable owner or a "
            "provably-unapplied send failure (the pre-columns sender "
            "silently dropped these).",
            registry=self.registry,
        )
        self.global_dropped_hits = Counter(
            "gubernator_global_dropped_hits",
            "Aggregated GLOBAL hit lanes dropped: timeout-shaped send "
            "failures that may have applied server-side (requeueing "
            "would double-count) or requeue-carry overflow.",
            registry=self.registry,
        )
        # -- bounded ingress queue (service._IngressGate) --------------
        self.ingress_shed = Counter(
            "gubernator_ingress_shed_total",
            "Lanes shed by the bounded ingress queue "
            "(GUBER_INGRESS_QUEUE_LANES) with a 429-style error.",
            registry=self.registry,
        )
        # -- overlapped dispatch pipeline (models/shard.py) ------------
        self.dispatch_inflight = Gauge(
            "gubernator_dispatch_inflight",
            "Columnar batches dispatched to the device but not yet "
            "resolved (the dispatch pipeline's depth at scrape time).",
            registry=self.registry,
        )
        self.dispatch_inflight_hwm = Gauge(
            "gubernator_dispatch_inflight_hwm",
            "High-water mark of the dispatch pipeline depth since the "
            "previous scrape.",
            registry=self.registry,
        )
        self.dispatch_stage_seconds = Gauge(
            "gubernator_dispatch_stage_seconds",
            "Per-stage dispatch pipeline timings since the previous "
            "scrape (prepare/stage/launch/fetch/commit; stat = "
            "count/sum/max).  Cleared and rebuilt per scrape like the "
            "circuit-breaker gauges, so a quiet store reports nothing "
            "rather than a stale distribution.",
            ["stage", "stat"],
            registry=self.registry,
        )

    @contextmanager
    def observe_rpc(self, method: str):
        """Count + time one RPC by fully-qualified method name — the
        per-RPC tagging of the reference's stats handler
        (grpc_stats.go:95-118).  Status label is the WIRE outcome: "0"
        unless the handler raised (an unhealthy HealthCheck payload is
        still a successful RPC)."""
        start = time.perf_counter()
        status = "0"
        try:
            yield
        except BaseException:
            status = "1"
            raise
        finally:
            dt = time.perf_counter() - start
            self.request_counts.labels(status=status, method=method).inc()
            self.request_duration.labels(method=method).observe(dt)
            self.observe_latency(method, dt)

    def observe_latency(self, method: str, dt: float, ctx=None) -> None:
        """Histogram observation with a trace exemplar — shared by the
        sync observe_rpc (ambient per-thread context) and the async
        gateway finish path (which passes its span's context explicitly:
        completion threads have no ambient one)."""
        hist = self.request_duration_hist.labels(method=method)
        if ctx is None and tracing.enabled():
            ctx = tracing.current()
        if ctx is not None:
            try:
                hist.observe(dt, exemplar={"trace_id": ctx.trace_hex})
                return
            except (TypeError, ValueError):  # pragma: no cover
                pass  # prometheus_client without exemplar support
        hist.observe(dt)

    def render(self) -> bytes:
        return generate_latest(self.registry)

    def render_negotiated(self, accept: str) -> "tuple[str, bytes]":
        """(content_type, payload) honoring the scraper's Accept
        header: `application/openmetrics-text` gets the OpenMetrics
        exposition — the only format that carries the trace exemplars —
        everyone else the classic text format."""
        if "application/openmetrics-text" in (accept or "") and (
            openmetrics_latest is not None
        ):
            return OPENMETRICS_CONTENT_TYPE, openmetrics_latest(self.registry)
        return "text/plain; version=0.0.4", self.render()

    def set_build_info(self, store) -> None:
        """Pin the build-info series: version from the package, backend
        and mesh shape from the store's device topology (stores without
        a mesh report their shard layout)."""
        from . import __version__

        describe = getattr(store, "describe_topology", None)
        backend, mesh = ("unknown", "none")
        if describe is not None:
            try:
                backend, mesh = describe()
            except Exception:  # noqa: BLE001 — labels must never fail startup
                pass
        self.build_info.labels(
            version=__version__, backend=backend, mesh=mesh
        ).set(1)

    def observe_cache(self, store) -> None:
        """Refresh cache gauges from a ShardStore/MeshBucketStore."""
        self.cache_size.set(store.size())
        tables = getattr(store, "tables", None) or [store.table]
        hits = sum(t.hits for t in tables)
        misses = sum(t.misses for t in tables)
        # Counters are monotonic: set via inc of the delta.
        self._bump(self.cache_access_count.labels(type="hit"), hits)
        self._bump(self.cache_access_count.labels(type="miss"), misses)

    def observe_peers(self, peers) -> None:
        """Refresh the per-peer breaker state gauge from live
        PeerClients (collect-on-scrape, like observe_cache).  Rebuilt
        from scratch each scrape: a peer that left the cluster must
        drop off the gauge, not freeze at its last state forever."""
        self.circuit_state.clear()
        for p in peers:
            breaker = getattr(p, "breaker", None)
            info = getattr(p, "info", None)
            if breaker is None or info is None:
                continue
            self.circuit_state.labels(peer=info.grpc_address).set(
                breaker.state_code
            )

    def observe_dispatch(self, store) -> None:
        """Refresh the dispatch-pipeline gauges from a store
        (collect-on-scrape).  Per-stage series are cleared first — the
        stats are deltas since the last scrape (the PR 1 breaker-gauge
        convention), so departed stages drop off instead of freezing."""
        take = getattr(store, "take_pipeline_stats", None)
        if take is None:
            return
        stats, depth, hwm = take()
        self.dispatch_inflight.set(depth)
        self.dispatch_inflight_hwm.set(hwm)
        self.dispatch_stage_seconds.clear()
        for stage, (count, total_s, max_s) in stats.items():
            lab = self.dispatch_stage_seconds.labels
            lab(stage=stage, stat="count").set(count)
            lab(stage=stage, stat="sum").set(total_s)
            lab(stage=stage, stat="max").set(max_s)

    def _bump(self, counter, absolute: float) -> None:
        current = counter._value.get()  # noqa: SLF001
        if absolute > current:
            counter.inc(absolute - current)
