"""Peer transport client: lazy connections, request batching, error LRU.

Parity with peer_client.go: per-peer request queue drained into one
GetPeerRateLimits call when BatchLimit is reached or the BatchWait
window closes (peer_client.go:272-312); NO_BATCHING bypasses the queue
(:143-152); last-error LRU with 5-minute TTL surfaced via HealthCheck
(:206-235); graceful shutdown drains in-flight requests (:351-385).

Transport is HTTP/JSON against the peer's gateway endpoints (the
reference's gRPC data plane maps onto the same grpc-gateway JSON
surface this framework serves).
"""

from __future__ import annotations

import http.client
import json
import queue
import ssl
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

from .config import BehaviorConfig
from .types import (
    Behavior,
    GetRateLimitsRequest,
    GetRateLimitsResponse,
    PeerInfo,
    RateLimitRequest,
    RateLimitResponse,
    has_behavior,
)

ERR_CLOSING = "grpc: the client connection is closing"


class PeerError(Exception):
    def __init__(self, message: str, not_ready: bool = False):
        super().__init__(message)
        self.not_ready = not_ready


def is_not_ready(err: Exception) -> bool:
    """Reference `IsNotReady` (peer_client.go:405-412)."""
    return isinstance(err, PeerError) and err.not_ready


class PeerClient:
    LAST_ERR_TTL_S = 300.0  # peer_client.go:77 (5 minute TTL)

    def __init__(
        self,
        info: PeerInfo,
        behaviors: Optional[BehaviorConfig] = None,
        tls_context: Optional[ssl.SSLContext] = None,
    ):
        self.info = info
        self.behaviors = behaviors or BehaviorConfig()
        self.tls_context = tls_context
        self._conn_lock = threading.Lock()
        self._conn: Optional[http.client.HTTPConnection] = None
        self._queue: "queue.Queue[Tuple[RateLimitRequest, Future]]" = queue.Queue()
        self._shutdown = threading.Event()
        self._err_lock = threading.Lock()
        self._last_err: Dict[str, float] = {}  # message -> expiry timestamp
        self._worker: Optional[threading.Thread] = None
        self._worker_lock = threading.Lock()

    # ------------------------------------------------------------------
    def get_peer_rate_limit(
        self, req: RateLimitRequest, timeout_s: Optional[float] = None
    ) -> RateLimitResponse:
        """One rate limit from the owning peer; batched unless the
        request asks NO_BATCHING (peer_client.go:141-154)."""
        if has_behavior(req.behavior, Behavior.NO_BATCHING):
            resp = self.get_peer_rate_limits(
                GetRateLimitsRequest(requests=[req]), timeout_s=timeout_s
            )
            return resp.responses[0]
        if self._shutdown.is_set():
            raise PeerError(ERR_CLOSING, not_ready=True)
        self._ensure_worker()
        fut: Future = Future()
        self._queue.put((req, fut))
        timeout = timeout_s if timeout_s is not None else self.behaviors.batch_timeout_s
        return fut.result(timeout=timeout + 1.0)

    def get_peer_rate_limits(
        self, req: GetRateLimitsRequest, timeout_s: Optional[float] = None
    ) -> GetRateLimitsResponse:
        """Owner-authoritative batch (PeersV1.GetPeerRateLimits)."""
        body = self._post("/v1/peer.GetPeerRateLimits", req.to_json(), timeout_s)
        resp = GetRateLimitsResponse.from_json({"responses": body.get("rateLimits", [])})
        if len(resp.responses) != len(req.requests):
            raise PeerError("number of rate limits in peer response does not match request")
        return resp

    def update_peer_globals(self, globals_json: dict, timeout_s: Optional[float] = None) -> None:
        """PeersV1.UpdatePeerGlobals."""
        self._post("/v1/peer.UpdatePeerGlobals", globals_json, timeout_s)

    # ------------------------------------------------------------------
    def _ensure_worker(self) -> None:
        with self._worker_lock:
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(target=self._run, daemon=True)
                self._worker.start()

    def _run(self) -> None:
        """Batch loop (peer_client.go:272-312): first enqueue opens a
        BatchWait window; flush on BatchLimit or window close."""
        b = self.behaviors
        while not self._shutdown.is_set():
            try:
                first = self._queue.get(timeout=0.05)
            except queue.Empty:
                continue
            batch = [first]
            deadline = time.monotonic() + b.batch_wait_s
            while len(batch) < b.batch_limit:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    batch.append(self._queue.get(timeout=remaining))
                except queue.Empty:
                    break
            self._send_batch(batch)
        # Drain anything left after shutdown was requested.
        leftovers = []
        while True:
            try:
                leftovers.append(self._queue.get_nowait())
            except queue.Empty:
                break
        if leftovers:
            self._send_batch(leftovers)

    def _send_batch(self, batch: List[Tuple[RateLimitRequest, Future]]) -> None:
        """peer_client.go:316-348 sendQueue."""
        try:
            resp = self.get_peer_rate_limits(
                GetRateLimitsRequest(requests=[r for r, _ in batch]),
                timeout_s=self.behaviors.batch_timeout_s,
            )
        except Exception as e:  # noqa: BLE001
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)
            return
        for (_, fut), rl in zip(batch, resp.responses):
            if not fut.done():
                fut.set_result(rl)

    # ------------------------------------------------------------------
    def _post(self, path: str, payload: dict, timeout_s: Optional[float]) -> dict:
        timeout = timeout_s if timeout_s is not None else self.behaviors.batch_timeout_s
        data = json.dumps(payload).encode("utf-8")
        host = self.info.http_address or self.info.grpc_address
        with self._conn_lock:
            try:
                if self._conn is None:
                    hostname, _, port = host.partition(":")
                    if self.tls_context is not None:
                        self._conn = http.client.HTTPSConnection(
                            hostname, int(port or 443), timeout=timeout,
                            context=self.tls_context,
                        )
                    else:
                        self._conn = http.client.HTTPConnection(
                            hostname, int(port or 80), timeout=timeout
                        )
                self._conn.request(
                    "POST", path, body=data, headers={"Content-Type": "application/json"}
                )
                r = self._conn.getresponse()
                body = r.read()
                if r.status != 200:
                    raise PeerError(f"peer returned HTTP {r.status}: {body[:200]!r}")
                return json.loads(body) if body else {}
            except PeerError as e:
                self._set_last_err(str(e))
                self._reset_conn()
                raise
            except (OSError, http.client.HTTPException) as e:
                msg = f"connect to peer {host} failed: {e}"
                self._set_last_err(msg)
                self._reset_conn()
                raise PeerError(msg, not_ready=True) from e

    def _reset_conn(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
            self._conn = None

    # ------------------------------------------------------------------
    def _set_last_err(self, msg: str) -> None:
        """Error LRU with TTL (peer_client.go:206-220); messages include
        the peer address for HealthCheck reporting."""
        with self._err_lock:
            self._last_err[f"{msg} (peer: {self.info.grpc_address})"] = (
                time.monotonic() + self.LAST_ERR_TTL_S
            )

    def get_last_err(self) -> List[str]:
        now = time.monotonic()
        with self._err_lock:
            self._last_err = {m: t for m, t in self._last_err.items() if t > now}
            return list(self._last_err.keys())

    # ------------------------------------------------------------------
    def shutdown(self, timeout_s: float = 5.0) -> None:
        """Drain in-flight batches, then close (peer_client.go:351-385)."""
        self._shutdown.set()
        worker = self._worker
        if worker is not None and worker.is_alive():
            worker.join(timeout=timeout_s)
        with self._conn_lock:
            self._reset_conn()
