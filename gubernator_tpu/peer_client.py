"""Peer transport client: lazy connections, request batching, error LRU.

Parity with peer_client.go: per-peer request queue drained into one
GetPeerRateLimits call when BatchLimit is reached or the BatchWait
window closes (peer_client.go:272-312); NO_BATCHING bypasses the queue
(:143-152); last-error LRU with 5-minute TTL surfaced via HealthCheck
(:206-235); graceful shutdown drains in-flight requests (:351-385).

Default transport is gRPC against the peer's PeersV1 service — the
same data plane as the reference (lazy channel = the reference's lazy
`connect()`, peer_client.go:87-132).  An HTTP/JSON fallback speaks the
peer's gateway, used when TLS is configured with insecure_skip_verify
(gRPC channel credentials cannot skip verification) or on request.
"""

from __future__ import annotations

import http.client
import json
import ssl
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence, Tuple

import grpc

from . import faults as faults_mod
from . import wire
from .config import BehaviorConfig
from .faults import CircuitBreaker, FaultPlan
from .utils.batch_window import BatchWindow
from .proto import PEERS_V1_SERVICE
from .proto import peers_pb2 as peers_pb
from .types import (
    Behavior,
    GetRateLimitsRequest,
    GetRateLimitsResponse,
    PeerInfo,
    RateLimitRequest,
    RateLimitResponse,
    UpdatePeerGlobal,
    has_behavior,
)

ERR_CLOSING = "grpc: the client connection is closing"

# Only connection-level failures count as "not ready" (the reference's
# IsNotReady checks the connecting state machine, peer_client.go:405-412).
# DEADLINE_EXCEEDED is deliberately NOT here: a timed-out RPC may still
# have executed server-side (Python gRPC handlers run to completion after
# the client deadline), so retrying it would double-count hits.
_NOT_READY_CODES = (grpc.StatusCode.UNAVAILABLE,)


class PeerError(Exception):
    def __init__(self, message: str, not_ready: bool = False,
                 circuit_open: bool = False):
        super().__init__(message)
        self.not_ready = not_ready
        # The call never left this host: the peer's circuit breaker was
        # open.  Routers degrade to local evaluation instead of
        # retrying (faults.py; service._forward_one).
        self.circuit_open = circuit_open


def is_not_ready(err: Exception) -> bool:
    """Reference `IsNotReady` (peer_client.go:405-412)."""
    return isinstance(err, PeerError) and err.not_ready


def is_circuit_open(err: Exception) -> bool:
    """True when the failure is a breaker fast-fail — the RPC was never
    attempted, so degraded local evaluation is safe (no double-count
    risk) and retrying the same peer is pointless until the breaker's
    half-open probe succeeds."""
    return isinstance(err, PeerError) and err.circuit_open


class PeerClient:
    LAST_ERR_TTL_S = 300.0  # peer_client.go:77 (5 minute TTL)
    LAST_ERR_MAX = 100  # bounded LRU like the reference (peer_client.go:77)

    def __init__(
        self,
        info: PeerInfo,
        behaviors: Optional[BehaviorConfig] = None,
        tls_context: Optional[ssl.SSLContext] = None,
        channel_credentials: Optional[grpc.ChannelCredentials] = None,
        transport: str = "",  # "" = auto, "grpc", "http"
        metrics: object = None,  # Optional[Metrics]: breaker transition counts
        faults: Optional[FaultPlan] = None,  # None = honor faults.install()
    ):
        self.info = info
        self.behaviors = behaviors or BehaviorConfig()
        self.tls_context = tls_context
        self.channel_credentials = channel_credentials
        self.faults = faults
        self._metrics = metrics
        self.breaker = CircuitBreaker(
            failure_threshold=self.behaviors.circuit_threshold,
            open_interval_s=self.behaviors.circuit_open_interval_s,
            on_transition=self._on_breaker_transition,
        )
        if not transport:
            # insecure_skip_verify TLS has no gRPC equivalent: the ssl
            # context fallback is the only transport that can honor it.
            transport = (
                "http"
                if tls_context is not None and channel_credentials is None
                else "grpc"
            )
        self.transport = transport
        self._conn_lock = threading.Lock()
        self._conn: Optional[http.client.HTTPConnection] = None
        self._channel: Optional[grpc.Channel] = None
        self._rpc_get_peer_rate_limits = None
        self._rpc_update_peer_globals = None
        self._shutdown = threading.Event()
        self._err_lock = threading.Lock()
        self._last_err: Dict[str, float] = {}  # message -> expiry timestamp
        # Lazy worker: idle peers (never forwarded to) spawn no thread.
        self._window = BatchWindow(
            self._send_batch,
            self.behaviors.batch_wait_s,
            self.behaviors.batch_limit,
            lazy=True,
        )

    # ------------------------------------------------------------------
    def get_peer_rate_limit(
        self, req: RateLimitRequest, timeout_s: Optional[float] = None
    ) -> RateLimitResponse:
        """One rate limit from the owning peer; batched unless the
        request asks NO_BATCHING (peer_client.go:141-154)."""
        if has_behavior(req.behavior, Behavior.NO_BATCHING):
            resp = self.get_peer_rate_limits(
                GetRateLimitsRequest(requests=[req]), timeout_s=timeout_s
            )
            return resp.responses[0]
        if self._shutdown.is_set():
            raise PeerError(ERR_CLOSING, not_ready=True)
        fut: Future = Future()
        self._window.submit((req, fut))
        timeout = timeout_s if timeout_s is not None else self.behaviors.batch_timeout_s
        return fut.result(timeout=timeout + 1.0)

    def get_peer_rate_limits(
        self, req: GetRateLimitsRequest, timeout_s: Optional[float] = None,
        _draining: bool = False,
    ) -> GetRateLimitsResponse:
        """Owner-authoritative batch (PeersV1.GetPeerRateLimits).
        `_draining` lets the shutdown drain flush already-queued
        requests through the still-open connection
        (peer_client.go:351-385) after new requests are refused."""
        n = len(req.requests)

        def _count_check(got: int) -> None:
            # Runs inside the _guarded_call region: a peer that
            # consistently returns the wrong number of rate limits
            # (version skew, corruption) trips its breaker like any
            # transport failure would.
            if got != n:
                msg = (
                    f"GetPeerRateLimits to peer {self.info.grpc_address} "
                    f"returned {got} rate limits for {n} requests"
                )
                self._set_last_err(msg)
                raise PeerError(msg)

        if self.transport == "http":
            body = self._post(
                "/v1/peer.GetPeerRateLimits", req.to_json(), timeout_s,
                check=lambda b: _count_check(len(b.get("rateLimits", []))),
            )
            resp = GetRateLimitsResponse.from_json(
                {"responses": body.get("rateLimits", [])}
            )
        else:
            m = self._grpc_call(
                "GetPeerRateLimits",
                wire.peer_rate_limits_req_to_pb(req),
                timeout_s,
                allow_closing=_draining,
                check=lambda m: _count_check(len(m.rate_limits)),
            )
            resp = wire.peer_rate_limits_resp_from_pb(m)
        return resp

    def update_peer_globals(
        self, updates: Sequence[UpdatePeerGlobal], timeout_s: Optional[float] = None
    ) -> None:
        """PeersV1.UpdatePeerGlobals."""
        if self.transport == "http":
            payload = {"globals": [u.to_json() for u in updates]}
            self._post("/v1/peer.UpdatePeerGlobals", payload, timeout_s)
        else:
            self._grpc_call(
                "UpdatePeerGlobals", wire.update_globals_req_to_pb(updates), timeout_s
            )

    # ------------------------------------------------------------------
    def _send_batch(self, batch: List[Tuple[RateLimitRequest, Future]]) -> None:
        """peer_client.go:316-348 sendQueue."""
        try:
            resp = self.get_peer_rate_limits(
                GetRateLimitsRequest(requests=[r for r, _ in batch]),
                timeout_s=self.behaviors.batch_timeout_s,
                _draining=True,
            )
        except Exception as e:  # noqa: BLE001
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)
            return
        for (_, fut), rl in zip(batch, resp.responses):
            if not fut.done():
                fut.set_result(rl)

    # ------------------------------------------------------------------
    # gRPC transport (lazy channel = peer_client.go:87-132 connect())
    # ------------------------------------------------------------------
    def _ensure_channel(self):
        """Returns (get_peer_rate_limits, update_peer_globals) stubs,
        building the channel lazily.  The stubs are captured and
        returned under the lock: _reset_channel may null the attributes
        concurrently (a racing thread observing a torn state must not
        see None)."""
        with self._conn_lock:
            if self._channel is None:
                target = self.info.grpc_address
                options = [("grpc.max_receive_message_length", 1024 * 1024)]
                if self.channel_credentials is not None:
                    self._channel = grpc.secure_channel(
                        target, self.channel_credentials, options=options
                    )
                else:
                    self._channel = grpc.insecure_channel(target, options=options)
                self._rpc_get_peer_rate_limits = self._channel.unary_unary(
                    f"/{PEERS_V1_SERVICE}/GetPeerRateLimits",
                    request_serializer=peers_pb.GetPeerRateLimitsReq.SerializeToString,
                    response_deserializer=peers_pb.GetPeerRateLimitsResp.FromString,
                )
                self._rpc_update_peer_globals = self._channel.unary_unary(
                    f"/{PEERS_V1_SERVICE}/UpdatePeerGlobals",
                    request_serializer=peers_pb.UpdatePeerGlobalsReq.SerializeToString,
                    response_deserializer=peers_pb.UpdatePeerGlobalsResp.FromString,
                )
            return self._rpc_get_peer_rate_limits, self._rpc_update_peer_globals

    # ------------------------------------------------------------------
    # Fault-tolerance wrap: every transport call passes the breaker gate
    # then the installed fault plan (faults.py) before touching the wire.
    # ------------------------------------------------------------------
    def _on_breaker_transition(self, state: str) -> None:
        if self._metrics is not None:
            self._metrics.circuit_transitions.labels(
                peer=self.info.grpc_address, to=state
            ).inc()

    def _breaker_gate(self, op: str) -> None:
        """Raise the circuit-open fast-fail, or reserve the call slot
        (every non-raising return MUST be paired with exactly one
        breaker.record_success/record_failure)."""
        if not self.breaker.allow():
            raise PeerError(
                f"{op} to peer {self.info.grpc_address} rejected: "
                f"circuit breaker open",
                not_ready=True,
                circuit_open=True,
            )

    def _fault_check(self, op: str) -> None:
        """Consult the fault plan (instance-level, else the process-wide
        installed one).  An injected ERROR/DROP raises the same
        PeerError shape a real transport failure would — downstream
        retry/breaker/health behavior is exercised for real."""
        fp = self.faults if self.faults is not None else faults_mod.active()
        if fp is None:
            return
        act = fp.intercept(self.info.grpc_address, op)
        if act is None:
            return
        if act.kind == faults_mod.DELAY:
            time.sleep(act.delay_s)
            return
        msg = f"{op} to peer {self.info.grpc_address} failed: {act.message}"
        self._set_last_err(msg)
        raise PeerError(msg, not_ready=act.not_ready)

    def _guarded_call(self, op: str, fn, check=None):
        """The breaker protocol, shared by BOTH transports: gate ->
        injected-fault check -> fn() -> optional reply check -> record.
        Every non-raising _breaker_gate() pairs with exactly one
        record_success/record_failure (the half-open probe slot,
        faults.CircuitBreaker).  `check` runs INSIDE the guarded region
        so a structurally bad reply (wrong response count) counts as a
        breaker failure like any transport error, instead of resetting
        the failure streak before the caller notices."""
        self._breaker_gate(op)
        try:
            self._fault_check(op)
            out = fn()
            if check is not None:
                check(out)
        except BaseException:
            self.breaker.record_failure()
            raise
        self.breaker.record_success()
        return out

    def _grpc_call(self, method: str, request, timeout_s: Optional[float],
                   allow_closing: bool = False, check=None):
        if self._shutdown.is_set() and not allow_closing:
            raise PeerError(ERR_CLOSING, not_ready=True)
        return self._guarded_call(
            method, lambda: self._grpc_inner(method, request, timeout_s), check
        )

    def _grpc_inner(self, method: str, request, timeout_s: Optional[float]):
        try:
            get_rl, update_g = self._ensure_channel()
            rpc = get_rl if method == "GetPeerRateLimits" else update_g
            timeout = (
                timeout_s if timeout_s is not None else self.behaviors.batch_timeout_s
            )
            return rpc(request, timeout=timeout)
        except grpc.RpcError as e:
            code = e.code() if hasattr(e, "code") else None
            msg = f"{method} to peer {self.info.grpc_address} failed: {code}: {e.details() if hasattr(e, 'details') else e}"
            self._set_last_err(msg)
            # Drop the channel so the next call redials immediately
            # instead of sitting in gRPC's reconnect backoff (the lazy
            # reconnect of peer_client.go:87-132; a restarted peer at
            # the same address must be reachable right away).
            if code == grpc.StatusCode.UNAVAILABLE:
                self._reset_channel()
            raise PeerError(msg, not_ready=code in _NOT_READY_CODES) from e

    def _reset_channel(self) -> None:
        with self._conn_lock:
            if self._channel is not None:
                self._channel.close()
                self._channel = None
                self._rpc_get_peer_rate_limits = None
                self._rpc_update_peer_globals = None

    # ------------------------------------------------------------------
    # HTTP/JSON fallback transport (the peer's gateway surface)
    # ------------------------------------------------------------------
    def _post(self, path: str, payload: dict, timeout_s: Optional[float],
              check=None) -> dict:
        op = path.rpartition(".")[2]  # /v1/peer.GetPeerRateLimits -> op
        return self._guarded_call(
            op, lambda: self._post_inner(path, payload, timeout_s), check
        )

    def _post_inner(self, path: str, payload: dict, timeout_s: Optional[float]) -> dict:
        timeout = timeout_s if timeout_s is not None else self.behaviors.batch_timeout_s
        data = json.dumps(payload).encode("utf-8")
        host = self.info.http_address or self.info.grpc_address
        with self._conn_lock:
            try:
                if self._conn is None:
                    hostname, _, port = host.partition(":")
                    if self.tls_context is not None:
                        self._conn = http.client.HTTPSConnection(
                            hostname, int(port or 443), timeout=timeout,
                            context=self.tls_context,
                        )
                    else:
                        self._conn = http.client.HTTPConnection(
                            hostname, int(port or 80), timeout=timeout
                        )
                self._conn.request(
                    "POST", path, body=data, headers={"Content-Type": "application/json"}
                )
                r = self._conn.getresponse()
                body = r.read()
                if r.status != 200:
                    raise PeerError(f"peer returned HTTP {r.status}: {body[:200]!r}")
                return json.loads(body) if body else {}
            except PeerError as e:
                self._set_last_err(str(e))
                self._reset_conn()
                raise
            except (OSError, http.client.HTTPException) as e:
                msg = f"connect to peer {host} failed: {e}"
                self._set_last_err(msg)
                self._reset_conn()
                raise PeerError(msg, not_ready=True) from e

    def _reset_conn(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
            self._conn = None

    # ------------------------------------------------------------------
    def _set_last_err(self, msg: str) -> None:
        """Error LRU with TTL (peer_client.go:206-220); messages include
        the peer address for HealthCheck reporting.  Bounded at
        LAST_ERR_MAX entries: a flood of distinct error messages evicts
        the oldest instead of growing without bound between
        get_last_err() calls (reference uses a fixed-size LRU)."""
        with self._err_lock:
            key = f"{msg} (peer: {self.info.grpc_address})"
            # Re-inserting moves the key to the end: recency order.
            self._last_err.pop(key, None)
            self._last_err[key] = time.monotonic() + self.LAST_ERR_TTL_S
            while len(self._last_err) > self.LAST_ERR_MAX:
                self._last_err.pop(next(iter(self._last_err)))

    def get_last_err(self) -> List[str]:
        now = time.monotonic()
        with self._err_lock:
            self._last_err = {m: t for m, t in self._last_err.items() if t > now}
            return list(self._last_err.keys())

    # ------------------------------------------------------------------
    def shutdown(self, timeout_s: float = 5.0) -> None:
        """Drain in-flight batches, then close (peer_client.go:351-385)."""
        self._shutdown.set()
        self._window.stop(timeout_s=timeout_s)
        with self._conn_lock:
            self._reset_conn()
            if self._channel is not None:
                self._channel.close()
                self._channel = None
