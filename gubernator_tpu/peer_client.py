"""Peer transport client: lazy connections, columnar forward
coalescing, error LRU.

Parity with peer_client.go: per-peer request queue drained into one
GetPeerRateLimits call when BatchLimit is reached or the BatchWait
window closes (peer_client.go:272-312); NO_BATCHING bypasses the queue
(:143-152); last-error LRU with 5-minute TTL surfaced via HealthCheck
(:206-235); graceful shutdown drains in-flight requests (:351-385).

The forward queue is COLUMNAR (the peer half of the zero-dataclass
hot path, wire.py "columnar peer hop"): submissions accumulate lanes
into numpy-backed column buffers instead of per-request dataclasses,
the adaptive BatchWindow flushes them as ONE columnar RPC per <=
batch_limit lanes, and every waiter gets back a slice of the shared
decoded response arrays.  Wire encoding negotiates per peer: proto
columns (gRPC) / the binary frame (HTTP) first; a peer that answers
UNIMPLEMENTED / HTTP 400 is remembered as classic-only and served the
per-request encoding from then on.

Default transport is gRPC against the peer's PeersV1 service — the
same data plane as the reference (lazy channel = the reference's lazy
`connect()`, peer_client.go:87-132).  An HTTP fallback speaks the
peer's gateway, used when TLS is configured with insecure_skip_verify
(gRPC channel credentials cannot skip verification) or on request.
"""

from __future__ import annotations

import http.client
import json
import ssl
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence, Tuple

import grpc
import numpy as np

from . import audit
from . import faults as faults_mod
from . import profiling
from . import saturation
from . import tracing
from . import wire
from .config import MAX_BATCH_SIZE, PEER_COLUMNS_MAX_LANES, BehaviorConfig
from .faults import CircuitBreaker, FaultPlan
from .utils.batch_window import BatchWindow
from .proto import PEERS_V1_SERVICE
from .proto import peers_columns_pb2 as pc_pb
from .proto import peers_pb2 as peers_pb
from .types import (
    Behavior,
    GetRateLimitsRequest,
    GetRateLimitsResponse,
    PeerInfo,
    RateLimitRequest,
    RateLimitResponse,
    UpdatePeerGlobal,
    has_behavior,
)

ERR_CLOSING = "grpc: the client connection is closing"

# Only connection-level failures count as "not ready" (the reference's
# IsNotReady checks the connecting state machine, peer_client.go:405-412).
# DEADLINE_EXCEEDED is deliberately NOT here: a timed-out RPC may still
# have executed server-side (Python gRPC handlers run to completion after
# the client deadline), so retrying it would double-count hits.
_NOT_READY_CODES = (grpc.StatusCode.UNAVAILABLE,)


class PeerError(Exception):
    def __init__(self, message: str, not_ready: bool = False,
                 circuit_open: bool = False, http_status: int = 0):
        super().__init__(message)
        self.not_ready = not_ready
        # The call never left this host: the peer's circuit breaker was
        # open.  Routers degrade to local evaluation instead of
        # retrying (faults.py; service._forward_one).
        self.circuit_open = circuit_open
        # HTTP transport only: the peer's status code (0 = not an HTTP
        # status failure).  The columns negotiation reads it — a 400 to
        # a columns frame means "old peer, speak JSON".
        self.http_status = http_status


def is_not_ready(err: Exception) -> bool:
    """Reference `IsNotReady` (peer_client.go:405-412)."""
    return isinstance(err, PeerError) and err.not_ready


def is_circuit_open(err: Exception) -> bool:
    """True when the failure is a breaker fast-fail — the RPC was never
    attempted, so degraded local evaluation is safe (no double-count
    risk) and retrying the same peer is pointless until the breaker's
    half-open probe succeeds."""
    return isinstance(err, PeerError) and err.circuit_open


class PeerClient:
    LAST_ERR_TTL_S = 300.0  # peer_client.go:77 (5 minute TTL)
    LAST_ERR_MAX = 100  # bounded LRU like the reference (peer_client.go:77)

    def __init__(
        self,
        info: PeerInfo,
        behaviors: Optional[BehaviorConfig] = None,
        tls_context: Optional[ssl.SSLContext] = None,
        channel_credentials: Optional[grpc.ChannelCredentials] = None,
        transport: str = "",  # "" = auto, "grpc", "http"
        metrics: object = None,  # Optional[Metrics]: breaker transition counts
        faults: Optional[FaultPlan] = None,  # None = honor faults.install()
        blackbox: object = None,  # Optional[BlackBox]: wire traffic tap
    ):
        self.info = info
        self.behaviors = behaviors or BehaviorConfig()
        self.tls_context = tls_context
        self.channel_credentials = channel_credentials
        self.faults = faults
        self._metrics = metrics
        # Incident black box (blackbox.py): _http_roundtrip taps every
        # outbound GUBC frame + its response here — the one choke point
        # ALL HTTP peer traffic (forward, globals, transfer, region,
        # and fault-injected redeliveries) flows through.
        self.blackbox = blackbox
        self.breaker = CircuitBreaker(
            failure_threshold=self.behaviors.circuit_threshold,
            open_interval_s=self.behaviors.circuit_open_interval_s,
            on_transition=self._on_breaker_transition,
        )
        if not transport:
            # insecure_skip_verify TLS has no gRPC equivalent: the ssl
            # context fallback is the only transport that can honor it.
            transport = (
                "http"
                if tls_context is not None and channel_credentials is None
                else "grpc"
            )
        self.transport = transport
        self._conn_lock = threading.Lock()
        self._conn: Optional[http.client.HTTPConnection] = None
        self._channel: Optional[grpc.Channel] = None
        self._rpc_get_peer_rate_limits = None
        self._rpc_get_peer_rate_limits_columns = None
        self._rpc_update_peer_globals = None
        self._rpc_update_peer_globals_columns = None
        self._rpc_transfer_ownership = None
        self._rpc_update_region_columns = None
        self._shutdown = threading.Event()
        self._err_lock = threading.Lock()
        self._last_err: Dict[str, float] = {}  # message -> expiry timestamp
        # Columnar wire negotiation: None = untried (probe columns
        # first), True = peer speaks columns, False = classic only
        # (config opt-out, or the peer answered UNIMPLEMENTED / 400 to
        # the probe).  Sticky for the client's lifetime — a peer that
        # upgrades in place re-negotiates when churn rebuilds the
        # client (service.set_peers).
        self._columnar: Optional[bool] = (
            None if self.behaviors.peer_columns else False
        )
        # Whether the peer accepts the frame trace-context trailer
        # (HTTP transport only; gRPC needs no probe — proto3 unknown
        # fields are skipped).  None = untried: the first SAMPLED frame
        # probes; a peer that answers "length mismatch" predates the
        # trailer and is resent the same frame without it.
        self._trace_frames: Optional[bool] = None
        # GLOBAL broadcast encoding negotiation, independent of the
        # forward-hop flag above (its own GUBER_GLOBAL_COLUMNS knob):
        # None = untried (probe columns first), True = peer takes the
        # columnar broadcast, False = classic per-item only.  Sticky for
        # the client's lifetime, like _columnar.
        self._globals_columnar: Optional[bool] = (
            None if getattr(self.behaviors, "global_columns", True) else False
        )
        # Multi-region federation negotiation (federation.py), on its
        # own GUBER_REGION_COLUMNS knob: None = untried (the first
        # region send probes the columnar encoding), True = peer takes
        # RegionColumns, False = classic per-item GetPeerRateLimits
        # only (pre-federation peer, or its knob is off) — sticky for
        # the client's lifetime like the other planes.
        self._region_columnar: Optional[bool] = (
            None if getattr(self.behaviors, "region_columns", True) else False
        )
        # Ownership-transfer plane negotiation (reshard.py), on its own
        # GUBER_RESHARD knob: None = untried (the first transfer
        # probes), True = peer accepts transfers, False = no transfer
        # surface (pre-reshard peer, or its knob is off) — sticky for
        # the client's lifetime like the other planes; churn rebuilds
        # the client and re-negotiates.
        self._transfer_supported: Optional[bool] = (
            None if getattr(self.behaviors, "reshard", True) else False
        )
        # Per-RPC lane caps.  The operator's GUBER_BATCH_LIMIT keeps
        # meaning on both encodings: it is the classic per-RPC cap
        # verbatim, and the columnar cap scales with it (16.384x at the
        # default 1000) bounded by what the protocol allows.
        self._classic_cap = min(self.behaviors.batch_limit, MAX_BATCH_SIZE)
        self._columns_cap = max(
            1, PEER_COLUMNS_MAX_LANES * self._classic_cap // MAX_BATCH_SIZE
        )
        # Lazy worker: idle peers (never forwarded to) spawn no thread.
        # Items are ((names, uks, algo, beh, hits, limit, dur), fut)
        # COLUMN sub-batches; the limit counts LANES (weigh) and the
        # window adapts its wait to the arrival rate (batch_window.py).
        # A columns-capable peer accepts PEER_COLUMNS_MAX_LANES per
        # RPC, so the window coalesces up to the columnar cap per flush
        # (the whole point of the columnar hop: concurrent ingress
        # batches to one owner merge into ONE RPC); _send_batch chunks
        # down to what the negotiated encoding allows, and a peer that
        # negotiates down to classic shrinks the window itself
        # (_mark_classic) so flushes stop out-sizing its RPCs.
        self._window = BatchWindow(
            self._send_batch,
            self.behaviors.batch_wait_s,
            self._columns_cap
            if self.behaviors.peer_columns
            else self._classic_cap,
            lazy=True,
            adaptive=True,
            weigh=lambda item: len(item[0][0]),
        )

    # ------------------------------------------------------------------
    def get_peer_rate_limit(
        self, req: RateLimitRequest, timeout_s: Optional[float] = None,
        trace_ctx=None,
    ) -> RateLimitResponse:
        """One rate limit from the owning peer; batched unless the
        request asks NO_BATCHING (peer_client.go:141-154).  The batched
        path rides the columnar coalescer as a 1-lane sub-batch.
        `trace_ctx` carries the submitting request's span context when
        the caller runs on a pool thread with no ambient one
        (service._forward_one) — forward_columns falls back to
        tracing.current() otherwise."""
        if has_behavior(req.behavior, Behavior.NO_BATCHING):
            resp = self.get_peer_rate_limits(
                GetRateLimitsRequest(requests=[req]), timeout_s=timeout_s
            )
            return resp.responses[0]
        fut = self.forward_columns(
            (
                [req.name],
                [req.unique_key],
                np.array([int(req.algorithm)], np.int32),
                np.array([int(req.behavior)], np.int32),
                np.array([int(req.hits)], np.int64),
                np.array([int(req.limit)], np.int64),
                np.array([int(req.duration)], np.int64),
            ),
            trace_ctx=trace_ctx,
        )
        timeout = timeout_s if timeout_s is not None else self.behaviors.batch_timeout_s
        rc, lo, _hi = fut.result(timeout=timeout + 1.0)
        return rc.response_at(lo)

    def forward_columns(self, cols: "wire.PeerColumns",
                        trace_ctx=None) -> Future:
        """Submit a column sub-batch to the per-owner coalescing window
        (peer_client.go:272-312 sendQueue, columnar).  The future
        resolves to (result: service.ColumnarResult, lo, hi) — this
        sub-batch's slice of the shared flushed batch — or raises the
        transport/breaker failure.  `trace_ctx` (a tracing.SpanContext)
        rides the sub-batch so the flushed RPC can carry the wire
        trace-context column and link its peer.rpc span."""
        if self._shutdown.is_set():
            raise PeerError(ERR_CLOSING, not_ready=True)
        fut: Future = Future()
        if trace_ctx is None and tracing.enabled():
            trace_ctx = tracing.current()
        if trace_ctx is not None:
            fut._trace_ctx = trace_ctx  # read back at flush (same Future)
        self._window.submit((cols, fut))
        return fut

    def send_columns_direct(self, cols: "wire.PeerColumns",
                            timeout_s: Optional[float] = None,
                            trace_ctx=None):
        """One columnar GetPeerRateLimits RPC, no window (the
        NO_BATCHING group forward).  Returns service.ColumnarResult."""
        if self._shutdown.is_set():
            raise PeerError(ERR_CLOSING, not_ready=True)
        trace = None
        if trace_ctx is not None and tracing.enabled():
            trace = tracing.links_to_entries([trace_ctx], 0, len(cols[0]))
        return self._send_columns(
            cols,
            timeout_s if timeout_s is not None else self.behaviors.batch_timeout_s,
            trace=trace,
        )

    def get_peer_rate_limits(
        self, req: GetRateLimitsRequest, timeout_s: Optional[float] = None,
        _draining: bool = False,
    ) -> GetRateLimitsResponse:
        """Owner-authoritative batch (PeersV1.GetPeerRateLimits).
        `_draining` lets the shutdown drain flush already-queued
        requests through the still-open connection
        (peer_client.go:351-385) after new requests are refused."""
        n = len(req.requests)

        def _count_check(got: int) -> None:
            # Runs inside the _guarded_call region: a peer that
            # consistently returns the wrong number of rate limits
            # (version skew, corruption) trips its breaker like any
            # transport failure would.
            if got != n:
                msg = (
                    f"GetPeerRateLimits to peer {self.info.grpc_address} "
                    f"returned {got} rate limits for {n} requests"
                )
                self._set_last_err(msg)
                raise PeerError(msg)

        hits = sum(int(r.hits) for r in req.requests)
        audit.note("forward_admitted_hits", hits)
        if self.transport == "http":
            body = self._post(
                "/v1/peer.GetPeerRateLimits", req.to_json(), timeout_s,
                check=lambda b: _count_check(len(b.get("rateLimits", []))),
                wire_hits=hits,
            )
            resp = GetRateLimitsResponse.from_json(
                {"responses": body.get("rateLimits", [])}
            )
        else:
            m = self._grpc_call(
                "GetPeerRateLimits",
                wire.peer_rate_limits_req_to_pb(req),
                timeout_s,
                allow_closing=_draining,
                check=lambda m: _count_check(len(m.rate_limits)),
                wire_hits=hits,
            )
            resp = wire.peer_rate_limits_resp_from_pb(m)
        return resp

    def update_peer_globals(
        self, updates: Sequence[UpdatePeerGlobal], timeout_s: Optional[float] = None
    ) -> None:
        """PeersV1.UpdatePeerGlobals, classic per-item encoding (the
        legacy dataclass API; the GlobalManager's fan-out sends
        update_peer_globals_batch, which negotiates the columnar
        encoding and caches each encode across peers)."""
        if self.transport == "http":
            payload = {"globals": [u.to_json() for u in updates]}
            self._post("/v1/peer.UpdatePeerGlobals", payload, timeout_s)
        else:
            self._grpc_call(
                "UpdatePeerGlobals", wire.update_globals_req_to_pb(updates), timeout_s
            )

    def update_peer_globals_batch(
        self, batch: "wire.BroadcastBatch", timeout_s: Optional[float] = None,
        trace_ctx=None,
    ) -> None:
        """One GLOBAL broadcast send from a pre-encoded BroadcastBatch
        (encode-once fan-out: every peer reuses the same cached wire
        bytes).  Encoding negotiates per peer like the forward hop:
        proto columns (gRPC UpdatePeerGlobalsColumns) / the GUBC
        globals frame (HTTP, same /v1/peer.UpdatePeerGlobals path)
        first; a peer that answers UNIMPLEMENTED / 4xx is remembered as
        classic-only and resent the per-item encoding inside the same
        guarded call — the probe is breaker- and health-neutral.
        `trace_ctx` links the per-peer peer.rpc client span into the
        tick's global.sync trace (tracing.py)."""
        if self._shutdown.is_set():
            raise PeerError(ERR_CLOSING, not_ready=True)
        t0 = time.monotonic_ns()
        rpc_err: Optional[Exception] = None
        try:
            if self.transport == "http":
                self._guarded_call(
                    "UpdatePeerGlobals",
                    lambda: self._post_globals_inner(batch, timeout_s),
                )
            else:
                self._guarded_call(
                    "UpdatePeerGlobals",
                    lambda: self._grpc_globals_inner(batch, timeout_s),
                )
        except Exception as e:  # noqa: BLE001 — re-raised below
            rpc_err = e
            raise
        finally:
            if trace_ctx is not None:
                bt = tracing.new_batch([trace_ctx])
                if bt is not None:
                    attrs = dict(
                        peer=self.info.grpc_address,
                        op="UpdatePeerGlobals",
                        items=len(batch),
                        encoding=(
                            "columns" if self._globals_columnar else "classic"
                        ),
                    )
                    if rpc_err is not None:
                        attrs["error"] = str(rpc_err)
                    tracing.record_span(
                        "peer.rpc", bt.ctx,
                        start_ns=t0, end_ns=time.monotonic_ns(),
                        links=bt.links, **attrs,
                    )
        if self._metrics is not None:
            self._metrics.global_broadcast_batches.labels(
                encoding="columns" if self._globals_columnar else "classic"
            ).inc()

    def _grpc_globals_inner(self, batch: "wire.BroadcastBatch",
                            timeout_s: Optional[float]) -> None:
        """Columnar UpdatePeerGlobals over gRPC, falling back to the
        classic per-item message on UNIMPLEMENTED (the method never
        executed, so the classic resend cannot double-apply)."""
        timeout = (
            timeout_s if timeout_s is not None else self.behaviors.batch_timeout_s
        )
        try:
            _get_rl, upd, _get_cols, upd_cols = self._ensure_channel()
            if self._globals_columnar is not False:
                try:
                    upd_cols(batch.columns_pb(), timeout=timeout)
                    self._globals_columnar = True
                    return
                except grpc.RpcError as e:
                    code = e.code() if hasattr(e, "code") else None
                    if code == grpc.StatusCode.UNIMPLEMENTED:
                        self._globals_columnar = False
                    else:
                        raise
            upd(batch.classic_pb(), timeout=timeout)
        except grpc.RpcError as e:
            raise self._wrap_grpc_error("UpdatePeerGlobals", e) from e
        except ValueError as e:
            raise self._wrap_value_error("UpdatePeerGlobals", e) from e

    def _post_globals_inner(self, batch: "wire.BroadcastBatch",
                            timeout_s: Optional[float]) -> None:
        """Columnar UpdatePeerGlobals over HTTP: the GUBC globals frame
        against the same /v1/peer.UpdatePeerGlobals path (the receiver
        sniffs the magic).  An old peer rejects the frame — 4xx from
        its JSON parse, or the pre-columns gateway's 500 naming the
        codec failure — which proves it was not applied, so the classic
        per-item JSON resend inside this same guarded call is safe and
        the probe stays breaker/health-neutral."""
        if self._globals_columnar is not False:
            try:
                self._http_roundtrip(
                    "/v1/peer.UpdatePeerGlobals", batch.frame(), timeout_s,
                    wire.COLUMNS_CONTENT_TYPE,
                )
                self._globals_columnar = True
                return
            except PeerError as e:
                rejected = e.http_status in (400, 404, 415) or (
                    e.http_status == 500 and "codec can't decode" in str(e)
                )
                if not rejected:
                    raise
                self._globals_columnar = False
                # A benign version probe, not a peer failure: it must
                # not leave HealthCheck unhealthy for 5 minutes.
                self._clear_last_err(str(e))
        self._http_roundtrip(
            "/v1/peer.UpdatePeerGlobals", batch.classic_json_bytes(),
            timeout_s, "application/json",
        )

    # ------------------------------------------------------------------
    def update_region_columns(
        self, batch, timeout_s: Optional[float] = None, trace_ctx=None,
    ) -> None:
        """One cross-region hit send from a pre-encoded
        federation.RegionBatch (encode-once fan-out: every region's
        owner reuses the same cached wire bytes).  Encoding negotiates
        per peer like the other planes: proto columns (gRPC
        UpdateRegionColumns) / the GUBC kind-7 frame (HTTP,
        /v1/peer.UpdateRegionColumns) first; a peer that answers
        UNIMPLEMENTED / 404 is remembered as classic-only and resent
        the per-item GetPeerRateLimits encoding — the exact
        pre-federation wire — inside the same guarded call, so the
        probe is breaker- and health-neutral.

        Conservation accounting (audit.py): the batch's hits are noted
        `region_admitted_hits` once per logical send here, and
        `region_wire_hits` once per delivery that reached the peer
        (the guarded call's wire counter) — a FaultPlan DUPLICATE
        delivery doubles the wire side and trips region_conservation."""
        if self._shutdown.is_set():
            raise PeerError(ERR_CLOSING, not_ready=True)
        hits = batch.total_hits()
        audit.note("region_admitted_hits", hits)
        t0 = time.monotonic_ns()
        rpc_err: Optional[Exception] = None
        try:
            if self.transport == "http":
                self._guarded_call(
                    "UpdateRegionColumns",
                    lambda: self._post_region_inner(batch, timeout_s),
                    wire_hits=hits, wire_counter="region_wire_hits",
                )
            else:
                self._guarded_call(
                    "UpdateRegionColumns",
                    lambda: self._grpc_region_inner(batch, timeout_s),
                    wire_hits=hits, wire_counter="region_wire_hits",
                )
        except Exception as e:  # noqa: BLE001 — re-raised below
            rpc_err = e
            raise
        finally:
            if trace_ctx is not None:
                bt = tracing.new_batch([trace_ctx])
                if bt is not None:
                    attrs = dict(
                        peer=self.info.grpc_address,
                        op="UpdateRegionColumns",
                        lanes=len(batch),
                        encoding=(
                            "columns" if self._region_columnar else "classic"
                        ),
                    )
                    if rpc_err is not None:
                        attrs["error"] = str(rpc_err)
                    tracing.record_span(
                        "peer.rpc", bt.ctx,
                        start_ns=t0, end_ns=time.monotonic_ns(),
                        links=bt.links, **attrs,
                    )
        if self._metrics is not None:
            self._metrics.region_batches.labels(
                encoding="columns" if self._region_columnar else "classic"
            ).inc()

    def _grpc_region_inner(self, batch, timeout_s: Optional[float]) -> None:
        """Columnar UpdateRegionColumns over gRPC, falling back to the
        classic per-item GetPeerRateLimits chunks on UNIMPLEMENTED (the
        method never executed, so the classic resend cannot
        double-apply).  A classic chunk train that fails AFTER a chunk
        applied is no longer retry-safe: the error is re-shaped
        timeout-like (not_ready=False) so the sender drops counted
        instead of requeueing a partially-applied batch."""
        timeout = (
            timeout_s if timeout_s is not None else self.behaviors.batch_timeout_s
        )
        bb = self.blackbox
        if bb is not None and bb.live():
            # Canonical kind-7 frame of the proto send (see the
            # _grpc_columns_inner tap): per delivery, so a DUPLICATE
            # re-delivery records twice.
            bb.tap("out", self.info.grpc_address, batch.frame())
        try:
            get_rl, _upd, _get_cols, _upd_cols = self._ensure_channel()
            with self._conn_lock:
                rpc = self._rpc_update_region_columns
            if rpc is None:  # torn down by a concurrent reset
                raise PeerError(ERR_CLOSING, not_ready=True)
            if self._region_columnar is not False:
                try:
                    rpc(batch.columns_pb(), timeout=timeout)
                    self._region_columnar = True
                    return
                except grpc.RpcError as e:
                    code = e.code() if hasattr(e, "code") else None
                    if code == grpc.StatusCode.UNIMPLEMENTED:
                        self._region_columnar = False
                    else:
                        raise
            applied_any = False
            try:
                for m in batch.classic_pb_chunks(self._classic_cap):
                    get_rl(m, timeout=timeout)
                    applied_any = True
            except grpc.RpcError as e:
                err = self._wrap_grpc_error("UpdateRegionColumns", e)
                if applied_any:
                    err.not_ready = False
                raise err from e
        except PeerError:
            raise
        except grpc.RpcError as e:
            raise self._wrap_grpc_error("UpdateRegionColumns", e) from e
        except ValueError as e:
            raise self._wrap_value_error("UpdateRegionColumns", e) from e

    def _post_region_inner(self, batch, timeout_s: Optional[float]) -> None:
        """Region send over HTTP: the GUBC kind-7 frame against
        /v1/peer.UpdateRegionColumns.  An old peer (or
        GUBER_REGION_COLUMNS=0) has no handler on that path — 404,
        provably unapplied — so the classic per-item JSON resend to
        /v1/peer.GetPeerRateLimits inside this same guarded call is
        safe and the probe stays breaker/health-neutral.  Same
        partial-apply rule as the gRPC twin: a chunk-train failure
        after an applied chunk presents timeout-shaped."""
        if self._region_columnar is not False:
            try:
                self._http_roundtrip(
                    "/v1/peer.UpdateRegionColumns", batch.frame(), timeout_s,
                    wire.COLUMNS_CONTENT_TYPE,
                )
                self._region_columnar = True
                return
            except PeerError as e:
                rejected = e.http_status in (400, 404, 415, 501) or (
                    e.http_status == 500 and "codec can't decode" in str(e)
                )
                if not rejected:
                    raise
                self._region_columnar = False
                # A benign version probe, not a peer failure: it must
                # not leave HealthCheck unhealthy for 5 minutes.
                self._clear_last_err(str(e))
        applied_any = False
        try:
            for body in batch.classic_json_chunks(self._classic_cap):
                self._http_roundtrip(
                    "/v1/peer.GetPeerRateLimits", body, timeout_s,
                    "application/json",
                )
                applied_any = True
        except PeerError as e:
            if applied_any:
                e.not_ready = False
            raise

    # ------------------------------------------------------------------
    def transfer_ownership(
        self, cols, timeout_s: Optional[float] = None
    ) -> str:
        """Ship one ownership-transfer batch (reshard.TransferColumns)
        to this peer — the new owner of the batch's keys after a ring
        delta.  Returns:

          * "ok"          — the peer merge-committed the batch.
          * "unsupported" — the peer has no transfer surface
            (pre-reshard build or GUBER_RESHARD=0).  Sticky per client
            and breaker/health-neutral: a version answer, not a fault.
          * "fenced"      — the peer's ring changed again and it
            rejected this dead-epoch batch (FAILED_PRECONDITION / 409).
            Also breaker/health-neutral — the fence is the protocol
            working, not the peer failing.

        Raises PeerError on real transport failures (breaker-counted).
        The receive-side commit is monotone/idempotent, so retrying a
        timeout-shaped failure can never double-count."""
        if self._shutdown.is_set():
            raise PeerError(ERR_CLOSING, not_ready=True)
        if self._transfer_supported is False:
            return "unsupported"
        if self.transport == "http":
            return self._guarded_call(
                "TransferOwnership",
                lambda: self._post_transfer_inner(cols, timeout_s),
            )
        return self._guarded_call(
            "TransferOwnership",
            lambda: self._grpc_transfer_inner(cols, timeout_s),
        )

    def _grpc_transfer_inner(self, cols, timeout_s: Optional[float]) -> str:
        timeout = (
            timeout_s if timeout_s is not None else self.behaviors.batch_timeout_s
        )
        try:
            self._ensure_channel()
            with self._conn_lock:
                rpc = self._rpc_transfer_ownership
            if rpc is None:  # torn down by a concurrent reset
                raise PeerError(ERR_CLOSING, not_ready=True)
            try:
                rpc(wire.transfer_cols_to_pb(cols), timeout=timeout)
                self._transfer_supported = True
                return "ok"
            except grpc.RpcError as e:
                code = e.code() if hasattr(e, "code") else None
                if code == grpc.StatusCode.UNIMPLEMENTED:
                    # The method never executed: remember and let the
                    # caller fall back to classic (pre-reshard)
                    # semantics; the probe is breaker/health-neutral.
                    self._transfer_supported = False
                    return "unsupported"
                if code == grpc.StatusCode.FAILED_PRECONDITION:
                    return "fenced"
                raise
        except grpc.RpcError as e:
            raise self._wrap_grpc_error("TransferOwnership", e) from e
        except ValueError as e:
            raise self._wrap_value_error("TransferOwnership", e) from e

    def _post_transfer_inner(self, cols, timeout_s: Optional[float]) -> str:
        """Transfer over HTTP: the GUBC transfer frame against
        /v1/peer.TransferOwnership.  An old peer (or GUBER_RESHARD=0)
        has no handler on that path — 404, provably unapplied — and a
        receiver that fenced the epoch answers 409; both are remembered
        /returned without counting against breaker or health."""
        try:
            self._http_roundtrip(
                "/v1/peer.TransferOwnership",
                wire.encode_transfer_frame(cols),
                timeout_s, wire.COLUMNS_CONTENT_TYPE,
            )
            self._transfer_supported = True
            return "ok"
        except PeerError as e:
            if e.http_status in (400, 404, 415, 501):
                self._transfer_supported = False
                self._clear_last_err(str(e))
                return "unsupported"
            if e.http_status == 409:
                self._clear_last_err(str(e))
                return "fenced"
            raise

    # ------------------------------------------------------------------
    def _send_batch(self, batch: List[tuple]) -> None:
        """peer_client.go:316-348 sendQueue, columnar: concatenate the
        queued column sub-batches and send ONE columnar RPC per chunk.
        The chunk cap is what the peer is KNOWN to accept: a confirmed
        columns speaker takes PEER_COLUMNS_MAX_LANES; an unconfirmed or
        classic peer takes MAX_BATCH_SIZE (the probe that discovers an
        old peer falls back to the classic encoding inside the same
        call, so the probe chunk must already satisfy the classic cap).
        Waiters get (shared result, lo, hi) slices."""
        cap = (
            self._columns_cap if self._columnar is True
            else self._classic_cap
        )
        chunk: List[tuple] = []
        lanes = 0
        for item in batch:
            n = len(item[0][0])
            if chunk and lanes + n > cap:
                self._send_chunk(chunk)
                chunk, lanes = [], 0
                # A probe chunk may just have confirmed columns
                # support; later chunks of the same flush coalesce up
                # to the full columnar cap right away.
                cap = (
                    self._columns_cap if self._columnar is True
                    else self._classic_cap
                )
            chunk.append(item)
            lanes += n
        if chunk:
            self._send_chunk(chunk)

    def _mark_classic(self) -> None:
        """The peer negotiated down to the classic encoding: remember,
        and shrink the coalescing window to the classic per-RPC cap so
        future flushes are ONE RPC each — without this, a 16k-lane
        window against a classic peer becomes a train of sequential
        chunk RPCs whose late waiters outlive their timeout budget."""
        self._columnar = False
        self._window.limit = self._classic_cap

    def _classic_resend(self, cols: "wire.PeerColumns", send_chunk):
        """Downgraded resend shared by both transports: re-chunk a
        (possibly columnar-cap-sized) batch to the classic per-RPC cap
        and send each chunk with `send_chunk(sub) -> ColumnarResult`,
        concatenating the results lane-aligned."""
        n_total = len(cols[0])
        cap = self._classic_cap
        parts = []
        for lo in range(0, n_total, cap):
            parts.append(
                send_chunk(
                    wire.peer_columns_slice(cols, lo, min(lo + cap, n_total))
                )
            )
        return wire.concat_results(parts)

    def _trace_entries(self, chunk: List[tuple]):
        """Wire trace-context entries for a chunk: one lane-range entry
        per SAMPLED sub-batch (all lanes of one ingress submission share
        its context).  Returns (entries | None, link contexts)."""
        if not tracing.enabled():
            return None, ()
        entries, links, lo = [], [], 0
        for c, fut in chunk:
            hi = lo + len(c[0])
            ctx = getattr(fut, "_trace_ctx", None)
            if ctx is not None:
                entries.append((lo, hi, ctx.trace_id, ctx.span_id))
                links.append(ctx)
            lo = hi
        return (entries or None), links

    def _send_chunk(self, chunk: List[tuple]) -> None:
        try:
            if len(chunk) == 1:
                cols = chunk[0][0]
            else:
                cols = (
                    [s for c, _ in chunk for s in c[0]],
                    [s for c, _ in chunk for s in c[1]],
                    *(
                        np.concatenate([c[i] for c, _ in chunk])
                        for i in range(2, 7)
                    ),
                )
            trace, links = self._trace_entries(chunk)
            t0 = time.monotonic_ns()
            rpc_err = None
            try:
                with profiling.scope("peer.rpc"):
                    rc = self._send_columns(
                        cols, self.behaviors.batch_timeout_s, _draining=True,
                        trace=trace,
                    )
            except Exception as e:  # noqa: BLE001 — re-raised below
                rpc_err = e
                raise
            finally:
                # Always-on attribution: the forwarded hop's round trip
                # is one of the waterfall's phases (saturation.py).
                saturation.observe_phase(
                    "peer.rpc", (time.monotonic_ns() - t0) / 1e9
                )
                bt = tracing.new_batch(links)
                if bt is not None:
                    # The client half of the cross-daemon hop: one span
                    # for the RPC, linked to every sampled sub-batch it
                    # coalesced (one RPC carries many traces — link,
                    # not nest).  A failed RPC stamps the error — the
                    # span must not read as a completed round trip.
                    attrs = dict(
                        peer=self.info.grpc_address,
                        lanes=len(cols[0]),
                        encoding="columns" if self._columnar else "classic",
                    )
                    if rpc_err is not None:
                        attrs["error"] = str(rpc_err)
                    tracing.record_span(
                        "peer.rpc", bt.ctx,
                        start_ns=t0, end_ns=time.monotonic_ns(),
                        links=links, **attrs,
                    )
        except Exception as e:  # noqa: BLE001
            for _, fut in chunk:
                if not fut.done():
                    fut.set_exception(e)
            return
        lo = 0
        for c, fut in chunk:
            hi = lo + len(c[0])
            if not fut.done():
                fut.set_result((rc, lo, hi))
            lo = hi

    def _send_columns(self, cols: "wire.PeerColumns",
                      timeout_s: Optional[float], _draining: bool = False,
                      trace=None):
        """One columnar GetPeerRateLimits over the configured transport
        (negotiating the encoding, see _columnar).  Returns a decoded
        service.ColumnarResult of exactly len(cols) lanes.  `trace`
        (wire.TraceEntry list) rides the columnar encodings only — the
        classic fallback drops it, pre-columns peers never see trace
        bytes."""
        n = len(cols[0])

        def _count_check(rc) -> None:
            # Inside the _guarded_call region: a wrong-count reply
            # trips the breaker like any transport failure.
            if rc.n != n:
                msg = (
                    f"GetPeerRateLimits to peer {self.info.grpc_address} "
                    f"returned {rc.n} rate limits for {n} requests"
                )
                self._set_last_err(msg)
                raise PeerError(msg)

        # Conservation ledger (audit.py): hits ADMITTED to the forward
        # wire, counted once per logical batch send; the per-delivery
        # twin (forward_wire_hits) is counted inside the guarded call.
        hits = int(cols[4].sum())
        audit.note("forward_admitted_hits", hits)
        if self.transport == "http":
            if self._shutdown.is_set() and not _draining:
                raise PeerError(ERR_CLOSING, not_ready=True)
            rc = self._guarded_call(
                "GetPeerRateLimits",
                lambda: self._post_columns_inner(cols, timeout_s, trace),
                _count_check,
                wire_hits=hits,
            )
        else:
            if self._shutdown.is_set() and not _draining:
                raise PeerError(ERR_CLOSING, not_ready=True)
            rc = self._guarded_call(
                "GetPeerRateLimits",
                lambda: self._grpc_columns_inner(cols, timeout_s, trace),
                _count_check,
                wire_hits=hits,
            )
        if self._metrics is not None:
            self._metrics.peer_columns_batches.labels(
                encoding="columns" if self._columnar else "classic"
            ).inc()
        return rc

    # ------------------------------------------------------------------
    # gRPC transport (lazy channel = peer_client.go:87-132 connect())
    # ------------------------------------------------------------------
    def _ensure_channel(self):
        """Returns (get_peer_rate_limits, update_peer_globals,
        get_peer_rate_limits_columns, update_peer_globals_columns)
        stubs, building the channel lazily.  The stubs are captured and
        returned under the lock: _reset_channel may null the attributes
        concurrently (a racing thread observing a torn state must not
        see None)."""
        with self._conn_lock:
            if self._channel is None:
                target = self.info.grpc_address
                options = [("grpc.max_receive_message_length", 1024 * 1024)]
                if self.channel_credentials is not None:
                    self._channel = grpc.secure_channel(
                        target, self.channel_credentials, options=options
                    )
                else:
                    self._channel = grpc.insecure_channel(target, options=options)
                self._rpc_get_peer_rate_limits = self._channel.unary_unary(
                    f"/{PEERS_V1_SERVICE}/GetPeerRateLimits",
                    request_serializer=peers_pb.GetPeerRateLimitsReq.SerializeToString,
                    response_deserializer=peers_pb.GetPeerRateLimitsResp.FromString,
                )
                self._rpc_get_peer_rate_limits_columns = self._channel.unary_unary(
                    f"/{PEERS_V1_SERVICE}/GetPeerRateLimitsColumns",
                    request_serializer=pc_pb.PeerColumnsReq.SerializeToString,
                    response_deserializer=pc_pb.PeerColumnsResp.FromString,
                )
                self._rpc_update_peer_globals = self._channel.unary_unary(
                    f"/{PEERS_V1_SERVICE}/UpdatePeerGlobals",
                    request_serializer=peers_pb.UpdatePeerGlobalsReq.SerializeToString,
                    response_deserializer=peers_pb.UpdatePeerGlobalsResp.FromString,
                )
                self._rpc_update_peer_globals_columns = self._channel.unary_unary(
                    f"/{PEERS_V1_SERVICE}/UpdatePeerGlobalsColumns",
                    request_serializer=pc_pb.GlobalsColumnsReq.SerializeToString,
                    response_deserializer=peers_pb.UpdatePeerGlobalsResp.FromString,
                )
                self._rpc_transfer_ownership = self._channel.unary_unary(
                    f"/{PEERS_V1_SERVICE}/TransferOwnership",
                    request_serializer=pc_pb.TransferColumnsReq.SerializeToString,
                    response_deserializer=pc_pb.TransferResp.FromString,
                )
                self._rpc_update_region_columns = self._channel.unary_unary(
                    f"/{PEERS_V1_SERVICE}/UpdateRegionColumns",
                    request_serializer=pc_pb.RegionColumnsReq.SerializeToString,
                    response_deserializer=pc_pb.RegionColumnsResp.FromString,
                )
            return (
                self._rpc_get_peer_rate_limits,
                self._rpc_update_peer_globals,
                self._rpc_get_peer_rate_limits_columns,
                self._rpc_update_peer_globals_columns,
            )

    # ------------------------------------------------------------------
    # Fault-tolerance wrap: every transport call passes the breaker gate
    # then the installed fault plan (faults.py) before touching the wire.
    # ------------------------------------------------------------------
    def _on_breaker_transition(self, state: str) -> None:
        if self._metrics is not None:
            self._metrics.circuit_transitions.labels(
                peer=self.info.grpc_address, to=state
            ).inc()
        if state == "open":
            # Flight-recorder event + automatic dump (tracing.py): the
            # recorder's last-N spans are exactly the context a breaker
            # trip needs preserved before traffic moves on.
            tracing.record_event(
                "breaker-open", peer=self.info.grpc_address
            )

    def _breaker_gate(self, op: str) -> None:
        """Raise the circuit-open fast-fail, or reserve the call slot
        (every non-raising return MUST be paired with exactly one
        breaker.record_success/record_failure)."""
        if not self.breaker.allow():
            raise PeerError(
                f"{op} to peer {self.info.grpc_address} rejected: "
                f"circuit breaker open",
                not_ready=True,
                circuit_open=True,
            )

    def _fault_check(self, op: str) -> bool:
        """Consult the fault plan (instance-level, else the process-wide
        installed one).  An injected ERROR/DROP raises the same
        PeerError shape a real transport failure would — downstream
        retry/breaker/health behavior is exercised for real.  Returns
        True when a DUPLICATE rule fired: the guarded call delivers the
        transport call twice (byzantine re-delivery chaos)."""
        fp = self.faults if self.faults is not None else faults_mod.active()
        if fp is None:
            return False
        act = fp.intercept(self.info.grpc_address, op)
        if act is None:
            return False
        if act.kind == faults_mod.DELAY:
            time.sleep(act.delay_s)
            return False
        if act.kind == faults_mod.DUPLICATE:
            tracing.record_event(
                "fault", op=op, peer=self.info.grpc_address,
                kind_detail=act.kind,
            )
            return True
        msg = f"{op} to peer {self.info.grpc_address} failed: {act.message}"
        self._set_last_err(msg)
        tracing.record_event(
            "fault", op=op, peer=self.info.grpc_address, kind_detail=act.kind
        )
        raise PeerError(msg, not_ready=act.not_ready)

    def _attempt(self, fn, wire_hits: int,
                 wire_counter: str = "forward_wire_hits"):
        """One transport delivery, conservation-accounted: the attempt
        counts its hits into the audit ledger when it REACHED the peer —
        a normal return, or a failure past the point of no return (a
        timeout-ambiguous error: the RPC may have applied server-side).
        Provably-unapplied failures (connection-level not_ready, the
        breaker's own fast-fail) never left this host, so they don't
        count — which is exactly why a legitimate retry/re-pick after
        one keeps `wire <= admitted` intact while a DUPLICATE delivery
        breaks it.  `wire_counter` names the ledger counter (the
        forward hop and the region plane keep separate pairs)."""
        try:
            out = fn()
        except BaseException as e:
            if wire_hits and not (
                isinstance(e, PeerError) and e.not_ready
            ):
                audit.note(wire_counter, wire_hits)
            raise
        if wire_hits:
            audit.note(wire_counter, wire_hits)
        return out

    def _guarded_call(self, op: str, fn, check=None, wire_hits: int = 0,
                      wire_counter: str = "forward_wire_hits"):
        """The breaker protocol, shared by BOTH transports: gate ->
        injected-fault check -> fn() -> optional reply check -> record.
        Every non-raising _breaker_gate() pairs with exactly one
        record_success/record_failure (the half-open probe slot,
        faults.CircuitBreaker).  `check` runs INSIDE the guarded region
        so a structurally bad reply (wrong response count) counts as a
        breaker failure like any transport error, instead of resetting
        the failure streak before the caller notices.  `wire_hits` is
        the batch's hit total for the conservation ledger (audit.py):
        counted once per delivery that reached the peer, into
        `wire_counter`."""
        self._breaker_gate(op)
        try:
            dup = self._fault_check(op)
            out = (
                fn() if not wire_hits
                else self._attempt(fn, wire_hits, wire_counter)
            )
            if dup:
                # The injected re-delivery: the duplicate's OWN failure
                # is swallowed (a dropped duplicate is a clean network
                # again) and its result discarded — but its hits reached
                # the peer, which the ledger must see.
                try:
                    self._attempt(fn, wire_hits, wire_counter)
                except Exception:  # noqa: BLE001 — duplicate lost in flight
                    pass
            if check is not None:
                check(out)
        except BaseException:
            self.breaker.record_failure()
            raise
        self.breaker.record_success()
        return out

    def _grpc_call(self, method: str, request, timeout_s: Optional[float],
                   allow_closing: bool = False, check=None,
                   wire_hits: int = 0):
        if self._shutdown.is_set() and not allow_closing:
            raise PeerError(ERR_CLOSING, not_ready=True)
        return self._guarded_call(
            method, lambda: self._grpc_inner(method, request, timeout_s),
            check, wire_hits=wire_hits,
        )

    def _grpc_inner(self, method: str, request, timeout_s: Optional[float]):
        try:
            get_rl, update_g, _, _ = self._ensure_channel()
            rpc = get_rl if method == "GetPeerRateLimits" else update_g
            timeout = (
                timeout_s if timeout_s is not None else self.behaviors.batch_timeout_s
            )
            return rpc(request, timeout=timeout)
        except grpc.RpcError as e:
            raise self._wrap_grpc_error(method, e) from e
        except ValueError as e:
            raise self._wrap_value_error(method, e) from e

    def _grpc_columns_inner(self, cols: "wire.PeerColumns",
                            timeout_s: Optional[float], trace=None):
        """Columnar GetPeerRateLimits over gRPC: proto columns against
        the peer's GetPeerRateLimitsColumns method; an UNIMPLEMENTED
        answer from an untried peer downgrades to the classic
        per-request encoding (same guarded call — the negotiation miss
        is not a breaker failure).  The trace column rides as a proto3
        field old receivers skip as unknown — no trace negotiation on
        this transport."""
        timeout = (
            timeout_s if timeout_s is not None else self.behaviors.batch_timeout_s
        )
        bb = self.blackbox
        if bb is not None and bb.live():
            # gRPC carries proto columns, not GUBC bytes — capture the
            # canonical frame encoding of the same columns so the ring
            # stays replayable.  Tapped here (per delivery, inside the
            # guarded call) so a DUPLICATE re-delivery records twice.
            bb.tap("out", self.info.grpc_address,
                   wire.encode_columns_frame(cols, trace=trace))
        try:
            get_rl, _upd, get_cols, _ = self._ensure_channel()
            if self._columnar is not False:
                try:
                    m = get_cols(
                        wire.peer_columns_req_to_pb(cols, trace=trace),
                        timeout=timeout,
                    )
                    self._columnar = True
                    return wire.result_from_peer_columns_pb(m)
                except grpc.RpcError as e:
                    code = e.code() if hasattr(e, "code") else None
                    if code == grpc.StatusCode.UNIMPLEMENTED:
                        # Old (or in-place downgraded, even after a
                        # confirmed columnar run) peer: UNIMPLEMENTED
                        # means the method never executed, so the
                        # classic resend below cannot double-count.
                        self._mark_classic()
                    else:
                        raise
            return self._classic_resend(
                cols,
                lambda sub: wire.result_from_classic_peer_pb(
                    get_rl(wire.peer_columns_to_classic_pb(sub), timeout=timeout)
                ),
            )
        except grpc.RpcError as e:
            raise self._wrap_grpc_error("GetPeerRateLimits", e) from e
        except ValueError as e:
            raise self._wrap_value_error("GetPeerRateLimits", e) from e

    def _wrap_grpc_error(self, method: str, e: grpc.RpcError) -> "PeerError":
        code = e.code() if hasattr(e, "code") else None
        msg = f"{method} to peer {self.info.grpc_address} failed: {code}: {e.details() if hasattr(e, 'details') else e}"
        self._set_last_err(msg)
        # Drop the channel so the next call redials immediately
        # instead of sitting in gRPC's reconnect backoff (the lazy
        # reconnect of peer_client.go:87-132; a restarted peer at
        # the same address must be reachable right away).
        if code == grpc.StatusCode.UNAVAILABLE:
            self._reset_channel()
        return PeerError(msg, not_ready=code in _NOT_READY_CODES)

    def _wrap_value_error(self, method: str, e: ValueError) -> "PeerError":
        """Two ValueError sources meet here: grpc's bare "Cannot invoke
        RPC: Channel closed!" from a shutdown racing a call (presented
        as the closing error, not a crash), and a reply that failed to
        decode (mismatched column lengths, corrupt payload) — a peer
        failure that must be recorded like any other so HealthCheck
        surfaces the misbehaving peer."""
        if "closed" in str(e).lower():
            return PeerError(ERR_CLOSING, not_ready=True)
        msg = f"{method} to peer {self.info.grpc_address} failed: {e}"
        self._set_last_err(msg)
        return PeerError(msg)

    def _reset_channel(self) -> None:
        with self._conn_lock:
            if self._channel is not None:
                self._channel.close()
                self._channel = None
                self._rpc_get_peer_rate_limits = None
                self._rpc_update_peer_globals = None
                self._rpc_get_peer_rate_limits_columns = None
                self._rpc_update_peer_globals_columns = None
                self._rpc_transfer_ownership = None
                self._rpc_update_region_columns = None

    # ------------------------------------------------------------------
    # HTTP/JSON fallback transport (the peer's gateway surface)
    # ------------------------------------------------------------------
    def _post(self, path: str, payload: dict, timeout_s: Optional[float],
              check=None, wire_hits: int = 0) -> dict:
        op = path.rpartition(".")[2]  # /v1/peer.GetPeerRateLimits -> op
        return self._guarded_call(
            op, lambda: self._post_inner(path, payload, timeout_s), check,
            wire_hits=wire_hits,
        )

    def _post_inner(self, path: str, payload: dict, timeout_s: Optional[float]) -> dict:
        body = self._http_roundtrip(
            path, json.dumps(payload).encode("utf-8"), timeout_s,
            "application/json",
        )
        return json.loads(body) if body else {}

    def _post_columns_inner(self, cols: "wire.PeerColumns",
                            timeout_s: Optional[float], trace=None):
        """Columnar GetPeerRateLimits over HTTP: the binary frame
        against the same /v1/peer.GetPeerRateLimits path (the receiver
        sniffs the magic).  An old peer answers 400 (its JSON parse
        fails) — remember and resend as classic per-request JSON inside
        the same guarded call.

        Trace trailer negotiation: the first SAMPLED frame to an
        untried peer probes with the trailer attached.  A columns-
        capable peer that predates it rejects the frame as a length
        mismatch (400, provably not applied) — remember trailer-free
        and resend the SAME frame without it, still inside this guarded
        call, so the probe is breaker- and health-neutral like the
        columns probe itself.  Unsampled traffic never probes: with
        GUBER_TRACE_SAMPLE=0 the wire is byte-identical to pre-trace."""
        if self._columnar is not False:
            with_trace = bool(trace) and self._trace_frames is not False
            frame = wire.encode_columns_frame(
                cols, trace=trace if with_trace else None
            )
            try:
                body = self._http_roundtrip(
                    "/v1/peer.GetPeerRateLimits", frame, timeout_s,
                    wire.COLUMNS_CONTENT_TYPE,
                )
            except PeerError as e:
                if (
                    with_trace
                    and e.http_status == 400
                    and "length mismatch" in str(e)
                ):
                    # Columns peer that predates the trace trailer: the
                    # decode rejected the frame before applying it, so
                    # the trailer-free resend cannot double-count.
                    self._trace_frames = False
                    self._clear_last_err(str(e))
                    return self._post_columns_inner(cols, timeout_s)
                # Downgrade when the frame was provably REJECTED, not
                # applied (safe to resend classic): a 4xx, or the old
                # gateway's 500 — pre-columns builds map the
                # UnicodeDecodeError json.loads raises on the frame's
                # binary columns to a 500 whose body names the codec
                # failure, so that exact shape is a version answer too.
                rejected = e.http_status in (400, 404, 415) or (
                    e.http_status == 500 and "codec can't decode" in str(e)
                )
                if rejected:
                    self._mark_classic()
                    # A benign version probe, not a peer failure: it
                    # must not leave HealthCheck unhealthy for 5 min.
                    self._clear_last_err(str(e))
                else:
                    raise
            else:
                if with_trace:
                    self._trace_frames = True
                if wire.is_columns_frame(body):
                    self._columnar = True
                    try:
                        return wire.decode_result_frame(body)
                    except ValueError as e:
                        msg = (
                            f"GetPeerRateLimits to peer "
                            f"{self.info.grpc_address} returned a "
                            f"malformed columns frame: {e}"
                        )
                        self._set_last_err(msg)
                        raise PeerError(msg) from e
                # 200 with a non-frame body: the peer ANSWERED (it may
                # well have applied the batch), so re-sending would
                # double-count every hit.  Fail this batch, and speak
                # classic from now on (whatever rewrote the response —
                # proxy, exotic build — clearly doesn't pass frames).
                self._mark_classic()
                msg = (
                    f"GetPeerRateLimits to peer {self.info.grpc_address} "
                    f"answered a columns frame with a non-frame 200 body"
                )
                self._set_last_err(msg)
                raise PeerError(msg)
        def _send_json_chunk(sub):
            body = self._http_roundtrip(
                "/v1/peer.GetPeerRateLimits",
                json.dumps(
                    wire.peer_columns_to_classic_json(sub)
                ).encode("utf-8"),
                timeout_s, "application/json",
            )
            return wire.result_from_classic_peer_json(
                json.loads(body) if body else {}
            )

        return self._classic_resend(cols, _send_json_chunk)

    def _http_roundtrip(self, path: str, data: bytes,
                        timeout_s: Optional[float], content_type: str) -> bytes:
        """One POST over the persistent peer connection; returns the
        raw response body.  Non-200 raises PeerError carrying the
        status (the columns negotiation reads it)."""
        timeout = timeout_s if timeout_s is not None else self.behaviors.batch_timeout_s
        host = self.info.http_address or self.info.grpc_address
        bb = self.blackbox
        if bb is not None:
            # Outbound tap BEFORE the send: a frame that times out or
            # double-delivers (FaultPlan DUPLICATE re-invokes this) is
            # exactly the evidence an incident bundle needs.
            bb.tap("out", host, data)
        with self._conn_lock:
            # not_ready marks a failure as provably-unapplied (safe to
            # retry/requeue).  That holds only until the request body
            # has been DELIVERED: a timeout while waiting for the
            # response may have executed server-side — the same reason
            # DEADLINE_EXCEEDED is excluded from _NOT_READY_CODES on
            # the gRPC transport — so post-send failures must not
            # present as retry-safe.  One exception: RemoteDisconnected
            # on a REUSED connection is the keep-alive expiry race (the
            # peer closed the idle socket before the request arrived —
            # the urllib3 retry rule), which stays retry-safe.
            fresh_conn = self._conn is None
            sent = False
            try:
                if self._conn is None:
                    hostname, _, port = host.partition(":")
                    if self.tls_context is not None:
                        self._conn = http.client.HTTPSConnection(
                            hostname, int(port or 443), timeout=timeout,
                            context=self.tls_context,
                        )
                    else:
                        self._conn = http.client.HTTPConnection(
                            hostname, int(port or 80), timeout=timeout
                        )
                self._conn.request(
                    "POST", path, body=data,
                    headers={"Content-Type": content_type},
                )
                sent = True
                r = self._conn.getresponse()
                body = r.read()
                if r.status != 200:
                    raise PeerError(
                        f"peer returned HTTP {r.status}: {body[:200]!r}",
                        http_status=r.status,
                    )
                if bb is not None:
                    bb.tap("in", host, body)
                return body
            except PeerError as e:
                self._set_last_err(str(e))
                self._reset_conn()
                raise
            except (OSError, http.client.HTTPException) as e:
                msg = f"connect to peer {host} failed: {e}"
                self._set_last_err(msg)
                self._reset_conn()
                retry_safe = not sent or (
                    not fresh_conn
                    and isinstance(e, http.client.RemoteDisconnected)
                )
                raise PeerError(msg, not_ready=retry_safe) from e

    def _reset_conn(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
            self._conn = None

    # ------------------------------------------------------------------
    def _set_last_err(self, msg: str) -> None:
        """Error LRU with TTL (peer_client.go:206-220); messages include
        the peer address for HealthCheck reporting.  Bounded at
        LAST_ERR_MAX entries: a flood of distinct error messages evicts
        the oldest instead of growing without bound between
        get_last_err() calls (reference uses a fixed-size LRU)."""
        with self._err_lock:
            key = f"{msg} (peer: {self.info.grpc_address})"
            # Re-inserting moves the key to the end: recency order.
            self._last_err.pop(key, None)
            self._last_err[key] = time.monotonic() + self.LAST_ERR_TTL_S
            while len(self._last_err) > self.LAST_ERR_MAX:
                self._last_err.pop(next(iter(self._last_err)))

    def _clear_last_err(self, msg: str) -> None:
        with self._err_lock:
            self._last_err.pop(f"{msg} (peer: {self.info.grpc_address})", None)

    def get_last_err(self) -> List[str]:
        now = time.monotonic()
        with self._err_lock:
            self._last_err = {m: t for m, t in self._last_err.items() if t > now}
            return list(self._last_err.keys())

    # ------------------------------------------------------------------
    def shutdown(self, timeout_s: float = 5.0) -> None:
        """Drain in-flight batches, then close (peer_client.go:351-385)."""
        self._shutdown.set()
        self._window.stop(timeout_s=timeout_s)
        with self._conn_lock:
            self._reset_conn()
            if self._channel is not None:
                self._channel.close()
                self._channel = None
