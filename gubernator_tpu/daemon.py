"""Daemon — process assembly (reference daemon.go).

Builds the mesh store + metrics + V1Service, serves the HTTP/JSON
gateway (client API, peer data plane, /metrics), wires peer discovery,
and handles graceful shutdown with Loader save.  `set_peers` stamps
IsOwner by advertise-address compare exactly like daemon.go:277-287.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import List, Optional, Sequence

from .config import DaemonConfig
from .gateway import GatewayServer
from .grpc_server import GrpcServer, channel_credentials
from .tls import setup_tls
from .metrics import Metrics
from .service import ServiceConfig, V1Service
from .types import PeerInfo
from .utils.clock import Clock, DEFAULT_CLOCK
from .utils.net import resolve_host_ip


class Daemon:
    def __init__(self, conf: DaemonConfig, clock: Optional[Clock] = None):
        self.conf = conf
        self.clock = clock or DEFAULT_CLOCK
        self.service: Optional[V1Service] = None
        self.gateway: Optional[GatewayServer] = None
        self.grpc: Optional[GrpcServer] = None
        self._pool = None
        self._closed = False

    # ------------------------------------------------------------------
    def start(self) -> "Daemon":
        """daemon.go:72-251.  On any startup failure, tear down whatever
        was already running — a half-started daemon must not leak bound
        ports and service threads to a retrying supervisor."""
        try:
            return self._start()
        except BaseException:
            self.close()
            raise

    def _start(self) -> "Daemon":
        # Tracing is process-wide (per-thread contexts, one flight
        # recorder); the daemon's parsed GUBER_TRACE_SAMPLE wins over
        # the module's import-time env default — unconditionally, so a
        # config that says 0 also DISABLES tracing a stale environment
        # variable turned on.
        from . import blackbox, profiling, telemetry, tracing

        tracing.set_sample_rate(self.conf.behaviors.trace_sample)
        # The incident black box's master switch is process-wide like
        # tracing; the parsed GUBER_BLACKBOX wins over the module's
        # import-time env default, in both directions.  (The rings,
        # bundle dir and budgets are per-service — V1Service builds
        # them from the behaviors below.)
        blackbox.set_enabled(self.conf.behaviors.blackbox)
        # XLA telemetry is process-wide like tracing; the parsed
        # GUBER_XLA_TELEMETRY wins over the module's import-time env
        # default, in both directions.
        telemetry.set_enabled(self.conf.behaviors.xla_telemetry)
        telemetry.set_storm(
            self.conf.behaviors.xla_storm,
            self.conf.behaviors.xla_storm_window_s,
        )
        # The continuous host profiler is process-wide like tracing;
        # the parsed GUBER_PROFILE/GUBER_PROFILE_HZ win over the
        # module's import-time env defaults, in both directions (the
        # sampler thread starts on first enable and idles at one
        # branch per tick when disabled).
        profiling.set_hz(self.conf.behaviors.profile_hz)
        profiling.set_enabled(self.conf.behaviors.profile)
        # Everything compiled from here to the end of startup warmup is
        # warmup by definition; after mark_steady() below any further
        # backend compile counts as a steady-state recompile (shape
        # churn) and can trip the recompile-storm dump.
        telemetry.begin_warmup()
        tls_conf = setup_tls(self.conf.tls)
        server_tls = tls_conf.server_ctx if tls_conf else None
        # Peer data plane credentials: gRPC channel creds unless the
        # config demands skipped verification, which only the ssl-context
        # HTTP fallback honors (PeerClient picks the transport).
        peer_creds = None
        if tls_conf is not None and not tls_conf.insecure_skip_verify:
            peer_creds = channel_credentials(tls_conf)
        metrics = Metrics()
        svc_conf = ServiceConfig(
            cache_size=self.conf.cache_size,
            back_cache_size=self.conf.back_cache_size,
            global_cache_size=self.conf.global_cache_size,
            behaviors=self.conf.behaviors,
            data_center=self.conf.data_center,
            persist_store=self.conf.store,
            loader=self.conf.loader,
            snapshot_path=getattr(self.conf, "snapshot_path", ""),
            blackbox_dir=getattr(self.conf, "blackbox_dir", ""),
            clock=self.clock,
            metrics=metrics,
            devices=self.conf.devices,
            peer_tls_context=tls_conf.client_ctx if tls_conf else None,
            peer_channel_credentials=peer_creds,
            fault_plan=self.conf.fault_plan,
        )
        self.service = V1Service(svc_conf)
        # Compile the device programs BEFORE accepting traffic: a cold
        # first dispatch (remote-tunnel compiles take tens of seconds)
        # would otherwise land inside a client's RPC deadline.
        self.service.store.warmup(
            self.clock.now_ms(), warm_shapes=self.conf.warmup_shapes
        )
        telemetry.mark_steady()
        grpc_listen = self.conf.grpc_listen_address
        if not grpc_listen:
            host, _, _ = self.conf.listen_address.partition(":")
            grpc_listen = f"{host or '127.0.0.1'}:0"
        self.grpc = GrpcServer(
            self.service, grpc_listen, tls_conf=tls_conf,
            max_conn_age_s=getattr(self.conf, "grpc_max_conn_age_s", 0),
        ).start()
        # HTTP edge selection (measured A/B in RESULTS.md round 5): the
        # C++ epoll edge (NativeGatewayServer) wins tail latency (1000-
        # lane p99 85ms -> 15ms) and per-request overhead, but on a
        # 1-core host the stdlib gateway's unbounded blocked threads
        # keep more device windows in flight and win bulk-batch
        # throughput ~15-20%.  Default is therefore the stdlib gateway;
        # GUBER_NATIVE_HTTP=1 / native_http=True opts into the native
        # edge (latency-sensitive or many-core deployments).  TLS always
        # uses the Python+ssl gateway.
        self.gateway = None
        if self.conf.native_http is True and server_tls is not None:
            raise RuntimeError(
                "GUBER_NATIVE_HTTP=1 is incompatible with TLS: the native "
                "edge has no TLS support (use the default stdlib gateway)"
            )
        if server_tls is None and self.conf.native_http is True:
            from . import native as _native
            from .gateway import NativeGatewayServer

            if not _native.available():
                raise RuntimeError(
                    f"GUBER_NATIVE_HTTP=1 but native runtime unavailable: "
                    f"{_native.build_error()}"
                )
            self.gateway = NativeGatewayServer(
                self.service, self.conf.listen_address,
                n_workers=self.conf.native_workers,
                acceptors=getattr(self.conf, "acceptors", 1),
                uds_path=getattr(self.conf, "uds_path", ""),
            )
            # Native ingress service loop (architecture.md "Native
            # service loop"): steady-state kind-5 frames run GIL-free
            # from socket to device pipeline, Python at batch
            # granularity only.  GUBER_NATIVE_INGRESS=0 = the PR 8
            # edge, behavior-identical (the interop/A-B off switch).
            if (
                self.conf.behaviors.native_ingress
                and self.service.serves_ingress_columns
            ):
                from .gateway import NativeIngressPump

                pump = NativeIngressPump(self.service).start()
                pump.update_ring()
                self.gateway.pump = pump
        if self.gateway is None:
            self.gateway = GatewayServer(
                self.service, self.conf.listen_address, tls_context=server_tls
            )
        self.gateway.start()
        # Port 0 resolves at bind time; a wildcard host — bound OR
        # explicitly configured — must be replaced by a routable IP
        # before peers see it (net.go:12-33 via config.go:249).  The
        # advertise address names the gRPC data plane (config.go:249).
        self.service.conf.advertise_address = resolve_host_ip(
            self.conf.advertise_address or self.grpc.address
        )
        self.http_advertise = resolve_host_ip(self.gateway.address)

        if self.conf.peer_discovery_type == "static":
            # A static daemon with no peer list serves standalone: it is
            # its own (sole) owner for every key.
            self.set_peers(self.conf.peers or [self.peer_info])
        elif self.conf.peer_discovery_type == "file":
            from .peers import FilePool

            self._pool = FilePool(self.conf.peers_file, on_update=self.set_peers)
        elif self.conf.peer_discovery_type in ("etcd", "member-list", "k8s"):
            from .peers import make_pool

            self._pool = make_pool(
                self.conf.peer_discovery_type,
                self.conf,
                on_update=self.set_peers,
                advertise=self.peer_info,
            )
        self.wait_for_connect()
        return self

    # ------------------------------------------------------------------
    @property
    def peer_info(self) -> PeerInfo:
        return PeerInfo(
            grpc_address=self.service.conf.advertise_address,
            http_address=self.http_advertise,
            data_center=self.conf.data_center,
        )

    def set_peers(self, peers: Sequence[PeerInfo]) -> None:
        """Stamp IsOwner by address compare, then hand to the service
        (daemon.go:277-287).  Both of this daemon's addresses count as
        "me": a static peer list naming only the HTTP address (the
        reference's lists name gRPC addresses, but a gateway-only config
        is legal here) must still self-identify.

        Late updates after close() are dropped: a discovery poller
        thread racing shutdown must not rebuild pickers (or trigger a
        resharding handoff) against a half-torn-down service."""
        if self._closed or self.service is None:
            return
        mine = {self.service.conf.advertise_address, self.http_advertise}
        stamped = []
        for p in peers:
            q = PeerInfo(
                grpc_address=p.grpc_address,
                http_address=p.http_address or p.grpc_address,
                data_center=p.data_center,
                is_owner=(p.grpc_address in mine or p.http_address in mine),
            )
            stamped.append(q)
        self.service.set_peers(stamped)

    # ------------------------------------------------------------------
    def wait_for_connect(self, timeout_s: float = 10.0) -> None:
        """Block until every listener accepts (daemon.go:305-344)."""
        deadline = time.monotonic() + timeout_s
        for address in (self.gateway.address, self.grpc.address):
            host, _, port = address.partition(":")
            while True:
                try:
                    with socket.create_connection((host, int(port)), timeout=0.5):
                        break
                except OSError:
                    if time.monotonic() >= deadline:
                        raise TimeoutError(
                            f"listener at {address} never became reachable"
                        )
                    time.sleep(0.05)

    # ------------------------------------------------------------------
    def close(self) -> None:
        """daemon.go:254-274 (Loader save happens in service.close)."""
        if self._closed:
            return
        self._closed = True
        if self._pool is not None:
            self._pool.close()
        if self.service is not None:
            self.service.close()
        if self.grpc is not None:
            self.grpc.close()
        if self.gateway is not None:
            self.gateway.close()


def spawn_daemon(conf: DaemonConfig, clock: Optional[Clock] = None) -> Daemon:
    """daemon.go:59-70."""
    return Daemon(conf, clock=clock).start()
