"""GLOBAL-behavior kernels: replica caches, hit accumulators, and the
collective sync program.

Reference model (global.go, gubernator.go:231-272, architecture.md:46-74):
a GLOBAL rate limit is owned by one peer; every other peer answers from
a local cache of the owner's last broadcast status, asynchronously
forwards aggregated hits to the owner, and the owner broadcasts
authoritative status back.  Three RPC pipelines (QueueHit->sendHits,
GetPeerRateLimits, UpdatePeerGlobals) implement this.

TPU-native redesign: "peers" are mesh shards.  GLOBAL keys get a
process-wide dense id (gslot) so every shard indexes the same [G]
replica columns.  Per shard:
  * replica columns rep_* [G]      — the owner's last broadcast status
                                     (the non-owner cache of
                                     gubernator.go:263-270, ExpireAt =
                                     ResetTime)
  * hit accumulator ghits [G]      — hits answered locally, not yet
                                     forwarded (globalManager.asyncQueue
                                     aggregation, global.go:83-91)

The answer kernel (answer_batch) extends the bucket kernel: lanes whose
replica entry is live answer from it WITHOUT touching local buckets
(gubernator.go:241-249); lanes whose entry is dead fall through to a
normal local-bucket evaluation, exactly the reference's
"process as if we own it" fallback (gubernator.go:250-254).  Either
way the lane's hits scatter-add into ghits (duplicate gslots are safe:
scatter-add commutes).

The sync program (global_sync) is ONE shard_map over the mesh replacing
all three RPC pipelines with collectives:
  1. psum(ghits)            — hit aggregation to owners
                              (replaces sendHits, global.go:120-160)
  2. owners apply the summed hits to their buckets via the bucket
     kernel (replaces GetPeerRateLimits -> getRateLimit)
  3. psum of owner-masked status — authoritative broadcast
                              (replaces broadcastPeers, global.go:198-243;
                              sum works because exactly one shard owns
                              each gslot)
  4. every shard writes its replica columns; accumulators reset.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..types import Behavior
from . import buckets
from .buckets import BucketState, RequestBatch, BatchOutput

_I64 = jnp.int64
_I32 = jnp.int32


class GlobalColumns(NamedTuple):
    """Per-shard GLOBAL state (leading axis [G] per shard).

    rep_*: cached owner-broadcast status (the RateLimitResp cache item of
    gubernator.go:263-270).  ghits: locally-accumulated unforwarded hits.
    """

    rep_status: jax.Array  # i32[G]
    rep_limit: jax.Array  # i64[G]
    rep_remaining: jax.Array  # i64[G]
    rep_reset: jax.Array  # i64[G]
    rep_expire: jax.Array  # i64[G]
    ghits: jax.Array  # i64[G]


class GlobalBatchExtra(NamedTuple):
    """Extra per-lane request columns for GLOBAL routing.

    gslot: process-wide GLOBAL key id; -1 for non-GLOBAL lanes and for
    GLOBAL lanes evaluated at their owner shard (those take the normal
    bucket path; only the dirty flag is tracked host-side).
    """

    gslot: jax.Array  # i32[B]


class SyncConfig(NamedTuple):
    """Per-gslot apply config for the sync step, host-provided (the host
    mirrors the last-seen request config per GLOBAL key, standing in for
    the full RateLimitReq the reference forwards in GetPeerRateLimits)."""

    owner_slot: jax.Array  # i32[G] owner shard's local bucket slot
    owner_shard: jax.Array  # i32[G]
    algorithm: jax.Array  # i32[G]
    behavior: jax.Array  # i32[G] (GLOBAL bit stripped host-side)
    limit: jax.Array  # i64[G]
    duration: jax.Array  # i64[G]
    greg_expire: jax.Array  # i64[G]
    greg_duration: jax.Array  # i64[G]


def clear_gslots(gcols: GlobalColumns, gslots) -> GlobalColumns:
    """Zero the rows of recycled gslots (host evicted their keys).

    Run immediately at eviction so a reused gslot can never serve the
    previous key's cached status.  Unforwarded ghits for the evicted key
    are dropped — analogous to the reference losing a key's state on LRU
    eviction (cache.go:115-130).
    """
    idx = jnp.asarray(gslots, _I32)
    return GlobalColumns(
        rep_status=gcols.rep_status.at[idx].set(0, mode="drop"),
        rep_limit=gcols.rep_limit.at[idx].set(0, mode="drop"),
        rep_remaining=gcols.rep_remaining.at[idx].set(0, mode="drop"),
        rep_reset=gcols.rep_reset.at[idx].set(0, mode="drop"),
        rep_expire=gcols.rep_expire.at[idx].set(0, mode="drop"),
        ghits=gcols.ghits.at[idx].set(0, mode="drop"),
    )


def set_replica(gcols: GlobalColumns, gslots, status, limit, remaining, reset) -> GlobalColumns:
    """Write owner-broadcast statuses into replica rows — the receive
    side of UpdatePeerGlobals (gubernator.go:259-272): the cache item is
    the resp, keyed by HashKey, expiring at ResetTime."""
    G = gcols.rep_status.shape[0]
    idx = jnp.asarray(gslots, _I32)
    idx = jnp.where(idx >= 0, idx, G)  # drop invalid (negative wraps!)
    drop = dict(mode="drop")
    return GlobalColumns(
        rep_status=gcols.rep_status.at[idx].set(jnp.asarray(status, _I32), **drop),
        rep_limit=gcols.rep_limit.at[idx].set(jnp.asarray(limit, _I64), **drop),
        rep_remaining=gcols.rep_remaining.at[idx].set(jnp.asarray(remaining, _I64), **drop),
        rep_reset=gcols.rep_reset.at[idx].set(jnp.asarray(reset, _I64), **drop),
        rep_expire=gcols.rep_expire.at[idx].set(jnp.asarray(reset, _I64), **drop),
        ghits=gcols.ghits,
    )


def init_global_columns(g_capacity: int) -> GlobalColumns:
    z64 = jnp.zeros((g_capacity,), _I64)
    return GlobalColumns(
        rep_status=jnp.zeros((g_capacity,), _I32),
        rep_limit=z64,
        rep_remaining=z64,
        rep_reset=z64,
        rep_expire=z64,
        ghits=z64,
    )


def answer_batch(
    state: BucketState,
    gcols: GlobalColumns,
    req: RequestBatch,
    extra: GlobalBatchExtra,
    now_ms,
    cold_cond: bool = True,
):
    """Unified per-shard request kernel: bucket evaluation + GLOBAL
    replica-cache short-circuit + hit accumulation.

    Returns (new_state, new_gcols, out, cached) where cached[b] marks
    lanes answered from the replica cache (no local bucket mutation —
    the host must skip its slot-table commit for those lanes).
    """
    now = jnp.asarray(now_ms, _I64)
    G = gcols.rep_status.shape[0]
    has_g = extra.gslot >= 0
    g = jnp.clip(extra.gslot, 0, G - 1)

    # Live replica entry => answer from cache (gubernator.go:241-249).
    cached = has_g & (gcols.rep_expire[g] >= now)

    # Cached lanes skip local bucket evaluation entirely.
    local_req = req._replace(slot=jnp.where(cached, -1, req.slot))
    new_state, out = buckets.apply_batch(state, local_req, now, cold_cond=cold_cond)

    status = jnp.where(cached, gcols.rep_status[g], out.status)
    limit = jnp.where(cached, gcols.rep_limit[g], out.limit)
    remaining = jnp.where(cached, gcols.rep_remaining[g], out.remaining)
    reset_time = jnp.where(cached, gcols.rep_reset[g], out.reset_time)

    # Async hit forwarding: aggregate into the accumulator
    # (globalManager.QueueHit + the sum at global.go:83-91).  Non-GLOBAL
    # lanes map to G (out of bounds) so mode='drop' drops them —
    # `.at[-1]` would wrap to the last gslot.
    gs = jnp.where(has_g, extra.gslot, G)
    new_gcols = gcols._replace(ghits=gcols.ghits.at[gs].add(req.hits, mode="drop"))

    out = BatchOutput(
        status=status,
        limit=limit,
        remaining=remaining,
        reset_time=reset_time,
        new_expire=out.new_expire,
        removed=out.removed,
        pre_expire=out.pre_expire,
    )
    return new_state, new_gcols, out, cached


def global_sync(
    state: BucketState,
    gcols: GlobalColumns,
    cfg: SyncConfig,
    dirty,  # bool[G] — this shard owns these gslots and touched them locally
    now_ms,
    *,
    axis: str,
):
    """One GLOBAL sync step for one shard, meant to run inside shard_map
    over `axis`.  Collectives replace the reference's three RPC
    pipelines (see module docstring)."""
    now = jnp.asarray(now_ms, _I64)
    my = jax.lax.axis_index(axis).astype(_I32)

    total = jax.lax.psum(gcols.ghits, axis)  # hit aggregation -> owners

    mine = cfg.owner_shard == my
    # Owners apply when there are forwarded hits or local dirt; hits==0
    # lanes are pure status reads (broadcastPeers' Hits=0 getRateLimit,
    # global.go:202-214).
    any_dirty = jax.lax.psum(jnp.where(mine & dirty, 1, 0).astype(_I32), axis) > 0
    active = (total > 0) | any_dirty
    apply_mask = mine & active & (cfg.owner_slot >= 0)

    batch = RequestBatch(
        slot=jnp.where(apply_mask, cfg.owner_slot, -1),
        exists=apply_mask,  # kernel re-validates expiry device-side
        algorithm=cfg.algorithm,
        behavior=cfg.behavior,
        hits=total,
        limit=cfg.limit,
        duration=cfg.duration,
        greg_expire=cfg.greg_expire,
        greg_duration=cfg.greg_duration,
    )
    new_state, out = buckets.apply_batch(state, batch, now)

    # Authoritative broadcast: exactly one shard owns each gslot, so a
    # masked psum is the broadcast (replaces UpdatePeerGlobals).
    def bcast(v):
        return jax.lax.psum(jnp.where(apply_mask, v, 0), axis)

    b_status = bcast(out.status.astype(_I32))
    b_limit = bcast(out.limit)
    b_remaining = bcast(out.remaining)
    b_reset = bcast(out.reset_time)
    applied = jax.lax.psum(apply_mask.astype(_I32), axis) > 0

    new_gcols = GlobalColumns(
        rep_status=jnp.where(applied, b_status, gcols.rep_status),
        rep_limit=jnp.where(applied, b_limit, gcols.rep_limit),
        rep_remaining=jnp.where(applied, b_remaining, gcols.rep_remaining),
        # Non-owner cache item expires at ResetTime (gubernator.go:268).
        rep_reset=jnp.where(applied, b_reset, gcols.rep_reset),
        rep_expire=jnp.where(applied, b_reset, gcols.rep_expire),
        ghits=jnp.zeros_like(gcols.ghits),
    )
    # `total` is returned so the host tier can forward hits for keys
    # whose authoritative owner is a REMOTE daemon (owner_shard == -1:
    # no local shard applies, but the aggregated count must reach the
    # owner via the peer transport — the sendHits leg, global.go:120-160).
    return new_state, new_gcols, out, applied, total
