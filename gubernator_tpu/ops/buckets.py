"""Vectorized token-bucket / leaky-bucket kernels over struct-of-arrays state.

This is the TPU-native replacement for the reference's per-key, mutex-
serialized algorithm functions (`algorithms.go:24-180` tokenBucket,
`algorithms.go:183-336` leakyBucket).  Instead of one Go-map lookup and
pointer mutation per request, bucket state lives as integer columns on
device and a whole request batch is evaluated in one jitted, branchless
program: gather slot rows -> select across the reference's control-flow
paths with `jnp.where` -> scatter rows back.

Semantics preserved exactly (each cited to the reference):
  * expired slot == cache miss, recreate in place      (cache.go:138-163)
  * algorithm switch resets the bucket                 (algorithms.go:54-62,196-204)
  * RESET_REMAINING: token removes the bucket, leaky refills to limit
                                                       (algorithms.go:36-47,206-208)
  * limit hot-change adds the delta to remaining, clamped at 0
                                                       (algorithms.go:70-78)
  * token duration hot-change re-derives expiry from CreatedAt and
    recreates if already expired; stored Duration is NOT updated
                                                       (algorithms.go:87-105)
  * hits == 0 is a status query                        (algorithms.go:107-110,280-283)
  * remaining == 0  -> OVER_LIMIT (token: sticky Status update)
                                                       (algorithms.go:112-117,260-264)
  * hits == remaining -> drain to exactly 0            (algorithms.go:119-124,266-271)
  * hits >  remaining -> OVER_LIMIT without mutating   (algorithms.go:126-130,273-278)
  * first hit creates the bucket; hits > limit -> OVER_LIMIT
    (token keeps remaining=limit, leaky keeps 0)       (algorithms.go:161-166,318-323)
  * leaky leak applied only when >= 1 whole token leaked
                                                       (algorithms.go:234-241)
  * leaky remaining clamped to limit                   (algorithms.go:243-245)

Divergences (documented, deliberate):
  * leaky `remaining` is fixed-point int64 (scale 2**20) instead of Go
    float64 — TPUs have no native f64.  The leak amount
    `elapsed * limit / duration` is computed EXACTLY (128-bit integer
    muldiv) where the reference double-rounds through float64
    (`rate = duration/limit; leak = elapsed/rate`), so for rates that
    are not exactly representable in binary (e.g. duration=1000,
    limit=30) the reference can under-count a leak by one whole token
    at exact multiples; this implementation is the mathematically exact
    value.  Bounded by 1 token per leak event; pinned by
    tests/test_algorithms.py::test_leaky_nonrepresentable_rate.
  * supported magnitude domain: limit and hits up to 2**43 (the
    fixed-point scale consumes 20 bits); the reference's float64 loses
    integer exactness past 2**53 anyway.
  * the reference sets the leaky expiry to `now * duration` — an obvious
    bug (algorithms.go:287); we use `now + duration` (the create path's
    `now + duration`, algorithms.go:326, applied consistently).

Time is an explicit kernel argument (`now_ms`), which is what makes the
reference's frozen-clock test strategy (functional_test.go:108-167) work
unchanged here.

Gregorian calendar values cannot be computed on device; the host
precomputes `greg_expire` / `greg_duration` per request (as the reference
does inline at algorithms.go:90-95,140-145,216-232) and the kernel
selects them when the DURATION_IS_GREGORIAN bit is set.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..types import Algorithm, Behavior, Status

# Fixed-point scale for leaky-bucket fractional remaining.
LEAKY_SCALE_BITS = 20
LEAKY_SCALE = 1 << LEAKY_SCALE_BITS

_I64 = jnp.int64
_I32 = jnp.int32
_U64 = jnp.uint64


def _muldiv128(a, b, d):
    """Exact (floor(a*b/d), a*b mod d) for 0 <= a,b < 2**63, 1 <= d < 2**63.

    `a * b` overflows int64 for legal proto values (elapsed_ms * limit),
    so the product is formed as a 128-bit (hi, lo) pair from 32x32
    partials and divided by shift-subtract long division.  The quotient
    must fit in int64 — guaranteed by callers via a <= d (=> q <= b).
    128 data-independent iterations; vectorizes cleanly across lanes.
    """
    a = a.astype(_U64)
    b = b.astype(_U64)
    d = jnp.maximum(d.astype(_U64), jnp.uint64(1))
    mask = jnp.uint64(0xFFFFFFFF)
    a_lo, a_hi = a & mask, a >> 32
    b_lo, b_hi = b & mask, b >> 32
    ll = a_lo * b_lo
    mid = a_lo * b_hi + (ll >> 32)  # no overflow: < 2**64
    mid2 = mid + a_hi * b_lo
    carry = (mid2 < mid).astype(_U64)
    lo = (mid2 << 32) | (ll & mask)
    hi = a_hi * b_hi + (mid2 >> 32) + (carry << 32)

    def body(_, st):
        r, q, hi, lo = st
        top = hi >> 63
        hi = (hi << 1) | (lo >> 63)
        lo = lo << 1
        r = (r << 1) | top
        take = r >= d
        r = jnp.where(take, r - d, r)
        q = (q << 1) | take.astype(_U64)
        return r, q, hi, lo

    z = jnp.zeros_like(a)
    r, q, _, _ = jax.lax.fori_loop(0, 128, body, (z, z, hi, lo))
    return q.astype(_I64), r.astype(_I64)


def _leak_amounts(el_c, lim_nn, rn):
    """Exact (floor(el*lim/rn), floor((el*lim mod rn) * SCALE / rn)).

    Fast path (pure int64, no loop): decompose lim = qL*rn + rL, so
    el*lim/rn = el*qL + el*rL/rn.  el <= rn (callers clip), hence
    el*qL <= lim fits; el*rL fits whenever el <= MAX64/rL.  That covers
    every realistic config (any duration < ~24.8 days, or any
    limit%duration small); only when BOTH duration > 2**31.5 ms AND
    elapsed*remainder actually overflow does the whole batch fall back
    to the 128-bit long-division loop (_muldiv128) via lax.cond — the
    branch is data-dependent, so the loop costs nothing when unused.
    """
    qL = lim_nn // rn
    rL = lim_nn % rn
    max64 = jnp.asarray((1 << 63) - 1, _I64)
    safe_rl = jnp.maximum(rL, 1)
    ok = ((rL == 0) | (el_c <= max64 // safe_rl)) & (rn < (1 << 43))

    def fast(_):
        prod = el_c * rL
        lw = el_c * qL + prod // rn
        lr = prod % rn
        frac = (lr * LEAKY_SCALE) // rn
        return lw, frac

    def slow(_):
        lw, lr = _muldiv128(el_c, lim_nn, rn)
        frac, _ = _muldiv128(lr, jnp.full_like(lr, LEAKY_SCALE), rn)
        return lw, frac

    return jax.lax.cond(jnp.all(ok), fast, slow, None)


class BucketState(NamedTuple):
    """Bucket table for one shard (capacity C), stored as TWO row-major
    int32 arrays of shape [C, 8].

    Logically each slot holds the union of the reference's
    TokenBucketItem / LeakyBucketItem (store.go:11-24) plus CacheItem
    bookkeeping (cache.go:64-76): algo, limit, remaining (leaky scaled
    by LEAKY_SCALE), duration, stamp (CreatedAt/UpdatedAt), expire_at
    (expiry-as-miss), sticky status.  Every int64 value is a lo/hi i32
    pair; algo+status pack into one flags lane (bits 0-1 algo, bit 2
    status).

    PHYSICAL layout (measured on TPU v5e, round 3): XLA's random-index
    scatter is the kernel's whole cost, and its price is per scattered
    ROW, not per element — 11 separate [C] column scatters cost ~24ms
    per 131k batch where ONE [C,8] row scatter costs ~2.7ms (and i64
    rows cost ~6x i32 rows).  So the state is two 8-lane i32 row
    tables split by write frequency:

      hot[C, 8]  — rewritten on every hit:
        0 flags, 1 remaining_lo, 2 remaining_hi, 3 stamp_lo,
        4 stamp_hi, 5 expire_lo, 6 expire_hi, 7 spare
      cold[C, 8] — rewritten only when a lane's stored config changes
                   (create, limit/duration hot-change, algo switch):
        0 limit_lo, 1 limit_hi, 2 duration_lo, 3 duration_hi, 4-7 spare

    The cold scatter is guarded by a lax.cond on "any lane changed its
    config", so steady-state traffic pays exactly one row scatter per
    batch.  The kernel recomposes int64 after the gather and decomposes
    before the scatter, so the arithmetic (and the wire formats) are
    bit-identical to the logical layout.  Host exchange uses BucketRows.
    """

    hot: jax.Array  # i32[C, 8]
    cold: jax.Array  # i32[C, 8]


# hot lane indices
_H_FLAGS, _H_REM_LO, _H_REM_HI = 0, 1, 2
_H_STAMP_LO, _H_STAMP_HI, _H_EXP_LO, _H_EXP_HI = 3, 4, 5, 6
# cold lane indices
_C_LIM_LO, _C_LIM_HI, _C_DUR_LO, _C_DUR_HI = 0, 1, 2, 3


class BucketRows(NamedTuple):
    """Logical (composed int64) row form: the host exchange format for
    Store/Loader snapshots and row injection (read_rows/write_rows)."""

    algo: jax.Array  # i32[N]
    limit: jax.Array  # i64[N]
    remaining: jax.Array  # i64[N]
    duration: jax.Array  # i64[N]
    stamp: jax.Array  # i64[N]
    expire_at: jax.Array  # i64[N]
    status: jax.Array  # i32[N]


_MASK32 = (1 << 32) - 1


def _compose64(lo, hi):
    """Exact int64 from a lo/hi int32 pair (sign lives in hi)."""
    return (hi.astype(_I64) << 32) | (lo.astype(_I64) & _MASK32)


def _lo32(v):
    return v.astype(_I32)  # modular truncation keeps the low 32 bits


def _hi32(v):
    return (v >> 32).astype(_I32)


def _pack_hot(flags, remaining, stamp, expire) -> jax.Array:
    """Stack hot row values into [N, 8] (lane order: see BucketState)."""
    z = jnp.zeros_like(flags)
    return jnp.stack(
        (
            flags,
            _lo32(remaining), _hi32(remaining),
            _lo32(stamp), _hi32(stamp),
            _lo32(expire), _hi32(expire),
            z,
        ),
        axis=-1,
    )


def _pack_cold(limit, duration) -> jax.Array:
    """Stack cold row values into [N, 8]."""
    z = jnp.zeros_like(_lo32(limit))
    return jnp.stack(
        (
            _lo32(limit), _hi32(limit),
            _lo32(duration), _hi32(duration),
            z, z, z, z,
        ),
        axis=-1,
    )


def rows_to_split(rows: BucketRows) -> BucketState:
    """Decompose logical rows into the hot/cold row layout (same
    leading length); the write-side twin of read_rows' composition."""
    algo = jnp.asarray(rows.algo, _I32)
    status = jnp.asarray(rows.status, _I32)
    limit = jnp.asarray(rows.limit, _I64)
    remaining = jnp.asarray(rows.remaining, _I64)
    duration = jnp.asarray(rows.duration, _I64)
    stamp = jnp.asarray(rows.stamp, _I64)
    expire = jnp.asarray(rows.expire_at, _I64)
    flags = (algo & 3) | ((status & 1) << 2)
    return BucketState(
        hot=_pack_hot(flags, remaining, stamp, expire),
        cold=_pack_cold(limit, duration),
    )


class RequestBatch(NamedTuple):
    """One device-ready batch of resolved requests (length B, padded).

    `slot` indexes into the BucketState columns; -1 marks a padding lane
    (scatters drop, responses are garbage and masked host-side).
    `exists` is the host's claim that the slot currently maps this key;
    the kernel still validates expiry device-side.
    """

    slot: jax.Array  # i32[B]
    exists: jax.Array  # bool[B]
    algorithm: jax.Array  # i32[B]
    behavior: jax.Array  # i32[B]
    hits: jax.Array  # i64[B]
    limit: jax.Array  # i64[B]
    duration: jax.Array  # i64[B]
    greg_expire: jax.Array  # i64[B] (0 unless DURATION_IS_GREGORIAN)
    greg_duration: jax.Array  # i64[B] (0 unless DURATION_IS_GREGORIAN)
    # Analytic-duplicate extension (grouped planner,
    # gt_batch_plan_grouped): occurrence index within a uniform
    # duplicate group, and whether this lane scatters state (the last
    # occurrence).  None => every lane is its own group (occ=0,
    # write=valid), which is byte-identical to the pre-extension kernel.
    occ: "jax.Array | None" = None  # i32[B]
    write: "jax.Array | None" = None  # bool[B]


class BatchOutput(NamedTuple):
    """Per-lane responses plus host-mirror bookkeeping."""

    status: jax.Array  # i32[B]
    limit: jax.Array  # i64[B]
    remaining: jax.Array  # i64[B]
    reset_time: jax.Array  # i64[B]
    new_expire: jax.Array  # i64[B]  slot expire_at after this request
    removed: jax.Array  # bool[B] token RESET_REMAINING freed the slot
    # The slot's stored expiry as this lane's round GATHERED it (free:
    # the kernel reads it anyway).  The narrow wire's -2 keep-sentinel
    # detector; replaces a separate whole-batch pre-gather that round 4
    # measured at ~1ms/131k batch on TPU (probe_r4b_narrow).
    pre_expire: jax.Array  # i64[B]


def init_state(capacity: int) -> BucketState:
    """Fresh all-expired bucket table (expire_at=0 => every slot is free)."""
    return BucketState(
        hot=jnp.zeros((capacity, 8), _I32),
        cold=jnp.zeros((capacity, 8), _I32),
    )


def make_batch(
    slot,
    exists,
    algorithm,
    behavior,
    hits,
    limit,
    duration,
    greg_expire=None,
    greg_duration=None,
    occ=None,
    write=None,
) -> RequestBatch:
    """Convenience constructor coercing host arrays to kernel dtypes."""
    slot = jnp.asarray(slot, _I32)
    z = jnp.zeros_like(jnp.asarray(hits, _I64))
    return RequestBatch(
        slot=slot,
        exists=jnp.asarray(exists, bool),
        algorithm=jnp.asarray(algorithm, _I32),
        behavior=jnp.asarray(behavior, _I32),
        hits=jnp.asarray(hits, _I64),
        limit=jnp.asarray(limit, _I64),
        duration=jnp.asarray(duration, _I64),
        greg_expire=z if greg_expire is None else jnp.asarray(greg_expire, _I64),
        greg_duration=z if greg_duration is None else jnp.asarray(greg_duration, _I64),
        occ=None if occ is None else jnp.asarray(occ, _I32),
        write=None if write is None else jnp.asarray(write, bool),
    )


def apply_batch(
    state: BucketState, req: RequestBatch, now_ms, cold_cond: bool = True
) -> "tuple[BucketState, BatchOutput]":
    """Evaluate one batch against the bucket table.

    Pure function: returns (new_state, responses).  Slots must be unique
    within the batch (the host splits duplicate-key batches into
    flush-separated rounds; see ShardStore.apply) so the gather/scatter
    is race-free.

    `cold_cond` (static) guards the cold-row scatter with a lax.cond so
    steady-state batches skip it.  Under jax.vmap (the mesh store's
    per-shard kernels) cond lowers to executing BOTH branches plus a
    select — strictly worse than scattering unconditionally — so
    vmapped callers must pass cold_cond=False.
    """
    out, new = _apply_compute(state, req, now_ms)
    state = _commit_rows(state, req, new, cold_cond)
    return state, out


class _NewRows(NamedTuple):
    """Per-lane post-batch row values (the commit's input): what
    _apply_compute would store for each lane, before any scatter."""

    flags: jax.Array  # i32[B]
    rem: jax.Array  # i64[B]
    stamp: jax.Array  # i64[B]
    exp: jax.Array  # i64[B]
    limit: jax.Array  # i64[B]
    dur: jax.Array  # i64[B]
    writes: jax.Array  # bool[B] — lanes that commit state
    cold_changed: jax.Array  # bool[B] — writes whose stored config changed


def _commit_rows(state: BucketState, req, new: _NewRows, cold_cond: bool):
    """Per-lane row scatter (every lane submits a row; dropped lanes
    still pay the scatter's per-submitted-row price — the compact
    commit below avoids that when the plan allows)."""
    C = state.hot.shape[0]
    # Non-write lanes map to DISTINCT out-of-bounds indices (C + lane)
    # rather than a shared C: mode='drop' discards them either way, but
    # unique_indices=True promises uniqueness over the WHOLE index
    # vector and repeated sentinels would be undefined behavior.
    lane = jnp.arange(req.slot.shape[0], dtype=_I32)
    oob = C + lane
    scat = jnp.where(new.writes, req.slot, oob)
    drop = dict(mode="drop", unique_indices=True)
    new_hot = state.hot.at[scat].set(
        _pack_hot(new.flags, new.rem, new.stamp, new.exp), **drop
    )

    scat_cold = jnp.where(new.cold_changed, req.slot, oob)
    cold_rows = _pack_cold(new.limit, new.dur)

    if cold_cond:
        def _scatter_cold(args):
            cold, idx, rows = args
            return cold.at[idx].set(rows, **drop)

        def _keep_cold(args):
            return args[0]

        new_cold = jax.lax.cond(
            jnp.any(new.cold_changed), _scatter_cold, _keep_cold,
            (state.cold, scat_cold, cold_rows),
        )
    else:
        new_cold = state.cold.at[scat_cold].set(cold_rows, **drop)
    return BucketState(hot=new_hot, cold=new_cold)


def _apply_compute(
    state: BucketState, req: RequestBatch, now_ms
) -> "tuple[BatchOutput, _NewRows]":
    """The batch evaluation WITHOUT the state commit: returns the
    responses plus every lane's post-batch row values (see apply_batch
    for semantics; the split exists so commits can be compacted)."""
    now = jnp.asarray(now_ms, _I64)
    C = state.hot.shape[0]

    valid = req.slot >= 0
    s = jnp.clip(req.slot, 0, C - 1)

    # Two row gathers (cheap, vectorized) instead of 11 column gathers.
    hot_g = state.hot[s]  # [B, 8]
    cold_g = state.cold[s]  # [B, 8]
    g_flags = hot_g[:, _H_FLAGS]
    g_algo = g_flags & 3
    g_status = (g_flags >> 2) & 1
    g_limit = _compose64(cold_g[:, _C_LIM_LO], cold_g[:, _C_LIM_HI])
    g_rem = _compose64(hot_g[:, _H_REM_LO], hot_g[:, _H_REM_HI])
    g_dur = _compose64(cold_g[:, _C_DUR_LO], cold_g[:, _C_DUR_HI])
    g_stamp = _compose64(hot_g[:, _H_STAMP_LO], hot_g[:, _H_STAMP_HI])
    g_exp = _compose64(hot_g[:, _H_EXP_LO], hot_g[:, _H_EXP_HI])

    # Expiry-as-miss: reference expires strictly (`ExpireAt < now`,
    # cache.go:151), so a slot at exactly its expiry is still live.
    live = req.exists & (g_exp >= now)
    exist = live & (g_algo == req.algorithm)  # algo switch => recreate

    is_tok = req.algorithm == int(Algorithm.TOKEN_BUCKET)
    greg = (req.behavior & int(Behavior.DURATION_IS_GREGORIAN)) != 0
    reset_b = (req.behavior & int(Behavior.RESET_REMAINING)) != 0
    hits = req.hits
    OVER = jnp.asarray(int(Status.OVER_LIMIT), _I32)
    UNDER = jnp.asarray(int(Status.UNDER_LIMIT), _I32)

    # Analytic-duplicate support: a uniform duplicate group (same key,
    # identical config/hits, no RESET_REMAINING — enforced by the
    # grouped planner) runs entirely in one round.  Every lane reads the
    # SAME pre-group slot row; occurrence j's pre-hit remaining is
    # derived in closed form (the first j duplicates accepted
    # min(j, base // hits) hits), and only the last occurrence scatters.
    # occ=None degenerates to occ=0 everywhere: byte-identical to the
    # ungrouped kernel.
    occ64 = None if req.occ is None else req.occ.astype(_I64)
    hs = jnp.maximum(hits, 1)

    def occ_rem(base):
        if occ64 is None:
            return base
        taken = jnp.minimum(occ64, base // hs)
        return jnp.where(hits > 0, base - hits * taken, base)

    # ---------------- token bucket, existing item ----------------
    # RESET_REMAINING is checked before the algorithm-switch cast in the
    # reference (algorithms.go:36 precedes :54), so it applies to any live
    # slot regardless of the stored algorithm.
    tok_reset = live & is_tok & reset_b  # algorithms.go:36-47

    # Limit hot-change: remaining += r.limit - t.limit, clamp 0 (algorithms.go:70-78)
    t_rem0 = jnp.maximum(g_rem + (req.limit - g_limit), 0)

    # Duration hot-change (algorithms.go:87-105); expiry derives from CreatedAt.
    dur_changed = g_dur != req.duration
    exp_from_cfg = jnp.where(greg, req.greg_expire, g_stamp + req.duration)
    dur_expired = dur_changed & (exp_from_cfg < now)  # => remove + recreate
    t_exp = jnp.where(dur_changed, exp_from_cfg, g_exp)

    tok_exist = exist & is_tok & ~reset_b & ~dur_expired
    do_hit = hits > 0
    t_rem0 = occ_rem(t_rem0)  # this occurrence's pre-hit remaining
    can_take = do_hit & (hits <= t_rem0)  # covers == and < ; mutates
    t_rem1 = jnp.where(can_take, t_rem0 - hits, t_rem0)
    t_resp_status = jnp.where(
        do_hit & ((t_rem0 == 0) | (hits > t_rem0)), OVER, g_status
    )
    # Sticky status persists only via the remaining==0 path (algorithms.go:112-117)
    t_new_status = jnp.where(do_hit & (t_rem0 == 0), OVER, g_status)

    # ---------------- token bucket, fresh create ----------------
    # (selected in sel() as the fallback for token lanes that are neither
    # tok_reset nor tok_exist: plain miss, algo switch, or dur_expired)
    # Occurrence j applies to the remaining the first lane's create left
    # behind; hits > pre-hit remaining covers the hits > limit case of
    # lane 0 (algorithms.go:161-166) and every later over/at-zero lane.
    c_exp_tok = jnp.where(greg, req.greg_expire, now + req.duration)
    remc = occ_rem(req.limit)
    c_over = hits > remc
    c_rem_tok = jnp.where(c_over, remc, remc - hits)
    # Sticky for grouped creates: a later occurrence that found the
    # fresh bucket already drained sets OVER exactly as the exist path
    # would have in its sequential round (do_hit & pre-rem == 0).
    if occ64 is None:
        c_status_store = UNDER * jnp.ones_like(g_status)
    else:
        c_status_store = jnp.where(
            (occ64 > 0) & do_hit & (remc == 0), OVER, UNDER
        )

    # ---------------- leaky bucket, existing item ----------------
    lky_exist = exist & ~is_tok
    l_rem = jnp.where(lky_exist & reset_b, req.limit * LEAKY_SCALE, g_rem)  # :206-208

    rate_num = jnp.where(greg, req.greg_duration, req.duration)
    dur_eff = jnp.where(greg, req.greg_expire - now, req.duration)
    lim_safe = jnp.maximum(req.limit, 1)

    elapsed = now - g_stamp
    rn = jnp.maximum(rate_num, 1)  # duration<=0 degenerates to instant refill
    el_c = jnp.clip(elapsed, 0, rn)  # leak can't exceed one full refill
    lim_nn = jnp.maximum(req.limit, 0)
    # leak = elapsed * limit / duration, exact + overflow-safe.
    leak_whole, leak_frac = _leak_amounts(el_c, lim_nn, rn)
    leak_s = leak_whole * LEAKY_SCALE + leak_frac
    do_leak = leak_whole > 0  # only whole tokens trigger (algorithms.go:238-241)
    l_rem = jnp.where(do_leak, l_rem + leak_s, l_rem)
    l_stamp = jnp.where(do_leak, now, g_stamp)
    l_rem = jnp.where(l_rem // LEAKY_SCALE > req.limit, req.limit * LEAKY_SCALE, l_rem)

    rem_int0 = l_rem // LEAKY_SCALE
    l_reset = now + rate_num // lim_safe  # now + int64(rate) (algorithms.go:251)

    # Occurrence offset: earlier duplicates consumed whole tokens only
    # (the fractional part never changes within one `now`).
    rem_int = occ_rem(rem_int0)
    l_rem_base = l_rem - (rem_int0 - rem_int) * LEAKY_SCALE

    at_zero = rem_int == 0  # algorithms.go:260-264 (OVER even for hits==0)
    exact = ~at_zero & (rem_int == hits)  # algorithms.go:266-271
    overflow = ~at_zero & ~exact & (hits > rem_int)  # algorithms.go:273-278
    take = exact | (~at_zero & ~overflow & (hits > 0))
    l_rem_f = jnp.where(take, l_rem_base - hits * LEAKY_SCALE, l_rem_base)
    l_resp_rem = jnp.where(exact, 0, jnp.where(take, l_rem_f // LEAKY_SCALE, rem_int))
    l_resp_status = jnp.where(at_zero | overflow, OVER, UNDER)
    # Expiry refresh only on the plain-subtract path (algorithms.go:287):
    # for a group, "any accepted occurrence so far was a plain subtract".
    taken_cnt = jnp.where(hits > 0, (rem_int0 - rem_int) // hs, 0) + take.astype(_I64)
    drained_exactly = (hits > 0) & (taken_cnt > 0) & (rem_int - hits * take.astype(_I64) == 0)
    any_plain = (taken_cnt - drained_exactly.astype(_I64)) >= 1
    l_exp = jnp.where(any_plain, now + dur_eff, g_exp)

    # ---------------- leaky bucket, fresh create ----------------
    # Over-create clamps stored remaining to 0 (algorithms.go:318-323),
    # so later occurrences of an over-create group see 0, not limit.
    lky_create = ~is_tok & ~exist
    lc_over_all = hits > req.limit
    remlc = occ_rem(req.limit)
    if occ64 is not None:
        remlc = jnp.where(lc_over_all & (occ64 > 0), 0, remlc)
    lc_take = (hits > 0) & (hits <= remlc)
    lc_over = hits > remlc  # covers lane 0's hits > limit and drained lanes
    lc_rem = jnp.where(lc_over_all, 0, (remlc - hits * lc_take) * LEAKY_SCALE)
    lc_resp_rem = jnp.where(lc_take, remlc - hits, jnp.where(lc_over_all, 0, remlc))
    lc_exp = now + dur_eff
    lc_reset = now + dur_eff // lim_safe  # algorithms.go:315 (integer div)

    # ---------------- merge the five paths ----------------
    def sel(tok_reset_v, tok_exist_v, tok_create_v, lky_exist_v, lky_create_v):
        out = jnp.where(
            is_tok,
            jnp.where(
                tok_reset,
                tok_reset_v,
                jnp.where(tok_exist, tok_exist_v, tok_create_v),
            ),
            jnp.where(lky_exist, lky_exist_v, lky_create_v),
        )
        return out

    z64 = jnp.zeros_like(hits)
    resp_status = sel(
        UNDER * jnp.ones_like(g_status),
        t_resp_status,
        jnp.where(c_over, OVER, UNDER),
        l_resp_status,
        jnp.where(lc_over, OVER, UNDER),
    )
    resp_rem = sel(
        req.limit,
        jnp.where(can_take, t_rem1, t_rem0),
        c_rem_tok,
        l_resp_rem,
        lc_resp_rem,
    )
    resp_reset = sel(z64, t_exp, c_exp_tok, l_reset, lc_reset)

    n_algo = jnp.where(valid, req.algorithm, g_algo)
    n_limit = sel(g_limit, req.limit, req.limit, req.limit, req.limit)
    n_rem = sel(g_rem, t_rem1, c_rem_tok, l_rem_f, lc_rem)
    # Token stored Duration only set at create (algorithms.go:87-105 never
    # writes t.Duration); leaky existing stores the raw request duration
    # (algorithms.go:212), leaky create stores the adjusted one (:307).
    n_dur = sel(g_dur, g_dur, req.duration, req.duration, dur_eff)
    n_stamp = sel(g_stamp, g_stamp, now, l_stamp, now)
    n_exp = sel(z64, t_exp, c_exp_tok, l_exp, lc_exp)
    n_status = sel(
        UNDER * jnp.ones_like(g_status), t_new_status, c_status_store, UNDER, UNDER
    )

    removed = tok_reset & valid

    # Padding lanes (slot=-1) must NOT write; in grouped mode only the
    # LAST occurrence of each duplicate group writes.  The cold row is
    # rewritten only when a write lane actually changed its stored
    # config (create, limit or duration hot-change, algo switch).
    writes = valid if req.write is None else (valid & req.write)
    n_flags = (n_algo & 3) | ((n_status & 1) << 2)
    cold_changed = writes & ((n_limit != g_limit) | (n_dur != g_dur))

    out = BatchOutput(
        status=jnp.where(valid, resp_status, UNDER),
        limit=jnp.where(valid, req.limit, z64),
        remaining=jnp.where(valid, resp_rem, z64),
        reset_time=jnp.where(valid, resp_reset, z64),
        new_expire=jnp.where(valid, n_exp, z64),
        removed=removed,
        pre_expire=jnp.where(valid, g_exp, z64),
    )
    new = _NewRows(
        flags=n_flags, rem=n_rem, stamp=n_stamp, exp=n_exp,
        limit=n_limit, dur=n_dur, writes=writes, cold_changed=cold_changed,
    )
    return out, new


apply_batch_jit = jax.jit(apply_batch, donate_argnums=0)


def _pack_output(out: BatchOutput, with_pre: bool = False) -> jax.Array:
    """Fuse the per-lane outputs into ONE i64[4, B] array so the host
    pays a single device->host transfer per batch instead of five (each
    blocking readback is a full RTT — the dominant cost when the device
    sits behind a network tunnel).  Row 0 packs status (bit 0) and
    removed (bit 1); rows 1-3 are remaining / reset_time / new_expire.
    `limit` is an echo of the request and never leaves the device.
    `with_pre` appends pre_expire as row 4 (narrow-wire sentinel input,
    consumed on device — it never reaches the host wire)."""
    row0 = out.status.astype(_I64) | (out.removed.astype(_I64) << 1)
    rows = (row0, out.remaining, out.reset_time, out.new_expire)
    if with_pre:
        rows = rows + (out.pre_expire,)
    return jnp.stack(rows)


def unpack_output(packed):
    """Host-side twin of _pack_output: (status, removed, remaining,
    reset_time, new_expire) numpy views from the packed i64[4, B]."""
    row0 = packed[0]
    return (
        (row0 & 1).astype("int32"),
        (row0 >> 1).astype(bool),
        packed[1],
        packed[2],
        packed[3],
    )


def apply_rounds(
    state: BucketState, req: RequestBatch, round_id, n_rounds, now_ms,
    cold_cond: bool = True,
) -> "tuple[BucketState, jax.Array]":
    """Evaluate a whole duplicate-key batch in ONE dispatch.

    `round_id[i]` assigns each lane to a sequential round (computed by
    the host planner: unique keys+slots per round); the loop applies
    round r's lanes while masking the rest, so the k-th request for a
    key observes the (k-1)-th's state — the reference's mutex
    serialization (gubernator.go:336-337) — without a host round-trip
    between rounds.  `n_rounds` is a traced scalar: one compilation
    serves every round count at a given batch width.

    Returns (new_state, packed_output i64[4, B]); decode with
    unpack_output.
    """
    return _apply_rounds_impl(
        state, req, round_id, n_rounds, now_ms, cold_cond, with_pre=False
    )


def _apply_rounds_impl(
    state, req, round_id, n_rounds, now_ms, cold_cond, with_pre
):
    """Shared rounds loop; with_pre=True carries pre_expire as row 4
    (the narrow wire's on-device sentinel input)."""
    B = req.slot.shape[0]
    packed0 = jnp.zeros((5 if with_pre else 4, B), _I64)

    def cond(c):
        return c[0] < n_rounds

    def body(c):
        r, st, packed = c
        active = round_id == r
        req_r = req._replace(slot=jnp.where(active, req.slot, -1))
        st, out = apply_batch(st, req_r, now_ms, cold_cond=cold_cond)
        packed = jnp.where(
            active[None, :], _pack_output(out, with_pre=with_pre), packed
        )
        return r + 1, st, packed

    _, state, packed = jax.lax.while_loop(
        cond, body, (jnp.asarray(0, _I32), state, packed0)
    )
    return state, packed


apply_rounds_jit = jax.jit(
    apply_rounds, donate_argnums=0, static_argnames=("cold_cond",)
)


class RequestBatch32(NamedTuple):
    """Narrow-wire twin of RequestBatch: i32 value columns, Gregorian
    expiry as a delta from `now_ms`.  Halves host->device bytes and is
    usable whenever the batch's values fit (the common case: hits,
    limit, duration < 2**31 and no monthly/yearly Gregorian resets).
    The kernel computes in int64 regardless — only the WIRE narrows,
    which is what matters when the device sits across a thin link."""

    slot: jax.Array  # i32[B]
    exists: jax.Array  # bool[B]
    algorithm: jax.Array  # i32[B]
    behavior: jax.Array  # i32[B]
    hits: jax.Array  # i32[B]
    limit: jax.Array  # i32[B]
    duration: jax.Array  # i32[B]
    greg_expire_delta: jax.Array  # i32[B] (greg_expire - now; 0 if unused)
    greg_duration: jax.Array  # i32[B]
    occ: "jax.Array | None" = None  # i32[B]
    write: "jax.Array | None" = None  # bool[B]


def make_batch32(
    slot, exists, algorithm, behavior, hits, limit, duration,
    greg_expire_delta=None, greg_duration=None, occ=None, write=None,
) -> RequestBatch32:
    z = jnp.zeros_like(jnp.asarray(hits, _I32))
    return RequestBatch32(
        slot=jnp.asarray(slot, _I32),
        exists=jnp.asarray(exists, bool),
        algorithm=jnp.asarray(algorithm, _I32),
        behavior=jnp.asarray(behavior, _I32),
        hits=jnp.asarray(hits, _I32),
        limit=jnp.asarray(limit, _I32),
        duration=jnp.asarray(duration, _I32),
        greg_expire_delta=z if greg_expire_delta is None else jnp.asarray(greg_expire_delta, _I32),
        greg_duration=z if greg_duration is None else jnp.asarray(greg_duration, _I32),
        occ=None if occ is None else jnp.asarray(occ, _I32),
        write=None if write is None else jnp.asarray(write, bool),
    )


def apply_rounds32(
    state: BucketState, req32: RequestBatch32, round_id, n_rounds, now_ms,
    cold_cond: bool = True,
) -> "tuple[BucketState, jax.Array]":
    """apply_rounds with an int32 wire on BOTH directions.

    Input columns upcast on device; the packed result narrows to
    i32[4, B] (row 0 bit-packs status/removed; rows 1-3 are remaining,
    reset_time - now, new_expire - now).  Callers must guarantee the
    narrow preconditions (ShardStore checks them host-side):
    limit/hits/duration in [0, 2**31) and Gregorian deltas in range.
    Those bound every value the kernel COMPUTES this batch; a time the
    kernel merely passes through unchanged (a live bucket's stored
    expiry, which may lie arbitrarily far in the future from a wide
    batch) is encoded as the sentinel -2 ("unchanged") and reconstructed
    host-side from the slot table (unpack_output32), never clipped.
    """
    now = jnp.asarray(now_ms, _I64)
    req = RequestBatch(
        slot=req32.slot,
        exists=req32.exists,
        algorithm=req32.algorithm,
        behavior=req32.behavior,
        hits=req32.hits.astype(_I64),
        limit=req32.limit.astype(_I64),
        duration=req32.duration.astype(_I64),
        greg_expire=now + req32.greg_expire_delta.astype(_I64),
        greg_duration=req32.greg_duration.astype(_I64),
        occ=req32.occ,
        write=req32.write,
    )
    # The -2 pass-through detector rides the packed output as row 4:
    # each lane's stored expiry as its OWN round gathered it.  (Round 4
    # replaced a separate whole-batch pre-gather measured at ~1ms per
    # 131k batch; the per-round value is equivalent for the sentinel
    # because -2 fires only for values unrepresentable on this wire,
    # which no round of a narrow batch can have WRITTEN — any such
    # value predates the batch, so pre-round == pre-batch.)
    state, packed64 = _apply_rounds_impl(
        state, req, round_id, n_rounds, now_ms, cold_cond, with_pre=True
    )
    pre_exp = packed64[4]
    hi = jnp.asarray((1 << 31) - 1, _I64)

    def delta(v):
        # -1: absolute 0 (removed slot / no reset) — restore exact 0.
        # -2: UNREPRESENTABLE pass-through (a live bucket's far-future
        #     stored time, only reachable unchanged from pre-batch
        #     state) — host reconstructs the absolute value.  The
        #     sentinel must fire ONLY when the delta would clip: a
        #     representable value always rides the wire verbatim, so a
        #     coincidental v == pre_exp (e.g. an eviction-recycled slot
        #     recreated at the same expiry) still commits correctly.
        d = v - now
        fits = (d >= 0) & (d <= hi)
        return jnp.where(
            v == 0, -1, jnp.where(fits, d, jnp.where(v == pre_exp, -2, jnp.clip(d, 0, hi)))
        )

    packed32 = jnp.stack(
        (
            packed64[0],
            jnp.clip(packed64[1], 0, hi),
            delta(packed64[2]),
            delta(packed64[3]),
        )
    ).astype(_I32)
    return state, packed32


apply_rounds32_jit = jax.jit(
    apply_rounds32, donate_argnums=0, static_argnames=("cold_cond",)
)


def apply_compact32(
    state: BucketState, req32: RequestBatch32, wlane, now_ms,
) -> "tuple[BucketState, jax.Array]":
    """Single-round narrow kernel with a COMPACTED commit.

    XLA's random-row scatter prices per SUBMITTED row — ~21ns each on
    TPU v5e — whether or not mode='drop' discards it, so the per-lane
    commit pays for all B lanes even when the grouped planner marked
    only ~25% as writers (measured Zipf write fraction 0.235,
    probe/bench round 4).  Here the host ALSO sends `wlane` (i32[Pw]):
    the batch lanes that commit state, compacted and padded with -1.
    The kernel computes all lanes as usual, then gathers just the
    write lanes' rows and scatters Pw rows instead of B.

    Legal ONLY for single-round plans (n_rounds == 1 — the grouped
    planner's common case): multi-round batches need the scatter
    between rounds.  Callers guarantee wlane lists exactly the plan's
    write lanes.  Output packing is identical to apply_rounds32.
    """
    now = jnp.asarray(now_ms, _I64)
    req = RequestBatch(
        slot=req32.slot,
        exists=req32.exists,
        algorithm=req32.algorithm,
        behavior=req32.behavior,
        hits=req32.hits.astype(_I64),
        limit=req32.limit.astype(_I64),
        duration=req32.duration.astype(_I64),
        greg_expire=now + req32.greg_expire_delta.astype(_I64),
        greg_duration=req32.greg_duration.astype(_I64),
        occ=req32.occ,
        write=req32.write,
    )
    out, new = _apply_compute(state, req, now_ms)

    C = state.hot.shape[0]
    wl = jnp.clip(wlane, 0, req.slot.shape[0] - 1)
    wvalid = (wlane >= 0) & new.writes[wl]
    lane = jnp.arange(wlane.shape[0], dtype=_I32)
    dst = jnp.where(wvalid, req.slot[wl], C + lane)
    drop = dict(mode="drop", unique_indices=True)
    hot_rows = _pack_hot(new.flags, new.rem, new.stamp, new.exp)[wl]
    new_hot = state.hot.at[dst].set(hot_rows, **drop)

    ccold = wvalid & new.cold_changed[wl]
    dst_cold = jnp.where(ccold, req.slot[wl], C + lane)
    cold_rows = _pack_cold(new.limit, new.dur)[wl]

    def _scatter_cold(args):
        cold, idx, rows = args
        return cold.at[idx].set(rows, **drop)

    new_cold = jax.lax.cond(
        jnp.any(ccold), _scatter_cold, lambda a: a[0],
        (state.cold, dst_cold, cold_rows),
    )
    state = BucketState(hot=new_hot, cold=new_cold)

    pre_exp = out.pre_expire
    hi = jnp.asarray((1 << 31) - 1, _I64)

    def delta(v):
        d = v - now
        fits = (d >= 0) & (d <= hi)
        return jnp.where(
            v == 0, -1,
            jnp.where(fits, d, jnp.where(v == pre_exp, -2, jnp.clip(d, 0, hi))),
        )

    row0 = out.status.astype(_I64) | (out.removed.astype(_I64) << 1)
    packed32 = jnp.stack(
        (
            row0,
            jnp.clip(out.remaining, 0, hi),
            delta(out.reset_time),
            delta(out.new_expire),
        )
    ).astype(_I32)
    return state, packed32


apply_compact32_jit = jax.jit(apply_compact32, donate_argnums=0)


class RequestBatchDict(NamedTuple):
    """Config-dictionary wire: the narrowest host->device encoding.

    Realistic traffic shares a handful of (algorithm, behavior, hits,
    limit, duration, gregorian) configurations across a batch, so the
    wire carries a K<=256-row config TABLE plus one u8 index per lane
    instead of seven full value columns.  Per-lane payload: slot i32 +
    flags u8 (bit0 exists, bit1 write) + cfg u8 + occ u16 = 8 bytes,
    ~5x less than RequestBatch32's 42 — and on a thin link the batch
    bytes ARE the throughput ceiling.  The kernel expands via table
    gathers (K-sized, trivially cached on device) and delegates to
    apply_rounds32, so semantics and the packed i32 output are
    byte-identical to the narrow wire."""

    slot: jax.Array  # i32[B]
    flags: jax.Array  # u8[B]: bit0 exists, bit1 write
    cfg: jax.Array  # u8[B] index into the table rows
    occ: jax.Array  # u16[B]
    t_algorithm: jax.Array  # i32[K]
    t_behavior: jax.Array  # i32[K]
    t_hits: jax.Array  # i32[K]
    t_limit: jax.Array  # i32[K]
    t_duration: jax.Array  # i32[K]
    t_greg_expire_delta: jax.Array  # i32[K]
    t_greg_duration: jax.Array  # i32[K]


DICT_TABLE_ROWS = 256  # fixed so K never forces a recompile


def apply_rounds_dict(
    state: BucketState, reqd: RequestBatchDict, round_id8, n_rounds, now_ms,
    cold_cond: bool = True,
) -> "tuple[BucketState, jax.Array]":
    """apply_rounds32 behind the config-dictionary wire.  round_id8 is
    u8 (planner guarantees n_rounds <= 255 or falls back)."""
    cfg = reqd.cfg.astype(_I32)
    req32 = RequestBatch32(
        slot=reqd.slot,
        exists=(reqd.flags & 1) != 0,
        algorithm=reqd.t_algorithm[cfg],
        behavior=reqd.t_behavior[cfg],
        hits=reqd.t_hits[cfg],
        limit=reqd.t_limit[cfg],
        duration=reqd.t_duration[cfg],
        greg_expire_delta=reqd.t_greg_expire_delta[cfg],
        greg_duration=reqd.t_greg_duration[cfg],
        occ=reqd.occ.astype(_I32),
        write=(reqd.flags & 2) != 0,
    )
    return apply_rounds32(
        state, req32, round_id8.astype(_I32), n_rounds, now_ms,
        cold_cond=cold_cond,
    )


DICT_WIRE_TABLE_WORDS = 2 * DICT_TABLE_ROWS + 5 * 2 * DICT_TABLE_ROWS


def pack_dict_wire(slot, exists, write, cfg, occ, round_id, table) -> "jax.Array":
    """Serialize one dict-wire batch into a SINGLE i32 buffer.

    The dict wire's 12 separate arrays cost 12 host->device transfers
    per dispatch; at service batch sizes (<=4096 lanes) the per-call
    overhead dwarfs the bytes, so everything rides one
    [S, 3P + DICT_WIRE_TABLE_WORDS] i32 array instead (host packs with
    numpy views, device unpacks with free slices/shifts inside the
    jit):

      words [0,P)    slot (i32)
      words [P,2P)   occ | flags<<16 | cfg<<24   (flags: bit0 exists,
                                                  bit1 write)
      words [2P,3P)  round_id
      words [3P,..)  config-table rows: algo(256), behavior(256), then
                     hits/limit/duration/greg_expire_delta/
                     greg_duration as i64 lo/hi word pairs (512 each)

    The value rows are 64-bit so ANY magnitude (monthly Gregorian
    expiries, >2^31 limits) rides the dict wire — per-lane bytes are
    unchanged because values live in the 256-row table.

    Inputs are [S, P] arrays plus the 7-row table as [rows][256]
    (shared across shards — the wire carries it once per shard row
    only to keep the buffer rectangular).
    """
    import numpy as np

    S, P = slot.shape
    w = np.empty((S, 3 * P + DICT_WIRE_TABLE_WORDS), dtype=np.int32)
    w[:, :P] = slot
    meta = occ.astype(np.int32) & 0xFFFF
    meta |= (exists.astype(np.int32) | (write.astype(np.int32) << 1)) << 16
    meta |= cfg.astype(np.int32) << 24
    w[:, P:2 * P] = meta
    w[:, 2 * P:3 * P] = round_id
    pos = 3 * P
    for k in range(2):  # algo, behavior: i32
        w[:, pos:pos + DICT_TABLE_ROWS] = table[k].astype(np.int32)
        pos += DICT_TABLE_ROWS
    for k in range(2, 7):  # value rows: i64 as lo/hi
        v = table[k].astype(np.int64)
        w[:, pos:pos + DICT_TABLE_ROWS] = (v & 0xFFFFFFFF).astype(np.uint32).view(np.int32)
        pos += DICT_TABLE_ROWS
        w[:, pos:pos + DICT_TABLE_ROWS] = (v >> 32).astype(np.int32)
        pos += DICT_TABLE_ROWS
    return w


def unpack_dict_wire(w, P: int):
    """Device-side twin of pack_dict_wire for ONE shard row: returns
    (slot, flags, cfg u8, occ, rid, [7 table value arrays — value rows
    composed to i64]).  Pure slicing/shifting — fuses into the kernel
    for free."""
    slot = w[:P]
    meta = w[P:2 * P]
    occ = (meta & 0xFFFF).astype(jnp.uint16)
    fl = (meta >> 16) & 0xFF
    cfg = ((meta >> 24) & 0xFF).astype(jnp.uint8)
    rid = w[2 * P:3 * P]
    pos = 3 * P
    rows = []
    for k in range(2):
        rows.append(w[pos:pos + DICT_TABLE_ROWS])
        pos += DICT_TABLE_ROWS
    for k in range(5):
        lo = w[pos:pos + DICT_TABLE_ROWS]
        pos += DICT_TABLE_ROWS
        hi = w[pos:pos + DICT_TABLE_ROWS]
        pos += DICT_TABLE_ROWS
        rows.append(_compose64(lo, hi))
    return slot, fl, cfg, occ, rid, rows


def apply_rounds_packed(
    state: BucketState, wire, n_rounds, now_ms, cold_cond: bool = True
) -> "tuple[BucketState, jax.Array]":
    """Narrow-output dict kernel behind the single-buffer wire.  Host
    precondition (narrow_ok): every value and every time the kernel
    computes fits the i32 output deltas."""
    P = (wire.shape[0] - DICT_WIRE_TABLE_WORDS) // 3
    slot, fl, cfg, occ, rid, rows = unpack_dict_wire(wire, P)
    reqd = RequestBatchDict(
        slot=slot,
        flags=fl.astype(jnp.uint8),
        cfg=cfg,
        occ=occ,
        t_algorithm=rows[0],
        t_behavior=rows[1],
        t_hits=rows[2].astype(_I32),
        t_limit=rows[3].astype(_I32),
        t_duration=rows[4].astype(_I32),
        t_greg_expire_delta=rows[5].astype(_I32),
        t_greg_duration=rows[6].astype(_I32),
    )
    return apply_rounds_dict(state, reqd, rid, n_rounds, now_ms, cold_cond=cold_cond)


apply_rounds_packed_jit = jax.jit(
    apply_rounds_packed, donate_argnums=0, static_argnames=("cold_cond",)
)


def apply_compact_packed(
    state: BucketState, wire, wlane, now_ms
) -> "tuple[BucketState, jax.Array]":
    """apply_compact32 behind the single-buffer dict wire: the
    production fast path for SINGLE-ROUND narrow batches — the compact
    commit scatters only the plan's write lanes (wlane i32[Pw],
    -1-padded) instead of all B lanes.  The wire's round_id words are
    ignored (every lane is round 0 by the caller's n_rounds==1
    guarantee)."""
    P = (wire.shape[0] - DICT_WIRE_TABLE_WORDS) // 3
    slot, fl, cfg, occ, _rid, rows = unpack_dict_wire(wire, P)
    cfg = cfg.astype(_I32)
    req32 = RequestBatch32(
        slot=slot,
        exists=(fl & 1) != 0,
        algorithm=rows[0][cfg],
        behavior=rows[1][cfg],
        hits=rows[2][cfg].astype(_I32),
        limit=rows[3][cfg].astype(_I32),
        duration=rows[4][cfg].astype(_I32),
        greg_expire_delta=rows[5][cfg].astype(_I32),
        greg_duration=rows[6][cfg].astype(_I32),
        occ=occ.astype(_I32),
        write=(fl & 2) != 0,
    )
    return apply_compact32(state, req32, wlane, now_ms)


def apply_rounds_packed_wide(
    state: BucketState, wire, n_rounds, now_ms, cold_cond: bool = True
) -> "tuple[BucketState, jax.Array]":
    """Wide-output twin of apply_rounds_packed: same single-buffer wire,
    int64 compute and a packed i64[4, B] result (decode with
    unpack_output).  This is what keeps monthly/yearly Gregorian
    batches on the dict wire: their far-future expiries exceed the
    narrow output's i32 deltas, but per-lane bytes are identical —
    only the readback doubles.  Matches interval.go:82-146 being
    first-class in the reference."""
    now = jnp.asarray(now_ms, _I64)
    P = (wire.shape[0] - DICT_WIRE_TABLE_WORDS) // 3
    slot, fl, cfg, occ, rid, rows = unpack_dict_wire(wire, P)
    cfg = cfg.astype(_I32)
    delta = rows[5][cfg]
    greg_dur = rows[6][cfg]
    req = RequestBatch(
        slot=slot,
        exists=(fl & 1) != 0,
        algorithm=rows[0][cfg],
        behavior=rows[1][cfg],
        hits=rows[2][cfg],
        limit=rows[3][cfg],
        duration=rows[4][cfg],
        greg_expire=jnp.where(greg_dur != 0, now + delta, 0),
        greg_duration=greg_dur,
        occ=occ.astype(_I32),
        write=(fl & 2) != 0,
    )
    return apply_rounds(state, req, rid, n_rounds, now_ms, cold_cond=cold_cond)


apply_rounds_packed_wide_jit = jax.jit(
    apply_rounds_packed_wide, donate_argnums=0, static_argnames=("cold_cond",)
)

# Donating twins for the overlapped dispatch pipeline (models/shard.py):
# the wire buffer is a fresh per-batch device upload that nothing reads
# after the kernel, so donating it lets XLA recycle its bytes into the
# outputs instead of allocating per batch.  Separate wrappers — the
# plain _jit forms accept host numpy wires (tests, fallback callers),
# which donation would spam warnings about.
apply_rounds_packed_donated = jax.jit(
    apply_rounds_packed, donate_argnums=(0, 1), static_argnames=("cold_cond",)
)
apply_rounds_packed_wide_donated = jax.jit(
    apply_rounds_packed_wide, donate_argnums=(0, 1), static_argnames=("cold_cond",)
)


def apply_rounds_packed_fused(state, wires, n_rounds_vec, now_vec,
                              wide: bool = False, cold_cond: bool = True):
    """Apply K same-shape packed-wire batches SEQUENTIALLY inside one
    program (the launch-fusion kernel of the overlapped dispatch
    pipeline, models/shard.py ColumnarPipeline._launch_group).

    Semantically identical to K solo apply_rounds_packed[_wide] calls in
    order — batch i+1 sees the state batch i left — but the host pays
    ONE dispatch (and the caller one readback) for the group, so the
    fixed per-dispatch cost (per-call enqueue; on a tunnel device a
    full RPC) amortizes over K batches.  `wires` is a tuple of K
    equal-shape wire buffers; n_rounds_vec/now_vec are [K] arrays
    (traced, so one compilation per (K, wire-shape) serves every round
    count and timestamp).  Returns (state, stacked [K, 4, P] results).
    """
    fn = apply_rounds_packed_wide if wide else apply_rounds_packed
    outs = []
    for i, w in enumerate(wires):
        state, packed = fn(state, w, n_rounds_vec[i], now_vec[i],
                           cold_cond=cold_cond)
        outs.append(packed)
    return state, jnp.stack(outs)


_FUSED_PACKED_JIT: dict = {}


def fused_packed_jit(k: int, wide: bool, cold_cond: bool = True,
                     donate_wires: bool = True):
    """Jitted apply_rounds_packed_fused for a fixed group size `k`
    (call as fn(state, w_0, ..., w_{k-1}, n_rounds_vec, now_vec)).
    State is always donated; wires too unless `donate_wires` is False
    (CPU zero-copies uploads from host numpy, so their buffers are not
    donatable there — the caller passes the platform's verdict).
    Cached module-wide so all stores in a process share one compilation
    per (k, wide, cold_cond, shape)."""
    key = (k, wide, cold_cond, donate_wires)
    fn = _FUSED_PACKED_JIT.get(key)
    if fn is None:

        def run(state, *args):
            return apply_rounds_packed_fused(
                state, args[:k], args[k], args[k + 1],
                wide=wide, cold_cond=cold_cond,
            )

        donate = tuple(range(k + 1)) if donate_wires else (0,)
        fn = jax.jit(run, donate_argnums=donate)
        _FUSED_PACKED_JIT[key] = fn
        # XLA telemetry (telemetry.py): one more distinct jitted
        # callable in the program population — the compile itself is
        # counted by the monitoring listener when it happens.
        from .. import telemetry

        telemetry.note_program_created(
            f"fused_packed:k{k}:{'wide' if wide else 'narrow'}"
        )
    return fn


def build_config_dict(cols, now_ms: int):
    """Host half of the dict wire: map each lane's 7 value columns to a
    row index in a <=256-row table.  Returns (cfg_idx u8[B], table
    7x i32[DICT_TABLE_ROWS]) or None when the batch has too many
    distinct configs (caller falls back to RequestBatch32).  Exact by
    construction: lanes group by a 64-bit polynomial mix of the
    columns, then every lane is VERIFIED equal to its group
    representative — a hash collision degrades to fallback, never to a
    wrong config."""
    import numpy as np

    greg_delta = np.where(
        cols.greg_duration != 0, cols.greg_expire - now_ms, 0
    ).astype(np.int64)
    arrays = (
        cols.algo, cols.behavior, cols.hits, cols.limit, cols.duration,
        greg_delta, cols.greg_duration,
    )
    n = len(cols.algo)
    if n == 0:
        return None
    with np.errstate(over="ignore"):
        h = np.zeros(n, np.int64)
        for c in arrays:
            h = h * np.int64(1000003) + c.astype(np.int64)
    uq, idx_first, inv = np.unique(h, return_index=True, return_inverse=True)
    if len(uq) > DICT_TABLE_ROWS:
        return None
    for c in arrays:
        if not np.array_equal(c[idx_first][inv], c):
            return None  # collision: correctness over compactness
    table = []
    for c in arrays:
        # i64 rows: the table is 256 entries, so wide values (monthly/
        # yearly Gregorian expiries, >2^31 limits) cost nothing per
        # lane — the whole batch stays on the dict wire.
        row = np.zeros(DICT_TABLE_ROWS, np.int64)
        row[: len(uq)] = c[idx_first]
        table.append(row)
    return inv.astype(np.uint8), tuple(table)


def unpack_output32(packed, now_ms: int, table_expire):
    """Host-side twin of apply_rounds32's packing: (status, removed,
    remaining, reset_time, new_expire) with absolute int64 times.

    Sentinels: -1 decodes to absolute 0 (removed/no-reset); -2 means
    "unchanged pass-through" — reset_time reconstructs from
    `table_expire` (the slot table's pre-commit value, identical to the
    device's pre-batch expire), and new_expire stays -1 so commit_plan
    skips the (already correct) host bookkeeping.
    """
    import numpy as np

    row0 = packed[0]
    te = np.asarray(table_expire, dtype="int64")

    def undelta(row, keep):
        d = row.astype("int64")
        return np.where(d == -2, keep, np.where(d == -1, 0, d + now_ms))

    return (
        (row0 & 1).astype("int32"),
        ((row0 >> 1) & 1).astype(bool),
        packed[1].astype("int64"),
        undelta(packed[2], te),
        undelta(packed[3], np.int64(-1)),
    )


@jax.jit
def read_rows(state: BucketState, slots) -> BucketRows:
    """Gather full bucket rows for the given slots (host-bound: Store
    OnChange callbacks and Loader snapshots need the item state the way
    the reference passes CacheItems, store.go:29-45)."""
    s = jnp.asarray(slots, _I32)
    hot = state.hot[s]
    cold = state.cold[s]
    flags = hot[:, _H_FLAGS]
    return BucketRows(
        algo=flags & 3,
        limit=_compose64(cold[:, _C_LIM_LO], cold[:, _C_LIM_HI]),
        remaining=_compose64(hot[:, _H_REM_LO], hot[:, _H_REM_HI]),
        duration=_compose64(cold[:, _C_DUR_LO], cold[:, _C_DUR_HI]),
        stamp=_compose64(hot[:, _H_STAMP_LO], hot[:, _H_STAMP_HI]),
        expire_at=_compose64(hot[:, _H_EXP_LO], hot[:, _H_EXP_HI]),
        status=(flags >> 2) & 1,
    )


@partial(jax.jit, donate_argnums=0)
def write_rows(state: BucketState, slots, rows: BucketRows) -> BucketState:
    """Scatter full bucket rows (Store.Get results / Loader.Load items).
    Negative slots are mapped out of bounds and dropped."""
    C = state.hot.shape[0]
    s = jnp.asarray(slots, _I32)
    s = jnp.where(s >= 0, s, C)
    vals = rows_to_split(rows)
    return BucketState(
        hot=state.hot.at[s].set(vals.hot, mode="drop"),
        cold=state.cold.at[s].set(vals.cold, mode="drop"),
    )


class BackState(NamedTuple):
    """Back tier of the two-tier bucket table (same [Cb, 8] i32 hot/cold
    row layout as BucketState).

    Kernel lanes only ever address the FRONT table; rows move between
    tiers via `apply_moves` (host-planned promotions/demotions, see
    native Table two-tier mode).  The split exists because the hot
    scatter's cost scales with the table it targets (~2.4ns/slot
    measured on TPU v5e) — a 2M-slot table prices every batch ~5.9ms
    where a 262k front prices ~2.7ms, while the back tier is touched
    only by the (batched, usually empty) move program."""

    hot: jax.Array  # i32[Cb, 8]
    cold: jax.Array  # i32[Cb, 8]


def init_back(capacity: int) -> BackState:
    return BackState(
        hot=jnp.zeros((capacity, 8), _I32),
        cold=jnp.zeros((capacity, 8), _I32),
    )


def apply_moves(
    state: BucketState, back: BackState,
    promo_kind, promo_src, promo_dst, demo_src, demo_dst,
) -> "tuple[BucketState, BackState]":
    """Apply one drain window of tier moves.

    Demotions gather PRE-promotion front rows and scatter them into the
    back tier; promotions gather from the back tier (kind 0) or from
    the front (kind 1 — a row demoted and re-promoted inside the same
    window, which never reached the back table; the host rewrites those
    sources, gt_table_take_moves contract).  src=-1 marks a padding or
    cancelled record (dropped via out-of-bounds destinations).  The
    host guarantees destination uniqueness within a window
    (unique_indices) — see the native Table's cancel_pending_demo.
    """
    Cf = state.hot.shape[0]
    Cb = back.hot.shape[0]
    drop = dict(mode="drop", unique_indices=True)

    nd = demo_src.shape[0]
    dsrc = jnp.clip(demo_src, 0, Cf - 1)
    lane_d = jnp.arange(nd, dtype=_I32)
    ddst = jnp.where(demo_src >= 0, demo_dst, Cb + lane_d)
    new_back = BackState(
        hot=back.hot.at[ddst].set(state.hot[dsrc], **drop),
        cold=back.cold.at[ddst].set(state.cold[dsrc], **drop),
    )

    np_ = promo_src.shape[0]
    from_front = (promo_kind == 1)[:, None]
    psrc_b = jnp.clip(promo_src, 0, Cb - 1)
    psrc_f = jnp.clip(promo_src, 0, Cf - 1)
    # kind 0 reads the PRE-demo back rows (input `back`): a promo source
    # overlapping a same-window demo destination is impossible by the
    # host's rewrite/cancel rules, so input rows are always current.
    ph = jnp.where(from_front, state.hot[psrc_f], back.hot[psrc_b])
    pc = jnp.where(from_front, state.cold[psrc_f], back.cold[psrc_b])
    lane_p = jnp.arange(np_, dtype=_I32)
    pdst = jnp.where(promo_src >= 0, promo_dst, Cf + lane_p)
    new_state = BucketState(
        hot=state.hot.at[pdst].set(ph, **drop),
        cold=state.cold.at[pdst].set(pc, **drop),
    )
    return new_state, new_back


def read_back_rows(back: BackState, slots) -> BucketRows:
    """Gather full logical rows from the back tier (snapshot path)."""
    s = jnp.asarray(slots, _I32)
    hot = back.hot[s]
    cold = back.cold[s]
    flags = hot[:, _H_FLAGS]
    return BucketRows(
        algo=flags & 3,
        limit=_compose64(cold[:, _C_LIM_LO], cold[:, _C_LIM_HI]),
        remaining=_compose64(hot[:, _H_REM_LO], hot[:, _H_REM_HI]),
        duration=_compose64(cold[:, _C_DUR_LO], cold[:, _C_DUR_HI]),
        stamp=_compose64(hot[:, _H_STAMP_LO], hot[:, _H_STAMP_HI]),
        expire_at=_compose64(hot[:, _H_EXP_LO], hot[:, _H_EXP_HI]),
        status=(flags >> 2) & 1,
    )
