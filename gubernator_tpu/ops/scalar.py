"""Host-side scalar twin of the device kernel (the express lane's
singleton fast path).

On a CPU backend a single-lane check pays the full XLA dispatch
machinery — trace-cache lookup, argument flattening, a [64]-padded
gather/scatter program, readback — for arithmetic that is a handful of
integer ops.  This module evaluates ONE lane of `_apply_compute`
(ops/buckets.py) directly on the host, reading and writing the bucket
row IN PLACE through a writable view of the CPU device buffer, so an
express singleton skips device dispatch entirely.

Safety contract (why the in-place write is sound):

* CPU only — `available()` gates on the buffer actually living in host
  memory (`unsafe_buffer_pointer` + a write/readback probe at import of
  the capability, never assumed).
* The write happens at the batch's LAUNCH turn, under the store's
  `_lock` (the same lock every jit launch holds), so no XLA program is
  reading or donating the buffer while the row is mutated — exactly the
  window in which the kernel's own scatter would have landed.
* Ticket order is untouched: the scalar batch holds an ordinary
  pipeline ticket and its commit runs through the ordinary FIFO drain,
  so interleaved scalar and device batches replay in plan order.

Semantics are a line-for-line port of `_apply_compute` for one lane
(occ=0, write=True — a singleton is always its own duplicate group),
including the kernel's documented divergences from the Go reference
(exact integer leak math, fixed-point leaky remaining).  Equivalence is
pinned by tests/test_express.py's randomized oracle runs against the
device kernel, expiry edges included.
"""

from __future__ import annotations

import ctypes
from typing import Optional, Tuple

import numpy as np

from ..types import Algorithm, Behavior, Status
from .buckets import LEAKY_SCALE

# hot/cold lane indices (BucketState layout, ops/buckets.py)
_H_FLAGS, _H_REM_LO, _H_REM_HI = 0, 1, 2
_H_STAMP_LO, _H_STAMP_HI, _H_EXP_LO, _H_EXP_HI = 3, 4, 5, 6
_C_LIM_LO, _C_LIM_HI, _C_DUR_LO, _C_DUR_HI = 0, 1, 2, 3

_MASK32 = (1 << 32) - 1
_MASK64 = (1 << 64) - 1


def _i64(v: int) -> int:
    """Wrap a Python int to int64 two's-complement (the kernel's
    arithmetic domain)."""
    v &= _MASK64
    return v - (1 << 64) if v >= (1 << 63) else v


def _compose64(lo: int, hi: int) -> int:
    """Exact int64 from a lo/hi int32 pair (sign lives in hi)."""
    return (int(hi) << 32) | (int(lo) & _MASK32)


def _lo32(v: int) -> int:
    """Low 32 bits as a SIGNED int32 value (modular truncation, the
    kernel's _lo32 — numpy rejects out-of-range assignment)."""
    w = v & _MASK32
    return w - (1 << 32) if w >= (1 << 31) else w


def _hi32(v: int) -> int:
    """High 32 bits as a signed int32 value."""
    w = (v >> 32) & _MASK32
    return w - (1 << 32) if w >= (1 << 31) else w


# ---------------------------------------------------------------------
# Writable host views of CPU jax buffers
# ---------------------------------------------------------------------

def _writable_view(dev_arr) -> Optional[np.ndarray]:
    """A WRITABLE numpy view of a single-device CPU jax array's buffer.
    Returns None when the capability is unavailable (non-CPU backend,
    jax without unsafe_buffer_pointer, zero-size buffer)."""
    try:
        db = (
            dev_arr.addressable_data(0)
            if hasattr(dev_arr, "addressable_data") else dev_arr
        )
        if db.dtype != np.int32:
            return None
        n = int(np.prod(db.shape))
        if n == 0:
            return None
        ptr = db.unsafe_buffer_pointer()
        buf = (ctypes.c_int32 * n).from_address(ptr)
        return np.frombuffer(buf, dtype=np.int32).reshape(db.shape)
    except Exception:  # noqa: BLE001 — capability probe, never fatal
        return None


def shard_view(dev_arr, s: int) -> Optional[np.ndarray]:
    """Writable view of shard `s` of a 1-D-sharded jax array (leading
    axis partitioned across devices), shaped like that shard's block.
    None when unavailable."""
    try:
        for fr in dev_arr.addressable_shards:
            idx = fr.index[0]
            start = 0 if idx.start is None else idx.start
            stop = dev_arr.shape[0] if idx.stop is None else idx.stop
            if start <= s < stop:
                v = _writable_view(fr.data)
                if v is None:
                    return None
                # Offset within the shard block (replicated axes keep
                # the whole range; partitioned blocks start at `start`).
                return v[s - start]
    except Exception:  # noqa: BLE001
        return None
    return None


def single_view(dev_arr) -> Optional[np.ndarray]:
    """Writable view of an unsharded (single-device) jax array."""
    return _writable_view(dev_arr)


def device_is_cpu(device) -> bool:
    try:
        if device is not None:
            return device.platform == "cpu"
        import jax

        return jax.default_backend() == "cpu"
    except Exception:  # noqa: BLE001
        return False


def probe(state_hot, sharded: bool = False) -> bool:
    """One-time capability probe: can we obtain a writable view of this
    state array's buffer AND does the write alias the buffer jax reads?
    Probes the first row's spare lane (hot lane 7 — always zero and
    ignored by the kernel) and restores it.  Called once per store,
    under the store lock."""
    v = shard_view(state_hot, 0) if sharded else single_view(state_hot)
    if v is None:
        return False
    flat = v.reshape(-1)
    old = int(flat[7])
    try:
        flat[7] = 0x5CA1A
        try:
            got = int(np.asarray(state_hot).reshape(-1)[7])
        except IndexError:
            # The known jax CPU readback flake (models/shard.py
            # host_readback — not importable here without a cycle):
            # one retry, so a transient cannot silently disable the
            # scalar path for the store's whole lifetime.
            got = int(np.asarray(state_hot).reshape(-1)[7])
        return got == 0x5CA1A
    except Exception:  # noqa: BLE001
        return False
    finally:
        # The sentinel must never outlive the probe, even on failure.
        try:
            flat[7] = old
        except Exception:  # noqa: BLE001
            pass


# ---------------------------------------------------------------------
# The scalar kernel twin
# ---------------------------------------------------------------------

def _leak_amounts(el_c: int, lim_nn: int, rn: int) -> Tuple[int, int]:
    """Exact (floor(el*lim/rn), floor((el*lim mod rn) * SCALE / rn)) —
    Python ints are exact at any magnitude, matching _muldiv128."""
    prod = el_c * lim_nn
    lw = prod // rn
    lr = prod % rn
    return lw, (lr * LEAKY_SCALE) // rn


def apply_one(
    hot_row: np.ndarray,
    cold_row: np.ndarray,
    *,
    exists: bool,
    algorithm: int,
    behavior: int,
    hits: int,
    limit: int,
    duration: int,
    greg_expire: int,
    greg_duration: int,
    now_ms: int,
) -> Tuple[int, int, int, int, bool]:
    """Evaluate one lane against its bucket row and WRITE the row in
    place (hot + cold, the kernel's commit).  Returns
    (status, remaining, reset_time, new_expire, removed) — exactly the
    per-lane values `_pack_output` would carry for this lane.

    `hot_row`/`cold_row` are writable int32[8] views of the slot's rows;
    `exists` is the planner's claim that the slot maps this key (expiry
    is revalidated here, like the kernel does device-side)."""
    now = int(now_ms)
    algorithm = int(algorithm)
    behavior = int(behavior)
    hits = int(hits)
    limit = int(limit)
    duration = int(duration)
    greg_expire = int(greg_expire)
    greg_duration = int(greg_duration)

    # -- gather (two row reads) ---------------------------------------
    g_flags = int(hot_row[_H_FLAGS])
    g_algo = g_flags & 3
    g_status = (g_flags >> 2) & 1
    g_limit = _compose64(cold_row[_C_LIM_LO], cold_row[_C_LIM_HI])
    g_rem = _compose64(hot_row[_H_REM_LO], hot_row[_H_REM_HI])
    g_dur = _compose64(cold_row[_C_DUR_LO], cold_row[_C_DUR_HI])
    g_stamp = _compose64(hot_row[_H_STAMP_LO], hot_row[_H_STAMP_HI])
    g_exp = _compose64(hot_row[_H_EXP_LO], hot_row[_H_EXP_HI])

    live = bool(exists) and g_exp >= now
    exist = live and g_algo == algorithm

    is_tok = algorithm == int(Algorithm.TOKEN_BUCKET)
    greg = (behavior & int(Behavior.DURATION_IS_GREGORIAN)) != 0
    reset_b = (behavior & int(Behavior.RESET_REMAINING)) != 0
    OVER = int(Status.OVER_LIMIT)
    UNDER = int(Status.UNDER_LIMIT)
    do_hit = hits > 0

    if is_tok:
        if live and reset_b:
            # -- token RESET_REMAINING: remove the bucket -------------
            status, resp_rem, resp_reset = UNDER, limit, 0
            n_rem, n_stamp, n_exp = g_rem, g_stamp, 0
            n_limit, n_dur, n_status = g_limit, g_dur, UNDER
            removed = True
        else:
            dur_changed = g_dur != duration
            exp_from_cfg = greg_expire if greg else _i64(g_stamp + duration)
            dur_expired = dur_changed and exp_from_cfg < now
            t_exp = exp_from_cfg if dur_changed else g_exp
            if exist and not dur_expired:
                # -- token, existing item -------------------------------
                t_rem0 = max(g_rem + (limit - g_limit), 0)
                can_take = do_hit and hits <= t_rem0
                t_rem1 = t_rem0 - hits if can_take else t_rem0
                status = (
                    OVER if do_hit and (t_rem0 == 0 or hits > t_rem0)
                    else g_status
                )
                n_status = OVER if do_hit and t_rem0 == 0 else g_status
                resp_rem = t_rem1 if can_take else t_rem0
                resp_reset = t_exp
                n_rem, n_stamp, n_exp = t_rem1, g_stamp, t_exp
                n_limit, n_dur = limit, g_dur
                removed = False
            else:
                # -- token, fresh create --------------------------------
                c_exp = greg_expire if greg else _i64(now + duration)
                c_over = hits > limit
                c_rem = limit if c_over else limit - hits
                status = OVER if c_over else UNDER
                resp_rem, resp_reset = c_rem, c_exp
                n_rem, n_stamp, n_exp = c_rem, now, c_exp
                n_limit, n_dur, n_status = limit, duration, UNDER
                removed = False
    else:
        rate_num = greg_duration if greg else duration
        dur_eff = _i64(greg_expire - now) if greg else duration
        lim_safe = max(limit, 1)
        if exist:
            # -- leaky, existing item ------------------------------------
            l_rem = limit * LEAKY_SCALE if reset_b else g_rem
            rn = max(rate_num, 1)
            el_c = min(max(now - g_stamp, 0), rn)
            lim_nn = max(limit, 0)
            leak_whole, leak_frac = _leak_amounts(el_c, lim_nn, rn)
            leak_s = leak_whole * LEAKY_SCALE + leak_frac
            do_leak = leak_whole > 0
            if do_leak:
                l_rem = l_rem + leak_s
            l_stamp = now if do_leak else g_stamp
            if l_rem // LEAKY_SCALE > limit:
                l_rem = limit * LEAKY_SCALE
            rem_int = l_rem // LEAKY_SCALE
            l_reset = _i64(now + rate_num // lim_safe)
            at_zero = rem_int == 0
            exact = (not at_zero) and rem_int == hits
            overflow = (not at_zero) and (not exact) and hits > rem_int
            take = exact or ((not at_zero) and (not overflow) and hits > 0)
            l_rem_f = l_rem - hits * LEAKY_SCALE if take else l_rem
            resp_rem = 0 if exact else (l_rem_f // LEAKY_SCALE if take else rem_int)
            status = OVER if (at_zero or overflow) else UNDER
            drained_exactly = do_hit and take and (rem_int - hits) == 0
            any_plain = (int(take) - int(drained_exactly)) >= 1
            l_exp = _i64(now + dur_eff) if any_plain else g_exp
            resp_reset = l_reset
            n_rem, n_stamp, n_exp = l_rem_f, l_stamp, l_exp
            n_limit, n_dur, n_status = limit, duration, UNDER
            removed = False
        else:
            # -- leaky, fresh create -------------------------------------
            lc_over = hits > limit
            lc_take = do_hit and hits <= limit
            lc_rem = 0 if lc_over else (limit - hits * int(lc_take)) * LEAKY_SCALE
            resp_rem = (limit - hits) if lc_take else (0 if lc_over else limit)
            status = OVER if lc_over else UNDER
            lc_exp = _i64(now + dur_eff)
            resp_reset = _i64(now + dur_eff // lim_safe)
            n_rem, n_stamp, n_exp = lc_rem, now, lc_exp
            n_limit, n_dur, n_status = limit, dur_eff, UNDER
            removed = False

    # -- commit (the kernel's row scatter, in place) -------------------
    n_flags = (algorithm & 3) | ((int(n_status) & 1) << 2)
    n_rem = _i64(n_rem)
    n_stamp = _i64(n_stamp)
    n_exp = _i64(n_exp)
    n_limit = _i64(n_limit)
    n_dur = _i64(n_dur)
    hot_row[_H_FLAGS] = n_flags
    hot_row[_H_REM_LO] = _lo32(n_rem)
    hot_row[_H_REM_HI] = _hi32(n_rem)
    hot_row[_H_STAMP_LO] = _lo32(n_stamp)
    hot_row[_H_STAMP_HI] = _hi32(n_stamp)
    hot_row[_H_EXP_LO] = _lo32(n_exp)
    hot_row[_H_EXP_HI] = _hi32(n_exp)
    hot_row[7] = 0
    # Cold write is unconditional: when the stored config did not
    # change the values are equal and the write is a no-op — identical
    # end state to the kernel's cond-guarded scatter.
    cold_row[_C_LIM_LO] = _lo32(n_limit)
    cold_row[_C_LIM_HI] = _hi32(n_limit)
    cold_row[_C_DUR_LO] = _lo32(n_dur)
    cold_row[_C_DUR_HI] = _hi32(n_dur)
    cold_row[4] = cold_row[5] = cold_row[6] = cold_row[7] = 0

    return int(status), _i64(resp_rem), _i64(resp_reset), n_exp, removed
