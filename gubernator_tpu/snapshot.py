"""Durability plane: crash-safe columnar device-state snapshots.

Every daemon restart used to zero every device-resident bucket — a
deploy or crash at production traffic was a cluster-wide rate-limit
reset (ROADMAP item 4's failure class).  This module persists the
packed device arrays across process lives:

  * DUMP — ONE mesh-wide D2H gather (`store.snapshot_columns`, the
    reshard `drain_keys` playbook's all-keys variant: resolve every
    resident key's slot host-side, gather the full bucket rows in one
    device program) produces a `reshard.TransferColumns` batch, encoded
    into a versioned + CRC-checksummed on-disk format.  The gather
    rides the dispatch pipeline's plan lock (the same drain-then-lock
    envelope every wholesale state reader uses); the encode and file
    I/O run OUTSIDE every store lock, so launches resume the moment the
    gather's readback lands.
  * CRASH SAFETY — snapshots are written to a same-directory temp
    file, fsync'd, and atomically rename(2)'d over the previous
    snapshot (then the directory entry is fsync'd).  A reader can NEVER
    observe a torn file: it sees the old complete snapshot or the new
    complete snapshot, nothing in between — `kill -9` mid-write leaves
    the previous snapshot intact and loadable (chaos-tested).
  * RESTORE — at boot, ONE H2D commit (`store.commit_transfer`, the
    reshard monotone merge) replays the snapshot into the fresh device
    state.  The merge is monotone (lower remaining wins, expired rows
    dropped), so a STALE snapshot can never un-spend hits admitted
    after it was taken, and a snapshot restored late (after traffic
    already started) can never resurrect budget — the staleness slack
    is bounded by the hits admitted between the last completed snapshot
    and the crash, exactly the contract architecture.md "Durability"
    documents.
  * RING FENCING — the header stamps the membership fingerprint
    (`reshard.ring_fingerprint`) the daemon served under when the
    snapshot was written.  When the restarted daemon's bootstrap
    membership differs, the restored keys this daemon no longer owns
    are handed off through the EXISTING reshard transfer path
    (V1Service.set_peers schedules the same drain -> transfer pass a
    live ring delta gets); a matching fingerprint means ownership is
    unchanged by construction and restore costs nothing further.
    `read_snapshot(expected_ring=...)` additionally supports strict
    fencing (reject a wrong-ring file outright) for tools and
    Store-SPI deployments that want it.

Corrupt, truncated, bit-flipped, or wrong-version files are rejected
LOUDLY at boot: counted in gubernator_snapshot_restores{result=
"rejected"}, a `snapshot-rejected` flight-recorder event (auto-dump),
and a cold start — never a partial or garbage restore.

File format v1 (little-endian; golden-pinned in tests/test_snapshot.py
— layout frozen, changing ANY byte requires a version bump):

  offset  size  field
  0       4     magic "GUBS"
  4       1     version (1)
  5       1     reserved (0)
  6       4     u32 n (lanes)
  10      8     i64 saved_at_ms (daemon clock at the gather)
  18      8     u64 ring_hash (membership fingerprint; 0 = unfenced)
  26      4     u32 key_bytes (total packed key bytes)
  30      4*n   u32[n] key END offsets into the key blob
  ..      kb    key blob (utf-8, concatenated)
  ..      4*n   i32[n] algorithm
  ..      4*n   i32[n] status
  ..      8*n   i64[n] limit
  ..      8*n   i64[n] remaining
  ..      8*n   i64[n] duration
  ..      8*n   i64[n] stamp
  ..      8*n   i64[n] expire_at
  tail    4     u32 crc32 (zlib) of every preceding byte
"""

from __future__ import annotations

import os
import struct
import threading
import time
import zlib
from typing import Optional, Tuple

import numpy as np

from . import audit
from . import tracing
from .reshard import TransferColumns
from .utils.logging import category_logger

logger = category_logger("snapshot")

SNAPSHOT_MAGIC = b"GUBS"
SNAPSHOT_VERSION = 1
_HEADER = struct.Struct("<4sBBIqQI")  # magic ver rsvd n saved_at ring kb
_CRC = struct.Struct("<I")


class SnapshotError(ValueError):
    """A snapshot file that must not be restored (corrupt, truncated,
    wrong version, checksum mismatch, or — under strict fencing — a
    wrong ring fingerprint)."""


def encode_snapshot(cols: TransferColumns, saved_at_ms: int,
                    ring_hash: int = 0) -> bytes:
    """TransferColumns -> the on-disk byte layout (checksum included)."""
    n = len(cols)
    key_bytes = [k.encode("utf-8") for k in cols.keys]
    offsets = np.cumsum(
        np.fromiter((len(b) for b in key_bytes), np.uint32, count=n),
        dtype=np.uint32,
    ) if n else np.zeros(0, np.uint32)
    blob = b"".join(key_bytes)
    parts = [
        _HEADER.pack(
            SNAPSHOT_MAGIC, SNAPSHOT_VERSION, 0, n,
            int(saved_at_ms), int(ring_hash) & 0xFFFFFFFFFFFFFFFF,
            len(blob),
        ),
        offsets.tobytes(),
        blob,
        np.ascontiguousarray(cols.algorithm, np.int32).tobytes(),
        np.ascontiguousarray(cols.status, np.int32).tobytes(),
        np.ascontiguousarray(cols.limit, np.int64).tobytes(),
        np.ascontiguousarray(cols.remaining, np.int64).tobytes(),
        np.ascontiguousarray(cols.duration, np.int64).tobytes(),
        np.ascontiguousarray(cols.stamp, np.int64).tobytes(),
        np.ascontiguousarray(cols.expire_at, np.int64).tobytes(),
    ]
    body = b"".join(parts)
    return body + _CRC.pack(zlib.crc32(body) & 0xFFFFFFFF)


def decode_snapshot(raw: bytes,
                    expected_ring: Optional[int] = None
                    ) -> Tuple[TransferColumns, dict]:
    """Bytes -> (TransferColumns, meta).  Raises SnapshotError on any
    defect; `expected_ring` (strict fencing) additionally rejects a
    FENCED file (nonzero ring_hash) whose membership fingerprint does
    not match — an unfenced file (ring_hash 0) is accepted anywhere,
    the TransferColumns convention."""
    if len(raw) < _HEADER.size + _CRC.size:
        raise SnapshotError(f"truncated snapshot ({len(raw)} bytes)")
    magic, version, _rsvd, n, saved_at, ring_hash, kb = _HEADER.unpack_from(
        raw, 0
    )
    if magic != SNAPSHOT_MAGIC:
        raise SnapshotError(f"bad magic {magic!r}")
    if version != SNAPSHOT_VERSION:
        raise SnapshotError(f"unsupported snapshot version {version}")
    total = _HEADER.size + 4 * n + kb + (4 + 4 + 8 * 5) * n + _CRC.size
    if len(raw) != total:
        raise SnapshotError(
            f"truncated snapshot ({len(raw)} bytes, expected {total})"
        )
    (crc,) = _CRC.unpack_from(raw, total - _CRC.size)
    if zlib.crc32(raw[: total - _CRC.size]) & 0xFFFFFFFF != crc:
        raise SnapshotError("checksum mismatch (bit rot or torn write)")
    if (expected_ring is not None and ring_hash != 0
            and ring_hash != (int(expected_ring) & 0xFFFFFFFFFFFFFFFF)):
        raise SnapshotError(
            f"ring fingerprint mismatch (file {ring_hash:016x}, "
            f"expected {int(expected_ring) & 0xFFFFFFFFFFFFFFFF:016x})"
        )
    pos = _HEADER.size
    offsets = np.frombuffer(raw, np.uint32, count=n, offset=pos)
    pos += 4 * n
    blob = raw[pos: pos + kb]
    if n and int(offsets[-1]) != kb:
        raise SnapshotError("key blob length mismatch")
    pos += kb

    def arr(dtype, width):
        nonlocal pos
        a = np.frombuffer(raw, dtype, count=n, offset=pos)
        pos += width * n
        return a

    algorithm = arr(np.int32, 4)
    status = arr(np.int32, 4)
    limit = arr(np.int64, 8)
    remaining = arr(np.int64, 8)
    duration = arr(np.int64, 8)
    stamp = arr(np.int64, 8)
    expire_at = arr(np.int64, 8)
    keys = []
    lo = 0
    try:
        for hi in offsets:
            keys.append(blob[lo:hi].decode("utf-8"))
            lo = int(hi)
    except UnicodeDecodeError as e:
        raise SnapshotError(f"invalid utf-8 in key blob: {e}") from None
    cols = TransferColumns(
        keys=keys,
        algorithm=algorithm.copy(),
        status=status.copy(),
        limit=limit.copy(),
        remaining=remaining.copy(),
        duration=duration.copy(),
        stamp=stamp.copy(),
        expire_at=expire_at.copy(),
        ring_hash=int(ring_hash),
    )
    meta = {
        "version": version,
        "lanes": n,
        "saved_at_ms": int(saved_at),
        "ring_hash": int(ring_hash),
        "bytes": total,
    }
    return cols, meta


def write_snapshot(path: str, cols: TransferColumns, saved_at_ms: int,
                   ring_hash: int = 0) -> int:
    """Crash-safe write: encode, write to a same-directory temp file,
    fsync, atomic rename over `path`, fsync the directory.  A reader
    (or a restart after `kill -9` at ANY instant of this sequence) sees
    either the previous complete snapshot or the new complete snapshot
    — never a torn file.  Returns the byte size written."""
    raw = encode_snapshot(cols, saved_at_ms, ring_hash)
    d = os.path.dirname(os.path.abspath(path)) or "."
    tmp = os.path.join(d, f".{os.path.basename(path)}.tmp.{os.getpid()}")
    try:
        with open(tmp, "wb") as f:
            f.write(raw)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    try:
        dirfd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(dirfd)
        finally:
            os.close(dirfd)
    except OSError:  # pragma: no cover — exotic fs without dir fsync
        pass
    return len(raw)


def read_snapshot(path: str, expected_ring: Optional[int] = None
                  ) -> Tuple[TransferColumns, dict]:
    """Load + verify one snapshot file (see decode_snapshot)."""
    with open(path, "rb") as f:
        raw = f.read()
    return decode_snapshot(raw, expected_ring=expected_ring)


# ---------------------------------------------------------------------
# Loader-SPI bridge: the reference's CacheItem stream over the columnar
# path, so custom persistence backends written against store.go port
# unchanged while the device work stays O(1) programs per batch.
# ---------------------------------------------------------------------
def columns_to_items(cols: TransferColumns):
    """TransferColumns -> List[store.CacheItem] (Loader.save feed)."""
    from .models.shard import _rows_to_items
    from .ops import buckets

    rows = buckets.BucketRows(
        algo=cols.algorithm, limit=cols.limit, remaining=cols.remaining,
        duration=cols.duration, stamp=cols.stamp, expire_at=cols.expire_at,
        status=cols.status,
    )
    return _rows_to_items(cols.keys, rows)


def items_to_columns(items) -> TransferColumns:
    """Iterable[store.CacheItem] -> TransferColumns (Loader.load feed:
    the whole stream commits in ONE device program via
    store.commit_transfer instead of one row-scatter per item)."""
    from .ops.buckets import LEAKY_SCALE
    from .store import LeakyBucketItem
    from .types import Algorithm

    items = list(items)
    n = len(items)
    cols = TransferColumns.empty()
    if n == 0:
        return cols
    keys, algo, status, limit, remaining, duration, stamp, expire = (
        [], np.empty(n, np.int32), np.zeros(n, np.int32),
        np.empty(n, np.int64), np.empty(n, np.int64),
        np.empty(n, np.int64), np.empty(n, np.int64), np.empty(n, np.int64),
    )
    for i, item in enumerate(items):
        v = item.value
        keys.append(item.key)
        expire[i] = int(item.expire_at)
        if isinstance(v, LeakyBucketItem):
            algo[i] = int(Algorithm.LEAKY_BUCKET)
            remaining[i] = int(v.remaining * LEAKY_SCALE)
            stamp[i] = int(v.updated_at)
        else:
            algo[i] = int(item.algorithm)
            remaining[i] = int(v.remaining)
            stamp[i] = int(v.created_at)
            status[i] = int(v.status)
        limit[i] = int(v.limit)
        duration[i] = int(v.duration)
    return TransferColumns(
        keys=keys, algorithm=algo, status=status, limit=limit,
        remaining=remaining, duration=duration, stamp=stamp,
        expire_at=expire,
    )


class SnapshotManager:
    """Dump/restore orchestration for one V1Service: restore at boot,
    save on close()/SIGTERM and on the GUBER_SNAPSHOT_INTERVAL cadence.
    Disabled entirely (every method an early return) when no path is
    configured — GUBER_SNAPSHOT=0 is exactly the pre-durability
    daemon."""

    def __init__(self, service, path: str = "", interval_s: float = 0.0):
        self.service = service
        self.path = path or ""
        self.interval_s = max(float(interval_s or 0.0), 0.0)
        # A custom Store-SPI object without the columnar gather/commit
        # pair cannot ride this plane; its persistence is the Loader.
        self.enabled = bool(self.path) and hasattr(
            service.store, "snapshot_columns"
        ) and hasattr(service.store, "commit_transfer")
        # Host-side counters (exported via Metrics.observe_snapshot and
        # served raw in GET /debug/status).
        self.saves_ok = 0
        self.saves_failed = 0
        self.restored_lanes = 0
        self.saved_lanes = 0
        self.restore_result = "disabled" if not self.enabled else "pending"
        self.last_save_unix = 0.0
        self.last_save_bytes = 0
        self.last_save_seconds = 0.0
        self.last_restore_seconds = 0.0
        # Ring fingerprint the restored file was saved under (None =
        # nothing restored / unfenced): V1Service.set_peers compares it
        # against the bootstrap membership and hands off no-longer-owned
        # keys through the reshard transfer path on mismatch.
        self.restored_ring_hash: Optional[int] = None
        self._save_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _sweep_orphan_temps(self) -> None:
        """Remove stale `.{name}.tmp.{pid}` siblings a crash mid-write
        left behind (each process writes a pid-suffixed temp and only
        unlinks its OWN on a caught exception — `kill -9` orphans it;
        a crash-looping daemon must not accrete one ~file-sized orphan
        per crash).  Boot-time only: this daemon owns the path, so any
        temp here is dead by definition."""
        d = os.path.dirname(os.path.abspath(self.path)) or "."
        prefix = f".{os.path.basename(self.path)}.tmp."
        try:
            names = os.listdir(d)
        except OSError:
            return
        for name in names:
            if name.startswith(prefix):
                try:
                    os.unlink(os.path.join(d, name))
                    logger.info("removed orphaned snapshot temp %s", name)
                except OSError:  # pragma: no cover — raced/forbidden
                    pass

    # -- restore (boot) ------------------------------------------------
    def restore(self) -> int:
        """Load + verify + ONE H2D merge-commit.  Any defect is a loud
        cold start: counted, flight-recorder `snapshot-rejected` event
        (auto-dump), logged — never a partial restore.  Returns lanes
        committed."""
        if not self.enabled:
            return 0
        self._sweep_orphan_temps()
        m = self.service.metrics
        if not os.path.exists(self.path):
            self.restore_result = "absent"
            if m is not None:
                m.snapshot_restores.labels(result="absent").inc()
            return 0
        t0 = time.perf_counter()
        try:
            cols, meta = read_snapshot(self.path)
        except (SnapshotError, OSError) as e:
            self.restore_result = "rejected"
            if m is not None:
                m.snapshot_restores.labels(result="rejected").inc()
            tracing.record_event(
                "snapshot-rejected", path=self.path, reason=str(e)
            )
            logger.warning(
                "snapshot %s REJECTED (cold start): %s", self.path, e
            )
            return 0
        audit.note("snapshot_loaded_lanes", len(cols))
        now_ms = self.service.clock.now_ms()
        committed = self.service.store.commit_transfer(cols, now_ms)
        audit.note("snapshot_committed_lanes", committed)
        if committed > len(cols):
            # The snapshot_restore conservation break (a commit minting
            # lanes) must fire HERE, not ride the windowed Auditor: the
            # auditor is constructed AFTER the boot restore (its arm()
            # baselines these notes away) and its first-pass extent
            # seeding would swallow a one-shot boot excess anyway.
            if m is not None:
                m.audit_violations.labels(invariant="snapshot_restore").inc()
            tracing.record_event(
                "audit-violation", invariant="snapshot_restore",
                excess=committed - len(cols),
            )
            logger.warning(
                "snapshot restore VIOLATION: committed %d lanes from a "
                "%d-lane file", committed, len(cols),
            )
        self.last_restore_seconds = time.perf_counter() - t0
        self.restored_lanes = committed
        self.restore_result = "ok"
        self.restored_ring_hash = meta["ring_hash"] or None
        if m is not None:
            m.snapshot_restores.labels(result="ok").inc()
            m.snapshot_lanes.labels(direction="restored").inc(committed)
        logger.info(
            "restored %d/%d snapshot lanes from %s "
            "(saved_at_ms=%d ring=%016x, %.1fms)",
            committed, meta["lanes"], self.path, meta["saved_at_ms"],
            meta["ring_hash"], self.last_restore_seconds * 1e3,
        )
        return committed

    # -- save (interval / close / SIGTERM) -----------------------------
    def save_now(self, reason: str = "interval") -> bool:
        """One dump: gather (under the store's drain-then-lock envelope,
        one device program), then encode + crash-safe write OUTSIDE
        every store lock.  Serialized against concurrent saves; returns
        success."""
        if not self.enabled:
            return False
        m = self.service.metrics
        with self._save_lock:
            t0 = time.perf_counter()
            try:
                now_ms = self.service.clock.now_ms()
                cols = self.service.store.snapshot_columns(now_ms)
                size = write_snapshot(
                    self.path, cols, now_ms,
                    ring_hash=getattr(self.service, "ring_hash", 0),
                )
            except Exception as e:  # noqa: BLE001 — a failed dump must
                # never take the serving path (or shutdown) down.
                self.saves_failed += 1
                if m is not None:
                    m.snapshot_writes.labels(result="error").inc()
                logger.warning(
                    "snapshot save (%s) to %s failed: %s",
                    reason, self.path, e,
                )
                return False
            self.last_save_seconds = time.perf_counter() - t0
            self.last_save_unix = time.time()
            self.last_save_bytes = size
            self.saves_ok += 1
            self.saved_lanes += len(cols)
            audit.note("snapshot_saved_lanes", len(cols))
            if m is not None:
                m.snapshot_writes.labels(result="ok").inc()
                m.snapshot_lanes.labels(direction="saved").inc(len(cols))
            logger.debug(
                "snapshot save (%s): %d lanes, %d bytes, %.1fms",
                reason, len(cols), size, self.last_save_seconds * 1e3,
            )
            return True

    def start(self) -> None:
        """Start the background cadence (no-op when disabled or
        interval 0 = shutdown-only snapshots)."""
        if not self.enabled or self.interval_s <= 0 or self._thread:
            return
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="snapshot-writer"
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.save_now("interval")
            except Exception:  # noqa: BLE001 — the writer must never die
                logger.exception("snapshot interval save failed")

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout_s)
            self._thread = None

    def snapshot(self) -> dict:
        """The /debug/status "snapshot" section."""
        return {
            "enabled": self.enabled,
            "path": self.path,
            "intervalS": self.interval_s,
            "savesOk": self.saves_ok,
            "savesFailed": self.saves_failed,
            "savedLanes": self.saved_lanes,
            "restore": self.restore_result,
            "restoredLanes": self.restored_lanes,
            "lastSaveUnix": self.last_save_unix,
            "lastSaveBytes": self.last_save_bytes,
            "lastSaveSeconds": round(self.last_save_seconds, 4),
            "lastRestoreSeconds": round(self.last_restore_seconds, 4),
        }
