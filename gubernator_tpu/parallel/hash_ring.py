"""Replicated consistent-hash ring: key -> owner peer.

Parity with the reference `ReplicatedConsistentHash`
(replicated_hash.go:36-119): 512 virtual nodes per peer, vnode hash =
hash_fn(str(replica_index) + hex(md5(peer_key))), sorted ring with
binary search, wrap-around at the top.  Default hash is FNV-1 64
(replicated_hash.go:31), selectable to FNV-1a — both pinned by the
reference's distribution test (replicated_hash_test.go:40-86), which we
reproduce exactly.

TPU-native addition: `get_batch` resolves whole key batches via
numpy `searchsorted` over the vnode array instead of per-key binary
search loops — the host-side analogue of vectorizing the kernel.
"""

from __future__ import annotations

import hashlib
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..utils import hashing

DEFAULT_REPLICAS = 512  # replicated_hash.go:29

HashFn = Callable[[str], int]


def _fnv1_str(s: str) -> int:
    return hashing.fnv1_64(s.encode("utf-8"))


def _fnv1a_str(s: str) -> int:
    return hashing.fnv1a_64(s.encode("utf-8"))


class ReplicatedConsistentHash:
    """Maps keys to peer ids (strings).  The service layer owns the
    peer-id -> transport-client mapping."""

    def __init__(self, hash_fn: Optional[HashFn] = None, replicas: int = DEFAULT_REPLICAS):
        self.hash_fn: HashFn = hash_fn or _fnv1_str
        self.replicas = replicas
        self._peers: Dict[str, object] = {}
        self._vnode_hashes = np.zeros(0, dtype=np.uint64)
        self._vnode_owner: List[str] = []
        # Integer owner codes per vnode (peer insertion order), so
        # get_batch_codes resolves a whole batch with one fancy index —
        # no per-lane owner-id string handling (service.py
        # _submit_columns routing).
        self._vnode_code = np.zeros(0, dtype=np.int32)
        self._code_ids: List[str] = []

    def new(self) -> "ReplicatedConsistentHash":
        """Fresh empty picker with the same config (replicated_hash.go:61-67)."""
        return ReplicatedConsistentHash(self.hash_fn, self.replicas)

    def size(self) -> int:
        return len(self._peers)

    def peers(self) -> List[object]:
        return list(self._peers.values())

    def peer_ids(self) -> List[str]:
        return list(self._peers.keys())

    def get_by_peer_id(self, peer_id: str):
        return self._peers.get(peer_id)

    def add(self, peer_id: str, peer: object = None) -> None:
        """Add a peer; vnode key construction mirrors replicated_hash.go:78-91."""
        self._peers[peer_id] = peer if peer is not None else peer_id
        md5_hex = hashlib.md5(peer_id.encode("utf-8")).hexdigest()
        new_hashes = np.array(
            [self.hash_fn(f"{i}{md5_hex}") for i in range(self.replicas)], dtype=np.uint64
        )
        owners = [peer_id] * self.replicas
        all_hashes = np.concatenate([self._vnode_hashes, new_hashes])
        all_owners = self._vnode_owner + owners
        order = np.argsort(all_hashes, kind="stable")
        self._vnode_hashes = all_hashes[order]
        self._vnode_owner = [all_owners[i] for i in order]
        self._code_ids = list(self._peers.keys())
        codes = {pid: c for c, pid in enumerate(self._code_ids)}
        self._vnode_code = np.fromiter(
            (codes[o] for o in self._vnode_owner), np.int32,
            count=len(self._vnode_owner),
        )

    def get(self, key: str) -> str:
        """Owner peer id for a key (replicated_hash.go:104-119)."""
        if not self._peers:
            raise RuntimeError("unable to pick a peer; pool is empty")
        h = np.uint64(self.hash_fn(key))
        idx = int(np.searchsorted(self._vnode_hashes, h, side="left"))
        if idx == len(self._vnode_owner):
            idx = 0
        return self._vnode_owner[idx]

    def get_batch(self, keys: Sequence[str]) -> List[str]:
        """Vectorized owner lookup for a whole batch of keys.  The two
        stock hash functions hash the whole batch in the C++ runtime
        (native.fnv1_batch); custom hash_fns fall back per key."""
        if not self._peers:
            raise RuntimeError("unable to pick a peer; pool is empty")
        if self.hash_fn in (_fnv1_str, _fnv1a_str):
            from .. import native

            hs = native.fnv1_batch(keys, variant_1a=self.hash_fn is _fnv1a_str)
        else:
            hs = np.array([self.hash_fn(k) for k in keys], dtype=np.uint64)
        idxs = np.searchsorted(self._vnode_hashes, hs, side="left")
        n = len(self._vnode_owner)
        return [self._vnode_owner[i if i < n else 0] for i in idxs]

    def fingerprint(self) -> int:
        """Order-independent 64-bit identity of this ring's MEMBERSHIP
        (+ vnode count): the epoch stamp ownership transfers are fenced
        on (reshard.ring_fingerprint).  Two daemons that were handed
        the same peer list compute the same fingerprint with no
        coordination."""
        from ..reshard import ring_fingerprint

        return ring_fingerprint(sorted(self._peers.keys()), self.replicas)

    def get_batch_codes(self, keys, sketch=None) -> "tuple[np.ndarray, List[str]]":
        """Fully vectorized owner lookup: (codes i32[n], id_list) where
        codes index id_list (one entry per peer, insertion order).
        `keys` may be a list of strings or a native.PackedKeys — either
        way no per-lane Python objects are created here.

        `sketch` (saturation.HotKeySketch) piggybacks on the hashes
        this lookup computes anyway: hot-key detection costs zero
        extra hashing on the routing hot path."""
        if not self._peers:
            raise RuntimeError("unable to pick a peer; pool is empty")
        if self.hash_fn in (_fnv1_str, _fnv1a_str):
            from .. import native

            hs = native.fnv1_batch(keys, variant_1a=self.hash_fn is _fnv1a_str)
        else:
            hs = np.array([self.hash_fn(k) for k in keys], dtype=np.uint64)
        if sketch is not None:
            sketch.update(hs, keys)
        idxs = np.searchsorted(self._vnode_hashes, hs, side="left")
        idxs[idxs == len(self._vnode_owner)] = 0
        return self._vnode_code[idxs], self._code_ids


def fnv1_hash() -> HashFn:
    return _fnv1_str


def fnv1a_hash() -> HashFn:
    return _fnv1a_str
