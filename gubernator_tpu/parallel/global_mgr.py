"""Host-side bookkeeping for GLOBAL keys: the process-wide gslot table.

Every GLOBAL key gets one dense id (gslot) shared by all shards, so the
device-side replica columns and hit accumulators (ops/global_ops.py) are
uniformly indexed across the mesh.  The host mirrors per-key config
(the stand-in for the full RateLimitReq the reference forwards in
GetPeerRateLimits, global.go:129-145) and the owner's slot mapping.

The per-key config mirror is COLUMNAR: parallel name/unique_key
template arrays plus the numeric config columns replace the old
per-gslot RateLimitRequest dataclass cache, so the sync decode tail can
emit wire-ready column batches (GlobalsColumns / HitColumns) straight
from array indexing — no per-key object materialization on the GLOBAL
hot path.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from ..types import (
    Behavior,
    RateLimitRequest,
    RateLimitResponse,
    UpdatePeerGlobal,
    set_behavior,
)


@dataclass
class GlobalsColumns:
    """One GLOBAL broadcast batch in column form — the host-tier
    currency of the columnar replication plane (UpdatePeerGlobals).
    Lane i of every column is one key's authoritative status."""

    keys: List[str]
    algorithm: np.ndarray  # i32[n]
    status: np.ndarray  # i32[n]
    limit: np.ndarray  # i64[n]
    remaining: np.ndarray  # i64[n]
    reset_time: np.ndarray  # i64[n]

    def __len__(self) -> int:
        return len(self.keys)

    def update_at(self, i: int) -> UpdatePeerGlobal:
        """Materialize one lane as a dataclass (compat / classic legs)."""
        return UpdatePeerGlobal(
            key=self.keys[i],
            algorithm=int(self.algorithm[i]),
            status=RateLimitResponse(
                status=int(self.status[i]),
                limit=int(self.limit[i]),
                remaining=int(self.remaining[i]),
                reset_time=int(self.reset_time[i]),
            ),
        )

    def to_updates(self) -> List[UpdatePeerGlobal]:
        return [self.update_at(i) for i in range(len(self.keys))]

    def slice(self, lo: int, hi: int) -> "GlobalsColumns":
        """Lane slice (the sender's chunking to the receive-side lane
        cap)."""
        return GlobalsColumns(
            keys=self.keys[lo:hi],
            algorithm=self.algorithm[lo:hi],
            status=self.status[lo:hi],
            limit=self.limit[lo:hi],
            remaining=self.remaining[lo:hi],
            reset_time=self.reset_time[lo:hi],
        )

    @classmethod
    def from_updates(cls, updates) -> "GlobalsColumns":
        n = len(updates)
        return cls(
            keys=[u.key for u in updates],
            algorithm=np.fromiter(
                (u.algorithm for u in updates), np.int32, count=n
            ),
            status=np.fromiter(
                (u.status.status for u in updates), np.int32, count=n
            ),
            limit=np.fromiter(
                (u.status.limit for u in updates), np.int64, count=n
            ),
            remaining=np.fromiter(
                (u.status.remaining for u in updates), np.int64, count=n
            ),
            reset_time=np.fromiter(
                (u.status.reset_time for u in updates), np.int64, count=n
            ),
        )


@dataclass
class HitColumns:
    """Aggregated remote-owner hits in column form (the sendHits
    payload, global.go:120-160): the wire template columns of each
    key's last-seen request plus the device-accumulated hit total.
    Rides the columnar GetPeerRateLimits path (wire.PeerColumns layout
    = fields [:7] of this, in order)."""

    names: List[str]
    unique_keys: List[str]
    algorithm: np.ndarray  # i32[n]
    behavior: np.ndarray  # i32[n], GLOBAL bit set (the wire behavior)
    hits: np.ndarray  # i64[n]
    limit: np.ndarray  # i64[n]
    duration: np.ndarray  # i64[n]

    def __len__(self) -> int:
        return len(self.names)

    def hash_key_at(self, i: int) -> str:
        return f"{self.names[i]}_{self.unique_keys[i]}"

    def request_at(self, i: int) -> RateLimitRequest:
        return RateLimitRequest(
            name=self.names[i],
            unique_key=self.unique_keys[i],
            hits=int(self.hits[i]),
            limit=int(self.limit[i]),
            duration=int(self.duration[i]),
            algorithm=int(self.algorithm[i]),
            behavior=int(self.behavior[i]),
        )

    def to_requests(self) -> List[RateLimitRequest]:
        return [self.request_at(i) for i in range(len(self.names))]

    def subset(self, idx) -> "HitColumns":
        """Lane subset (index array) — the per-owner grouping split."""
        idx_a = np.asarray(idx, dtype=np.int64)
        return HitColumns(
            names=[self.names[int(i)] for i in idx_a],
            unique_keys=[self.unique_keys[int(i)] for i in idx_a],
            algorithm=self.algorithm[idx_a],
            behavior=self.behavior[idx_a],
            hits=self.hits[idx_a],
            limit=self.limit[idx_a],
            duration=self.duration[idx_a],
        )

    def peer_columns(self):
        """This batch as a wire.PeerColumns tuple (the columnar
        forwarded-batch currency PeerClient sends)."""
        return (
            self.names, self.unique_keys, self.algorithm, self.behavior,
            self.hits, self.limit, self.duration,
        )


class GlobalKeyTable:
    def __init__(self, capacity: int):
        self.capacity = capacity
        self._key_to_gslot: Dict[str, int] = {}
        self._gslot_to_key: List[Optional[str]] = [None] * capacity
        self._free: List[int] = list(range(capacity - 1, -1, -1))
        self._lru: "OrderedDict[int, None]" = OrderedDict()

        self.owner_shard = np.full(capacity, -1, dtype=np.int32)
        self.owner_slot = np.full(capacity, -1, dtype=np.int32)
        self.algorithm = np.zeros(capacity, dtype=np.int32)
        self.behavior = np.zeros(capacity, dtype=np.int32)  # GLOBAL bit stripped
        self.limit = np.zeros(capacity, dtype=np.int64)
        self.duration = np.zeros(capacity, dtype=np.int64)
        self.greg_expire = np.zeros(capacity, dtype=np.int64)
        self.greg_duration = np.zeros(capacity, dtype=np.int64)
        # Host mirror of the broadcast expiry (== device rep_expire rows).
        self.rep_expire = np.zeros(capacity, dtype=np.int64)
        # Wire template columns of the last-seen request per gslot — the
        # payload template for forwarding aggregated hits to a remote
        # owner (sendHits sends full RateLimitReqs, global.go:129-145).
        # A None name marks a gslot that never saw a request here (e.g.
        # assigned by a received broadcast): nothing to forward.
        self.names: List[Optional[str]] = [None] * capacity
        self.unique_keys: List[Optional[str]] = [None] * capacity

    def __len__(self) -> int:
        return len(self._key_to_gslot)

    def key_of(self, gslot: int) -> Optional[str]:
        return self._gslot_to_key[gslot]

    def get(self, key: str) -> Optional[int]:
        g = self._key_to_gslot.get(key)
        if g is not None:
            self._lru.move_to_end(g)
        return g

    def lookup_or_assign(self, key: str, owner_shard: int):
        """Returns (gslot, evicted_gslot_or_None).  The caller must clear
        the evicted gslot's device rows before reusing it."""
        g = self._key_to_gslot.get(key)
        if g is not None:
            self._lru.move_to_end(g)
            # Ownership can flip local <-> remote when the daemon ring
            # rebalances; always track the latest claim, resetting the
            # owner-slot mapping on a change.
            if self.owner_shard[g] != owner_shard:
                self.owner_shard[g] = owner_shard
                self.owner_slot[g] = -1
            return g, None
        evicted = None
        if self._free:
            g = self._free.pop()
        else:
            g, _ = self._lru.popitem(last=False)
            old = self._gslot_to_key[g]
            if old is not None:
                del self._key_to_gslot[old]
            evicted = g
        self._key_to_gslot[key] = g
        self._gslot_to_key[g] = key
        self._lru[g] = None
        self._lru.move_to_end(g)
        self.owner_shard[g] = owner_shard
        self.owner_slot[g] = -1
        self.rep_expire[g] = 0
        # A recycled gslot must not forward the previous key's template.
        self.names[g] = None
        self.unique_keys[g] = None
        return g, evicted

    def update_config(self, g: int, req, greg_expire: int, greg_duration: int) -> None:
        """Last-writer-wins config mirror.  (The reference keeps the
        FIRST queued request's config per window and sums hits,
        global.go:83-91; configs for one key are identical in practice.)"""
        self.algorithm[g] = int(req.algorithm)
        self.behavior[g] = set_behavior(req.behavior, Behavior.GLOBAL, False)
        self.limit[g] = req.limit
        self.duration[g] = req.duration
        self.greg_expire[g] = greg_expire
        self.greg_duration[g] = greg_duration
        self.names[g] = req.name
        self.unique_keys[g] = req.unique_key

    def request_template(self, g: int, hits: int) -> Optional[RateLimitRequest]:
        """Materialize the last-seen request of gslot `g` with `hits`
        substituted — the Store-SPI on_change leg, which still needs a
        dataclass per key.  None when no request was ever seen here."""
        name = self.names[g]
        if name is None:
            return None
        return RateLimitRequest(
            name=name,
            unique_key=self.unique_keys[g],
            hits=int(hits),
            limit=int(self.limit[g]),
            duration=int(self.duration[g]),
            algorithm=int(self.algorithm[g]),
            # The stored behavior has GLOBAL stripped; every templated
            # request was a GLOBAL request, so restore the bit.
            behavior=int(self.behavior[g]) | int(Behavior.GLOBAL),
        )

    def hit_columns(self, gslots: np.ndarray, totals: np.ndarray) -> HitColumns:
        """Wire-ready hit-forward columns for `gslots` (templated lanes
        only — callers pre-filter with `templated`), hits from the
        device accumulator `totals` (indexed by gslot)."""
        g = np.asarray(gslots, dtype=np.int64)
        return HitColumns(
            names=[self.names[int(i)] for i in g],
            unique_keys=[self.unique_keys[int(i)] for i in g],
            algorithm=self.algorithm[g].astype(np.int32),
            behavior=(
                self.behavior[g] | np.int32(int(Behavior.GLOBAL))
            ).astype(np.int32),
            hits=np.asarray(totals[g], dtype=np.int64),
            limit=self.limit[g].copy(),
            duration=self.duration[g].copy(),
        )

    def templated(self, gslots: np.ndarray) -> np.ndarray:
        """Mask of gslots with a request template (names[g] set)."""
        return np.fromiter(
            (self.names[int(g)] is not None for g in gslots),
            dtype=bool, count=len(gslots),
        )

    def active_gslots(self) -> List[int]:
        return list(self._key_to_gslot.values())
