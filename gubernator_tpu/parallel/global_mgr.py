"""Host-side bookkeeping for GLOBAL keys: the process-wide gslot table.

Every GLOBAL key gets one dense id (gslot) shared by all shards, so the
device-side replica columns and hit accumulators (ops/global_ops.py) are
uniformly indexed across the mesh.  The host mirrors per-key config
(the stand-in for the full RateLimitReq the reference forwards in
GetPeerRateLimits, global.go:129-145) and the owner's slot mapping.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from ..types import Behavior, set_behavior


class GlobalKeyTable:
    def __init__(self, capacity: int):
        self.capacity = capacity
        self._key_to_gslot: Dict[str, int] = {}
        self._gslot_to_key: List[Optional[str]] = [None] * capacity
        self._free: List[int] = list(range(capacity - 1, -1, -1))
        self._lru: "OrderedDict[int, None]" = OrderedDict()

        self.owner_shard = np.full(capacity, -1, dtype=np.int32)
        self.owner_slot = np.full(capacity, -1, dtype=np.int32)
        self.algorithm = np.zeros(capacity, dtype=np.int32)
        self.behavior = np.zeros(capacity, dtype=np.int32)  # GLOBAL bit stripped
        self.limit = np.zeros(capacity, dtype=np.int64)
        self.duration = np.zeros(capacity, dtype=np.int64)
        self.greg_expire = np.zeros(capacity, dtype=np.int64)
        self.greg_duration = np.zeros(capacity, dtype=np.int64)
        # Host mirror of the broadcast expiry (== device rep_expire rows).
        self.rep_expire = np.zeros(capacity, dtype=np.int64)
        # Last-seen request per gslot, the payload template for
        # forwarding aggregated hits to a remote owner (sendHits sends
        # full RateLimitReqs, global.go:129-145).
        self.req_proto: Dict[int, object] = {}

    def __len__(self) -> int:
        return len(self._key_to_gslot)

    def key_of(self, gslot: int) -> Optional[str]:
        return self._gslot_to_key[gslot]

    def get(self, key: str) -> Optional[int]:
        g = self._key_to_gslot.get(key)
        if g is not None:
            self._lru.move_to_end(g)
        return g

    def lookup_or_assign(self, key: str, owner_shard: int):
        """Returns (gslot, evicted_gslot_or_None).  The caller must clear
        the evicted gslot's device rows before reusing it."""
        g = self._key_to_gslot.get(key)
        if g is not None:
            self._lru.move_to_end(g)
            # Ownership can flip local <-> remote when the daemon ring
            # rebalances; always track the latest claim, resetting the
            # owner-slot mapping on a change.
            if self.owner_shard[g] != owner_shard:
                self.owner_shard[g] = owner_shard
                self.owner_slot[g] = -1
            return g, None
        evicted = None
        if self._free:
            g = self._free.pop()
        else:
            g, _ = self._lru.popitem(last=False)
            old = self._gslot_to_key[g]
            if old is not None:
                del self._key_to_gslot[old]
            evicted = g
        self._key_to_gslot[key] = g
        self._gslot_to_key[g] = key
        self._lru[g] = None
        self._lru.move_to_end(g)
        self.owner_shard[g] = owner_shard
        self.owner_slot[g] = -1
        self.rep_expire[g] = 0
        return g, evicted

    def update_config(self, g: int, req, greg_expire: int, greg_duration: int) -> None:
        """Last-writer-wins config mirror.  (The reference keeps the
        FIRST queued request's config per window and sums hits,
        global.go:83-91; configs for one key are identical in practice.)"""
        self.algorithm[g] = int(req.algorithm)
        self.behavior[g] = set_behavior(req.behavior, Behavior.GLOBAL, False)
        self.limit[g] = req.limit
        self.duration[g] = req.duration
        self.greg_expire[g] = greg_expire
        self.greg_duration[g] = greg_duration
        self.req_proto[g] = req

    def active_gslots(self) -> List[int]:
        return list(self._key_to_gslot.values())
