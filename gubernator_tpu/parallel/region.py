"""Region picker: data-center name -> per-region peer picker.

Parity with region_picker.go:7-95: `get_clients(key)` returns the owner
peer for the key in EVERY region (the MULTI_REGION fan-out set), and
`pick(dc, key)` the owner within one region.

Regions are INDEPENDENT rings: adding or removing a peer in one region
rebuilds only that region's ring, so ownership in every other region is
untouched (the per-region reshard-independence rule the federation
plane composes with — tests/test_region_picker.py pins it).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .hash_ring import ReplicatedConsistentHash


class RegionPicker:
    def __init__(self, template: Optional[ReplicatedConsistentHash] = None):
        self._template = template or ReplicatedConsistentHash()
        self.regions: Dict[str, ReplicatedConsistentHash] = {}

    def new(self) -> "RegionPicker":
        return RegionPicker(self._template.new())

    def add(self, peer) -> None:
        """peer must expose .info (PeerInfo); grouped by data_center
        (region_picker.go:88-95)."""
        dc = peer.info.data_center
        ring = self.regions.get(dc)
        if ring is None:
            ring = self._template.new()
            self.regions[dc] = ring
        ring.add(peer.info.grpc_address, peer)

    def remove(self, peer) -> None:
        """Drop one peer, rebuilding ONLY its region's ring (the rings
        have no point remove; other regions' ownership is untouched by
        construction).  A region whose last peer leaves disappears from
        `regions` entirely — `pick` answers None and `get_clients`
        skips it, never a phantom entry."""
        dc = peer.info.data_center
        ring = self.regions.get(dc)
        if ring is None:
            return
        addr = peer.info.grpc_address
        survivors = [
            p for p in ring.peers()
            if p is not None and p.info.grpc_address != addr
        ]
        if len(survivors) == ring.size():
            return  # not a member
        if not survivors:
            del self.regions[dc]
            return
        rebuilt = self._template.new()
        for p in survivors:
            rebuilt.add(p.info.grpc_address, p)
        self.regions[dc] = rebuilt

    def region_names(self) -> List[str]:
        """Data-center names with at least one peer (insertion order)."""
        return [dc for dc, ring in self.regions.items() if ring.size() > 0]

    def get_clients(self, key: str) -> List[object]:
        """Owner peer for the key in each region (region_picker.go:47-59):
        exactly ONE owner per non-empty region, never None — a ring
        whose mapped peer departed (or an emptied region) is skipped
        instead of emitting a None the send loop would have to guard
        (the pre-fix behavior crashed the MULTI_REGION flush)."""
        out = []
        for ring in self.regions.values():
            if ring.size() == 0:
                continue
            owner = ring.get_by_peer_id(ring.get(key))
            if owner is not None:
                out.append(owner)
        return out

    def pick(self, dc: str, key: str):
        """Owner peer for the key within one region, or None when the
        region is unknown/empty (callers treat None as unroutable and
        requeue — federation._run_locked)."""
        ring = self.regions.get(dc)
        if ring is None or ring.size() == 0:
            return None
        return ring.get_by_peer_id(ring.get(key))

    def peers(self) -> List[object]:
        out = []
        for ring in self.regions.values():
            out.extend(ring.peers())
        return out
