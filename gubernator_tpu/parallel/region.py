"""Region picker: data-center name -> per-region peer picker.

Parity with region_picker.go:7-95: `get_clients(key)` returns the owner
peer for the key in EVERY region (the MULTI_REGION fan-out set), and
`pick(dc, key)` the owner within one region.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .hash_ring import ReplicatedConsistentHash


class RegionPicker:
    def __init__(self, template: Optional[ReplicatedConsistentHash] = None):
        self._template = template or ReplicatedConsistentHash()
        self.regions: Dict[str, ReplicatedConsistentHash] = {}

    def new(self) -> "RegionPicker":
        return RegionPicker(self._template.new())

    def add(self, peer) -> None:
        """peer must expose .info (PeerInfo); grouped by data_center
        (region_picker.go:88-95)."""
        dc = peer.info.data_center
        ring = self.regions.get(dc)
        if ring is None:
            ring = self._template.new()
            self.regions[dc] = ring
        ring.add(peer.info.grpc_address, peer)

    def get_clients(self, key: str) -> List[object]:
        """Owner peer for the key in each region (region_picker.go:47-59)."""
        out = []
        for ring in self.regions.values():
            owner_id = ring.get(key)
            out.append(ring.get_by_peer_id(owner_id))
        return out

    def pick(self, dc: str, key: str):
        ring = self.regions.get(dc)
        if ring is None:
            return None
        return ring.get_by_peer_id(ring.get(key))

    def peers(self) -> List[object]:
        out = []
        for ring in self.regions.values():
            out.extend(ring.peers())
        return out
