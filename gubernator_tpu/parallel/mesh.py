"""Mesh-sharded bucket store: key ownership = device shard.

The TPU-native replacement for the reference's peer cluster
(replicated_hash.go key->owner + per-peer caches): bucket state columns
get a leading shard axis laid out over a 1-D `jax.sharding.Mesh`, and
one program applies every shard's request sub-batch to its own state
slice in a single dispatch.  What the reference does with N gRPC
servers and a consistent-hash ring across processes, this does with N
devices and a static shardmap inside one XLA program — peer traffic
becomes ICI traffic.

GLOBAL behavior (Behavior.GLOBAL) is fully supported: non-owner shards
answer from replica columns and accumulate hits device-side; a periodic
`sync_globals()` runs ONE shard_map collective program (psum hit
aggregation -> owner apply -> psum status broadcast) in place of the
reference's three RPC pipelines (global.go).  See ops/global_ops.py.

Key -> shard assignment is `fnv1a(key) % n_shards` (a static shardmap;
the dynamic-membership ring remains at the host/daemon tier for
multi-process deployments, parallel/hash_ring.py).  The mesh is static
for the process lifetime — the reference drops bucket state on
membership change anyway (architecture.md:5-11), so elasticity lives at
the host tier in both designs.
"""

from __future__ import annotations

from dataclasses import dataclass
import threading
from functools import partial
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import telemetry
from ..models.shard import (
    ColumnarPipeline,
    RoundPlanner,
    _rows_to_items,
    _Staged,
    _wire_donate_ok,
    build_round_arrays,
    host_readback,
    item_to_rows,
    make_columns,
    make_store_resolver,
    narrow_ok,
    pad_size,
    plan_grouped_python,
    prepare_requests,
)
from ..ops import scalar as scalar_ops
from ..models.slot_table import SlotTable
from ..ops import buckets, global_ops
from ..types import (
    Behavior,
    RateLimitRequest,
    RateLimitResponse,
    UpdatePeerGlobal,
    has_behavior,
)
from ..utils import hashing
from .global_mgr import GlobalKeyTable, GlobalsColumns, HitColumns

try:
    from jax import shard_map  # jax >= 0.6
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map


def shard_of_key(key: str, n_shards: int) -> int:
    """Static shardmap: fnv1a-64 of the hash key, modulo shard count."""
    return hashing.hash_string_64(key) % n_shards


def _pad_pow2(n: int, floor: int = 8) -> int:
    """Own pow2 size buckets (>= floor) for variable-length index
    arrays handed to jitted programs: every distinct shape is its own
    XLA compile, so unpadded tick-to-tick sizes would recompile inside
    the store lock."""
    m = floor
    while m < n:
        m <<= 1
    return m


@partial(jax.jit, donate_argnums=(0, 1))
def _answer_jit(state, gcols, batch, extra, now):
    """Per-shard answer kernel with PACKED output: one i64[S, 5, B]
    array carries status/removed/cached (bit-packed), limit, remaining,
    reset_time, new_expire, so the host pays ONE device->host transfer
    per round instead of seven (each blocking readback is a full RTT —
    the dominant cost when the device sits behind a network tunnel)."""

    def one(state_s, gcols_s, batch_s, extra_s):
        ns, ng, out, cached = global_ops.answer_batch(
            state_s, gcols_s, batch_s, extra_s, now, cold_cond=False
        )
        row0 = (
            out.status.astype(jnp.int64)
            | (out.removed.astype(jnp.int64) << 1)
            | (cached.astype(jnp.int64) << 2)
        )
        packed = jnp.stack(
            (row0, out.limit, out.remaining, out.reset_time, out.new_expire)
        )
        return ns, ng, packed

    return jax.vmap(one)(state, gcols, batch, extra)


@partial(jax.jit, donate_argnums=(0, 1))
def _answer_rounds_jit(state, gcols, batch, extra, round_id, n_rounds, now):
    """Fused multi-round answer: ALL duplicate rounds of ALL shards run
    inside one dispatch (`lax.while_loop` over rounds, like
    buckets.apply_rounds), with the same packed i64[S, 5, B] output as
    _answer_jit.  One device round-trip per batch regardless of key
    multiplicity — the thundering-herd case costs the same dispatch as
    a uniform batch.  `n_rounds` is a traced scalar: one compilation
    serves every round count at a given batch width."""

    def one(state_s, gcols_s, batch_s, extra_s, rid_s):
        B = batch_s.slot.shape[0]
        packed0 = jnp.zeros((5, B), jnp.int64)

        def cond(c):
            return c[0] < n_rounds

        def body(c):
            r, st, gc, packed = c
            active = rid_s == r
            b_r = batch_s._replace(slot=jnp.where(active, batch_s.slot, -1))
            e_r = extra_s._replace(gslot=jnp.where(active, extra_s.gslot, -1))
            st, gc, out, cached = global_ops.answer_batch(st, gc, b_r, e_r, now, cold_cond=False)
            row0 = (
                out.status.astype(jnp.int64)
                | (out.removed.astype(jnp.int64) << 1)
                | (cached.astype(jnp.int64) << 2)
            )
            newp = jnp.stack(
                (row0, out.limit, out.remaining, out.reset_time, out.new_expire)
            )
            packed = jnp.where(active[None, :], newp, packed)
            return r + 1, st, gc, packed

        _, st, gc, packed = jax.lax.while_loop(
            cond, body, (jnp.asarray(0, jnp.int32), state_s, gcols_s, packed0)
        )
        return st, gc, packed

    return jax.vmap(one)(state, gcols, batch, extra, round_id)


@partial(jax.jit, donate_argnums=0)
def _rounds32_mesh_jit(state, batch32, round_id, n_rounds, now):
    """Narrow-wire fused rounds across all shards: the columnar ingress
    kernel (no GLOBAL lanes, so gcols never ride the dispatch).  One
    i32[S, 4, B] packed result."""

    def one(state_s, batch_s, rid_s):
        return buckets.apply_rounds32(state_s, batch_s, rid_s, n_rounds, now, cold_cond=False)

    return jax.vmap(one)(state, batch32, round_id)


@partial(jax.jit, donate_argnums=0)
def _rounds64_mesh_jit(state, batch, round_id, n_rounds, now):
    """Wide-wire twin of _rounds32_mesh_jit (values exceeding int32)."""

    def one(state_s, batch_s, rid_s):
        return buckets.apply_rounds(state_s, batch_s, rid_s, n_rounds, now, cold_cond=False)

    return jax.vmap(one)(state, batch, round_id)


def _rounds_packed_mesh(state, wire, n_rounds, now):
    """Dict-wire rounds behind the single-buffer wire ([S, 3P+1792]
    i32, see buckets.pack_dict_wire): one sharded transfer per batch."""

    def one(state_s, w_s):
        return buckets.apply_rounds_packed(state_s, w_s, n_rounds, now, cold_cond=False)

    return jax.vmap(one)(state, wire)


def _rounds_packed_wide_mesh(state, wire, n_rounds, now):
    """Wide-output packed dict wire (values beyond int32 — monthly/
    yearly Gregorian expiries; i64[S, 4, B] result)."""

    def one(state_s, w_s):
        return buckets.apply_rounds_packed_wide(
            state_s, w_s, n_rounds, now, cold_cond=False
        )

    return jax.vmap(one)(state, wire)


_rounds_packed_mesh_jit = jax.jit(_rounds_packed_mesh, donate_argnums=0)
_rounds_packed_wide_mesh_jit = jax.jit(_rounds_packed_wide_mesh, donate_argnums=0)
# Donating twins for the overlapped dispatch pipeline: the wire is a
# fresh per-batch sharded upload nothing reads afterwards, so on real
# accelerators (not CPU, which zero-copies uploads) XLA can recycle its
# bytes into the outputs.
_rounds_packed_mesh_donated = jax.jit(_rounds_packed_mesh, donate_argnums=(0, 1))
_rounds_packed_wide_mesh_donated = jax.jit(
    _rounds_packed_wide_mesh, donate_argnums=(0, 1)
)

# Launch-fusion programs (ColumnarPipeline._launch_group): K same-shape
# dict-wire batches applied SEQUENTIALLY inside one sharded program —
# batch i+1 sees batch i's state, exactly as K solo dispatches would,
# but the host pays one dispatch and one stacked readback for the
# group.  Cached per (k, wide, donate) module-wide.
_MESH_FUSED_JIT: dict = {}


def _mesh_fused_packed_jit(k: int, wide: bool, donate_wires: bool = True):
    key = (k, wide, donate_wires)
    fn = _MESH_FUSED_JIT.get(key)
    if fn is None:
        base = (
            buckets.apply_rounds_packed_wide if wide
            else buckets.apply_rounds_packed
        )

        def run(state, *args):
            wires, nr, now = args[:k], args[k], args[k + 1]
            outs = []
            for i in range(k):

                def one(state_s, w_s):
                    return base(state_s, w_s, nr[i], now[i], cold_cond=False)

                state, packed = jax.vmap(one)(state, wires[i])
                outs.append(packed)
            return state, jnp.stack(outs)  # [k, S, 4, P]

        donate = tuple(range(k + 1)) if donate_wires else (0,)
        fn = jax.jit(run, donate_argnums=donate)
        _MESH_FUSED_JIT[key] = fn
        telemetry.note_program_created(
            f"mesh_fused:k{k}:{'wide' if wide else 'narrow'}"
        )
    return fn


@partial(jax.jit, donate_argnums=0)
def _set_replica_jit(gcols, gslots, status, limit, remaining, reset):
    return jax.vmap(
        global_ops.set_replica, in_axes=(0, None, None, None, None, None)
    )(gcols, gslots, status, limit, remaining, reset)


@partial(jax.jit, donate_argnums=0)
def _clear_jit(gcols, idx):
    return jax.vmap(global_ops.clear_gslots, in_axes=(0, None))(gcols, idx)


@partial(jax.jit, donate_argnums=(0, 1))
def _moves_mesh_jit(state, back, pk, ps, pd, ds, dd):
    """Apply one drain window of tier moves on every shard (see
    buckets.apply_moves; padded [S, Pm] move arrays, src=-1 no-ops)."""
    return jax.vmap(buckets.apply_moves)(state, back, pk, ps, pd, ds, dd)


@partial(jax.jit, donate_argnums=0)
def _write_row_jit(state, s, slot, rows):
    # Donated single-row scatter: store-miss injection / loader placement
    # without copying the whole [S, C] state.  `rows` is a logical
    # BucketRows; decompose into the split i32 layout first.
    vals = buckets.rows_to_split(rows)
    return jax.tree.map(lambda col, val: col.at[s, slot].set(val[0]), state, vals)


@jax.jit
def _gather_rows_mesh_jit(state, slots):
    """Reshard drain/merge gather: full bucket rows for [S, P] padded
    slot arrays — ONE device program per drain batch regardless of lane
    count (padding lanes carry slot sentinels whose garbage rows the
    host masks by per-shard count)."""
    return jax.vmap(buckets.read_rows)(state, slots)


@partial(jax.jit, donate_argnums=0)
def _write_rows_mesh_jit(state, slots, rows):
    """Reshard commit scatter: [S, P] transferred rows in one donated
    program (slot -1 = padding, dropped inside buckets.write_rows)."""
    return jax.vmap(buckets.write_rows)(state, slots, rows)


_SYNC_FN_CACHE: dict = {}

# Process-wide serialization of the GLOBAL sync collective — the mesh's
# ONLY cross-device rendezvous program (psum aggregate -> owner apply ->
# psum broadcast).  Two MeshBucketStores sharing one device set (the
# multi-daemon in-process test cluster on the 8-device virtual CPU
# mesh) can otherwise enqueue their sync programs in different per-
# device orders, and two interleaved rendezvous deadlock every device
# queue behind them.  Held from dispatch through the blocking readback;
# non-collective programs never rendezvous, so they need no ordering.
# Production runs one daemon (one store) per process: zero contention.
_SYNC_COLLECTIVE_LOCK = threading.Lock()


def _get_sync_fn(mesh: Mesh, axis: str):
    """One compiled GLOBAL-sync collective program per (mesh, axis)."""
    key = (mesh, axis)
    fn = _SYNC_FN_CACHE.get(key)
    if fn is None:

        def _sync_body(state, gcols, cfg, dirty, now):
            sq = lambda t: jax.tree.map(lambda a: a[0], t)
            ns, ngc, out, applied, total = global_ops.global_sync(
                sq(state), sq(gcols), cfg, dirty[0], now, axis=axis
            )
            # Pack every host-bound column into one i64[8, G] per shard
            # (one readback per sync, not nine): row 0 bit-packs
            # removed/applied; the rep_* rows are identical across
            # shards post-broadcast, so the host reads shard 0's copy.
            i64 = jnp.int64
            packed = jnp.stack(
                (
                    out.removed.astype(i64) | (applied.astype(i64) << 1),
                    out.new_expire,
                    total,
                    ngc.rep_status.astype(i64),
                    ngc.rep_limit,
                    ngc.rep_remaining,
                    ngc.rep_reset,
                    ngc.rep_expire,
                )
            )
            ex = lambda t: jax.tree.map(lambda a: a[None], t)
            return ex(ns), ex(ngc), packed[None]

        fn = jax.jit(
            shard_map(
                _sync_body,
                mesh=mesh,
                in_specs=(P(axis), P(axis), P(), P(axis), P()),
                out_specs=(P(axis), P(axis), P(axis)),
            ),
            donate_argnums=(0, 1),
        )
        _SYNC_FN_CACHE[key] = fn
    return fn


def make_mesh(devices: Optional[Sequence[jax.Device]] = None, axis: str = "shard") -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices), (axis,))


def _locked(fn):
    """Serialize store mutators on the instance lock (donated device
    buffers must never be used concurrently)."""

    def wrapper(self, *args, **kwargs):
        with self._lock:
            return fn(self, *args, **kwargs)

    wrapper.__name__ = fn.__name__
    wrapper.__doc__ = fn.__doc__
    return wrapper


def _drained_locked(fn):
    """_locked plus a pipeline drain first: mutators that read or commit
    the slot tables / state wholesale must observe every in-flight
    columnar batch's commits, and must hold the PLAN lock too so no new
    batch can plan against the state they are mutating
    (ColumnarPipeline._drain_then_lock)."""

    def wrapper(self, *args, **kwargs):
        self._drain_then_lock()
        try:
            return fn(self, *args, **kwargs)
        finally:
            self._unlock_drained()

    wrapper.__name__ = fn.__name__
    wrapper.__doc__ = fn.__doc__
    return wrapper


def _programmed(label, lazy=False):
    """XLA-telemetry label scope as a decorator (telemetry.program):
    applied INSIDE the lock decorators so the recorded wall time is the
    program work, not drain-wait backpressure.  `lazy` marks programs
    warmup deliberately defers (telemetry.program's lazy contract)."""

    def deco(fn):
        def wrapper(self, *args, **kwargs):
            with telemetry.program(label, lazy=lazy):
                return fn(self, *args, **kwargs)

        wrapper.__name__ = fn.__name__
        wrapper.__doc__ = fn.__doc__
        return wrapper

    return deco


@dataclass
class _MeshPrep:
    """Output of MeshBucketStore's prepare stage: the mesh plan plus
    the commit closure, handed to the unlocked stage step."""

    cols: object
    now_ms: int
    force_wire: Optional[str]
    n: int
    padded: int
    n_rounds: int
    narrow: bool
    mp: object  # NativeMeshPlanner
    pos: np.ndarray
    commit: object


@dataclass
class SyncResult:
    """Host-tier work produced by one GLOBAL sync collective.

    Both legs come back in COLUMN form, emitted straight from the sync
    decode arrays (no per-key dataclasses): `broadcast_cols` feeds the
    encode-once UpdatePeerGlobals fan-out, `remote_hit_cols` rides the
    columnar GetPeerRateLimits forward.  The dataclass views
    (`broadcasts` / `remote_hits`) materialize lazily for tests and the
    classic legs."""

    broadcast_cols: Optional[GlobalsColumns] = None
    remote_hit_cols: Optional[HitColumns] = None
    # False only for the empty early return (no active gslots, nothing
    # dirty): such passes never ran the collective, so observers tuning
    # windows from sync cost must ignore them.
    did_work: bool = True

    @property
    def broadcasts(self) -> List[UpdatePeerGlobal]:
        if self.broadcast_cols is None:
            return []
        return self.broadcast_cols.to_updates()

    @property
    def remote_hits(self) -> List[RateLimitRequest]:
        if self.remote_hit_cols is None:
            return []
        return self.remote_hit_cols.to_requests()

    @property
    def broadcast_count(self) -> int:
        return 0 if self.broadcast_cols is None else len(self.broadcast_cols)


class MeshBucketStore(ColumnarPipeline):
    """Bucket tables for all local shards, sharded over a device mesh.

    The host keeps one SlotTable per shard; requests are bucketed by
    `shard_of_key`, each shard's stream is round-planned independently
    (duplicate keys serialize within their shard), and all shards' round
    r runs as ONE sharded program dispatch.

    `apply(..., home_shard=s)` models the reference's ingress topology:
    the request arrived at peer s, which may not own the key.  GLOBAL
    requests at a non-owner answer locally (replica cache or as-if-owner
    fallback, gubernator.go:231-255) and forward hits at the next
    `sync_globals()`.  Non-GLOBAL requests always route to the owner
    (the in-process equivalent of the BATCHING forward,
    peer_client.go:237-268).
    """

    def __init__(
        self,
        capacity_per_shard: int = 50_000,
        g_capacity: int = 4096,
        mesh: Optional[Mesh] = None,
        devices: Optional[Sequence[jax.Device]] = None,
        store=None,
        use_native: bool = True,
        back_capacity_per_shard: int = 0,
    ):
        """back_capacity_per_shard > 0 enables the two-tier table: a
        small FRONT table (capacity_per_shard) absorbs every kernel
        scatter — whose cost scales with the table it targets — while
        front LRU evictions DEMOTE rows to a big device-resident back
        tier (FIFO) instead of dropping them, and later lookups PROMOTE
        them back.  Total capacity = front + back per shard; state is
        lost only when the back tier itself wraps.  Requires the native
        runtime; incompatible with the Store SPI (whose resolver
        injects rows synchronously mid-round).

        Sizing contract: the front must hold one BATCH's per-shard
        working set (unique keys) with room to spare — a single batch
        whose unique keys exceed the front capacity exhausts the
        pending-write eviction guard and degrades to the planner's
        all-pending fallback (reference-grade state loss, exactly as a
        single-tier table at that capacity would).  The tiering wins
        when the churn is ACROSS batches: each batch's keys fit the
        front, while the long-tail keyspace lives in the back."""
        self.store = store
        # One mutation lock: apply/sync/inject swap donated device
        # buffers, so concurrent callers (gateway handler threads, the
        # GlobalManager tick) must serialize — the role of the
        # reference's cache mutex (gubernator.go:336-337), held per
        # BATCH here instead of per request.
        self._lock = threading.RLock()
        self.mesh = mesh if mesh is not None else make_mesh(devices)
        (self.axis,) = self.mesh.axis_names
        self.n_shards = self.mesh.devices.size
        self.capacity_per_shard = capacity_per_shard
        self.g_capacity = g_capacity
        # C++ slot tables when the native runtime is available: the
        # Python scheduling loop stays (plan_grouped_python), but every
        # lookup/commit runs at C++ hash-map speed.
        from .. import native as _native

        self._native = use_native and _native.available()
        self._init_pipeline()  # FIFO of in-flight columnar batches
        _table = _native.NativeSlotTable if self._native else SlotTable
        self.tables = [_table(capacity_per_shard) for _ in range(self.n_shards)]
        self.back_capacity_per_shard = back_capacity_per_shard
        if back_capacity_per_shard > 0:
            if not self._native:
                raise RuntimeError("two-tier table requires the native runtime")
            if store is not None:
                raise ValueError("two-tier table is incompatible with a Store SPI")
            for t in self.tables:
                t.enable_back(back_capacity_per_shard)
        # One [S, C] array: per-shard views via algo_mirror[s], and the
        # columnar commit updates it with ONE vectorized scatter.
        self.algo_mirror = np.zeros(
            (self.n_shards, capacity_per_shard), dtype=np.int32
        )
        self.gtable = GlobalKeyTable(g_capacity)
        self.dirty = np.zeros((self.n_shards, g_capacity), dtype=bool)
        # Device programs dispatched by replica-batch commits — the
        # O(1)-dispatch-per-broadcast contract is pinned by counting,
        # not timing (tests/test_global_plane.py).
        self.replica_commit_dispatches = 0
        # Same counting contract for the resharding plane
        # (tests/test_reshard.py): one gather program per drain batch,
        # gather+scatter (2) per transfer commit.
        self.transfer_drain_dispatches = 0
        self.transfer_commit_dispatches = 0

        self._sharding = NamedSharding(self.mesh, P(self.axis))
        # Wire donation (launch stage): accelerators copy uploads, so
        # the wire buffer is recyclable; CPU zero-copies host numpy.
        self._wire_donate = _wire_donate_ok(self.mesh.devices.flat[0])
        self.state = self._stack_and_shard(buckets.init_state(capacity_per_shard))
        self.back = (
            self._stack_and_shard(buckets.init_back(back_capacity_per_shard))
            if back_capacity_per_shard > 0
            else None
        )
        self.gcols = self._stack_and_shard(global_ops.init_global_columns(g_capacity))

        # Jitted programs are MODULE-level (or cached per mesh) so every
        # store/daemon in a process shares one XLA compilation cache —
        # per-instance closures would recompile everything per daemon.
        self._answer_fn = _answer_jit
        self._answer_rounds_fn = _answer_rounds_jit
        self._sync_fn = _get_sync_fn(self.mesh, self.axis)
        self._set_replica_fn = _set_replica_jit
        self._clear_fn = _clear_jit
        self._write_row_fn = _write_row_jit

    def _stack_and_shard(self, single):
        stacked = jax.tree.map(
            lambda c: np.broadcast_to(np.asarray(c), (self.n_shards,) + c.shape).copy(), single
        )
        return jax.tree.map(lambda c: jax.device_put(c, self._sharding), stacked)

    def _drain_moves(self) -> None:
        """Apply every queued tier move (caller holds the store lock).

        Planning queues promotions/demotions in the C++ tables; this
        dispatches ONE small move program for the whole mesh so the
        rows are in their new homes before any program that reads
        front rows.  No-op (no dispatch) when nothing is queued — the
        steady state for front-resident traffic."""
        if self.back is None:
            return
        counts = [t.move_counts() for t in self.tables]
        max_p = max(p for p, _ in counts)
        max_d = max(d for _, d in counts)
        if max_p == 0 and max_d == 0:
            return
        S = self.n_shards
        pp, dp = _pad_pow2(max_p), _pad_pow2(max_d)
        pk = np.zeros((S, pp), dtype=np.int32)
        ps = np.full((S, pp), -1, dtype=np.int32)
        pd = np.zeros((S, pp), dtype=np.int32)
        ds = np.full((S, dp), -1, dtype=np.int32)
        dd = np.zeros((S, dp), dtype=np.int32)
        for s, t in enumerate(self.tables):
            n_p, n_d = counts[s]
            if n_p == 0 and n_d == 0:
                continue
            tpk, tps, tpd, tds, tdd = t.take_moves()
            pk[s, :n_p] = tpk
            ps[s, :n_p] = tps
            pd[s, :n_p] = tpd
            ds[s, :n_d] = tds
            dd[s, :n_d] = tdd
        put = lambda a: jax.device_put(a, self._sharding)  # noqa: E731
        self.state, self.back = _moves_mesh_jit(
            self.state, self.back, put(pk), put(ps), put(pd), put(ds), put(dd)
        )

    # ------------------------------------------------------------------
    @_drained_locked
    def apply(
        self,
        requests: Sequence[RateLimitRequest],
        now_ms: int,
        home_shard: Optional[int] = None,
        remote_global: bool = False,
    ) -> List[RateLimitResponse]:
        """Evaluate a batch across all shards; responses in request order.

        remote_global=True marks every GLOBAL request's authoritative
        owner as a REMOTE daemon (V1Service sets this when the hash ring
        maps the key to another peer): the key is answered locally from
        its replica cache / fallback bucket, hits accumulate device-side,
        and sync_globals() surfaces the totals for the host to forward.
        """
        responses: List[Optional[RateLimitResponse]] = [None] * len(requests)
        prepared = prepare_requests(requests, now_ms, responses)

        by_shard: List[list] = [[] for _ in range(self.n_shards)]
        for p in prepared:
            owner = shard_of_key(p.key, self.n_shards)
            target = owner
            if has_behavior(p.req.behavior, Behavior.GLOBAL):
                owner_mark = -1 if remote_global else owner
                g, evicted = self.gtable.lookup_or_assign(p.key, owner_mark)
                if evicted is not None:
                    self.gcols = self._clear_fn(self.gcols, np.array([evicted], np.int32))
                self.gtable.update_config(g, p.req, p.greg_expire, p.greg_duration)
                non_owner = remote_global or (home_shard is not None and home_shard != owner)
                if non_owner:
                    # Non-owner: answer locally, forward hits at sync
                    # (gubernator.go:231-255).
                    p.gslot = g
                    target = owner if remote_global else home_shard
                    if self.gtable.rep_expire[g] >= now_ms:
                        p.cached_hint = True
                else:
                    # Owner applies directly and owes a broadcast
                    # (getRateLimit's QueueUpdate, gubernator.go:339-341).
                    self.dirty[owner, g] = True
            by_shard[target].append(p)

        if self.store is None:
            self._apply_fused(by_shard, now_ms, responses)
        else:
            # Store SPI needs per-round host callbacks (get/on_change
            # between rounds), so it keeps the interleaved loop.
            planners = [
                RoundPlanner(
                    self.tables[s],
                    by_shard[s],
                    now_ms,
                    resolver=self._store_resolver(s, now_ms),
                )
                for s in range(self.n_shards)
            ]
            while True:
                chunks = [pl.next_chunk() for pl in planners]
                if not any(chunks):
                    break
                self._run_round(chunks, now_ms, responses)

        return [r if r is not None else RateLimitResponse() for r in responses]

    # ------------------------------------------------------------------
    # Columnar bulk ingress (zero-dataclass hot path)
    # ------------------------------------------------------------------
    @property
    def supports_columns(self) -> bool:
        """True when the zero-dataclass bulk path is usable (native host
        runtime present, no synchronous Store SPI callbacks)."""
        return self._native and self.store is None

    def describe_topology(self) -> "Tuple[str, str]":
        """(backend platform, mesh shape string) for the
        gubernator_build_info gauge: e.g. ("tpu", "8") for a flat
        8-device mesh."""
        try:
            platform = self.mesh.devices.flat[0].platform
        except Exception:  # noqa: BLE001
            platform = "unknown"
        return platform, "x".join(str(d) for d in self.mesh.devices.shape)

    def apply_columns(
        self, keys, algorithm, behavior, hits, limit, duration, now_ms: int,
        greg_expire=None, greg_duration=None, force_wire=None,
    ) -> dict:
        """Columnar bulk API over the whole mesh: keys bucket onto
        shards by the static shardmap (fnv1a % n_shards, batched in
        C++), each shard's stream round-plans in its own C++ table, and
        ALL shards' rounds run in ONE fused dispatch.  Returns a dict of
        numpy arrays (status/limit/remaining/reset_time) aligned with
        `keys`.  GLOBAL lanes are rejected — their replica-cache
        semantics live on the dataclass path (`apply`)."""
        return self.apply_columns_async(
            keys, algorithm, behavior, hits, limit, duration, now_ms,
            greg_expire, greg_duration, force_wire=force_wire,
        ).result()

    def apply_columns_async(
        self, keys, algorithm, behavior, hits, limit, duration, now_ms: int,
        greg_expire=None, greg_duration=None, force_wire=None,
    ) -> ColumnsHandle:
        """Pipelined apply_columns (see ShardStore.apply_columns_async):
        dispatch returns immediately; `handle.result()` blocks on the
        one packed readback.  Concurrent ingress threads overlap host
        planning with device compute via the ColumnarPipeline locks."""
        if not (self._native and self.store is None):
            raise RuntimeError(
                "apply_columns requires the native host runtime and no Store SPI"
            )
        cols = make_columns(
            algorithm, behavior, hits, limit, duration, len(keys),
            greg_expire, greg_duration,
        )
        if (cols.behavior & int(Behavior.GLOBAL)).any():
            raise ValueError("GLOBAL lanes must take the dataclass path (apply)")
        return self._submit_pipelined(keys, cols, now_ms, force_wire)

    def _prepare_columns(self, keys, cols, now_ms: int,
                         force_wire: Optional[str] = None) -> "_MeshPrep":
        """Stage 1 of the overlapped dispatch (under `_plan_lock`): the
        slot-table work only — gt_mesh_begin + gt_mesh_plan_grouped
        (hash/bucket every key, per-shard grouped round planning,
        padded [S, P] fill).  Tier moves queued by this plan stay
        queued; the LAUNCH stage drains them, ordered against the
        device program.  The commit side stays ONE C++ call
        (gt_mesh_finish_*: decode, slot-table commit, original-order
        scatter), safe against the NEXT batch's concurrent planning via
        the per-table native mutex."""
        from .. import native as _native

        n = len(keys)
        mp = _native.NativeMeshPlanner(self.tables, keys, now_ms)
        padded = pad_size(max(int(mp.counts.max()) if n else 1, 1))
        n_rounds = mp.plan_grouped(
            cols, int(Behavior.RESET_REMAINING), padded
        )
        pos = mp.pos[:n]
        narrow = narrow_ok(cols, now_ms) and force_wire != "wide"

        def commit(packed_np):
            with self._lock:
                if narrow:
                    status, rem, reset = mp.finish_narrow(packed_np, now_ms)
                else:
                    status, rem, reset = mp.finish_wide(packed_np)
                if n:
                    # Host algo mirror (Store-SPI bookkeeping parity):
                    # one vectorized 2-D scatter, no per-shard masks.
                    self.algo_mirror[
                        pos // padded, mp.slot.reshape(-1)[pos]
                    ] = cols.algo
            return status, rem, reset

        return _MeshPrep(
            cols=cols, now_ms=now_ms, force_wire=force_wire, n=n,
            padded=padded, n_rounds=n_rounds, narrow=narrow,
            mp=mp, pos=pos, commit=commit,
        )

    def _stage_columns(self, prep: "_MeshPrep") -> "_Staged":
        """Stage 2 (no locks): encode the wire and start the sharded
        H2D upload while older batches compute/transfer."""
        cols, now_ms, padded = prep.cols, prep.now_ms, prep.padded
        mp, pos, n_rounds, narrow = prep.mp, prep.pos, prep.n_rounds, prep.narrow
        S = self.n_shards
        dict_enc = None
        if prep.force_wire is None and n_rounds <= 255:
            # Values live in the dict wire's 256-row i64 table, so wide
            # batches (monthly/yearly Gregorian) stay on it too — only
            # the output width switches (apply_rounds_packed_wide).
            dict_enc = buckets.build_config_dict(cols, now_ms)

        if dict_enc is not None and int(mp.occ.max()) <= 65535:
            cfg_full, cfg_table = dict_enc
            cfg_a = np.zeros((S, padded), dtype=np.uint8)
            cfg_a.reshape(-1)[pos] = cfg_full
            # Single-buffer wire: ONE sharded host->device transfer per
            # batch instead of 12 (per-call overhead dominates at
            # service batch sizes).
            wire = buckets.pack_dict_wire(
                mp.slot, mp.exists, mp.write, cfg_a, mp.occ, mp.rid, cfg_table
            )
            wire_dev = jax.device_put(wire, self._sharding)
            # (A compacted-commit variant — scatter only the write
            # lanes, buckets.apply_compact32 — measured SLOWER on TPU
            # v5e despite submitting ~4x fewer rows: the scatter's
            # price at these shapes is not per-submitted-row.  See
            # benchmarks/RESULTS.md round-4 notes; the kernel remains
            # available and equivalence-tested.)
            if self._wire_donate:
                fn_packed = (
                    _rounds_packed_mesh_donated if narrow
                    else _rounds_packed_wide_mesh_donated
                )
            else:
                fn_packed = (
                    _rounds_packed_mesh_jit if narrow
                    else _rounds_packed_wide_mesh_jit
                )
            with self._stats_lock:
                self._seen_wire_shapes.add((wire.shape[1], narrow))
            return _Staged(
                solo=lambda state: fn_packed(state, wire_dev, n_rounds, now_ms),
                fuse_key=("dict", narrow, wire.shape[1]),
                wire_dev=wire_dev, n_rounds=n_rounds, now_ms=now_ms,
                wide=not narrow,
            )
        vdt = np.int32 if narrow else np.int64

        def scatter(col, dtype):
            a = np.zeros((S, padded), dtype=dtype)
            a.reshape(-1)[pos] = col
            return a

        if narrow:
            ge = np.where(
                cols.greg_duration != 0, cols.greg_expire - now_ms, 0
            )
        else:
            ge = cols.greg_expire
        mk = buckets.make_batch32 if narrow else buckets.make_batch
        batch = mk(
            mp.slot, mp.exists.astype(bool), scatter(cols.algo, np.int32),
            scatter(cols.behavior, np.int32), scatter(cols.hits, vdt),
            scatter(cols.limit, vdt), scatter(cols.duration, vdt),
            scatter(ge, vdt), scatter(cols.greg_duration, vdt),
            occ=mp.occ, write=mp.write.astype(bool),
        )
        batch = jax.tree.map(lambda a: jax.device_put(a, self._sharding), batch)
        rid_dev = jax.device_put(jnp.asarray(mp.rid), self._sharding)
        fn = _rounds32_mesh_jit if narrow else _rounds64_mesh_jit
        return _Staged(
            solo=lambda state: fn(state, batch, rid_dev, n_rounds, now_ms)
        )

    def _padded_lanes(self, prep) -> int:
        # Mesh pads PER SHARD: one launch scatters S * padded lanes.
        return prep.padded * self.n_shards

    def _pre_launch(self) -> None:
        # Tier moves queued by the group's plans (and any stale window)
        # must land before the batch programs read front rows.  One
        # drain covers the group: moves queued by a LATER plan are safe
        # to apply early — the pending-write guard keeps every
        # in-flight batch's slots out of the mover's reach.
        self._drain_moves()

    def _fused_launch_fn(self, k: int, wide: bool):
        return _mesh_fused_packed_jit(k, wide, donate_wires=self._wire_donate)

    # -- express scalar slot (ops/scalar.py) ---------------------------
    def _scalar_eligible(self, cols) -> bool:
        """Mesh twin of ShardStore._scalar_eligible: each lane of a
        small batch lives in exactly one shard, so the host evaluates
        them sequentially against the shards' rows through writable
        shard views — no mesh-wide program.  Two-tier stores are
        excluded (their plans queue tier moves that only the device
        launch drains)."""
        if not self.scalar_fast_path:
            return False
        if not 1 <= len(cols.hits) <= self.scalar_max_lanes:
            return False
        if not (self._native and self.store is None) or self.back is not None:
            return False
        if self._scalar_ok is None:
            with self._lock:
                # In-flight async programs must finish before the probe
                # writes a spare lane of the live buffer.
                jax.block_until_ready(self.state)
                self._scalar_ok = scalar_ops.device_is_cpu(
                    self.mesh.devices.flat[0]
                ) and scalar_ops.probe(self.state.hot, sharded=True)
        return self._scalar_ok

    def _stage_scalar(self, prep: "_MeshPrep") -> "_Staged":
        """Express stage: locate each lane's (shard, row) from the mesh
        plan and return the host-evaluation closure; its packed
        [S, 4, P] wide output feeds the unchanged mp.finish_wide commit
        (decode + slot-table commit + original-order scatter).  Lanes
        apply sequentially in submission order — the semantics the
        kernel's round/duplicate-group machinery reproduces (see
        ShardStore._stage_scalar for the exists rule)."""
        cols, mp, padded = prep.cols, prep.mp, prep.padded
        n = prep.n
        pos = prep.pos[:n].copy()
        now_ms = prep.now_ms
        S = self.n_shards

        def run():
            views: dict = {}
            packed = np.zeros((S, 4, padded), dtype=np.int64)
            for i in range(n):
                p = int(pos[i])
                s, j = p // padded, p % padded
                if s not in views:
                    hot = scalar_ops.shard_view(self.state.hot, s)
                    cold = scalar_ops.shard_view(self.state.cold, s)
                    if hot is None or cold is None:
                        raise RuntimeError(
                            "scalar fast path: state view unavailable"
                        )
                    views[s] = (hot, cold)
                hot, cold = views[s]
                slot = int(mp.slot[s, j])
                ex = bool(mp.exists[s, j]) or int(mp.occ[s, j]) > 0
                st, rem, reset, n_exp, removed = scalar_ops.apply_one(
                    hot[slot], cold[slot],
                    exists=ex,
                    algorithm=int(cols.algo[i]),
                    behavior=int(cols.behavior[i]),
                    hits=int(cols.hits[i]),
                    limit=int(cols.limit[i]),
                    duration=int(cols.duration[i]),
                    greg_expire=int(cols.greg_expire[i]),
                    greg_duration=int(cols.greg_duration[i]),
                    now_ms=now_ms,
                )
                packed[s, 0, j] = st | (int(removed) << 1)
                packed[s, 1, j] = rem
                packed[s, 2, j] = reset
                packed[s, 3, j] = n_exp
            return packed

        return _Staged(solo=None, scalar=run)

    # ------------------------------------------------------------------
    def _apply_fused(self, by_shard, now_ms: int, responses) -> None:
        """One dispatch for the whole batch: every shard's rounds run
        inside _answer_rounds_jit; one packed readback; one commit."""
        if not any(by_shard):
            return  # every request failed validation: nothing to dispatch
        S = self.n_shards
        plans = []
        n_rounds = 1
        maxb = 1
        for s in range(S):
            rid, occ, wr, nr = plan_grouped_python(
                self.tables[s], by_shard[s], now_ms
            )
            plans.append((rid, occ, wr))
            n_rounds = max(n_rounds, nr)
            maxb = max(maxb, len(by_shard[s]))
        self._drain_moves()  # tier moves queued by plan_grouped_python
        padded = pad_size(maxb)
        cols = [build_round_arrays(by_shard[s], padded) for s in range(S)]
        stacked = [np.stack([c[f] for c in cols]) for f in range(9)]
        rid_a = np.zeros((S, padded), np.int32)
        occ_a = np.zeros((S, padded), np.int32)
        wr_a = np.zeros((S, padded), dtype=bool)
        gslot = np.full((S, padded), -1, dtype=np.int32)
        for s in range(S):
            m = len(by_shard[s])
            if not m:
                continue
            rid, occ, wr = plans[s]
            rid_a[s, :m] = rid
            occ_a[s, :m] = occ
            wr_a[s, :m] = wr
            for i, p in enumerate(by_shard[s]):
                gslot[s, i] = p.gslot

        batch = buckets.RequestBatch(
            *[jnp.asarray(a) for a in stacked],
            occ=jnp.asarray(occ_a),
            write=jnp.asarray(wr_a),
        )
        batch = jax.tree.map(lambda c: jax.device_put(c, self._sharding), batch)
        extra = global_ops.GlobalBatchExtra(
            gslot=jax.device_put(jnp.asarray(gslot), self._sharding)
        )
        rid_dev = jax.device_put(jnp.asarray(rid_a), self._sharding)

        self.state, self.gcols, packed = self._answer_rounds_fn(
            self.state, self.gcols, batch, extra, rid_dev, n_rounds, now_ms
        )

        # Only scattering lanes commit bookkeeping (grouped
        # intermediates' new_expire is not the final state).
        self._decode_commit_respond(packed, by_shard, responses, write=wr_a)

    def _decode_commit_respond(self, packed, chunks, responses, write=None) -> np.ndarray:
        """Shared tail of both dispatch paths: decode the packed
        [S, 5, B] device result, fill responses, and fold bookkeeping
        back into the slot tables.  `write` masks which lanes commit
        (None = every non-cached lane, the single-round case).  Returns
        the cached mask for the Store-SPI caller."""
        packed_np = host_readback(packed)  # the one blocking transfer
        row0 = packed_np[:, 0]
        out_status = (row0 & 1).astype(np.int32)
        out_removed = ((row0 >> 1) & 1).astype(bool)
        cached_np = ((row0 >> 2) & 1).astype(bool)
        out_limit = packed_np[:, 1]
        out_rem = packed_np[:, 2]
        out_reset = packed_np[:, 3]
        out_exp = packed_np[:, 4]

        for s, chunk in enumerate(chunks):
            if not chunk:
                continue
            commit_slots, commit_exp, commit_rm, commit_keys = [], [], [], []
            for i, p in enumerate(chunk):
                commits = write[s, i] if write is not None else True
                if commits and not cached_np[s, i] and p.slot >= 0:
                    commit_slots.append(p.slot)
                    commit_exp.append(out_exp[s, i])
                    commit_rm.append(out_removed[s, i])
                    commit_keys.append(p.key)
                    self.algo_mirror[s][p.slot] = int(p.req.algorithm)
                responses[p.pos] = RateLimitResponse(
                    status=int(out_status[s, i]),
                    limit=int(out_limit[s, i]) if cached_np[s, i] else int(p.req.limit),
                    remaining=int(out_rem[s, i]),
                    reset_time=int(out_reset[s, i]),
                )
            self.tables[s].commit(commit_slots, commit_exp, commit_rm, keys=commit_keys)
        return cached_np

    # ------------------------------------------------------------------
    def _run_round(self, chunks, now_ms: int, responses) -> None:
        self._drain_moves()  # tier moves queued while planning the round
        padded = pad_size(max(max((len(c) for c in chunks), default=1), 1))
        cols = [build_round_arrays(c, padded) for c in chunks]
        stacked = [np.stack([col[f] for col in cols]) for f in range(9)]
        gslot = np.full((self.n_shards, padded), -1, dtype=np.int32)
        for s, chunk in enumerate(chunks):
            for i, p in enumerate(chunk):
                gslot[s, i] = p.gslot

        batch = buckets.RequestBatch(*[jnp.asarray(a) for a in stacked])
        batch = jax.tree.map(lambda c: jax.device_put(c, self._sharding), batch)
        extra = global_ops.GlobalBatchExtra(
            gslot=jax.device_put(jnp.asarray(gslot), self._sharding)
        )

        self.state, self.gcols, packed = self._answer_fn(
            self.state, self.gcols, batch, extra, now_ms
        )

        cached_np = self._decode_commit_respond(packed, chunks, responses)
        if self.store is not None:
            removed_np = (np.asarray(packed)[:, 0] >> 1 & 1).astype(bool)
            for s, chunk in enumerate(chunks):
                if chunk:
                    self._fire_store_callbacks(s, chunk, cached_np[s], removed_np[s])

    # ------------------------------------------------------------------
    # Store SPI (persistence) — same call pattern as ShardStore.
    # ------------------------------------------------------------------
    def _store_resolver(self, s: int, now_ms: int):
        return make_store_resolver(
            self.tables[s],
            self.algo_mirror[s],
            self.store,
            lambda slot, item: self._inject(s, slot, item),
            now_ms,
        )

    def _inject(self, s: int, slot: int, item) -> None:
        rows = item_to_rows(item)
        self.algo_mirror[s][slot] = int(rows.algo[0])
        self.state = self._write_row_fn(
            self.state, np.int32(s), np.int32(slot), rows
        )
        self.tables[s].set_expire(slot, item.expire_at)

    def _read_shard_rows(self, s: int, slots):
        idx = np.asarray(slots, np.int32)
        shard_state = jax.tree.map(lambda col: col[s], self.state)
        return jax.tree.map(np.asarray, buckets.read_rows(shard_state, idx))

    def _fire_store_callbacks(self, s: int, chunk, cached_row, removed_row) -> None:
        live = []
        for i, p in enumerate(chunk):
            if cached_row[i] or p.slot < 0:
                continue  # replica-cache answers never touch the store
            if removed_row[i]:
                self.store.remove(p.key)
            else:
                live.append((i, p))
        if not live:
            return
        rows = self._read_shard_rows(s, [p.slot for _, p in live])
        items = _rows_to_items([p.key for _, p in live], rows)
        for (_, p), item in zip(live, items):
            self.store.on_change(p.req, item)

    @_drained_locked
    def load_item(self, item) -> None:
        """Loader.Load path (gubernator.go:78-90), routed to the owner shard."""
        s = shard_of_key(item.key, self.n_shards)
        slot, _ = self.tables[s].lookup_or_assign(item.key, 0)
        # A promotion queued by the resolve would otherwise overwrite
        # the injected row at the next drain.
        self._drain_moves()
        self._inject(s, slot, item)

    @_drained_locked
    def snapshot_items(self):
        """Loader.Save path (gubernator.go:93-111) across all shards.
        Materialized under the lock so a concurrent apply cannot swap
        state buffers mid-snapshot."""
        self._drain_moves()  # pending promotions leave front rows stale
        items = []
        for s in range(self.n_shards):
            keys = self.tables[s].keys()
            if keys:
                slots = [self.tables[s].get_slot(k) for k in keys]
                rows = self._read_shard_rows(s, slots)
                items.extend(_rows_to_items(keys, rows))
            if self.back is not None:
                bkeys, bslots, _ = self.tables[s].back_entries()
                if bkeys:
                    back_shard = jax.tree.map(lambda col: col[s], self.back)
                    rows = jax.tree.map(
                        np.asarray,
                        buckets.read_back_rows(back_shard, bslots),
                    )
                    items.extend(_rows_to_items(bkeys, rows))
        return items

    # ------------------------------------------------------------------
    # Elastic membership: columnar state handoff (reshard.py).
    # ------------------------------------------------------------------
    @_drained_locked
    def resident_keys(self) -> "List[str]":
        """Every key currently resident in the FRONT slot tables (the
        ring-delta scan input).  Back-tier rows do not migrate: they
        are the cold long tail by construction, and a stale row at the
        old owner is unreachable once routing moves — it ages out of
        the FIFO (architecture.md "Membership & resharding" documents
        the bound).  Host-only, no device programs — but it must hold
        the PLAN lock like snapshot_items: the native table's key
        enumeration is a size-then-fill marshal, and a concurrent
        batch planner growing the table between the two calls would
        overrun the fill buffer."""
        out: List[str] = []
        for t in self.tables:
            out.extend(t.keys())
        return out

    def resident_mask(self, keys) -> np.ndarray:
        """Which keys currently map to a slot — the handoff peek's
        observe-don't-create filter (a zero-hit dispatch for an absent
        key would mint a shadow bucket that later rides the transfer
        plane as noise).  Single guarded C++ lookups per key: safe
        without the plan lock, unlike the size-then-fill enumeration
        resident_keys needs it for."""
        out = np.zeros(len(keys), dtype=bool)
        for j, k in enumerate(keys):
            t = self.tables[shard_of_key(k, self.n_shards)]
            out[j] = t.get_slot(k) is not None
        return out

    @_drained_locked
    @_programmed("mesh:reshard_gather", lazy=True)
    def drain_keys(self, keys, now_ms: int, remove: bool = True):
        """Drain moved keys off the device: resolve their slots in the
        host tables and gather the full bucket rows with ONE mesh-wide
        device program (the PR 5 readback playbook in reverse) —
        atomically with respect to dispatches (the pipeline is drained
        and the plan lock held).  With remove=True the keys also leave
        the tables immediately; the resharding handoff passes
        remove=False and calls forget_keys() only after the transfer is
        ACKED, so the old owner's copy stays readable (the
        double-dispatch peek target) for the whole in-flight window and
        an aborted transfer loses nothing.  Keys no longer resident
        (evicted/expired since the ring-delta scan) and GLOBAL keys
        (they migrate through their own replication plane — every peer
        already holds replica state and the new owner's first sync
        takes over aggregation) are skipped.  Returns a
        reshard.TransferColumns."""
        return self._gather_transfer_locked(keys, now_ms, remove,
                                            skip_global=True)

    @_drained_locked
    @_programmed("mesh:snapshot_gather", lazy=True)
    def snapshot_columns(self, now_ms: int):
        """Durability dump (snapshot.py): every FRONT-resident key's
        full bucket row in ONE mesh-wide gather program — drain_keys'
        all-keys variant.  Unlike a reshard drain it KEEPS the tables
        (gather-only) and INCLUDES owner-side GLOBAL buckets (they
        restore as ordinary rows; the gslot table and replica columns
        rebuild from traffic + broadcasts).  Back-tier rows are the
        cold long tail by construction and are not snapshotted — the
        same documented bound as the reshard plane.  Warmup keys stay
        out of the file."""
        keys = [
            k for t in self.tables for k in t.keys()
            if not k.startswith("__warmup__")
        ]
        return self._gather_transfer_locked(keys, now_ms, remove=False,
                                            skip_global=False)

    def _gather_transfer_locked(self, keys, now_ms: int, remove: bool,
                                skip_global: bool):
        from ..reshard import TransferColumns

        per_slot: List[List[int]] = [[] for _ in range(self.n_shards)]
        per_keys: List[List[str]] = [[] for _ in range(self.n_shards)]
        gkeys = self.gtable._key_to_gslot  # noqa: SLF001
        for k in keys:
            if skip_global and k in gkeys:
                continue
            s = shard_of_key(k, self.n_shards)
            slot = self.tables[s].get_slot(k)
            if slot is None:
                continue
            per_slot[s].append(slot)
            per_keys[s].append(k)
        max_n = max((len(x) for x in per_slot), default=0)
        if max_n == 0:
            return TransferColumns.empty()
        # Two-tier: get_slot may have queued promotions; land them so
        # the front rows we gather are current.
        self._drain_moves()
        S = self.n_shards
        P = _pad_pow2(max_n)
        slots = np.full((S, P), -1, dtype=np.int32)
        for s in range(S):
            if per_slot[s]:
                slots[s, : len(per_slot[s])] = per_slot[s]
        rows = jax.tree.map(
            np.asarray,
            _gather_rows_mesh_jit(
                self.state, jax.device_put(slots, self._sharding)
            ),
        )
        self.transfer_drain_dispatches += 1
        self.device_dispatches += 1
        out_keys: List[str] = []
        cols = {
            name: [] for name in (
                "algo", "status", "limit", "remaining", "duration",
                "stamp", "expire_at",
            )
        }
        for s in range(S):
            n = len(per_keys[s])
            if n == 0:
                continue
            out_keys.extend(per_keys[s])
            cols["algo"].append(rows.algo[s, :n])
            cols["status"].append(rows.status[s, :n])
            cols["limit"].append(rows.limit[s, :n])
            cols["remaining"].append(rows.remaining[s, :n])
            cols["duration"].append(rows.duration[s, :n])
            cols["stamp"].append(rows.stamp[s, :n])
            cols["expire_at"].append(rows.expire_at[s, :n])
            if remove:
                for k in per_keys[s]:
                    self.tables[s].remove(k)
        cat = {k: np.concatenate(v) for k, v in cols.items()}
        # Expired rows (warmup keys, long-idle buckets) are removed
        # from the tables like everything else but carry no state worth
        # shipping: filter them out of the wire payload.
        live = np.nonzero(cat["expire_at"] >= now_ms)[0]
        return TransferColumns(
            keys=[out_keys[int(i)] for i in live],
            algorithm=cat["algo"][live].astype(np.int32),
            status=cat["status"][live].astype(np.int32),
            limit=cat["limit"][live].astype(np.int64),
            remaining=cat["remaining"][live].astype(np.int64),
            duration=cat["duration"][live].astype(np.int64),
            stamp=cat["stamp"][live].astype(np.int64),
            expire_at=cat["expire_at"][live].astype(np.int64),
        )

    @_drained_locked
    def forget_keys(self, keys) -> None:
        """Drop keys from the host tables (no device program: a freed
        slot's stale row is overwritten on reassignment, exists=False).
        The resharding handoff calls this after a transfer is ACKED —
        hits the old owner admitted between the drain gather and this
        point are the documented in-flight slack."""
        for k in keys:
            self.tables[shard_of_key(k, self.n_shards)].remove(k)

    @_drained_locked
    @_programmed("mesh:reshard_commit", lazy=True)
    def commit_transfer(self, cols, now_ms: int) -> int:
        """Receive side of an ownership transfer: assign slots for the
        whole batch in the host tables, gather the CURRENT rows for
        keys already resident (they admitted traffic during the handoff
        window), MERGE monotonically (reshard.merge_transfer_rows:
        remaining=min, status/stamp/expire=max — idempotent, so a
        re-delivered transfer cannot double-count), and scatter the
        merged rows back with ONE donated program.  O(1) device
        dispatches per batch (gather + scatter), pinned by counting
        `transfer_commit_dispatches` / `device_dispatches` — the
        set_replica_batch playbook applied to the main bucket tables.
        Returns the number of lanes committed."""
        from ..reshard import merge_transfer_rows

        n = len(cols)
        if n == 0:
            return 0
        # Dead rows (already expired in transit) are not worth a slot.
        fresh = np.nonzero(np.asarray(cols.expire_at) >= now_ms)[0]
        # Duplicate keys keep the LAST lane (dict semantics; also keeps
        # the scatter's indices unique — duplicate scatter order is
        # unspecified).
        seen: Dict[str, int] = {}
        for j in fresh:
            seen[cols.keys[int(j)]] = int(j)
        idx = np.fromiter(seen.values(), dtype=np.int64, count=len(seen))
        if not idx.size:
            return 0
        m = idx.size
        shard_ix = np.empty(m, np.int32)
        slot_ix = np.empty(m, np.int32)
        exists_ix = np.zeros(m, dtype=bool)
        for j, i in enumerate(idx):
            k = cols.keys[int(i)]
            s = shard_of_key(k, self.n_shards)
            slot, exists = self.tables[s].lookup_or_assign(k, now_ms)
            shard_ix[j] = s
            slot_ix[j] = slot
            exists_ix[j] = exists
        # Two-tier: lookup_or_assign may queue promotions for keys that
        # lived in the back tier; land them before reading front rows.
        self._drain_moves()
        S = self.n_shards
        counts = np.bincount(shard_ix, minlength=S)
        P = _pad_pow2(int(counts.max()))
        slots = np.full((S, P), -1, dtype=np.int32)
        lane_of = np.empty(m, np.int64)  # (shard, col) -> flat lane j
        fill = np.zeros(S, np.int64)
        for j in range(m):
            s = int(shard_ix[j])
            slots[s, fill[s]] = slot_ix[j]
            lane_of[j] = s * P + fill[s]
            fill[s] += 1
        slots_dev = jax.device_put(slots, self._sharding)
        cur = jax.tree.map(
            np.asarray, _gather_rows_mesh_jit(self.state, slots_dev)
        )
        flat = lambda a: a.reshape(-1)[lane_of]  # noqa: E731
        merged = merge_transfer_rows(
            {
                "algo": flat(cur.algo),
                "status": flat(cur.status),
                "limit": flat(cur.limit),
                "remaining": flat(cur.remaining),
                "stamp": flat(cur.stamp),
                "expire_at": flat(cur.expire_at),
            },
            cols, idx, now_ms, exists_ix,
        )
        pack = {}
        for name, dtype in (
            ("algo", np.int32), ("status", np.int32), ("limit", np.int64),
            ("remaining", np.int64), ("duration", np.int64),
            ("stamp", np.int64), ("expire_at", np.int64),
        ):
            buf = np.zeros((S * P,), dtype=dtype)
            buf[lane_of] = merged[name]
            pack[name] = buf.reshape(S, P)
        self.state = _write_rows_mesh_jit(
            self.state,
            slots_dev,
            buckets.BucketRows(
                algo=pack["algo"], limit=pack["limit"],
                remaining=pack["remaining"], duration=pack["duration"],
                stamp=pack["stamp"], expire_at=pack["expire_at"],
                status=pack["status"],
            ),
        )
        self.transfer_commit_dispatches += 2
        self.device_dispatches += 2
        # Host mirrors: the algo mirror feeds algorithm-switch
        # detection; the table expiry feeds planning/eviction.
        self.algo_mirror[shard_ix, slot_ix] = merged["algo"]
        for j in range(m):
            self.tables[int(shard_ix[j])].set_expire(
                int(slot_ix[j]), int(merged["expire_at"][j])
            )
        return int(m)

    # ------------------------------------------------------------------
    def set_replica(self, update, now_ms: int) -> None:
        """Receive side of UpdatePeerGlobals (gubernator.go:259-272):
        store the owner daemon's authoritative status in the replica
        columns, expiring at ResetTime.  One code path with the batch
        receive: a single update is a 1-lane batch."""
        self.set_replica_batch(GlobalsColumns.from_updates([update]), now_ms)

    @_locked
    @_programmed("mesh:replica_commit")
    def set_replica_batch(self, cols: "GlobalsColumns", now_ms: int) -> None:
        """Batched receive side of UpdatePeerGlobals: decode the WHOLE
        broadcast into arrays and commit it with ONE gather/scatter
        device program (plus one clear program when assignments evicted
        gslots) and one vectorized host-mirror update — an N-item
        broadcast costs O(1) device dispatches, not N (the pre-columns
        receiver paid a full dispatch/readback RTT per item,
        `replica_commit_dispatches` counts the programs for the tests
        that pin this)."""
        n = len(cols)
        if n == 0:
            return
        gslots = np.empty(n, dtype=np.int64)
        evicted: List[int] = []
        for i, k in enumerate(cols.keys):
            g, ev = self.gtable.lookup_or_assign(k, -1)
            if ev is not None:
                evicted.append(ev)
            gslots[i] = g
        # Keep only lanes whose key STILL maps to its gslot: a lane can
        # go stale when a later assignment in this same batch recycled
        # its gslot under capacity pressure; and duplicate keys keep the
        # LAST lane (dict semantics of the per-item loop this replaces).
        keep = np.fromiter(
            (
                self.gtable._key_to_gslot.get(k) == int(g)  # noqa: SLF001
                for k, g in zip(cols.keys, gslots)
            ),
            dtype=bool, count=n,
        )
        idx = np.nonzero(keep)[0]
        if idx.size > 1:
            g_kept = gslots[idx]
            _, last_rev = np.unique(g_kept[::-1], return_index=True)
            idx = idx[(idx.size - 1) - last_rev]
        if evicted:
            # Zero recycled rows BEFORE the scatter: a slot evicted and
            # reassigned within this batch gets its new values next.
            # Padded to pow2 buckets with out-of-range indices (clear's
            # mode="drop" ignores them) so varying eviction counts stay
            # within a handful of compiled shapes.
            ev = sorted(set(evicted))
            ev_a = np.full(_pad_pow2(len(ev)), self.g_capacity, np.int32)
            ev_a[: len(ev)] = ev
            self.gcols = self._clear_fn(self.gcols, ev_a)
            self.replica_commit_dispatches += 1
        if not idx.size:
            return
        m = idx.size
        pad = _pad_pow2(m)
        # Pad the scatter to pow2 shape buckets: gslot -1 lanes are
        # dropped inside set_replica, so broadcasts of any size share
        # ~log2(g_capacity) compiled programs instead of one per size.
        gsel = np.full(pad, -1, np.int32)
        gsel[:m] = gslots[idx]
        status = np.zeros(pad, np.int32)
        status[:m] = np.asarray(cols.status, dtype=np.int32)[idx]
        limit = np.zeros(pad, np.int64)
        limit[:m] = np.asarray(cols.limit, dtype=np.int64)[idx]
        remaining = np.zeros(pad, np.int64)
        remaining[:m] = np.asarray(cols.remaining, dtype=np.int64)[idx]
        reset = np.zeros(pad, np.int64)
        reset[:m] = np.asarray(cols.reset_time, dtype=np.int64)[idx]
        self.gcols = self._set_replica_fn(
            self.gcols, gsel, status, limit, remaining, reset
        )
        self.replica_commit_dispatches += 1
        # Vectorized host mirror (rep_expire gates the replica-cache
        # hint; algorithm keeps the broadcast's authoritative value).
        self.gtable.rep_expire[gsel[:m]] = reset[:m]
        self.gtable.algorithm[gsel[:m]] = np.asarray(
            cols.algorithm, dtype=np.int32
        )[idx]

    # ------------------------------------------------------------------
    @_drained_locked
    def sync_globals(self, now_ms: int) -> "SyncResult":
        """Run one GLOBAL sync collective (the TPU-native stand-in for
        GlobalSyncWait ticks of all three global.go pipelines).

        The SyncResult carries what the HOST tier must fan out over the
        peer transport: authoritative statuses for keys this daemon owns
        (UpdatePeerGlobals broadcast) and aggregated hit totals for keys
        owned by remote daemons (GetPeerRateLimits forward).

        Sets `last_sync_cost_s` to the time spent INSIDE the lock
        (collective dispatch + readback + decode/commit) — the real
        recurring cost of a sync pass.  The GlobalManager's window
        tuner reads this instead of its own wall clock: the
        drain-then-lock wait ahead of it is serving-pipeline
        backpressure, and folding that into the window would inflate
        GlobalSyncWait ~10x under load (observed on the contended CPU
        host: wall-time syncs pinned the auto window at its 1s cap)."""
        import time as _time

        t0 = _time.perf_counter()
        with telemetry.program("mesh:global_sync"):
            res = self._sync_globals_locked(now_ms)
        if res.did_work:
            # No-work passes (empty early return) cost ~0 and would pin
            # a min-of-N window estimator at its floor; only passes that
            # ran the collective are valid sync-cost observations.
            self.last_sync_cost_s = _time.perf_counter() - t0
        return res

    def _sync_globals_locked(self, now_ms: int) -> "SyncResult":
        active = self.gtable.active_gslots()
        if not active and not self.dirty.any():
            return SyncResult(did_work=False)

        # Owner-slot resolution fast path: re-verifying every active
        # gslot's slot each pass is O(active) host work — at 50k-gslot
        # working sets that is the sync's dominant cost.  A shard whose
        # table reports an unchanged mapping GENERATION since the end of
        # the last sync cannot have moved/evicted/removed any key, so
        # its already-resolved gslots (owner_slot >= 0) are still valid;
        # only unresolved gslots and shards with mapping churn pay the
        # per-key verification.  (generation is bumped by assign/remap/
        # evict/remove in both table twins; value/expire writes and
        # in-place expiry reuse keep slot ownership and don't bump.)
        gens = [getattr(t, "generation", None) for t in self.tables]
        last = getattr(self, "_sync_gen", None)
        shard_clean = [
            last is not None and g is not None and last[o] == g
            for o, g in enumerate(gens)
        ]

        # Resolve each GLOBAL key's slot in its owner shard's table.
        # Assigning one key can evict another's slot under capacity
        # pressure, so iterate to a fixed point (bounded), then drop any
        # still-unstable entries from this sync.
        for _ in range(3):
            changed = False
            for g in active:
                o = int(self.gtable.owner_shard[g])
                if o < 0:
                    continue  # remote daemon owns it: no local slot
                if shard_clean[o] and self.gtable.owner_slot[g] >= 0:
                    continue
                key = self.gtable.key_of(g)
                slot = self.tables[o].get_slot(key)
                if slot is None:
                    slot, _ = self.tables[o].lookup_or_assign(key, now_ms)
                    changed = True
                    shard_clean[o] = False  # assignment may have evicted
                self.gtable.owner_slot[g] = slot
            if not changed:
                break
        for g in active:
            o = int(self.gtable.owner_shard[g])
            if o < 0 or (shard_clean[o] and self.gtable.owner_slot[g] >= 0):
                continue
            key = self.gtable.key_of(g)
            if self.tables[o].get_slot(key) != int(self.gtable.owner_slot[g]):
                self.gtable.owner_slot[g] = -1

        # Owner-slot resolution above may promote demoted GLOBAL keys;
        # their rows must be in the front table before the collective
        # reads them.
        self._drain_moves()
        cfg = global_ops.SyncConfig(
            owner_slot=jnp.asarray(self.gtable.owner_slot),
            owner_shard=jnp.asarray(self.gtable.owner_shard),
            algorithm=jnp.asarray(self.gtable.algorithm),
            behavior=jnp.asarray(self.gtable.behavior),
            limit=jnp.asarray(self.gtable.limit),
            duration=jnp.asarray(self.gtable.duration),
            greg_expire=jnp.asarray(self.gtable.greg_expire),
            greg_duration=jnp.asarray(self.gtable.greg_duration),
        )
        with _SYNC_COLLECTIVE_LOCK:
            dirty_dev = jax.device_put(jnp.asarray(self.dirty), self._sharding)
            self.state, self.gcols, packed = self._sync_fn(
                self.state, self.gcols, cfg, dirty_dev, now_ms
            )
            packed_np = host_readback(packed)  # [S, 8, G] — the one blocking transfer
        out_rm = (packed_np[:, 0] & 1).astype(bool)
        out_exp = packed_np[:, 1]
        # psum results are replicated across shards; read shard 0's copy.
        applied_np = ((packed_np[0, 0] >> 1) & 1).astype(bool)
        totals_np = packed_np[0, 2]
        rep_status = packed_np[0, 3]
        rep_limit = packed_np[0, 4]
        rep_remaining = packed_np[0, 5]
        rep_reset = packed_np[0, 6]
        self.gtable.rep_expire[:] = packed_np[0, 7]

        result = SyncResult()
        # Vectorized decode tail: the all-gslot Python loop was O(active)
        # per pass; numpy masks select the (typically sparse) gslots
        # that actually need host work — remote hit totals, applied
        # owner commits, broadcasts.
        act = np.fromiter(active, dtype=np.int64, count=len(active))
        owner_np = self.gtable.owner_shard[act]
        # Remote daemons' keys with aggregated hits: sendHits payloads
        # (global.go:120-160), emitted as wire-ready COLUMNS straight
        # from the template arrays — no per-key dataclasses.
        rsel = act[(owner_np < 0) & (totals_np[act] > 0)]
        if rsel.size:
            rsel = rsel[self.gtable.templated(rsel)]
        if rsel.size:
            result.remote_hit_cols = self.gtable.hit_columns(rsel, totals_np)
        local = act[owner_np >= 0]
        sel = local[applied_np[local] & (self.gtable.owner_slot[local] >= 0)]
        sel_shard = self.gtable.owner_shard[sel]
        for o in np.unique(sel_shard):
            o = int(o)
            idx = sel[sel_shard == o]
            slots = self.gtable.owner_slot[idx]
            keys = [self.gtable.key_of(int(g)) for g in idx]
            if self.store is not None:
                # Store SPI parity: the owner-side apply of forwarded
                # hits fires OnChange/Remove per key in the reference
                # (algorithms.go:64-68,38-40) — keep the per-key path.
                for k, g, slot in zip(keys, idx, slots):
                    g, slot = int(g), int(slot)
                    self.tables[o].commit(
                        [slot], [out_exp[o, g]], [out_rm[o, g]], keys=[k]
                    )
                    req = self.gtable.request_template(g, int(totals_np[g]))
                    if out_rm[o, g]:
                        self.store.remove(k)
                    elif req is not None:
                        rows = self._read_shard_rows(o, [slot])
                        self.store.on_change(req, _rows_to_items([k], rows)[0])
            else:
                self.tables[o].commit(
                    [int(s) for s in slots],
                    [int(e) for e in out_exp[o, idx]],
                    [bool(r) for r in out_rm[o, idx]],
                    keys=keys,
                )
            # Commit-removals unmapped their keys: invalidate now so the
            # post-commit generation snapshot below can't let a clean
            # shard skip re-resolving them next pass.
            for g in idx[out_rm[o, idx]]:
                self.gtable.owner_slot[int(g)] = -1
        # Authoritative statuses for the host broadcast leg, in column
        # form straight from the packed sync readback (the sender
        # encodes these ONCE and fans the same payload to every peer).
        if sel.size:
            result.broadcast_cols = GlobalsColumns(
                keys=[self.gtable.key_of(int(g)) for g in sel],
                algorithm=self.gtable.algorithm[sel].astype(np.int32),
                status=rep_status[sel].astype(np.int32),
                limit=np.asarray(rep_limit[sel], dtype=np.int64),
                remaining=np.asarray(rep_remaining[sel], dtype=np.int64),
                reset_time=np.asarray(rep_reset[sel], dtype=np.int64),
            )
        # Snapshot AFTER our own commits (which may bump generations):
        # shards untouched until the next sync verify nothing then.
        self._sync_gen = [getattr(t, "generation", None) for t in self.tables]
        self.dirty[:] = False
        return result

    # ------------------------------------------------------------------
    def measure_sync_cost_s(self, now_ms: int, iters: int = 6) -> float:
        """BENCHMARK UTILITY: device-only steady-state cost (seconds)
        of ONE GLOBAL sync collective on this mesh (the reference's
        sync is a map drain, global.go:163-195; here it is a device
        collective).  Enqueues `iters` syncs back-to-back (donated
        state chains them on device) and forces completion with one
        small readback — the only reliable barrier on a remote device.

        Do NOT call on a store serving GLOBAL traffic: the timed raw
        syncs drain device-side hit accumulations without the
        host-side commit/broadcast legs (the serving tuner instead
        times its real sync passes in situ, service.GlobalManager).
        Refuses (RuntimeError) if the store already tracks GLOBAL keys
        beyond its own calibration key — losing their accumulated hits
        would silently corrupt live traffic.  The authoritative check
        runs under the store lock (after the pipeline drain) so a key
        registered by a racing serving thread cannot slip past it."""

        req = RateLimitRequest(
            name="__synccal__", unique_key="__synccal__", hits=1,
            limit=1_000_000, duration=60_000, behavior=Behavior.GLOBAL,
        )
        cal_key = req.hash_key()

        def _guard():
            live = [
                k
                for k in (
                    self.gtable.key_of(g) for g in self.gtable.active_gslots()
                )
                if k is not None and k != cal_key
            ]
            if live:
                raise RuntimeError(
                    "measure_sync_cost_s would drain device-side GLOBAL hit "
                    "accumulations without the host commit/broadcast legs; "
                    f"refusing with {len(live)} live GLOBAL key(s), e.g. {live[:3]}"
                )

        _guard()  # fast fail before any device work
        self.apply([req], now_ms)
        self._drain_then_lock()
        try:
            _guard()  # authoritative: under the lock, pipeline drained
            # Resolve owner slots + compile the collective, under the
            # same lock (only the calibration key can exist here, so
            # discarding the SyncResult's host legs loses nothing).
            self._sync_globals_locked(now_ms)
            import time as _time

            cfg = global_ops.SyncConfig(
                owner_slot=jnp.asarray(self.gtable.owner_slot),
                owner_shard=jnp.asarray(self.gtable.owner_shard),
                algorithm=jnp.asarray(self.gtable.algorithm),
                behavior=jnp.asarray(self.gtable.behavior),
                limit=jnp.asarray(self.gtable.limit),
                duration=jnp.asarray(self.gtable.duration),
                greg_expire=jnp.asarray(self.gtable.greg_expire),
                greg_duration=jnp.asarray(self.gtable.greg_duration),
            )
            dirty_dev = jax.device_put(jnp.asarray(self.dirty), self._sharding)

            def one():
                self.state, self.gcols, packed = self._sync_fn(
                    self.state, self.gcols, cfg, dirty_dev, now_ms
                )
                return packed

            with _SYNC_COLLECTIVE_LOCK:
                packed = one()
                np.asarray(packed[:1, :1, :1])  # drain queue + honest mode
                t0 = _time.perf_counter()
                for _ in range(iters):
                    packed = one()
                np.asarray(packed[:1, :1, :1])
                return (_time.perf_counter() - t0) / iters
        finally:
            self._unlock_drained()

    # ------------------------------------------------------------------
    def warmup(self, now_ms: int, warm_shapes: Optional[Sequence[int]] = None) -> None:
        """Compile the hot programs before serving traffic.  A daemon
        that starts answering RPCs cold pays the first-dispatch XLA
        compile (tens of seconds over a remote-device tunnel) inside a
        client's 500ms deadline; run it here instead, behind the same
        readiness gate as WaitForConnect (daemon.go:242-248).  Uses a
        reserved key with a 1ms duration so the slot recycles on the
        next eviction scan.  The request carries Behavior.GLOBAL so the
        sync pass has an active gslot and actually dispatches the
        collective program — a plain request would early-return before
        compiling it."""
        req = RateLimitRequest(
            name="__warmup__", unique_key="__warmup__", hits=0, limit=1,
            duration=1, behavior=Behavior.GLOBAL,
        )
        self.apply([req], now_ms)
        self.sync_globals(now_ms)
        # Compile the batched replica-commit scatter at its smallest
        # pad bucket: the first received GLOBAL broadcast must not pay
        # the compile inside the sender's RPC deadline.  Reuses the
        # warmup key's gslot; reset_time in the past so the replica
        # row can never serve a cached answer.
        self.set_replica_batch(
            GlobalsColumns(
                keys=[req.hash_key()],
                algorithm=np.zeros(1, np.int32),
                status=np.zeros(1, np.int32),
                limit=np.ones(1, np.int64),
                remaining=np.zeros(1, np.int64),
                reset_time=np.full(1, now_ms - 1, np.int64),
            ),
            now_ms,
        )
        if self.back is not None:
            # Compile the tier-move program at its smallest pad bucket
            # (all-noop records): the first real demotion otherwise pays
            # the compile inside a client's deadline.
            S = self.n_shards
            noop = np.full((S, 8), -1, dtype=np.int32)
            z = np.zeros((S, 8), dtype=np.int32)
            put = lambda a: jax.device_put(a, self._sharding)  # noqa: E731
            with self._lock:
                self.state, self.back = _moves_mesh_jit(
                    self.state, self.back, put(z), put(noop), put(z),
                    put(noop), put(z),
                )
        if self._native and self.store is None:
            # Compile the columnar ingress kernels too (the gateway/gRPC
            # hot path).  Each pad_size bucket is its own XLA program,
            # and on a remote device even a compile-cache HIT pays a
            # multi-second executable load at first dispatch — so warm
            # every bucket the deployment expects (`warm_shapes`, lane
            # counts) during startup, not inside a client's deadline.
            # Warm each shape TWICE: with DISTINCT keys (spread over all
            # shards, compiling the pad_size(lanes/S) bucket even traffic
            # dispatches) AND with IDENTICAL keys (everything hashes to
            # one shard, compiling the pad_size(lanes) bucket a
            # duplicate-heavy batch dispatches — without this, a
            # hot-key storm's first dispatch pays a multi-second remote
            # executable load inside a client RPC deadline).  Both the
            # dict wire and the per-lane narrow-wire fallback get
            # compiled (the wide int64 path is rare enough to pay its
            # compile lazily).  1ms duration so the slots recycle.
            for lanes in sorted(set(warm_shapes or (1,))):
                lanes = max(int(lanes), 1)
                for keys in (
                    [f"__warmup__:{i}" for i in range(lanes)],
                    ["__warmup__:0"] * lanes,
                ):
                    for wire in (None, "narrow"):
                        self.apply_columns(
                            keys,
                            np.zeros(lanes, np.int32), np.zeros(lanes, np.int32),
                            np.zeros(lanes, np.int64), np.ones(lanes, np.int64),
                            np.ones(lanes, np.int64), now_ms, force_wire=wire,
                        )
            # Compile the launch-FUSION programs for every dict-wire
            # shape the warm shapes exercised: a backlogged coalescer
            # fuses consecutive same-shape batches into one program
            # (ColumnarPipeline._launch_group), and that program's
            # first dispatch must not pay its executable load inside a
            # client deadline.  All-noop wires (slot=-1 lanes) thread
            # the state through unchanged.
            S = self.n_shards
            with self._stats_lock:
                shapes = sorted(self._seen_wire_shapes)
            for W, narrow in shapes:
                if not narrow:
                    continue  # wide dict batches are rare: compile lazily
                P_lanes = (W - buckets.DICT_WIRE_TABLE_WORDS) // 3
                noop = np.zeros((S, W), dtype=np.int32)
                noop[:, :P_lanes] = -1  # slot=-1: every lane inert
                for k in (2, 4):
                    fn = _mesh_fused_packed_jit(
                        k, False, donate_wires=self._wire_donate
                    )
                    wires = [
                        jax.device_put(noop, self._sharding) for _ in range(k)
                    ]
                    with self._lock:
                        self.state, _ = fn(
                            self.state, *wires,
                            np.ones(k, np.int32),
                            np.full(k, now_ms, np.int64),
                        )

    def size(self) -> int:
        return sum(len(t) for t in self.tables)

    @_drained_locked
    def check_consistency(self) -> None:
        """Test/debug invariant sweep over the host tier (the
        race-detector analogue of the reference's `-race` runs,
        Makefile:8-9): every shard's key->slot mapping must be a
        bijection onto live slots and sized consistently.  Raises
        AssertionError on corruption."""
        for s in range(self.n_shards):
            t = self.tables[s]
            keys = t.keys()
            slots = [t.get_slot(k) for k in keys]
            assert None not in slots, f"shard {s}: unmapped key in keys()"
            assert len(set(slots)) == len(slots), f"shard {s}: slot aliasing"
            assert len(keys) == len(t), (
                f"shard {s}: size {len(t)} != mapped keys {len(keys)}"
            )
            assert all(0 <= x < self.capacity_per_shard for x in slots), (
                f"shard {s}: slot out of range"
            )
