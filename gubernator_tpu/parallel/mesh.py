"""Mesh-sharded bucket store: key ownership = device shard.

The TPU-native replacement for the reference's peer cluster
(replicated_hash.go key->owner + per-peer caches): bucket state columns
get a leading shard axis laid out over a 1-D `jax.sharding.Mesh`, and
one program applies every shard's request sub-batch to its own state
slice in a single dispatch.  What the reference does with N gRPC
servers and a consistent-hash ring across processes, this does with N
devices and a static shardmap inside one XLA program — peer traffic
becomes ICI traffic.

GLOBAL behavior (Behavior.GLOBAL) is fully supported: non-owner shards
answer from replica columns and accumulate hits device-side; a periodic
`sync_globals()` runs ONE shard_map collective program (psum hit
aggregation -> owner apply -> psum status broadcast) in place of the
reference's three RPC pipelines (global.go).  See ops/global_ops.py.

Key -> shard assignment is `fnv1a(key) % n_shards` (a static shardmap;
the dynamic-membership ring remains at the host/daemon tier for
multi-process deployments, parallel/hash_ring.py).  The mesh is static
for the process lifetime — the reference drops bucket state on
membership change anyway (architecture.md:5-11), so elasticity lives at
the host tier in both designs.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.shard import RoundPlanner, build_round_arrays, pad_size, prepare_requests
from ..models.slot_table import SlotTable
from ..ops import buckets, global_ops
from ..types import Behavior, RateLimitRequest, RateLimitResponse, has_behavior
from ..utils import hashing
from .global_mgr import GlobalKeyTable

try:
    from jax import shard_map  # jax >= 0.6
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map


def shard_of_key(key: str, n_shards: int) -> int:
    """Static shardmap: fnv1a-64 of the hash key, modulo shard count."""
    return hashing.hash_string_64(key) % n_shards


def make_mesh(devices: Optional[Sequence[jax.Device]] = None, axis: str = "shard") -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices), (axis,))


class MeshBucketStore:
    """Bucket tables for all local shards, sharded over a device mesh.

    The host keeps one SlotTable per shard; requests are bucketed by
    `shard_of_key`, each shard's stream is round-planned independently
    (duplicate keys serialize within their shard), and all shards' round
    r runs as ONE sharded program dispatch.

    `apply(..., home_shard=s)` models the reference's ingress topology:
    the request arrived at peer s, which may not own the key.  GLOBAL
    requests at a non-owner answer locally (replica cache or as-if-owner
    fallback, gubernator.go:231-255) and forward hits at the next
    `sync_globals()`.  Non-GLOBAL requests always route to the owner
    (the in-process equivalent of the BATCHING forward,
    peer_client.go:237-268).
    """

    def __init__(
        self,
        capacity_per_shard: int = 50_000,
        g_capacity: int = 4096,
        mesh: Optional[Mesh] = None,
        devices: Optional[Sequence[jax.Device]] = None,
    ):
        self.mesh = mesh if mesh is not None else make_mesh(devices)
        (self.axis,) = self.mesh.axis_names
        self.n_shards = self.mesh.devices.size
        self.capacity_per_shard = capacity_per_shard
        self.g_capacity = g_capacity
        self.tables = [SlotTable(capacity_per_shard) for _ in range(self.n_shards)]
        self.algo_mirror = [
            np.zeros(capacity_per_shard, dtype=np.int32) for _ in range(self.n_shards)
        ]
        self.gtable = GlobalKeyTable(g_capacity)
        self.dirty = np.zeros((self.n_shards, g_capacity), dtype=bool)

        self._sharding = NamedSharding(self.mesh, P(self.axis))
        self.state = self._stack_and_shard(buckets.init_state(capacity_per_shard))
        self.gcols = self._stack_and_shard(global_ops.init_global_columns(g_capacity))

        axis = self.axis

        @partial(jax.jit, donate_argnums=(0, 1))
        def _answer(state, gcols, batch, extra, now):
            return jax.vmap(global_ops.answer_batch, in_axes=(0, 0, 0, 0, None))(
                state, gcols, batch, extra, now
            )

        self._answer_fn = _answer

        def _sync_body(state, gcols, cfg, dirty, now):
            sq = lambda t: jax.tree.map(lambda a: a[0], t)
            ns, ngc, out, applied = global_ops.global_sync(
                sq(state), sq(gcols), cfg, dirty[0], now, axis=axis
            )
            ex = lambda t: jax.tree.map(lambda a: a[None], t)
            return ex(ns), ex(ngc), ex(out), applied[None]

        self._sync_fn = jax.jit(
            shard_map(
                _sync_body,
                mesh=self.mesh,
                in_specs=(P(axis), P(axis), P(), P(axis), P()),
                out_specs=(P(axis), P(axis), P(axis), P(axis)),
            ),
            donate_argnums=(0, 1),
        )

        @partial(jax.jit, donate_argnums=0)
        def _clear(gcols, idx):
            return jax.vmap(global_ops.clear_gslots, in_axes=(0, None))(gcols, idx)

        self._clear_fn = _clear

    def _stack_and_shard(self, single):
        stacked = jax.tree.map(
            lambda c: np.broadcast_to(np.asarray(c), (self.n_shards,) + c.shape).copy(), single
        )
        return jax.tree.map(lambda c: jax.device_put(c, self._sharding), stacked)

    # ------------------------------------------------------------------
    def apply(
        self,
        requests: Sequence[RateLimitRequest],
        now_ms: int,
        home_shard: Optional[int] = None,
    ) -> List[RateLimitResponse]:
        """Evaluate a batch across all shards; responses in request order."""
        responses: List[Optional[RateLimitResponse]] = [None] * len(requests)
        prepared = prepare_requests(requests, now_ms, responses)

        by_shard: List[list] = [[] for _ in range(self.n_shards)]
        for p in prepared:
            owner = shard_of_key(p.key, self.n_shards)
            target = owner
            if has_behavior(p.req.behavior, Behavior.GLOBAL):
                g, evicted = self.gtable.lookup_or_assign(p.key, owner)
                if evicted is not None:
                    self.gcols = self._clear_fn(self.gcols, np.array([evicted], np.int32))
                self.gtable.update_config(g, p.req, p.greg_expire, p.greg_duration)
                if home_shard is not None and home_shard != owner:
                    # Non-owner: answer locally, forward hits at sync
                    # (gubernator.go:231-255).
                    p.gslot = g
                    target = home_shard
                    if self.gtable.rep_expire[g] >= now_ms:
                        p.cached_hint = True
                else:
                    # Owner applies directly and owes a broadcast
                    # (getRateLimit's QueueUpdate, gubernator.go:339-341).
                    self.dirty[owner, g] = True
            by_shard[target].append(p)

        planners = [
            RoundPlanner(self.tables[s], by_shard[s], now_ms) for s in range(self.n_shards)
        ]
        while True:
            chunks = [pl.next_chunk() for pl in planners]
            if not any(chunks):
                break
            self._run_round(chunks, now_ms, responses)

        return [r if r is not None else RateLimitResponse() for r in responses]

    # ------------------------------------------------------------------
    def _run_round(self, chunks, now_ms: int, responses) -> None:
        padded = pad_size(max(max((len(c) for c in chunks), default=1), 1))
        cols = [build_round_arrays(c, padded) for c in chunks]
        stacked = [np.stack([col[f] for col in cols]) for f in range(9)]
        gslot = np.full((self.n_shards, padded), -1, dtype=np.int32)
        for s, chunk in enumerate(chunks):
            for i, p in enumerate(chunk):
                gslot[s, i] = p.gslot

        batch = buckets.RequestBatch(*[jnp.asarray(a) for a in stacked])
        batch = jax.tree.map(lambda c: jax.device_put(c, self._sharding), batch)
        extra = global_ops.GlobalBatchExtra(
            gslot=jax.device_put(jnp.asarray(gslot), self._sharding)
        )

        self.state, self.gcols, out, cached = self._answer_fn(
            self.state, self.gcols, batch, extra, now_ms
        )

        out_status = np.asarray(out.status)
        out_limit = np.asarray(out.limit)
        out_rem = np.asarray(out.remaining)
        out_reset = np.asarray(out.reset_time)
        out_exp = np.asarray(out.new_expire)
        out_removed = np.asarray(out.removed)
        cached_np = np.asarray(cached)

        for s, chunk in enumerate(chunks):
            if not chunk:
                continue
            commit_slots, commit_exp, commit_rm, commit_keys = [], [], [], []
            for i, p in enumerate(chunk):
                if not cached_np[s, i] and p.slot >= 0:
                    commit_slots.append(p.slot)
                    commit_exp.append(out_exp[s, i])
                    commit_rm.append(out_removed[s, i])
                    commit_keys.append(p.key)
                    self.algo_mirror[s][p.slot] = int(p.req.algorithm)
                responses[p.pos] = RateLimitResponse(
                    status=int(out_status[s, i]),
                    limit=int(out_limit[s, i]) if cached_np[s, i] else int(p.req.limit),
                    remaining=int(out_rem[s, i]),
                    reset_time=int(out_reset[s, i]),
                )
            self.tables[s].commit(commit_slots, commit_exp, commit_rm, keys=commit_keys)

    # ------------------------------------------------------------------
    def sync_globals(self, now_ms: int) -> int:
        """Run one GLOBAL sync collective (the TPU-native stand-in for
        GlobalSyncWait ticks of all three global.go pipelines).  Returns
        the number of keys broadcast."""
        active = self.gtable.active_gslots()
        if not active and not self.dirty.any():
            return 0

        # Resolve each GLOBAL key's slot in its owner shard's table.
        # Assigning one key can evict another's slot under capacity
        # pressure, so iterate to a fixed point (bounded), then drop any
        # still-unstable entries from this sync.
        for _ in range(3):
            changed = False
            for g in active:
                key = self.gtable.key_of(g)
                o = int(self.gtable.owner_shard[g])
                slot = self.tables[o].get_slot(key)
                if slot is None:
                    slot, _ = self.tables[o].lookup_or_assign(key, now_ms)
                    changed = True
                self.gtable.owner_slot[g] = slot
            if not changed:
                break
        for g in active:
            key = self.gtable.key_of(g)
            o = int(self.gtable.owner_shard[g])
            if self.tables[o].get_slot(key) != int(self.gtable.owner_slot[g]):
                self.gtable.owner_slot[g] = -1

        cfg = global_ops.SyncConfig(
            owner_slot=jnp.asarray(self.gtable.owner_slot),
            owner_shard=jnp.asarray(self.gtable.owner_shard),
            algorithm=jnp.asarray(self.gtable.algorithm),
            behavior=jnp.asarray(self.gtable.behavior),
            limit=jnp.asarray(self.gtable.limit),
            duration=jnp.asarray(self.gtable.duration),
            greg_expire=jnp.asarray(self.gtable.greg_expire),
            greg_duration=jnp.asarray(self.gtable.greg_duration),
        )
        dirty_dev = jax.device_put(jnp.asarray(self.dirty), self._sharding)
        self.state, self.gcols, out, applied = self._sync_fn(
            self.state, self.gcols, cfg, dirty_dev, now_ms
        )

        out_exp = np.asarray(out.new_expire)
        out_rm = np.asarray(out.removed)
        applied_np = np.asarray(applied)[0]
        self.gtable.rep_expire[:] = np.asarray(self.gcols.rep_expire)[0]

        n_bcast = 0
        for g in active:
            slot = int(self.gtable.owner_slot[g])
            if slot < 0 or not applied_np[g]:
                continue
            o = int(self.gtable.owner_shard[g])
            self.tables[o].commit(
                [slot], [out_exp[o, g]], [out_rm[o, g]], keys=[self.gtable.key_of(g)]
            )
            n_bcast += 1
        self.dirty[:] = False
        return n_bcast

    # ------------------------------------------------------------------
    def size(self) -> int:
        return sum(len(t) for t in self.tables)
