"""TLS subsystem (reference tls.go).

Builds server/client ssl contexts from files (tls.go:118-263) or
generates a self-signed CA + server certificate on the fly — AutoTLS
(tls.go:265-416, selfCert/selfCA) — via the openssl CLI (the stdlib has
no cert-generation API and `cryptography` is not in this image).
Supports the reference's client-auth modes: "" (off), "request"
(tls.ClientAuthType RequestClientCert) and "require-and-verify"
(RequireAndVerifyClientCert), plus insecure_skip_verify for the client
side.

The server context wraps the gateway listener; the client context is
handed to every PeerClient so peer data-plane traffic is encrypted and
(under mTLS) mutually authenticated, mirroring how the reference feeds
ClientTLS into the peer dialer (daemon.go:102-106, peer_client.go:87-132).
"""

from __future__ import annotations

import os
import ssl
import subprocess
import tempfile
from dataclasses import dataclass, field
from typing import Optional, Tuple

from .utils.net import discover_network_addresses


class TLSError(Exception):
    pass


@dataclass
class TLSConfig:
    """tls.go:30-104 equivalent (file paths; AutoTLS generates them)."""

    ca_file: str = ""
    ca_key_file: str = ""
    cert_file: str = ""
    key_file: str = ""
    auto_tls: bool = False
    client_auth: str = ""  # "", "request", "require-and-verify"
    client_auth_ca_file: str = ""  # CA used to verify client certs
    client_auth_cert_file: str = ""  # cert this node presents as a client
    client_auth_key_file: str = ""
    insecure_skip_verify: bool = False
    # Populated by setup_tls:
    server_ctx: Optional[ssl.SSLContext] = field(default=None, repr=False)
    client_ctx: Optional[ssl.SSLContext] = field(default=None, repr=False)

    @property
    def enabled(self) -> bool:
        return bool(self.auto_tls or self.cert_file or self.ca_file)


def _openssl(*args: str) -> None:
    try:
        subprocess.run(
            ["openssl", *args], check=True, capture_output=True, timeout=60
        )
    except FileNotFoundError as e:
        raise TLSError("AutoTLS requires the openssl binary") from e
    except subprocess.CalledProcessError as e:
        raise TLSError(
            f"openssl {' '.join(args[:2])} failed: {e.stderr.decode()[:300]}"
        ) from e


def self_ca(dir_: str) -> Tuple[str, str]:
    """Generate a self-signed CA (tls.go:364-416). Returns (crt, key)."""
    ca_key = os.path.join(dir_, "ca.key")
    ca_crt = os.path.join(dir_, "ca.crt")
    _openssl(
        "req", "-x509", "-newkey", "ec", "-pkeyopt", "ec_paramgen_curve:P-256",
        "-keyout", ca_key, "-out", ca_crt, "-days", "2", "-nodes",
        "-subj", "/O=gubernator-tpu/CN=auto-ca",
    )
    return ca_crt, ca_key


def self_cert(
    dir_: str, ca_crt: str, ca_key: str, name: str = "server",
    client: bool = False,
) -> Tuple[str, str]:
    """Generate a CA-signed cert (tls.go:265-362). SANs cover loopback,
    every non-loopback interface IP, their reverse-DNS names, and the
    hostname (net.go:70-106 discovery).  Returns (crt, key)."""
    key = os.path.join(dir_, f"{name}.key")
    csr = os.path.join(dir_, f"{name}.csr")
    crt = os.path.join(dir_, f"{name}.crt")
    ext = os.path.join(dir_, f"{name}.ext")
    sans = ["DNS:localhost", "IP:127.0.0.1", "IP:0.0.0.0"]
    ips, dns_names = discover_network_addresses()
    sans.extend(f"IP:{ip}" for ip in ips)
    sans.extend(f"DNS:{n}" for n in dns_names)
    try:
        import socket

        host = socket.gethostname()
        if f"DNS:{host}" not in sans:
            sans.append(f"DNS:{host}")
    except OSError:
        pass
    usage = "clientAuth" if client else "serverAuth,clientAuth"
    with open(ext, "w") as f:
        f.write(f"subjectAltName={','.join(sans)}\n")
        f.write(f"extendedKeyUsage={usage}\n")
    _openssl(
        "req", "-newkey", "ec", "-pkeyopt", "ec_paramgen_curve:P-256",
        "-keyout", key, "-out", csr, "-nodes",
        "-subj", f"/O=gubernator-tpu/CN={name}",
    )
    _openssl(
        "x509", "-req", "-in", csr, "-CA", ca_crt, "-CAkey", ca_key,
        "-CAcreateserial", "-out", crt, "-days", "2", "-extfile", ext,
    )
    return crt, key


def setup_tls(conf: Optional[TLSConfig]) -> Optional[TLSConfig]:
    """Assemble server_ctx/client_ctx (tls.go:118-263).  Mutates and
    returns conf; returns None when TLS is not configured."""
    if conf is None or not conf.enabled:
        return None

    if conf.auto_tls and not conf.cert_file:
        dir_ = tempfile.mkdtemp(prefix="guber-autotls-")
        if not conf.ca_file:
            conf.ca_file, conf.ca_key_file = self_ca(dir_)
        elif not conf.ca_key_file:
            raise TLSError("auto-tls with a provided CA requires ca_key_file")
        conf.cert_file, conf.key_file = self_cert(
            dir_, conf.ca_file, conf.ca_key_file
        )

    if not conf.cert_file or not conf.key_file:
        raise TLSError("TLS requires cert_file and key_file (or auto_tls)")

    server = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    server.load_cert_chain(conf.cert_file, conf.key_file)
    client_ca = conf.client_auth_ca_file or conf.ca_file
    if conf.client_auth:
        if not client_ca:
            raise TLSError(
                "client auth enabled but no CA to verify client certs "
                "(ca_file or client_auth_ca_file)"
            )
        server.load_verify_locations(client_ca)
        if conf.client_auth == "require-and-verify":
            server.verify_mode = ssl.CERT_REQUIRED
        elif conf.client_auth == "request":
            server.verify_mode = ssl.CERT_OPTIONAL
        else:
            raise TLSError(
                f"invalid client_auth '{conf.client_auth}'; expected "
                "'request' or 'require-and-verify'"
            )

    client = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    if conf.insecure_skip_verify:
        client.check_hostname = False
        client.verify_mode = ssl.CERT_NONE
    elif conf.ca_file:
        client.load_verify_locations(conf.ca_file)
    else:
        client.load_default_certs()
    # Under mTLS this node's peer-client must present a cert; AutoTLS
    # server certs carry clientAuth usage so the server pair is reused
    # (tls.go:188-207 equivalent).
    if conf.client_auth_cert_file:
        client.load_cert_chain(conf.client_auth_cert_file, conf.client_auth_key_file)
    elif conf.client_auth and conf.cert_file:
        client.load_cert_chain(conf.cert_file, conf.key_file)

    conf.server_ctx = server
    conf.client_ctx = client
    return conf


def client_context(
    ca_file: str = "",
    cert_file: str = "",
    key_file: str = "",
    insecure_skip_verify: bool = False,
) -> ssl.SSLContext:
    """Standalone client-side context builder (for V1Client users)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    if insecure_skip_verify:
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    elif ca_file:
        ctx.load_verify_locations(ca_file)
    else:
        ctx.load_default_certs()
    if cert_file:
        ctx.load_cert_chain(cert_file, key_file)
    return ctx
