"""Peer discovery pools.

The reference ships three backends (etcd lease+watch, memberlist gossip,
k8s informer — etcd.go / memberlist.go / kubernetes.go), all pushing
`[]PeerInfo` through an OnUpdate callback.  This build keeps the same
config surface (GUBER_PEER_DISCOVERY_TYPE) with zero-dependency
implementations:

  * static       — fixed list in DaemonConfig.peers (the cluster harness
                   and tests use this, like cluster/cluster.go bypasses
                   discovery entirely)
  * file         — a watched JSON file of PeerInfo entries; editing the
                   file is the membership event
  * member-list  — native SWIM gossip (gubernator_tpu.gossip), the
                   hashicorp/memberlist equivalent

etcd and k8s still raise until their native client planes land.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Callable, List, Optional

from .types import PeerInfo

OnUpdate = Callable[[List[PeerInfo]], None]


class StaticPool:
    """Fixed peer list, delivered once."""

    def __init__(self, peers: List[PeerInfo], on_update: OnUpdate):
        on_update(peers)

    def close(self) -> None:
        pass


class FilePool:
    """Watches a JSON file ([{"grpcAddress": ...}, ...]) by mtime poll;
    pushes the parsed list on change."""

    def __init__(self, path: str, on_update: OnUpdate, poll_s: float = 0.5):
        self.path = path
        self.on_update = on_update
        self.poll_s = poll_s
        self._stop = threading.Event()
        self._mtime = 0.0
        self._load()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _load(self) -> None:
        try:
            mtime = os.path.getmtime(self.path)
        except OSError:
            return
        if mtime == self._mtime:
            return
        self._mtime = mtime
        with open(self.path) as f:
            data = json.load(f)
        self.on_update([PeerInfo.from_json(p) for p in data])

    def _run(self) -> None:
        while not self._stop.wait(timeout=self.poll_s):
            try:
                self._load()
            except (OSError, json.JSONDecodeError):
                continue

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)


def make_pool(kind: str, conf, on_update: OnUpdate, advertise: Optional[PeerInfo] = None):
    """daemon.go:163-192 discovery switch.  `advertise` is this daemon's
    own PeerInfo, required by the backends that register/gossip
    themselves (member-list, etcd)."""
    if kind == "static":
        return StaticPool(conf.peers, on_update)
    if kind == "file":
        return FilePool(conf.peers_file, on_update)
    if kind == "etcd":
        try:
            import etcd3  # noqa: F401
        except ImportError as e:
            raise RuntimeError(
                "etcd peer discovery requires the 'etcd3' package, which is "
                "not installed in this environment; use 'static' or 'file'"
            ) from e
        raise NotImplementedError("etcd pool: install etcd3 and wire EtcdPool here")
    if kind == "member-list":
        from .gossip import GossipPool

        if not advertise:
            raise ValueError("member-list discovery requires an advertise PeerInfo")
        # Default bind: advertise_host:7946 (config.go:315) — binding
        # loopback would gossip an unreachable address to remote peers.
        adv_host = advertise.grpc_address.partition(":")[0]
        return GossipPool(
            advertise=advertise,
            member_list_address=conf.member_list_address or f"{adv_host}:7946",
            on_update=on_update,
            known_nodes=conf.member_list_known_nodes,
            node_name=conf.member_list_node_name,
        )
    if kind == "k8s":
        try:
            import kubernetes  # noqa: F401
        except ImportError as e:
            raise RuntimeError(
                "k8s peer discovery requires the 'kubernetes' package, which "
                "is not installed in this environment; use 'static' or 'file'"
            ) from e
        raise NotImplementedError("k8s pool: install kubernetes and wire K8sPool here")
    raise ValueError(f"unknown peer discovery type '{kind}'")
