"""Peer discovery pools.

The reference ships three backends (etcd lease+watch, memberlist gossip,
k8s informer — etcd.go / memberlist.go / kubernetes.go), all pushing
`[]PeerInfo` through an OnUpdate callback.  This build keeps the same
config surface (GUBER_PEER_DISCOVERY_TYPE) with zero-dependency
implementations:

  * static       — fixed list in DaemonConfig.peers (the cluster harness
                   and tests use this, like cluster/cluster.go bypasses
                   discovery entirely)
  * file         — a watched JSON file of PeerInfo entries; editing the
                   file is the membership event
  * member-list  — native SWIM gossip (gubernator_tpu.gossip), the
                   hashicorp/memberlist equivalent
  * etcd         — lease+watch registration against an etcd v3 cluster
                   over its public gRPC API (gubernator_tpu.etcd_pool)
  * k8s          — Endpoints/Pods list+watch over the Kubernetes HTTP
                   API with in-cluster credentials (gubernator_tpu.k8s_pool)
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Callable, List, Optional

from .types import PeerInfo

log = logging.getLogger("gubernator.peers")

OnUpdate = Callable[[List[PeerInfo]], None]


class StaticPool:
    """Fixed peer list, delivered once."""

    def __init__(self, peers: List[PeerInfo], on_update: OnUpdate):
        on_update(peers)

    def close(self) -> None:
        pass


class FilePool:
    """Watches a JSON file ([{"grpcAddress": ...}, ...]) by mtime poll;
    pushes the parsed list on change."""

    def __init__(self, path: str, on_update: OnUpdate, poll_s: float = 0.5):
        self.path = path
        self.on_update = on_update
        self.poll_s = poll_s
        self._stop = threading.Event()
        self._mtime = 0.0
        self._last_peers: "Optional[List[PeerInfo]]" = None
        try:
            # A torn/invalid file at construction is transient the same
            # way it is mid-poll: log and let the first tick retry
            # rather than failing daemon startup.
            self._load()
        except (OSError, ValueError) as e:
            log.warning("initial peers-file load failed, will retry: %s", e)
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _load(self) -> None:
        try:
            mtime = os.path.getmtime(self.path)
        except OSError:
            return
        if mtime == self._mtime:
            return
        with open(self.path) as f:
            data = json.load(f)
        if not isinstance(data, list):
            raise ValueError("peers file must be a JSON array of objects")
        peers = []
        for p in data:
            if not isinstance(p, dict):
                raise ValueError(f"peer entry must be a JSON object, got {p!r}")
            peers.append(PeerInfo.from_json(p))
        # Record the mtime only AFTER the content fully validated: a
        # poll landing on a half-written (or JSON-valid-but-wrong-shape)
        # file must retry on the next tick, not mark the content as
        # seen and drop the update forever.
        self._mtime = mtime
        if peers == self._last_peers:
            # Touched-but-unchanged file (config management rewrites,
            # atomic-replace deploy loops): membership didn't change,
            # so don't push a spurious update downstream — set_peers
            # would rebuild the pickers for nothing, and membership
            # no-ops must never look like ring churn to the resharding
            # plane.
            return
        self._last_peers = peers
        self.on_update(peers)

    def _run(self) -> None:
        while not self._stop.wait(timeout=self.poll_s):
            try:
                self._load()
            except (OSError, ValueError) as e:
                # JSONDecodeError is a ValueError; shape errors raise
                # ValueError explicitly above.
                log.debug("peers-file poll failed, retrying: %s", e)
                continue

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)


def make_pool(kind: str, conf, on_update: OnUpdate, advertise: Optional[PeerInfo] = None):
    """daemon.go:163-192 discovery switch.  `advertise` is this daemon's
    own PeerInfo, required by the backends that register/gossip
    themselves (member-list, etcd)."""
    if kind == "static":
        return StaticPool(conf.peers, on_update)
    if kind == "file":
        return FilePool(conf.peers_file, on_update)
    if kind == "etcd":
        from .etcd_pool import EtcdPool

        if not advertise:
            raise ValueError("etcd discovery requires an advertise PeerInfo")
        if conf.etcd_advertise_address:
            advertise = PeerInfo(
                grpc_address=conf.etcd_advertise_address,
                http_address=advertise.http_address,
                data_center=advertise.data_center,
            )
        from .etcd_pool import credentials_from_config

        return EtcdPool(
            advertise=advertise,
            on_update=on_update,
            endpoints=conf.etcd_endpoints,
            key_prefix=conf.etcd_key_prefix,
            credentials=credentials_from_config(conf),
            username=getattr(conf, "etcd_user", ""),
            password=getattr(conf, "etcd_password", ""),
        )
    if kind == "member-list":
        from .gossip import GossipPool

        if not advertise:
            raise ValueError("member-list discovery requires an advertise PeerInfo")
        # Default bind: advertise_host:7946 (config.go:315) — binding
        # loopback would gossip an unreachable address to remote peers.
        adv_host = advertise.grpc_address.partition(":")[0]
        return GossipPool(
            advertise=advertise,
            member_list_address=conf.member_list_address or f"{adv_host}:7946",
            on_update=on_update,
            known_nodes=conf.member_list_known_nodes,
            node_name=conf.member_list_node_name,
            seed=getattr(conf, "gossip_seed", None),
            faults=getattr(conf, "fault_plan", None),
        )
    if kind == "k8s":
        from .k8s_pool import K8sPool

        return K8sPool(
            on_update=on_update,
            namespace=conf.k8s_namespace,
            selector=conf.k8s_selector,
            pod_ip=conf.k8s_pod_ip,
            pod_port=conf.k8s_pod_port,
            mechanism=conf.k8s_mechanism,
        )
    raise ValueError(f"unknown peer discovery type '{kind}'")
